// Native ingest layer: batch string interning + parallel lexicographic
// sort for columnar snapshot builds.
//
// Role in the framework: the reference (authzed/gochugaru) is a pure-Go
// client whose server does all heavy lifting; in this TPU-native redesign
// the host-side ingest — interning (type, object-id) strings to dense
// int32 node ids and sorting edge columns into the device's binary-search
// layout — is the bottleneck at 100M-1B edges (SURVEY.md §7 "interning
// throughput at 1B edges is the real bottleneck").  This is the runtime
// piece that earns native code: a C ABI (consumed via ctypes, no pybind11
// in the image) wrapping
//   * an open-addressing string interner with an append-only arena, and
//   * an OpenMP-parallel sort over packed 93-bit (rel,res,subj,srel1) keys.
//
// Thread-safety: the interner is single-writer (callers serialize mutating
// calls — the Python side holds its store lock); reads of immutable
// prefixes are safe.  Sorting is stateless.
//
// Build: g++ -O3 -shared -fPIC -fopenmp ingest.cpp -o libgochugaru_ingest.so

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#include <parallel/algorithm>
#endif

namespace {

inline uint64_t hash_bytes(const char* data, uint64_t len, uint64_t seed) {
  // FNV-1a, then a final mix (good enough for open addressing; inputs are
  // short object ids)
  uint64_t h = 1469598103934665603ull ^ (seed * 0x9e3779b97f4a7c15ull);
  for (uint64_t i = 0; i < len; i++) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 1099511628211ull;
  }
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 33;
  return h;
}

struct Entry {
  uint64_t hash;
  uint64_t off;
  uint32_t len;
  int32_t type;
};

struct Interner {
  std::vector<char> arena;
  std::vector<Entry> entries;   // index == node id
  std::vector<int64_t> table;   // open addressing; -1 empty, else node id
  uint64_t mask = 0;

  Interner() {
    table.assign(1 << 16, -1);
    mask = table.size() - 1;
    arena.reserve(1 << 20);
  }

  void grow() {
    std::vector<int64_t> bigger(table.size() * 2, -1);
    uint64_t m = bigger.size() - 1;
    for (int64_t node = 0; node < static_cast<int64_t>(entries.size()); node++) {
      uint64_t slot = entries[node].hash & m;
      while (bigger[slot] != -1) slot = (slot + 1) & m;
      bigger[slot] = node;
    }
    table.swap(bigger);
    mask = m;
  }

  inline bool equals(int64_t node, int32_t type, const char* s, uint32_t len,
                     uint64_t h) const {
    const Entry& e = entries[node];
    return e.hash == h && e.type == type && e.len == len &&
           std::memcmp(arena.data() + e.off, s, len) == 0;
  }

  int64_t find(int32_t type, const char* s, uint32_t len) const {
    uint64_t h = hash_bytes(s, len, static_cast<uint64_t>(type) + 1);
    uint64_t slot = h & mask;
    while (true) {
      int64_t node = table[slot];
      if (node == -1) return -1;
      if (equals(node, type, s, len, h)) return node;
      slot = (slot + 1) & mask;
    }
  }

  int64_t intern(int32_t type, const char* s, uint32_t len) {
    uint64_t h = hash_bytes(s, len, static_cast<uint64_t>(type) + 1);
    uint64_t slot = h & mask;
    while (true) {
      int64_t node = table[slot];
      if (node == -1) break;
      if (equals(node, type, s, len, h)) return node;
      slot = (slot + 1) & mask;
    }
    if ((entries.size() + 1) * 10 >= table.size() * 7) {  // 0.7 load factor
      grow();
      slot = h & mask;
      while (table[slot] != -1) slot = (slot + 1) & mask;
    }
    int64_t node = static_cast<int64_t>(entries.size());
    Entry e;
    e.hash = h;
    e.off = arena.size();
    e.len = len;
    e.type = type;
    arena.insert(arena.end(), s, s + len);
    entries.push_back(e);
    table[slot] = node;
    return node;
  }
};

}  // namespace

extern "C" {

void* gi_new() { return new Interner(); }

void gi_free(void* h) { delete static_cast<Interner*>(h); }

int64_t gi_size(void* h) {
  return static_cast<int64_t>(static_cast<Interner*>(h)->entries.size());
}

// Intern n strings: buf holds concatenated bytes, offsets has n+1 entries,
// type_ids has n entries.  Writes node ids to out.
void gi_intern_batch(void* h, const char* buf, const int64_t* offsets,
                     int64_t n, const int32_t* type_ids, int32_t* out) {
  Interner* in = static_cast<Interner*>(h);
  for (int64_t i = 0; i < n; i++) {
    out[i] = static_cast<int32_t>(in->intern(
        type_ids[i], buf + offsets[i],
        static_cast<uint32_t>(offsets[i + 1] - offsets[i])));
  }
}

// Lookup without interning; -1 when absent.
void gi_lookup_batch(void* h, const char* buf, const int64_t* offsets,
                     int64_t n, const int32_t* type_ids, int32_t* out) {
  Interner* in = static_cast<Interner*>(h);
  for (int64_t i = 0; i < n; i++) {
    out[i] = static_cast<int32_t>(in->find(
        type_ids[i], buf + offsets[i],
        static_cast<uint32_t>(offsets[i + 1] - offsets[i])));
  }
}

// Per-node type ids for nodes [0, n).
void gi_node_types(void* h, int32_t* out, int64_t n) {
  Interner* in = static_cast<Interner*>(h);
  for (int64_t i = 0; i < n && i < static_cast<int64_t>(in->entries.size()); i++)
    out[i] = in->entries[i].type;
}

// Key of one node: returns length, copies up to cap bytes into out_str and
// the type id into out_type.  Returns -1 for an invalid node.
int64_t gi_key(void* h, int64_t node, char* out_str, int64_t cap,
               int32_t* out_type) {
  Interner* in = static_cast<Interner*>(h);
  if (node < 0 || node >= static_cast<int64_t>(in->entries.size())) return -1;
  const Entry& e = in->entries[node];
  *out_type = e.type;
  int64_t n = e.len < cap ? e.len : cap;
  std::memcpy(out_str, in->arena.data() + e.off, n);
  return e.len;
}

// Batched keys: concatenated id bytes of n nodes into out_buf (cap bytes),
// with out_offsets (n+1 entries, offsets[0] = 0) and out_types (n).
// Returns the total byte length needed — when it exceeds cap, nothing is
// written beyond what fits and the caller must retry with a bigger buffer.
// Invalid nodes get length 0 and type -1.
int64_t gi_keys_batch(void* h, const int64_t* nodes, int64_t n,
                      char* out_buf, int64_t cap, int64_t* out_offsets,
                      int32_t* out_types) {
  Interner* in = static_cast<Interner*>(h);
  const int64_t sz = static_cast<int64_t>(in->entries.size());
  int64_t total = 0;
  out_offsets[0] = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t node = nodes[i];
    if (node < 0 || node >= sz) {
      out_types[i] = -1;
      out_offsets[i + 1] = total;
      continue;
    }
    const Entry& e = in->entries[node];
    out_types[i] = e.type;
    if (total + e.len <= cap) {
      std::memcpy(out_buf + total, in->arena.data() + e.off, e.len);
    }
    total += e.len;
    out_offsets[i + 1] = total;
  }
  return total;
}

// Parallel lexsort by (a, b, c, d) — the snapshot's primary order
// (rel, res, subj, srel1).  Writes the permutation into out (int64[n]).
// Keys are packed into (hi, lo) uint64 pairs: hi = a<<32 | b-as-unsigned,
// lo = c<<32 | d-as-unsigned; int32 values are biased by 2^31 so signed
// order (e.g. srel1 = 0 for direct subjects, payload -1 never occurs in
// sort keys) is preserved under unsigned comparison.
// LSD radix passes over 16-bit digits: stable by construction and
// data-independent O(n) — a comparison sort of random 10M packed keys
// costs ~7s on this one-core host, the radix ~1.5s.  Passes whose digit
// is uniform across all keys are skipped (common for high digits).
//
// Each pass is OpenMP-parallel when threads are available: per-thread
// chunk histograms, a serial (digit-major, thread-minor) exclusive
// prefix over 65536·T counters, then a per-thread ordered scatter.
// Within a digit, elements land ordered by (chunk, in-chunk position) =
// their order in ``cur`` — exactly the serial stable permutation, so the
// output is bit-identical to np.argsort(kind="stable") regardless of T.
static bool radix_pass(const uint64_t* key, int shift, const int64_t* cur,
                       int64_t* nxt, int64_t n) {
  int T = 1;
#if defined(_OPENMP)
  T = omp_get_max_threads();
  if (T > 16) T = 16;
  if (T < 1) T = 1;
  if (n < (1 << 18)) T = 1;
#endif
  const int64_t chunk = (n + T - 1) / T;
  std::vector<int64_t> hist((size_t)T * 65536, 0);
  const uint16_t first = (uint16_t)(key[cur[0]] >> shift);
  std::vector<char> uni((size_t)T, 1);
#if defined(_OPENMP)
#pragma omp parallel for num_threads(T) schedule(static, 1)
#endif
  for (int t = 0; t < T; t++) {
    const int64_t lo = (int64_t)t * chunk;
    const int64_t hi = std::min(n, lo + chunk);
    int64_t* h = hist.data() + (size_t)t * 65536;
    char u = 1;
    for (int64_t i = lo; i < hi; i++) {
      const uint16_t d = (uint16_t)(key[cur[i]] >> shift);
      h[d]++;
      u &= (d == first);
    }
    uni[t] = u;
  }
  bool uniform = true;
  for (int t = 0; t < T; t++) uniform = uniform && uni[t];
  if (uniform) return false;
  int64_t run = 0;
  for (int64_t d = 0; d < 65536; d++) {
    for (int t = 0; t < T; t++) {
      const int64_t c = hist[(size_t)t * 65536 + d];
      hist[(size_t)t * 65536 + d] = run;
      run += c;
    }
  }
#if defined(_OPENMP)
#pragma omp parallel for num_threads(T) schedule(static, 1)
#endif
  for (int t = 0; t < T; t++) {
    const int64_t lo = (int64_t)t * chunk;
    const int64_t hi = std::min(n, lo + chunk);
    int64_t* off = hist.data() + (size_t)t * 65536;
    for (int64_t i = lo; i < hi; i++) {
      const uint16_t d = (uint16_t)(key[cur[i]] >> shift);
      nxt[off[d]++] = cur[i];
    }
  }
  return true;
}

static void radix_u64(const uint64_t* key, int64_t* perm, int64_t n,
                      std::vector<int64_t>& tmp) {
  if (n <= 1) return;
  if ((int64_t)tmp.size() < n) tmp.resize(n);
  int64_t* cur = perm;
  int64_t* nxt = tmp.data();
  for (int shift = 0; shift < 64; shift += 16) {
    if (radix_pass(key, shift, cur, nxt, n)) std::swap(cur, nxt);
  }
  if (cur != perm) std::copy(cur, cur + n, perm);
}

// Stable lexicographic permutation over up to three 64-bit words (w0
// major; w1/w2 may be null).  The generic front-end behind lexsorts
// whose key columns don't fit the packed-int32 entry points (e.g. the
// permission fold's (res, raw-k2, cav·ctx) dedup order).
static void radix_words(const uint64_t* const* words, int nwords,
                        int64_t* perm, int64_t n) {
  if (n <= 1) return;
  std::vector<int64_t> tmp;
  if ((int64_t)tmp.size() < n) tmp.resize(n);
  int64_t* cur = perm;
  int64_t* nxt = tmp.data();
  for (int w = nwords - 1; w >= 0; w--) {
    const uint64_t* key = words[w];
    for (int shift = 0; shift < 64; shift += 16) {
      if (radix_pass(key, shift, cur, nxt, n)) std::swap(cur, nxt);
    }
  }
  if (cur != perm) std::copy(cur, cur + n, perm);
}

void gi_lexsort4(const int32_t* a, const int32_t* b, const int32_t* c,
                 const int32_t* d, int64_t n, int64_t* out) {
  std::vector<uint64_t> hi(n), lo(n);
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; i++) {
    // flip the sign bit so signed int32 order == unsigned order
    uint64_t au = static_cast<uint32_t>(a[i]) ^ 0x80000000u;
    uint64_t bu = static_cast<uint32_t>(b[i]) ^ 0x80000000u;
    uint64_t cu = static_cast<uint32_t>(c[i]) ^ 0x80000000u;
    uint64_t du = static_cast<uint32_t>(d[i]) ^ 0x80000000u;
    hi[i] = (au << 32) | bu;
    lo[i] = (cu << 32) | du;
    out[i] = i;
  }
  std::vector<int64_t> tmp;
  radix_u64(lo.data(), out, n, tmp);  // minor word first: LSD over 128b
  radix_u64(hi.data(), out, n, tmp);
}

// Stable argsort of a single int32 column (radix).
void gi_argsort1(const int32_t* a, int64_t n, int64_t* out) {
  std::vector<uint64_t> key(n);
  for (int64_t i = 0; i < n; i++) {
    key[i] = static_cast<uint32_t>(a[i]) ^ 0x80000000u;
    out[i] = i;
  }
  std::vector<int64_t> tmp;
  radix_u64(key.data(), out, n, tmp);
}

// Exact join of two (h, l)-lexsorted int64 pair sets: out[j] = FIRST
// table position matching query j, or -1.  One linear merge — no
// per-run bisection, no Python.  Both sides must be sorted ascending.
void gi_join_sorted2(const int64_t* th, const int64_t* tl, int64_t nt,
                     const int64_t* qh, const int64_t* ql, int64_t nq,
                     int64_t* out) {
  int64_t i = 0;
  for (int64_t j = 0; j < nq; j++) {
    while (i < nt && (th[i] < qh[j] || (th[i] == qh[j] && tl[i] < ql[j]))) {
      i++;
    }
    out[j] = (i < nt && th[i] == qh[j] && tl[i] == ql[j]) ? i : -1;
  }
}

// Parallel stable lexsort by (a, b) — used for the membership-propagation
// view order (subj, srel).
void gi_lexsort2(const int32_t* a, const int32_t* b, int64_t n, int64_t* out) {
  std::vector<uint64_t> key(n);
  for (int64_t i = 0; i < n; i++) {
    uint64_t au = static_cast<uint32_t>(a[i]) ^ 0x80000000u;
    uint64_t bu = static_cast<uint32_t>(b[i]) ^ 0x80000000u;
    key[i] = (au << 32) | bu;
    out[i] = i;
  }
  std::vector<int64_t> tmp;
  radix_u64(key.data(), out, n, tmp);
}

// Stable permutation by up to three caller-packed uint64 words, w0 major
// (w1/w2 nullable).  The caller is responsible for order-preserving
// packing (non-negative int64 values reinterpret directly; pairs of
// int32 pack as hi<<32|lo with any needed bias applied before the call).
void gi_sortperm3(const uint64_t* w0, const uint64_t* w1, const uint64_t* w2,
                  int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; i++) out[i] = i;
  const uint64_t* words[3];
  int nwords = 0;
  if (w0) words[nwords++] = w0;
  if (w1) words[nwords++] = w1;
  if (w2) words[nwords++] = w2;
  if (nwords == 0) return;
  radix_words(words, nwords, out, n);
}

// Fused hash-bucket index build: given full 32-bit hashes and a pow2
// ``size``, computes bucket = h & (size-1) per row and emits the stable
// bucket-grouped row permutation (== np.argsort(bucket, kind="stable"))
// plus the bucket offset array (== cumsum of the bucket histogram).
// Replaces the mask/astype/bincount/argsort/cumsum chain of
// engine/hash.py build_hash with three linear passes.  Returns the max
// bucket occupancy (the device probe cap).
int64_t gi_hash_index32(const uint32_t* h, int64_t n, int64_t size,
                        int32_t* rows, int32_t* off) {
  const uint32_t mask = (uint32_t)(size - 1);
  std::vector<int32_t> cur(size, 0);
  int T = 1;
#if defined(_OPENMP)
  T = omp_get_max_threads();
  if (T > 8) T = 8;
  if (T < 1) T = 1;
  if (n < (1 << 20)) T = 1;
#endif
  // bucket-range ownership: thread t scans the whole hash column
  // (sequential, shared) but touches only its own bucket range — the
  // random counter/scatter traffic is what binds this loop, and it
  // splits cleanly.  Rows append in ascending i per bucket on every
  // thread, so the permutation is the stable one regardless of T.
  const int64_t brange = (size + T - 1) / T;
#if defined(_OPENMP)
#pragma omp parallel for num_threads(T) schedule(static, 1)
#endif
  for (int t = 0; t < T; t++) {
    const uint32_t blo = (uint32_t)((int64_t)t * brange);
    const uint32_t bhi =
        (uint32_t)std::min<int64_t>(size, (int64_t)(t + 1) * brange);
    for (int64_t i = 0; i < n; i++) {
      const uint32_t b = h[i] & mask;
      if (b >= blo && b < bhi) cur[b]++;
    }
  }
  int64_t cap = 0, run = 0;
  off[0] = 0;
  for (int64_t b = 0; b < size; b++) {
    const int64_t c = cur[b];
    if (c > cap) cap = c;
    cur[b] = (int32_t)run;
    run += c;
    off[b + 1] = (int32_t)run;
  }
#if defined(_OPENMP)
#pragma omp parallel for num_threads(T) schedule(static, 1)
#endif
  for (int t = 0; t < T; t++) {
    const uint32_t blo = (uint32_t)((int64_t)t * brange);
    const uint32_t bhi =
        (uint32_t)std::min<int64_t>(size, (int64_t)(t + 1) * brange);
    for (int64_t i = 0; i < n; i++) {
      const uint32_t b = h[i] & mask;
      if (b >= blo && b < bhi) rows[cur[b]++] = (int32_t)i;
    }
  }
  return cap;
}

// Fused dense subject-relation remap (engine/flat.py _m_srel1):
// out[i] = 0 when srel1[i] == 0, else k2map[srel1[i] - 1] + 1 — one pass
// instead of the clip/gather/where numpy chain.  k2map values may be -1
// ("never matches"), which maps to 0 - ... callers rely on exact numpy
// semantics: np.where(srel1 == 0, 0, k2[clip(srel1-1, 0, None)] + 1).
void gi_msrel1(const int32_t* srel1, const int32_t* k2map, int64_t mapn,
               int64_t n, int32_t* out) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; i++) {
    const int32_t s = srel1[i];
    if (s == 0) {
      out[i] = 0;
    } else {
      int64_t j = (int64_t)s - 1;
      if (j < 0) j = 0;  // np.clip(srel1 - 1, 0, None)
      if (j >= mapn) j = mapn - 1;
      out[i] = k2map[j] + 1;
    }
  }
}

// FNV-1a over int32 words + murmur3 finalizer — bit-identical to
// engine/hash.py mix32 (the device recomputes the same mix, so host and
// device hashes must agree exactly).  cols is an array of ncols pointers
// to int32 columns, passed as int64 addresses.
void gi_mix32(const int64_t* cols, int64_t ncols, int64_t n, uint32_t* out) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; i++) {
    uint32_t h = 2166136261u;
    for (int64_t j = 0; j < ncols; j++) {
      const int32_t* c = reinterpret_cast<const int32_t*>(cols[j]);
      h = (h ^ (uint32_t)c[i]) * 16777619u;
    }
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    out[i] = h;
  }
}

// Parallel gathers: out[i] = src[idx[i]] (callers guarantee bounds).
void gi_take32(const int32_t* src, const int64_t* idx, int64_t n,
               int32_t* out) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; i++) out[i] = src[idx[i]];
}

void gi_take64(const int64_t* src, const int64_t* idx, int64_t n,
               int64_t* out) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; i++) out[i] = src[idx[i]];
}

// Fused gather + interleave: out[i*stride + j] = cols[j][idx ? idx[i] : i]
// for j < w — one row-major pass instead of w column-major numpy gathers
// (the interleaved row write is a single cache line; the gathers are the
// only random traffic).  cols are int32 column addresses as in gi_mix32;
// idx (int32 row permutation) may be null for identity.
void gi_interleave32(const int64_t* cols, int64_t w, const int32_t* idx,
                     int64_t n, int32_t* out, int64_t stride) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; i++) {
    const int64_t r = idx ? (int64_t)idx[i] : i;
    int32_t* o = out + i * stride;
    for (int64_t j = 0; j < w; j++)
      o[j] = reinterpret_cast<const int32_t*>(cols[j])[r];
  }
}

// Run boundaries of a sorted key column: writes the start index of every
// equal-key run into starts (capacity n) and returns the run count — the
// sorted-runs half of build_range_hash without the boolean-mask /
// nonzero materialization.  Two-phase parallel: per-chunk boundary
// counts, then an offset-aware fill.
static int64_t run_bounds_impl(const int64_t* k64, const int32_t* k32,
                               int64_t n, int64_t* starts) {
  if (n == 0) return 0;
  int T = 1;
#if defined(_OPENMP)
  T = omp_get_max_threads();
  if (T > 16) T = 16;
  if (T < 1) T = 1;
  if (n < (1 << 18)) T = 1;
#endif
  const int64_t chunk = (n + T - 1) / T;
  std::vector<int64_t> cnt((size_t)T, 0);
#if defined(_OPENMP)
#pragma omp parallel for num_threads(T) schedule(static, 1)
#endif
  for (int t = 0; t < T; t++) {
    const int64_t lo = (int64_t)t * chunk;
    const int64_t hi = std::min(n, lo + chunk);
    int64_t c = 0;
    for (int64_t i = lo; i < hi; i++) {
      if (i == 0) { c++; continue; }
      const bool b = k64 ? (k64[i] != k64[i - 1]) : (k32[i] != k32[i - 1]);
      c += b ? 1 : 0;
    }
    cnt[t] = c;
  }
  std::vector<int64_t> base((size_t)T + 1, 0);
  for (int t = 0; t < T; t++) base[t + 1] = base[t] + cnt[t];
#if defined(_OPENMP)
#pragma omp parallel for num_threads(T) schedule(static, 1)
#endif
  for (int t = 0; t < T; t++) {
    const int64_t lo = (int64_t)t * chunk;
    const int64_t hi = std::min(n, lo + chunk);
    int64_t at = base[t];
    for (int64_t i = lo; i < hi; i++) {
      const bool b =
          i == 0 || (k64 ? (k64[i] != k64[i - 1]) : (k32[i] != k32[i - 1]));
      if (b) starts[at++] = i;
    }
  }
  return base[T];
}

int64_t gi_run_bounds64(const int64_t* k, int64_t n, int64_t* starts) {
  return run_bounds_impl(k, nullptr, n, starts);
}

int64_t gi_run_bounds32(const int32_t* k, int64_t n, int64_t* starts) {
  return run_bounds_impl(nullptr, k, n, starts);
}

// Fused dense-radix key packing: out[i] = (int32)(a[i] * radix + b[i]) —
// the engine/flat.py _pack inner op without the int64 temporary pair.
void gi_pack32(const int32_t* a, const int32_t* b, int64_t radix, int64_t n,
               int32_t* out) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; i++)
    out[i] = (int32_t)((int64_t)a[i] * radix + (int64_t)b[i]);
}

}  // extern "C"

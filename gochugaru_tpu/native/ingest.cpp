// Native ingest layer: batch string interning + parallel lexicographic
// sort for columnar snapshot builds.
//
// Role in the framework: the reference (authzed/gochugaru) is a pure-Go
// client whose server does all heavy lifting; in this TPU-native redesign
// the host-side ingest — interning (type, object-id) strings to dense
// int32 node ids and sorting edge columns into the device's binary-search
// layout — is the bottleneck at 100M-1B edges (SURVEY.md §7 "interning
// throughput at 1B edges is the real bottleneck").  This is the runtime
// piece that earns native code: a C ABI (consumed via ctypes, no pybind11
// in the image) wrapping
//   * an open-addressing string interner with an append-only arena, and
//   * an OpenMP-parallel sort over packed 93-bit (rel,res,subj,srel1) keys.
//
// Thread-safety: the interner is single-writer (callers serialize mutating
// calls — the Python side holds its store lock); reads of immutable
// prefixes are safe.  Sorting is stateless.
//
// Build: g++ -O3 -shared -fPIC -fopenmp ingest.cpp -o libgochugaru_ingest.so

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#include <parallel/algorithm>
#endif

namespace {

inline uint64_t hash_bytes(const char* data, uint64_t len, uint64_t seed) {
  // FNV-1a, then a final mix (good enough for open addressing; inputs are
  // short object ids)
  uint64_t h = 1469598103934665603ull ^ (seed * 0x9e3779b97f4a7c15ull);
  for (uint64_t i = 0; i < len; i++) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 1099511628211ull;
  }
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 33;
  return h;
}

struct Entry {
  uint64_t hash;
  uint64_t off;
  uint32_t len;
  int32_t type;
};

struct Interner {
  std::vector<char> arena;
  std::vector<Entry> entries;   // index == node id
  std::vector<int64_t> table;   // open addressing; -1 empty, else node id
  uint64_t mask = 0;

  Interner() {
    table.assign(1 << 16, -1);
    mask = table.size() - 1;
    arena.reserve(1 << 20);
  }

  void grow() {
    std::vector<int64_t> bigger(table.size() * 2, -1);
    uint64_t m = bigger.size() - 1;
    for (int64_t node = 0; node < static_cast<int64_t>(entries.size()); node++) {
      uint64_t slot = entries[node].hash & m;
      while (bigger[slot] != -1) slot = (slot + 1) & m;
      bigger[slot] = node;
    }
    table.swap(bigger);
    mask = m;
  }

  inline bool equals(int64_t node, int32_t type, const char* s, uint32_t len,
                     uint64_t h) const {
    const Entry& e = entries[node];
    return e.hash == h && e.type == type && e.len == len &&
           std::memcmp(arena.data() + e.off, s, len) == 0;
  }

  int64_t find(int32_t type, const char* s, uint32_t len) const {
    uint64_t h = hash_bytes(s, len, static_cast<uint64_t>(type) + 1);
    uint64_t slot = h & mask;
    while (true) {
      int64_t node = table[slot];
      if (node == -1) return -1;
      if (equals(node, type, s, len, h)) return node;
      slot = (slot + 1) & mask;
    }
  }

  int64_t intern(int32_t type, const char* s, uint32_t len) {
    uint64_t h = hash_bytes(s, len, static_cast<uint64_t>(type) + 1);
    uint64_t slot = h & mask;
    while (true) {
      int64_t node = table[slot];
      if (node == -1) break;
      if (equals(node, type, s, len, h)) return node;
      slot = (slot + 1) & mask;
    }
    if ((entries.size() + 1) * 10 >= table.size() * 7) {  // 0.7 load factor
      grow();
      slot = h & mask;
      while (table[slot] != -1) slot = (slot + 1) & mask;
    }
    int64_t node = static_cast<int64_t>(entries.size());
    Entry e;
    e.hash = h;
    e.off = arena.size();
    e.len = len;
    e.type = type;
    arena.insert(arena.end(), s, s + len);
    entries.push_back(e);
    table[slot] = node;
    return node;
  }
};

}  // namespace

extern "C" {

void* gi_new() { return new Interner(); }

void gi_free(void* h) { delete static_cast<Interner*>(h); }

int64_t gi_size(void* h) {
  return static_cast<int64_t>(static_cast<Interner*>(h)->entries.size());
}

// Intern n strings: buf holds concatenated bytes, offsets has n+1 entries,
// type_ids has n entries.  Writes node ids to out.
void gi_intern_batch(void* h, const char* buf, const int64_t* offsets,
                     int64_t n, const int32_t* type_ids, int32_t* out) {
  Interner* in = static_cast<Interner*>(h);
  for (int64_t i = 0; i < n; i++) {
    out[i] = static_cast<int32_t>(in->intern(
        type_ids[i], buf + offsets[i],
        static_cast<uint32_t>(offsets[i + 1] - offsets[i])));
  }
}

// Lookup without interning; -1 when absent.
void gi_lookup_batch(void* h, const char* buf, const int64_t* offsets,
                     int64_t n, const int32_t* type_ids, int32_t* out) {
  Interner* in = static_cast<Interner*>(h);
  for (int64_t i = 0; i < n; i++) {
    out[i] = static_cast<int32_t>(in->find(
        type_ids[i], buf + offsets[i],
        static_cast<uint32_t>(offsets[i + 1] - offsets[i])));
  }
}

// Per-node type ids for nodes [0, n).
void gi_node_types(void* h, int32_t* out, int64_t n) {
  Interner* in = static_cast<Interner*>(h);
  for (int64_t i = 0; i < n && i < static_cast<int64_t>(in->entries.size()); i++)
    out[i] = in->entries[i].type;
}

// Key of one node: returns length, copies up to cap bytes into out_str and
// the type id into out_type.  Returns -1 for an invalid node.
int64_t gi_key(void* h, int64_t node, char* out_str, int64_t cap,
               int32_t* out_type) {
  Interner* in = static_cast<Interner*>(h);
  if (node < 0 || node >= static_cast<int64_t>(in->entries.size())) return -1;
  const Entry& e = in->entries[node];
  *out_type = e.type;
  int64_t n = e.len < cap ? e.len : cap;
  std::memcpy(out_str, in->arena.data() + e.off, n);
  return e.len;
}

// Batched keys: concatenated id bytes of n nodes into out_buf (cap bytes),
// with out_offsets (n+1 entries, offsets[0] = 0) and out_types (n).
// Returns the total byte length needed — when it exceeds cap, nothing is
// written beyond what fits and the caller must retry with a bigger buffer.
// Invalid nodes get length 0 and type -1.
int64_t gi_keys_batch(void* h, const int64_t* nodes, int64_t n,
                      char* out_buf, int64_t cap, int64_t* out_offsets,
                      int32_t* out_types) {
  Interner* in = static_cast<Interner*>(h);
  const int64_t sz = static_cast<int64_t>(in->entries.size());
  int64_t total = 0;
  out_offsets[0] = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t node = nodes[i];
    if (node < 0 || node >= sz) {
      out_types[i] = -1;
      out_offsets[i + 1] = total;
      continue;
    }
    const Entry& e = in->entries[node];
    out_types[i] = e.type;
    if (total + e.len <= cap) {
      std::memcpy(out_buf + total, in->arena.data() + e.off, e.len);
    }
    total += e.len;
    out_offsets[i + 1] = total;
  }
  return total;
}

// Parallel lexsort by (a, b, c, d) — the snapshot's primary order
// (rel, res, subj, srel1).  Writes the permutation into out (int64[n]).
// Keys are packed into (hi, lo) uint64 pairs: hi = a<<32 | b-as-unsigned,
// lo = c<<32 | d-as-unsigned; int32 values are biased by 2^31 so signed
// order (e.g. srel1 = 0 for direct subjects, payload -1 never occurs in
// sort keys) is preserved under unsigned comparison.
// LSD radix passes over 16-bit digits: stable by construction and
// data-independent O(n) — a comparison sort of random 10M packed keys
// costs ~7s on this one-core host, the radix ~1.5s.  Passes whose digit
// is uniform across all keys are skipped (common for high digits).
static void radix_u64(const uint64_t* key, int64_t* perm, int64_t n,
                      std::vector<int64_t>& tmp) {
  if (n <= 1) return;
  if ((int64_t)tmp.size() < n) tmp.resize(n);
  int64_t* cur = perm;
  int64_t* nxt = tmp.data();
  std::vector<int64_t> cnt(65537);
  for (int shift = 0; shift < 64; shift += 16) {
    std::fill(cnt.begin(), cnt.end(), 0);
    const uint16_t first = (uint16_t)(key[cur[0]] >> shift);
    bool uniform = true;
    for (int64_t i = 0; i < n; i++) {
      const uint16_t d = (uint16_t)(key[cur[i]] >> shift);
      cnt[(int64_t)d + 1]++;
      uniform &= (d == first);
    }
    if (uniform) continue;
    for (int64_t b = 1; b <= 65536; b++) cnt[b] += cnt[b - 1];
    for (int64_t i = 0; i < n; i++) {
      const uint16_t d = (uint16_t)(key[cur[i]] >> shift);
      nxt[cnt[d]++] = cur[i];
    }
    std::swap(cur, nxt);
  }
  if (cur != perm) std::copy(cur, cur + n, perm);
}

void gi_lexsort4(const int32_t* a, const int32_t* b, const int32_t* c,
                 const int32_t* d, int64_t n, int64_t* out) {
  std::vector<uint64_t> hi(n), lo(n);
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; i++) {
    // flip the sign bit so signed int32 order == unsigned order
    uint64_t au = static_cast<uint32_t>(a[i]) ^ 0x80000000u;
    uint64_t bu = static_cast<uint32_t>(b[i]) ^ 0x80000000u;
    uint64_t cu = static_cast<uint32_t>(c[i]) ^ 0x80000000u;
    uint64_t du = static_cast<uint32_t>(d[i]) ^ 0x80000000u;
    hi[i] = (au << 32) | bu;
    lo[i] = (cu << 32) | du;
    out[i] = i;
  }
  std::vector<int64_t> tmp;
  radix_u64(lo.data(), out, n, tmp);  // minor word first: LSD over 128b
  radix_u64(hi.data(), out, n, tmp);
}

// Stable argsort of a single int32 column (radix).
void gi_argsort1(const int32_t* a, int64_t n, int64_t* out) {
  std::vector<uint64_t> key(n);
  for (int64_t i = 0; i < n; i++) {
    key[i] = static_cast<uint32_t>(a[i]) ^ 0x80000000u;
    out[i] = i;
  }
  std::vector<int64_t> tmp;
  radix_u64(key.data(), out, n, tmp);
}

// Exact join of two (h, l)-lexsorted int64 pair sets: out[j] = FIRST
// table position matching query j, or -1.  One linear merge — no
// per-run bisection, no Python.  Both sides must be sorted ascending.
void gi_join_sorted2(const int64_t* th, const int64_t* tl, int64_t nt,
                     const int64_t* qh, const int64_t* ql, int64_t nq,
                     int64_t* out) {
  int64_t i = 0;
  for (int64_t j = 0; j < nq; j++) {
    while (i < nt && (th[i] < qh[j] || (th[i] == qh[j] && tl[i] < ql[j]))) {
      i++;
    }
    out[j] = (i < nt && th[i] == qh[j] && tl[i] == ql[j]) ? i : -1;
  }
}

// Parallel stable lexsort by (a, b) — used for the membership-propagation
// view order (subj, srel).
void gi_lexsort2(const int32_t* a, const int32_t* b, int64_t n, int64_t* out) {
  std::vector<uint64_t> key(n);
  for (int64_t i = 0; i < n; i++) {
    uint64_t au = static_cast<uint32_t>(a[i]) ^ 0x80000000u;
    uint64_t bu = static_cast<uint32_t>(b[i]) ^ 0x80000000u;
    key[i] = (au << 32) | bu;
    out[i] = i;
  }
  std::vector<int64_t> tmp;
  radix_u64(key.data(), out, n, tmp);
}

}  // extern "C"

"""Sorting front-ends over the native library, numpy fallback included.

``lexsort4`` is the snapshot primary order (rel, res, subj, srel1) — the
layout every device binary search assumes (store/snapshot.py).  At 100M
rows numpy's single-threaded lexsort is tens of seconds; the native
OpenMP sort over packed 64-bit key pairs is the difference between
"rebuild is interactive" and "rebuild is a coffee break" (SURVEY.md §7).
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import lib


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def lexsort4(a: np.ndarray, b: np.ndarray, c: np.ndarray, d: np.ndarray) -> np.ndarray:
    """Permutation sorting rows by (a, b, c, d), ints.  Equivalent to
    ``np.lexsort((d, c, b, a))``."""
    L = lib()
    n = a.shape[0]
    if L is None or n < (1 << 16):
        return np.lexsort((d, c, b, a))
    a32 = np.ascontiguousarray(a, np.int32)
    b32 = np.ascontiguousarray(b, np.int32)
    c32 = np.ascontiguousarray(c, np.int32)
    d32 = np.ascontiguousarray(d, np.int32)
    out = np.empty(n, np.int64)
    L.gi_lexsort4(
        _i32ptr(a32), _i32ptr(b32), _i32ptr(c32), _i32ptr(d32),
        ctypes.c_int64(n), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def lexsort2(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Stable permutation by (a, b) — ``np.lexsort((b, a))``."""
    L = lib()
    n = a.shape[0]
    if L is None or n < (1 << 16):
        return np.lexsort((b, a))
    a32 = np.ascontiguousarray(a, np.int32)
    b32 = np.ascontiguousarray(b, np.int32)
    out = np.empty(n, np.int64)
    L.gi_lexsort2(
        _i32ptr(a32), _i32ptr(b32), ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def argsort1(a: np.ndarray) -> np.ndarray:
    """Stable argsort of one int column — ``np.argsort(a, kind='stable')``."""
    L = lib()
    n = a.shape[0]
    if L is None or n < (1 << 16):
        return np.argsort(a, kind="stable")
    a32 = np.ascontiguousarray(a, np.int32)
    out = np.empty(n, np.int64)
    L.gi_argsort1(
        _i32ptr(a32), ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def sortperm_words(words, fallback_cols) -> np.ndarray:
    """Stable permutation sorting rows by up to three uint64 words
    (``words[0]`` major).  The caller packs its key columns into words
    with any order-preserving encoding (non-negative int64 reinterpret
    directly; int32 pairs pack as ``hi<<32 | lo`` after biasing).
    ``fallback_cols`` is the np.lexsort key tuple (minor first) producing
    the identical permutation when the native library is unavailable."""
    L = lib()
    n = int(words[0].shape[0])
    if L is None or n < (1 << 16):
        return np.lexsort(fallback_cols)
    def as_u64(w):
        if w.dtype == np.int64 and w.flags.c_contiguous:
            return w.view(np.uint64)  # non-negative by contract: free
        return np.ascontiguousarray(w, np.uint64)

    ws = [as_u64(w) for w in words[:3]]
    out = np.empty(n, np.int64)
    pu = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    ptrs = [pu(w) for w in ws] + [None] * (3 - len(ws))
    L.gi_sortperm3(
        ptrs[0], ptrs[1], ptrs[2], ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def sorted_runs(k: np.ndarray) -> np.ndarray:
    """Start indices of the equal-key runs of a SORTED key column — the
    group-by/offset primitive of build_range_hash and the fold dedups.
    One parallel native pass; the numpy fallback materializes the usual
    boolean first-mask."""
    n = int(k.shape[0])
    L = lib()
    if L is None or n < (1 << 16):
        if n == 0:
            return np.zeros(0, np.int64)
        first = np.ones(n, bool)
        first[1:] = k[1:] != k[:-1]
        return np.nonzero(first)[0]
    starts = np.empty(n, np.int64)
    p64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    if k.dtype == np.int32:
        kk = np.ascontiguousarray(k, np.int32)
        G = L.gi_run_bounds32(_i32ptr(kk), ctypes.c_int64(n), p64(starts))
    else:
        kk = np.ascontiguousarray(k, np.int64)
        G = L.gi_run_bounds64(p64(kk), ctypes.c_int64(n), p64(starts))
    return starts[:G]


def take32(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Parallel ``src[idx]`` for an int32 source and int64 index — the
    permutation-apply of the snapshot/fold builds."""
    L = lib()
    n = int(idx.shape[0])
    if L is None or n < (1 << 16):
        return np.ascontiguousarray(src, np.int32)[idx]
    s = np.ascontiguousarray(src, np.int32)
    ii = np.ascontiguousarray(idx, np.int64)
    out = np.empty(n, np.int32)
    L.gi_take32(
        _i32ptr(s), ii.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n), _i32ptr(out),
    )
    return out


def take64(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Parallel ``src[idx]`` for an int64 source and int64 index."""
    L = lib()
    n = int(idx.shape[0])
    if L is None or n < (1 << 16):
        return np.ascontiguousarray(src, np.int64)[idx]
    s = np.ascontiguousarray(src, np.int64)
    ii = np.ascontiguousarray(idx, np.int64)
    out = np.empty(n, np.int64)
    p64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    L.gi_take64(p64(s), p64(ii), ctypes.c_int64(n), p64(out))
    return out


def fill_interleaved(
    out: np.ndarray, cols, rows: "np.ndarray | None"
) -> bool:
    """Fill ``out[i, j] = cols[j][rows[i]]`` (identity when ``rows`` is
    None) for the first ``len(cols[0])`` rows of a C-contiguous int32
    [n_pad, w] matrix — the gather+transpose of interleave_buckets /
    interleave_rows in one parallel row-major pass.  Returns False when
    the native library is unavailable (caller falls back)."""
    L = lib()
    n = int(cols[0].shape[0]) if cols else 0
    if L is None or n < (1 << 16):
        return False
    # the native pass writes n rows through raw pointers: a mismatched
    # permutation or an undersized output must fail loudly here, not
    # corrupt the heap
    if rows is not None and int(rows.shape[0]) != n:
        raise ValueError(
            f"fill_interleaved: rows has {rows.shape[0]} entries, "
            f"columns have {n}"
        )
    if out.shape[0] < n or out.shape[1] < len(cols):
        raise ValueError(
            f"fill_interleaved: out {out.shape} too small for "
            f"{n}x{len(cols)}"
        )
    cc = [np.ascontiguousarray(c, np.int32) for c in cols]
    ptrs = np.array([c.ctypes.data for c in cc], np.int64)
    rr = None
    if rows is not None:
        rr = np.ascontiguousarray(rows, np.int32)
    L.gi_interleave32(
        ptrs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(len(cc)),
        _i32ptr(rr) if rr is not None else None,
        ctypes.c_int64(n), _i32ptr(out), ctypes.c_int64(out.shape[1]),
    )
    return True


def hash_index32(h_full: np.ndarray, size: int):
    """Stable bucket-grouped rows + offsets for 32-bit hashes masked to
    ``size`` buckets: (rows int32[n], off int32[size+1], cap) — or None
    when the native library is unavailable (build_hash falls back to the
    mask/bincount/argsort/cumsum chain)."""
    L = lib()
    n = int(h_full.shape[0])
    if L is None or n < (1 << 16):
        return None
    h = np.ascontiguousarray(h_full, np.uint32)
    rows = np.empty(n, np.int32)
    off = np.empty(size + 1, np.int32)
    cap = L.gi_hash_index32(
        h.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        ctypes.c_int64(n), ctypes.c_int64(size), _i32ptr(rows), _i32ptr(off),
    )
    return rows, off, int(cap)


def mix32_native(cols) -> "np.ndarray | None":
    """Native parallel mix32 over int32 columns (bit-identical to
    engine/hash.py mix32), or None when unavailable."""
    L = lib()
    n = int(cols[0].shape[0]) if cols else 0
    if L is None or n < (1 << 16):
        return None
    cc = [np.ascontiguousarray(c, np.int32) for c in cols]
    ptrs = np.array([c.ctypes.data for c in cc], np.int64)
    out = np.empty(n, np.uint32)
    L.gi_mix32(
        ptrs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(len(cc)), ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out


def pack32(a: np.ndarray, b: np.ndarray, radix: int) -> np.ndarray:
    """Parallel ``(a * radix + b).astype(int32)`` without the int64
    temporaries — engine/flat.py's dense key packing."""
    L = lib()
    n = int(a.shape[0])
    if L is None or n < (1 << 16):
        return (a.astype(np.int64) * radix + b).astype(np.int32)
    aa = np.ascontiguousarray(a, np.int32)
    bb = np.ascontiguousarray(b, np.int32)
    out = np.empty(n, np.int32)
    L.gi_pack32(
        _i32ptr(aa), _i32ptr(bb), ctypes.c_int64(radix), ctypes.c_int64(n),
        _i32ptr(out),
    )
    return out


def join_sorted2(
    th: np.ndarray, tl: np.ndarray, qh: np.ndarray, ql: np.ndarray
) -> np.ndarray:
    """Exact join of (h, l)-lexsorted int64 pair sets: first table
    position per query, -1 on miss.  One native linear merge; the numpy
    fallback is the two-level grouped search (store/delta.py)."""
    L = lib()
    nq = qh.shape[0]
    if L is None or nq < (1 << 12):
        from ..store.delta import find_in_view

        return find_in_view(th, tl, qh, ql)
    th = np.ascontiguousarray(th, np.int64)
    tl = np.ascontiguousarray(tl, np.int64)
    qh = np.ascontiguousarray(qh, np.int64)
    ql = np.ascontiguousarray(ql, np.int64)
    out = np.empty(nq, np.int64)
    p64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    L.gi_join_sorted2(
        p64(th), p64(tl), ctypes.c_int64(th.shape[0]),
        p64(qh), p64(ql), ctypes.c_int64(nq), p64(out),
    )
    return out

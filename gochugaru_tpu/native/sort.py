"""Sorting front-ends over the native library, numpy fallback included.

``lexsort4`` is the snapshot primary order (rel, res, subj, srel1) — the
layout every device binary search assumes (store/snapshot.py).  At 100M
rows numpy's single-threaded lexsort is tens of seconds; the native
OpenMP sort over packed 64-bit key pairs is the difference between
"rebuild is interactive" and "rebuild is a coffee break" (SURVEY.md §7).
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import lib


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def lexsort4(a: np.ndarray, b: np.ndarray, c: np.ndarray, d: np.ndarray) -> np.ndarray:
    """Permutation sorting rows by (a, b, c, d), ints.  Equivalent to
    ``np.lexsort((d, c, b, a))``."""
    L = lib()
    n = a.shape[0]
    if L is None or n < (1 << 16):
        return np.lexsort((d, c, b, a))
    a32 = np.ascontiguousarray(a, np.int32)
    b32 = np.ascontiguousarray(b, np.int32)
    c32 = np.ascontiguousarray(c, np.int32)
    d32 = np.ascontiguousarray(d, np.int32)
    out = np.empty(n, np.int64)
    L.gi_lexsort4(
        _i32ptr(a32), _i32ptr(b32), _i32ptr(c32), _i32ptr(d32),
        ctypes.c_int64(n), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def lexsort2(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Stable permutation by (a, b) — ``np.lexsort((b, a))``."""
    L = lib()
    n = a.shape[0]
    if L is None or n < (1 << 16):
        return np.lexsort((b, a))
    a32 = np.ascontiguousarray(a, np.int32)
    b32 = np.ascontiguousarray(b, np.int32)
    out = np.empty(n, np.int64)
    L.gi_lexsort2(
        _i32ptr(a32), _i32ptr(b32), ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def argsort1(a: np.ndarray) -> np.ndarray:
    """Stable argsort of one int column — ``np.argsort(a, kind='stable')``."""
    L = lib()
    n = a.shape[0]
    if L is None or n < (1 << 16):
        return np.argsort(a, kind="stable")
    a32 = np.ascontiguousarray(a, np.int32)
    out = np.empty(n, np.int64)
    L.gi_argsort1(
        _i32ptr(a32), ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def join_sorted2(
    th: np.ndarray, tl: np.ndarray, qh: np.ndarray, ql: np.ndarray
) -> np.ndarray:
    """Exact join of (h, l)-lexsorted int64 pair sets: first table
    position per query, -1 on miss.  One native linear merge; the numpy
    fallback is the two-level grouped search (store/delta.py)."""
    L = lib()
    nq = qh.shape[0]
    if L is None or nq < (1 << 12):
        from ..store.delta import find_in_view

        return find_in_view(th, tl, qh, ql)
    th = np.ascontiguousarray(th, np.int64)
    tl = np.ascontiguousarray(tl, np.int64)
    qh = np.ascontiguousarray(qh, np.int64)
    ql = np.ascontiguousarray(ql, np.int64)
    out = np.empty(nq, np.int64)
    p64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    L.gi_join_sorted2(
        p64(th), p64(tl), ctypes.c_int64(th.shape[0]),
        p64(qh), p64(ql), ctypes.c_int64(nq), p64(out),
    )
    return out

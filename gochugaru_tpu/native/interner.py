"""Native-backed string interner with the same surface as
``store.interner.Interner`` plus columnar batch entry points.

(type, object_id) pairs map to dense append-only int32 node ids — the
property that lets Watch-driven re-indexing patch device buffers instead
of rebuilding them (BASELINE config 5).  The hash table and string arena
live in C++ (native/ingest.cpp); this wrapper adds the type-name table
(Python: a handful of entries), thread-safety, and numpy-friendly batch
interning for the bulk Import path (client/client.go:438-465 is the
reference's equivalent ingestion surface).
"""

from __future__ import annotations

import ctypes
import threading
from typing import List, Sequence, Tuple

import numpy as np

from . import available, lib


class NativeInterner:
    """Drop-in for store.interner.Interner, backed by the C++ arena."""

    def __init__(self) -> None:
        self._lib = lib()
        if self._lib is None:
            raise RuntimeError("native ingest library unavailable")
        self._h = ctypes.c_void_p(self._lib.gi_new())
        self._lock = threading.Lock()
        self._types = {}
        self._type_names: List[str] = []

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        try:
            if getattr(self, "_h", None) and self._lib is not None:
                self._lib.gi_free(self._h)
                self._h = None
        except Exception:
            pass

    # -- types (tiny; kept in Python) -----------------------------------
    def type_id(self, type_name: str) -> int:
        with self._lock:
            return self._type_id_locked(type_name)

    def _type_id_locked(self, type_name: str) -> int:
        tid = self._types.get(type_name)
        if tid is None:
            tid = len(self._type_names)
            self._types[type_name] = tid
            self._type_names.append(type_name)
        return tid

    def type_name(self, tid: int) -> str:
        return self._type_names[tid]

    def type_lookup(self, type_name: str) -> int:
        with self._lock:
            return self._types.get(type_name, -1)

    # -- batch plumbing --------------------------------------------------
    @staticmethod
    def _pack(ids: Sequence[str]) -> Tuple[bytes, np.ndarray]:
        # fast path: ONE join + ONE encode; when the result is pure
        # ASCII, character lengths equal byte lengths so the offsets
        # come from map(len) without per-string encodes (2M-id batches:
        # ~1.3s → ~0.3s).  Any non-ASCII id falls back to the exact
        # per-string form
        joined = "".join(ids)
        buf = joined.encode("utf-8")
        if len(buf) == len(joined):
            offsets = np.zeros(len(ids) + 1, np.int64)
            np.cumsum(np.fromiter(map(len, ids), np.int64, len(ids)),
                      out=offsets[1:])
            return buf, offsets
        bufs = [s.encode("utf-8") for s in ids]
        offsets = np.zeros(len(bufs) + 1, np.int64)
        np.cumsum([len(b) for b in bufs], out=offsets[1:])
        return b"".join(bufs), offsets

    def _batch(self, fn, type_ids: np.ndarray, ids: Sequence[str]) -> np.ndarray:
        buf, offsets = self._pack(ids)
        out = np.empty(len(ids), np.int32)
        fn(
            self._h, buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(len(ids)),
            np.ascontiguousarray(type_ids, np.int32).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)
            ),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out

    # -- single-item surface (Interner parity) ---------------------------
    def node(self, type_name: str, object_id: str) -> int:
        with self._lock:
            tid = self._type_id_locked(type_name)
            return int(
                self._batch(self._lib.gi_intern_batch, np.array([tid]), [object_id])[0]
            )

    def lookup(self, type_name: str, object_id: str) -> int:
        with self._lock:
            tid = self._types.get(type_name)
            if tid is None:
                return -1
            return int(
                self._batch(self._lib.gi_lookup_batch, np.array([tid]), [object_id])[0]
            )

    def key_of(self, node: int) -> Tuple[str, str]:
        out_type = ctypes.c_int32(0)
        cap = 256
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.gi_key(
                self._h, ctypes.c_int64(node), buf, ctypes.c_int64(cap),
                ctypes.byref(out_type),
            )
            if n < 0:
                raise IndexError(f"unknown node {node}")
            if n <= cap:
                return self._type_names[out_type.value], buf.raw[:n].decode("utf-8")
            cap = int(n)

    def __len__(self) -> int:
        return int(self._lib.gi_size(self._h))

    def _keys_raw(self, nodes):
        """The shared native fetch behind both key-decode paths:
        (node array, raw id bytes, byte offsets list, type-id list).
        Under the lock: concurrent interning may reallocate the C++
        entry/arena vectors mid-copy (the Python Interner's lock-free
        read contract does not transfer to std::vector)."""
        nn = np.ascontiguousarray(nodes, np.int64)
        n = int(nn.shape[0])
        if n == 0:
            return nn, b"", [0], []
        offs = np.empty(n + 1, np.int64)
        types = np.empty(n, np.int32)
        cap = max(32 * n, 4096)
        with self._lock:
            while True:
                buf = ctypes.create_string_buffer(cap)
                total = int(self._lib.gi_keys_batch(
                    self._h,
                    nn.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    ctypes.c_int64(n), buf, ctypes.c_int64(cap),
                    offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    types.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ))
                if total <= cap:
                    break
                cap = total
        return nn, buf.raw, offs.tolist(), types.tolist()

    def keys_batch(self, nodes) -> List[Tuple[str, str]]:
        """(type, id) pairs for an int array of nodes in ONE native call
        (plus a retry when the id bytes outgrow the buffer guess) — the
        batched decode path behind snapshot exports."""
        nn, raw, o, tl = self._keys_raw(nodes)
        tn = self._type_names
        out = []
        for i in range(len(tl)):
            t = tl[i]
            if t < 0:  # C++ invalid-node sentinel — match key_of's raise
                raise IndexError(f"unknown node {int(nn[i])}")
            out.append((tn[t], raw[o[i] : o[i + 1]].decode("utf-8")))
        return out

    def keys_columns(self, nodes) -> Tuple[List[str], List[str]]:
        """(type_names, ids) as two parallel LISTS — the columnar decode
        path (snapshot exports): one whole-buffer utf-8 decode plus
        C-speed str slicing when the ids are ASCII, instead of a per-row
        bytes slice + decode + tuple."""
        nn, raw, o, tl = self._keys_raw(nodes)
        n = len(tl)
        if n == 0:
            return [], []
        if min(tl) < 0:
            # any negative type id is the invalid-node sentinel (mirror
            # keys_batch's t < 0 tolerance, not an exact -1 match)
            bad = next(i for i, t in enumerate(tl) if t < 0)
            raise IndexError(f"unknown node {int(nn[bad])}")
        text = raw[: o[n]].decode("utf-8")
        if len(text) == o[n]:  # pure ASCII: byte offsets == char offsets
            ids = [text[o[i] : o[i + 1]] for i in range(n)]
        else:
            ids = [raw[o[i] : o[i + 1]].decode("utf-8") for i in range(n)]
        tn = self._type_names
        return [tn[t] for t in tl], ids

    @property
    def num_types(self) -> int:
        return len(self._type_names)

    def node_type_array(self) -> np.ndarray:
        with self._lock:
            n = len(self)
            out = np.empty(max(n, 0), np.int32)
            if n:
                self._lib.gi_node_types(
                    self._h,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    ctypes.c_int64(n),
                )
            return out

    def node_type_tail(self, start: int) -> np.ndarray:
        """Type ids of nodes interned at or after ``start`` (see
        store/interner.py).  The C fill is one flat memcpy, so slicing
        it keeps no Python-loop constant."""
        return self.node_type_array()[start:]

    # -- columnar bulk entry points --------------------------------------
    def node_batch(self, type_name: str, ids: Sequence[str]) -> np.ndarray:
        """Intern many ids of one type; returns int32 node ids."""
        with self._lock:
            tid = self._type_id_locked(type_name)
            return self._batch(
                self._lib.gi_intern_batch,
                np.full(len(ids), tid, np.int32), ids,
            )

    def node_batch_typed(
        self, type_ids: np.ndarray, ids: Sequence[str]
    ) -> np.ndarray:
        """Intern many (interner-type-id, id) pairs at once."""
        with self._lock:
            return self._batch(self._lib.gi_intern_batch, type_ids, ids)

    def lookup_batch(self, type_name: str, ids: Sequence[str]) -> np.ndarray:
        with self._lock:
            tid = self._types.get(type_name)
            if tid is None:
                return np.full(len(ids), -1, np.int32)
            return self._batch(
                self._lib.gi_lookup_batch,
                np.full(len(ids), tid, np.int32), ids,
            )


def make_interner():
    """The framework's default interner: native when the C++ layer loads,
    pure-Python otherwise (identical semantics either way)."""
    if available():
        return NativeInterner()
    from ..store.interner import Interner

    return Interner()

"""Native runtime layer (C++ via ctypes).

The reference delegates all heavy lifting to a server; here the host-side
ingest pipeline is part of the framework, and its hot paths — bulk string
interning and the primary-order lexsort feeding the device's binary-search
layout — are implemented in C++ (``ingest.cpp``) and loaded through a C
ABI.  Everything degrades gracefully: if the shared library can't be
built/loaded (no compiler, exotic platform), ``available()`` is False and
callers fall back to the pure-numpy/python paths with identical results.

The library is compiled on first use with g++ (the image has no pybind11;
ctypes needs only a .so), cached next to this file, and rebuilt whenever
the cached binary was not built from the current ``ingest.cpp`` — the
source hash is stored in a sidecar stamp file, so a stale or foreign
binary is never silently loaded (mtimes are useless for this: a fresh
checkout gives source and binary the same timestamp).  The binary itself
is never committed to version control.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ingest.cpp")
_SO = os.path.join(_HERE, "libgochugaru_ingest.so")
_STAMP = _SO + ".srchash"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _src_hash() -> Optional[str]:
    try:
        with open(_SRC, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


def _build(src_hash: str) -> bool:
    cmds = [
        ["g++", "-O3", "-shared", "-fPIC", "-fopenmp", "-std=c++17",
         _SRC, "-o", _SO],
        # no-OpenMP fallback (serial sort)
        ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
    ]
    for cmd in cmds:
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
            if r.returncode == 0:
                with open(_STAMP, "w") as f:
                    f.write(src_hash)
                return True
        except (OSError, subprocess.TimeoutExpired):
            return False
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            want = _src_hash()
            if want is None:
                return None
            have = None
            if os.path.exists(_SO) and os.path.exists(_STAMP):
                try:
                    with open(_STAMP) as f:
                        have = f.read().strip()
                except OSError:
                    have = None
            if have != want and not _build(want):
                return None
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        c = ctypes
        lib.gi_new.restype = c.c_void_p
        lib.gi_free.argtypes = [c.c_void_p]
        lib.gi_size.argtypes = [c.c_void_p]
        lib.gi_size.restype = c.c_int64
        lib.gi_intern_batch.argtypes = [
            c.c_void_p, c.c_char_p, c.POINTER(c.c_int64), c.c_int64,
            c.POINTER(c.c_int32), c.POINTER(c.c_int32),
        ]
        lib.gi_lookup_batch.argtypes = lib.gi_intern_batch.argtypes
        lib.gi_node_types.argtypes = [c.c_void_p, c.POINTER(c.c_int32), c.c_int64]
        lib.gi_key.argtypes = [
            c.c_void_p, c.c_int64, c.c_char_p, c.c_int64, c.POINTER(c.c_int32),
        ]
        lib.gi_key.restype = c.c_int64
        lib.gi_keys_batch.argtypes = [
            c.c_void_p, c.POINTER(c.c_int64), c.c_int64, c.c_char_p,
            c.c_int64, c.POINTER(c.c_int64), c.POINTER(c.c_int32),
        ]
        lib.gi_keys_batch.restype = c.c_int64
        for name in ("gi_lexsort4",):
            fn = getattr(lib, name)
            fn.argtypes = [
                c.POINTER(c.c_int32), c.POINTER(c.c_int32),
                c.POINTER(c.c_int32), c.POINTER(c.c_int32),
                c.c_int64, c.POINTER(c.c_int64),
            ]
        lib.gi_lexsort2.argtypes = [
            c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.gi_argsort1.argtypes = [
            c.POINTER(c.c_int32), c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.gi_join_sorted2.argtypes = [
            c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_int64,
            c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_int64,
            c.POINTER(c.c_int64),
        ]
        lib.gi_sortperm3.argtypes = [
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint64), c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.gi_hash_index32.argtypes = [
            c.POINTER(c.c_uint32), c.c_int64, c.c_int64,
            c.POINTER(c.c_int32), c.POINTER(c.c_int32),
        ]
        lib.gi_hash_index32.restype = c.c_int64
        lib.gi_mix32.argtypes = [
            c.POINTER(c.c_int64), c.c_int64, c.c_int64, c.POINTER(c.c_uint32),
        ]
        lib.gi_take32.argtypes = [
            c.POINTER(c.c_int32), c.POINTER(c.c_int64), c.c_int64,
            c.POINTER(c.c_int32),
        ]
        lib.gi_take64.argtypes = [
            c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_int64,
            c.POINTER(c.c_int64),
        ]
        lib.gi_interleave32.argtypes = [
            c.POINTER(c.c_int64), c.c_int64, c.POINTER(c.c_int32), c.c_int64,
            c.POINTER(c.c_int32), c.c_int64,
        ]
        lib.gi_run_bounds64.argtypes = [
            c.POINTER(c.c_int64), c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.gi_run_bounds64.restype = c.c_int64
        lib.gi_run_bounds32.argtypes = [
            c.POINTER(c.c_int32), c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.gi_run_bounds32.restype = c.c_int64
        lib.gi_pack32.argtypes = [
            c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.c_int64, c.c_int64,
            c.POINTER(c.c_int32),
        ]
        lib.gi_msrel1.argtypes = [
            c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.c_int64, c.c_int64,
            c.POINTER(c.c_int32),
        ]
        _lib = lib
        return _lib


#: test hook + escape hatch: GOCHUGARU_NATIVE=0 (or set_enabled(False))
#: forces every native-accelerated path onto its pure-numpy fallback —
#: tests/test_prepare_parity.py builds both ways and asserts bitwise
#: equality of every produced table.
_forced_off = os.environ.get("GOCHUGARU_NATIVE", "").strip() == "0"


def set_enabled(on: bool) -> None:
    global _forced_off
    _forced_off = not on


def enabled() -> bool:
    """Whether the native layer is currently allowed (it may still be
    unavailable if the library failed to build)."""
    return not _forced_off


def available() -> bool:
    return lib() is not None


def lib() -> Optional[ctypes.CDLL]:
    if _forced_off:
        return None
    return _load()

"""Exponential-backoff retry for retriable errors.

Mirrors the reference's envelope exactly: initial 50 ms, max interval 2 s,
multiplier 1.5, randomization factor 0.5 (client/client.go:205-210 with
cenkalti/backoff defaults), bounded by the context deadline.

Cancellation-honesty contract (tests/test_retry.py):
- the default backoff pause is the *context-aware* ``ctx.wait``, so a
  cancellation arriving mid-backoff interrupts the pause instead of
  waiting it out;
- ``ctx.err()`` is re-checked immediately after every pause, so a
  cancellation or deadline that landed during the backoff surfaces
  before the next ``fn()`` attempt, never after it;
- a deadline clamp that produces ``pause == 0`` skips the sleep call
  entirely (an injected fake sleep must not observe zero-length pauses).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

from . import metrics as _metrics
from . import perf as _perf
from . import trace as _trace
from .context import Context
from .errors import DeadlineExceededError, PermanentError, is_retriable

T = TypeVar("T")

INITIAL_INTERVAL = 0.050
MAX_INTERVAL = 2.0
MULTIPLIER = 1.5  # backoff.DefaultMultiplier
RANDOMIZATION_FACTOR = 0.5  # backoff.DefaultRandomizationFactor


def retry_retriable_errors(
    ctx: Context,
    fn: Callable[[], T],
    *,
    sleep: Optional[Callable[[float], None]] = None,
    max_tries: Optional[int] = None,
) -> T:
    """Run ``fn`` until it succeeds or fails permanently
    (client/client.go:193-211).  ``max_tries`` is an escape hatch for tests
    and deadline-less engine paths; the reference bounds retries only by
    the context.  ``sleep`` overrides the backoff pause (tests inject a
    fake); the default pause is ``ctx.wait`` so cancellation interrupts
    the backoff."""
    interval = INITIAL_INTERVAL
    tries = 0
    # the request's trace span rides the context (utils/trace.py); the
    # disabled path is one branch returning the NOOP singleton
    span = _trace.span_of(ctx)
    while True:
        err = ctx.err()
        if err is not None:
            raise err
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classify every error
            tries += 1
            if isinstance(e, PermanentError) and e.__cause__ is not None:
                raise e.__cause__
            if not is_retriable(e):
                raise
            if max_tries is not None and tries >= max_tries:
                raise
            dl = ctx.deadline()
            if dl is not None and time.monotonic() >= dl:
                raise DeadlineExceededError("context deadline exceeded") from e
            delta = RANDOMIZATION_FACTOR * interval
            pause = random.uniform(interval - delta, interval + delta)
            if dl is not None:
                # Never sleep past the deadline (backoff.WithContext behavior).
                pause = min(pause, max(dl - time.monotonic(), 0.0))
            _metrics.default.inc("retry.retries")
            span.event(
                "retry",
                error=type(e).__name__, attempt=tries,
                pause_s=round(pause, 6),
            )
            if pause > 0.0:
                _tp0 = time.perf_counter()
                if sleep is not None:
                    sleep(pause)
                else:
                    # context-aware pause: returns early on cancellation
                    ctx.wait(pause)
                # wall-time ledger: backoff pauses are attributed (a
                # chaos window's retry time must not read as idle); one
                # branch when no measurement window is armed
                _perf.report_wall("backoff", _tp0, time.perf_counter())
            # re-check immediately after the pause: a cancellation or
            # deadline that landed during the backoff must surface before
            # the next fn() attempt
            err = ctx.err()
            if err is not None:
                raise err
            interval = min(interval * MULTIPLIER, MAX_INTERVAL)

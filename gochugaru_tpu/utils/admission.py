"""Admission control for the dispatch path: bounded in-flight gate,
deadline-budget shedding, and a circuit breaker for the latency path.

The north star is a serving system, and a serving system's failure mode
under overload must be *load shedding*, not queue growth: a dispatch
gate that refuses work with ``ShedError`` (an ``UnavailableError``
subclass) converts overload into client-side exponential backoff through
the existing retry envelope — the same contract a gRPC server states by
returning ``codes.Unavailable``.  Samyama's unified in-database design
(PAPERS.md) leans on exactly this to keep hardware-accelerated paths
honest under overload; Graphulo benchmarks the degraded mode explicitly.

Three mechanisms, composed by the client (client.py ``check``):

- **DispatchGate** — a bounded in-flight counter.  ``admit()`` raises
  ``ShedError`` when ``max_inflight`` dispatches are already in the
  engine; no queueing, no blocking.  Counter: ``admission.sheds``.
- **Deadline budget** — ``check_deadline`` sheds a dispatch whose
  context deadline cannot cover the expected dispatch cost (client-local
  EWMA of recent dispatch times, floored by ``deadline_floor_s``): a
  check that would blow its deadline is rejected before H2D, not after
  the kernel has burned the budget.  Counter:
  ``admission.deadline_sheds``.
- **CircuitBreaker** — trips OPEN after ``breaker_threshold``
  *consecutive* transient dispatch failures; while open, latency-mode
  traffic routes back to the batch path (the latency path's pinned
  kernels and staging buffers are the most state-coupled dispatch
  surface, so it is first to lose trust).  After ``breaker_cooldown_s``
  the breaker HALF-OPENs and admits probes; one success closes it, one
  failure re-trips.  Counters: ``breaker.trips``, ``breaker.half_opens``,
  ``breaker.closes``; gauge ``breaker.state`` (0/1/2 =
  closed/half-open/open).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Optional

from . import metrics as _metrics
from . import trace as _trace
from .context import Context
from .errors import DeadlineExceededError, ShedError

#: breaker states (also the ``breaker.state`` gauge values)
CLOSED, HALF_OPEN, OPEN = 0, 1, 2

#: EWMA weight of the newest dispatch-cost sample
_EWMA_ALPHA = 0.2


@dataclass(frozen=True)
class AdmissionConfig:
    """Tuning for the client's admission controller."""

    #: concurrent dispatches admitted before shedding (0 disables the gate)
    max_inflight: int = 64
    #: consecutive transient dispatch failures that trip the breaker
    #: (0 disables the breaker)
    breaker_threshold: int = 5
    #: seconds OPEN before the breaker half-opens a probe
    breaker_cooldown_s: float = 0.25
    #: floor on the expected-dispatch-cost estimate used for deadline
    #: shedding; 0.0 means "shed only on observed history" (a fresh
    #: client never deadline-sheds until it has its own samples)
    deadline_floor_s: float = 0.0
    #: False disables deadline-budget shedding entirely (requests whose
    #: deadline already passed still fail in the retry envelope itself)
    deadline_shed: bool = True


class DispatchGate:
    """Bounded in-flight dispatch counter.  Shed-don't-queue: a full gate
    raises immediately so the caller's retry envelope backs off instead
    of this layer buffering unboundedly."""

    def __init__(
        self, max_inflight: int, registry: Optional[_metrics.Metrics] = None
    ) -> None:
        self.max_inflight = max_inflight
        self._m = registry or _metrics.default
        self._lock = threading.Lock()
        self._inflight = 0

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @contextmanager
    def admit(self, span=_trace.NOOP):
        if self.max_inflight > 0:
            with self._lock:
                if self._inflight >= self.max_inflight:
                    self._m.inc("admission.sheds")
                    span.event(
                        "admission.shed",
                        error="ShedError", inflight=self._inflight,
                    )
                    span.set_attr("shed_error", "ShedError")
                    raise ShedError(
                        f"dispatch admission: {self._inflight} in-flight"
                        f" >= max_inflight {self.max_inflight}"
                    )
                self._inflight += 1
                self._m.set_gauge("admission.inflight", self._inflight)
                span.event("admission.admit", inflight=self._inflight)
        else:
            span.event("admission.admit", inflight=-1)
        try:
            yield
        finally:
            if self.max_inflight > 0:
                with self._lock:
                    self._inflight -= 1
                    self._m.set_gauge("admission.inflight", self._inflight)


class CircuitBreaker:
    """Consecutive-transient-failure breaker gating the latency path.

    ``allow_latency()`` answers "may this dispatch use the latency-mode
    path right now"; ``record_success``/``record_failure`` feed it from
    dispatch outcomes.  ``clock`` is injectable so tests drive the
    cooldown deterministically."""

    def __init__(
        self,
        threshold: int,
        cooldown_s: float,
        registry: Optional[_metrics.Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._m = registry or _metrics.default
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._m.set_gauge("breaker.state", CLOSED)

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def allow_latency(self) -> bool:
        if self.threshold <= 0:
            return True
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._state = HALF_OPEN
                    self._m.inc("breaker.half_opens")
                    self._m.set_gauge("breaker.state", HALF_OPEN)
                    return True  # this dispatch is the probe
                return False
            return True  # HALF_OPEN: probes flow until an outcome lands

    def record_success(self, probe: bool = False) -> None:
        """Feed one successful dispatch.  ``probe`` says the dispatch
        actually ran on the latency path: only a successful latency
        *probe* may close an open breaker — a batch-path success says
        nothing about the latency path's health, so while OPEN the
        breaker keeps rerouting until the half-open probe succeeds."""
        if self.threshold <= 0:
            return
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN and probe:
                self._state = CLOSED
                self._m.inc("breaker.closes")
                self._m.set_gauge("breaker.state", CLOSED)

    def record_failure(self) -> None:
        """Feed one *transient* dispatch failure (callers classify first:
        permanent errors say nothing about path health)."""
        if self.threshold <= 0:
            return
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                # failed probe: straight back to OPEN, fresh cooldown
                self._state = OPEN
                self._opened_at = self._clock()
                self._m.inc("breaker.trips")
                self._m.set_gauge("breaker.state", OPEN)
            elif (
                self._state == CLOSED
                and self._consecutive_failures >= self.threshold
            ):
                self._state = OPEN
                self._opened_at = self._clock()
                self._m.inc("breaker.trips")
                self._m.set_gauge("breaker.state", OPEN)


class AdmissionController:
    """The client-facing bundle: gate + breaker + deadline budget."""

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        registry: Optional[_metrics.Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.config = config or AdmissionConfig()
        self._m = registry or _metrics.default
        self._clock = clock
        self.gate = DispatchGate(self.config.max_inflight, registry=self._m)
        self.breaker = CircuitBreaker(
            self.config.breaker_threshold,
            self.config.breaker_cooldown_s,
            registry=self._m,
            clock=clock,
        )
        self._lock = threading.Lock()
        #: client-local EWMA of dispatch cost (seconds); None until the
        #: first sample so a fresh client never sheds on other clients'
        #: history
        self._cost_ewma: Optional[float] = None

    # -- deadline budget -------------------------------------------------
    def expected_cost_s(self) -> float:
        with self._lock:
            ewma = self._cost_ewma
        return max(self.config.deadline_floor_s, ewma or 0.0)

    def observe_cost(self, seconds: float) -> None:
        with self._lock:
            if self._cost_ewma is None:
                self._cost_ewma = seconds
            else:
                self._cost_ewma += _EWMA_ALPHA * (seconds - self._cost_ewma)

    def check_deadline(self, ctx: Context, span=_trace.NOOP) -> None:
        """Shed a dispatch whose deadline cannot cover the expected cost
        — before any device work (pre-H2D), not after the kernel has
        spent the budget.  Raises ``DeadlineExceededError`` (classified,
        retriable; the retry envelope converts it into a bounded wait
        that expires exactly at the context deadline).

        Every shed HALVES the estimate: the EWMA learns from admitted
        dispatches only, and a one-off cold-start outlier (snapshot
        materialization, first-compile) must not lock deadline-bearing
        traffic out forever — after a few decaying sheds the estimate
        drops under real deadlines and requests flow again, re-teaching
        the EWMA from warm samples."""
        if not self.config.deadline_shed:
            return
        dl = ctx.deadline()
        if dl is None:
            return
        remaining = dl - self._clock()
        est = self.expected_cost_s()
        if remaining <= 0 or (est > 0.0 and remaining < est):
            if remaining > 0:
                # the ESTIMATE caused this shed: decay it
                with self._lock:
                    if self._cost_ewma is not None:
                        self._cost_ewma /= 2.0
            self._m.inc("admission.deadline_sheds")
            span.event(
                "admission.deadline_shed",
                remaining_s=round(max(remaining, 0.0), 6),
                expected_s=round(est, 6),
            )
            raise DeadlineExceededError(
                f"deadline budget: {max(remaining, 0.0) * 1000:.1f} ms remain,"
                f" dispatch expected to take {est * 1000:.1f} ms"
            )

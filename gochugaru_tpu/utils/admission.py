"""Admission control for the dispatch path: bounded in-flight gate,
deadline-budget shedding, and a circuit breaker for the latency path.

The north star is a serving system, and a serving system's failure mode
under overload must be *load shedding*, not queue growth: a dispatch
gate that refuses work with ``ShedError`` (an ``UnavailableError``
subclass) converts overload into client-side exponential backoff through
the existing retry envelope — the same contract a gRPC server states by
returning ``codes.Unavailable``.  Samyama's unified in-database design
(PAPERS.md) leans on exactly this to keep hardware-accelerated paths
honest under overload; Graphulo benchmarks the degraded mode explicitly.

Three mechanisms, composed by the client (client.py ``check``):

- **DispatchGate** — a bounded in-flight counter.  ``admit()`` raises
  ``ShedError`` when ``max_inflight`` dispatches are already in the
  engine; no queueing, no blocking.  Counter: ``admission.sheds``.
- **Deadline budget** — ``check_deadline`` sheds a dispatch whose
  context deadline cannot cover the expected dispatch cost (client-local
  EWMA of recent dispatch times, floored by ``deadline_floor_s``): a
  check that would blow its deadline is rejected before H2D, not after
  the kernel has burned the budget.  Counter:
  ``admission.deadline_sheds``.
- **CircuitBreaker** — trips OPEN after ``breaker_threshold``
  *consecutive* transient dispatch failures; while open, latency-mode
  traffic routes back to the batch path (the latency path's pinned
  kernels and staging buffers are the most state-coupled dispatch
  surface, so it is first to lose trust).  After ``breaker_cooldown_s``
  the breaker HALF-OPENs and admits probes; one success closes it, one
  failure re-trips.  Counters: ``breaker.trips``, ``breaker.half_opens``,
  ``breaker.closes``; gauge ``breaker.state`` (0/1/2 =
  closed/half-open/open).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Optional

from . import metrics as _metrics
from . import trace as _trace
from .context import Context
from .errors import DeadlineExceededError, ShedError

#: breaker states (also the ``breaker.state`` gauge values)
CLOSED, HALF_OPEN, OPEN = 0, 1, 2

#: EWMA weight of the newest dispatch-cost sample
_EWMA_ALPHA = 0.2


class CostModel:
    """The ONE expected-dispatch-cost estimate the deadline shed and the
    serving batcher's hold-back share (serve/batcher.py).

    The original scalar EWMA was tuned for caller-formed batches: one
    number regardless of batch size.  A micro-batch former needs "what
    will a tier-1024 dispatch cost" to decide whether holding a request
    another 500 µs blows its deadline — so the model keeps one EWMA per
    ladder tier (keyed by the tier's integer size, so tuned non-pow2
    ladders work unchanged; seeded from the scalar estimate until the
    tier has its own samples) on top of the overall scalar, and both
    consumers read the SAME object: there is no second EWMA to drift.

    ``decay()`` halves every estimate — the deadline shed's cold-start
    escape hatch (see ``AdmissionController.check_deadline``)."""

    def __init__(self, floor_s: float = 0.0) -> None:
        self.floor_s = floor_s
        self._lock = threading.Lock()
        self._overall: Optional[float] = None
        self._by_tier: dict = {}

    def observe(self, seconds: float, tier: Optional[int] = None) -> None:
        """Tier-less samples (caller-formed dispatches) feed the overall
        scalar; tier-tagged samples (the batcher's coalesced dispatches)
        feed ONLY their tier — a 4096-tier batch costing 10x a small
        dispatch must not inflate the estimate the tier-less deadline
        shed reads, or small deadline-bearing requests shed spuriously
        whenever serving traffic runs hot."""
        with self._lock:
            if tier is None:
                if self._overall is None:
                    self._overall = seconds
                else:
                    self._overall += _EWMA_ALPHA * (seconds - self._overall)
            else:
                cur = self._by_tier.get(tier)
                if cur is None:
                    self._by_tier[tier] = seconds
                else:
                    self._by_tier[tier] = cur + _EWMA_ALPHA * (seconds - cur)

    def expected_s(self, tier: Optional[int] = None) -> float:
        """Expected dispatch seconds — the tier's own EWMA when it has
        samples, else the overall estimate, else (tier-less with only
        tiered samples) the CHEAPEST tier's estimate: a request not yet
        assigned a tier could land on the cheapest one, so shedding
        against anything costlier would over-shed.  Floored by
        ``floor_s``."""
        with self._lock:
            est = None
            if tier is not None:
                est = self._by_tier.get(tier)
            if est is None:
                est = self._overall
            if est is None and self._by_tier:
                est = min(self._by_tier.values())
        return max(self.floor_s, est or 0.0)

    def has_samples(self) -> bool:
        with self._lock:
            return self._overall is not None or bool(self._by_tier)

    def state(self) -> dict:
        """Introspection snapshot — dumped into flight-recorder incident
        bundles (utils/trace.py) so "what did the system THINK a dispatch
        cost when it tripped" is part of the diagnosis record."""
        with self._lock:
            return {
                "floor_s": self.floor_s,
                "overall_s": self._overall,
                "by_tier_s": dict(sorted(self._by_tier.items())),
            }

    def decay(self) -> None:
        """Halve the estimate the TIER-LESS readout is built from —
        learning happens on admitted dispatches only, so a one-off
        cold-start outlier must not lock deadline-bearing traffic out
        forever.  Only the channel the shed actually read decays: the
        overall scalar when it has samples, else the cheapest tier (the
        min-fallback ``expected_s(None)`` returns).  Accurate per-tier
        estimates the serving hold-back relies on are NOT collateral —
        repeated caller-formed sheds must not teach the batcher that a
        4096-tier dispatch is free."""
        with self._lock:
            if self._overall is not None:
                self._overall /= 2.0
            elif self._by_tier:
                k = min(self._by_tier, key=self._by_tier.get)
                self._by_tier[k] /= 2.0


@dataclass(frozen=True)
class AdmissionConfig:
    """Tuning for the client's admission controller."""

    #: concurrent dispatches admitted before shedding (0 disables the gate)
    max_inflight: int = 64
    #: consecutive transient dispatch failures that trip the breaker
    #: (0 disables the breaker)
    breaker_threshold: int = 5
    #: seconds OPEN before the breaker half-opens a probe
    breaker_cooldown_s: float = 0.25
    #: floor on the expected-dispatch-cost estimate used for deadline
    #: shedding; 0.0 means "shed only on observed history" (a fresh
    #: client never deadline-sheds until it has its own samples)
    deadline_floor_s: float = 0.0
    #: False disables deadline-budget shedding entirely (requests whose
    #: deadline already passed still fail in the retry envelope itself)
    deadline_shed: bool = True


class DispatchGate:
    """Bounded in-flight dispatch counter.  Shed-don't-queue: a full gate
    raises immediately so the caller's retry envelope backs off instead
    of this layer buffering unboundedly."""

    def __init__(
        self, max_inflight: int, registry: Optional[_metrics.Metrics] = None
    ) -> None:
        self.max_inflight = max_inflight
        self._m = registry or _metrics.default
        self._lock = threading.Lock()
        self._inflight = 0

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @contextmanager
    def admit(self, span=_trace.NOOP):
        if self.max_inflight > 0:
            shed_at = None
            with self._lock:
                if self._inflight >= self.max_inflight:
                    self._m.inc("admission.sheds")
                    shed_at = self._inflight
                else:
                    self._inflight += 1
                    inflight = self._inflight
                    self._m.set_gauge("admission.inflight", inflight)
            if shed_at is not None:
                # everything below runs OUTSIDE the gate lock: a shed
                # burst crossing the spike threshold spawns an incident
                # capture thread, and that spawn must not serialize the
                # admits/releases the gate exists to keep moving (the
                # same hoist the breaker's trip trigger does)
                span.event(
                    "admission.shed", error="ShedError", inflight=shed_at
                )
                span.set_attr("shed_error", "ShedError")
                # one shed is overload working as designed; a BURST of
                # sheds is an incident — the flight recorder's spike
                # detector decides which this is
                _trace.note_anomaly("shed")
                raise ShedError(
                    f"dispatch admission: {shed_at} in-flight"
                    f" >= max_inflight {self.max_inflight}"
                )
            span.event("admission.admit", inflight=inflight)
        else:
            span.event("admission.admit", inflight=-1)
        try:
            yield
        finally:
            if self.max_inflight > 0:
                with self._lock:
                    self._inflight -= 1
                    self._m.set_gauge("admission.inflight", self._inflight)


class CircuitBreaker:
    """Consecutive-transient-failure breaker gating the latency path.

    ``allow_latency()`` answers "may this dispatch use the latency-mode
    path right now"; ``record_success``/``record_failure`` feed it from
    dispatch outcomes.  ``clock`` is injectable so tests drive the
    cooldown deterministically."""

    def __init__(
        self,
        threshold: int,
        cooldown_s: float,
        registry: Optional[_metrics.Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._m = registry or _metrics.default
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._m.set_gauge("breaker.state", CLOSED)

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def allow_latency(self) -> bool:
        if self.threshold <= 0:
            return True
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._state = HALF_OPEN
                    self._m.inc("breaker.half_opens")
                    self._m.set_gauge("breaker.state", HALF_OPEN)
                    return True  # this dispatch is the probe
                return False
            return True  # HALF_OPEN: probes flow until an outcome lands

    def record_success(self, probe: bool = False) -> None:
        """Feed one successful dispatch.  ``probe`` says the dispatch
        actually ran on the latency path: only a successful latency
        *probe* may close an open breaker — a batch-path success says
        nothing about the latency path's health, so while OPEN the
        breaker keeps rerouting until the half-open probe succeeds."""
        if self.threshold <= 0:
            return
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN and probe:
                self._state = CLOSED
                self._m.inc("breaker.closes")
                self._m.set_gauge("breaker.state", CLOSED)

    def record_failure(self) -> None:
        """Feed one *transient* dispatch failure (callers classify first:
        permanent errors say nothing about path health)."""
        if self.threshold <= 0:
            return
        tripped = False
        with self._lock:
            self._consecutive_failures += 1
            consecutive = self._consecutive_failures
            if self._state == HALF_OPEN:
                # failed probe: straight back to OPEN, fresh cooldown
                self._state = OPEN
                self._opened_at = self._clock()
                self._m.inc("breaker.trips")
                self._m.set_gauge("breaker.state", OPEN)
                tripped = True
            elif (
                self._state == CLOSED
                and self._consecutive_failures >= self.threshold
            ):
                self._state = OPEN
                self._opened_at = self._clock()
                self._m.inc("breaker.trips")
                self._m.set_gauge("breaker.state", OPEN)
                tripped = True
        if tripped:
            # flight-recorder trigger OUTSIDE the lock (the capture
            # thread spawn must not serialize other dispatch outcomes):
            # a breaker trip freezes the last N request traces — the
            # consecutive failures that tripped it are in the ring
            _trace.trigger_incident(
                "breaker.trip", consecutive=consecutive,
                threshold=self.threshold,
            )


class AdmissionController:
    """The client-facing bundle: gate + breaker + deadline budget."""

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        registry: Optional[_metrics.Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.config = config or AdmissionConfig()
        self._m = registry or _metrics.default
        self._clock = clock
        self.gate = DispatchGate(self.config.max_inflight, registry=self._m)
        self.breaker = CircuitBreaker(
            self.config.breaker_threshold,
            self.config.breaker_cooldown_s,
            registry=self._m,
            clock=clock,
        )
        #: the shared dispatch-cost model (per-tier EWMA + overall);
        #: client-local — None samples until the first dispatch so a
        #: fresh client never sheds on other clients' history.  The
        #: serving batcher (serve/batcher.py) reads and feeds the SAME
        #: object for its hold-back decisions — one cost model, two
        #: consumers, no duplicated EWMA
        self.cost = CostModel(self.config.deadline_floor_s)

    def report(self) -> dict:
        """Backpressure snapshot: what a fleet replica publishes in its
        health payload (fleet/replica.py) so the router can see each
        member's admission state alongside its freshness."""
        return {
            "inflight": self.gate.inflight,
            "max_inflight": self.config.max_inflight,
            "breaker": self.breaker.state,
        }

    # -- deadline budget -------------------------------------------------
    def expected_cost_s(self, tier: Optional[int] = None) -> float:
        return self.cost.expected_s(tier)

    def observe_cost(self, seconds: float, tier: Optional[int] = None) -> None:
        self.cost.observe(seconds, tier)

    def check_deadline(self, ctx: Context, span=_trace.NOOP) -> None:
        """Shed a dispatch whose deadline cannot cover the expected cost
        — before any device work (pre-H2D), not after the kernel has
        spent the budget.  Raises ``DeadlineExceededError`` (classified,
        retriable; the retry envelope converts it into a bounded wait
        that expires exactly at the context deadline).

        Every shed HALVES the estimate: the EWMA learns from admitted
        dispatches only, and a one-off cold-start outlier (snapshot
        materialization, first-compile) must not lock deadline-bearing
        traffic out forever — after a few decaying sheds the estimate
        drops under real deadlines and requests flow again, re-teaching
        the EWMA from warm samples."""
        if not self.config.deadline_shed:
            return
        dl = ctx.deadline()
        if dl is None:
            return
        remaining = dl - self._clock()
        est = self.expected_cost_s()
        if remaining <= 0 or (est > 0.0 and remaining < est):
            if remaining > 0:
                # the ESTIMATE caused this shed: decay it
                self.cost.decay()
            self._m.inc("admission.deadline_sheds")
            _trace.note_anomaly("shed")
            span.event(
                "admission.deadline_shed",
                remaining_s=round(max(remaining, 0.0), 6),
                expected_s=round(est, 6),
            )
            raise DeadlineExceededError(
                f"deadline budget: {max(remaining, 0.0) * 1000:.1f} ms remain,"
                f" dispatch expected to take {est * 1000:.1f} ms"
            )

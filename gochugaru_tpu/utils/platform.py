"""Platform forcing for tests / dryruns / degraded benches.

The environment's sitecustomize pins JAX onto the one-chip remote TPU
tunnel (JAX_PLATFORMS=axon) and pre-imports jax, so overriding the
platform needs both the env var (for subprocesses) and a live
``jax.config`` update (for this process).  One definition here so the
test conftest, the driver's multichip dryrun, and the bench's degraded
path cannot drift.
"""

from __future__ import annotations

import os
import re


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Force JAX onto the CPU backend, optionally with ``n_devices``
    virtual devices (replacing any pre-set device-count flag, which may
    carry a different count)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags
    # the sitecustomize pre-imports jax, so the env var alone is not
    # honored — force the platform through the live config too (the
    # backend itself initializes lazily, so XLA_FLAGS still takes effect)
    import jax

    jax.config.update("jax_platforms", "cpu")

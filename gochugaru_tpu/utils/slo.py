"""Declarative SLOs evaluated as multi-window burn rates.

The serving stack publishes latency through timer rings and failure
modes through counters (utils/metrics.py), but nothing answered "is the
service inside its objectives RIGHT NOW, and how fast is it spending its
error budget" — the question an on-call (and the flight recorder's
trigger bus) actually asks.  This module is the standard SRE shape:

- an **SLO** declares either a latency objective over an existing timer
  ("p99 of ``serve.request_s`` ≤ 50 ms" ⇒ at most 1% of requests may
  exceed 50 ms) or an error/shed budget over counters ("sheds ≤ 5% of
  submissions");
- the **burn rate** of a window is (bad fraction over the window) ÷
  (budgeted bad fraction): burn 1.0 spends the budget exactly at the
  sustainable rate, burn 10 exhausts a day's budget in ~2.4 hours;
- **multi-window** evaluation (one short, one long window) is the
  standard de-noiser: the long window proves the burn is sustained, the
  short window proves it is still happening — an alert needs BOTH above
  threshold, so a brief spike (short only) or an old, recovered incident
  (long only) does not page.

Latency burn is computed from EXACT over-objective counts, not quantile
estimates: the engine arms ``Metrics.set_timer_threshold`` for each
latency SLO, so every ``observe()`` classifies its sample against the
objective at record time and the per-window "bad" count is a plain
counter delta (the timer sample ring has no timestamps, so windowed
quantiles over it would be guesses).

``SLOEngine`` samples the cumulative (bad, total) pairs on a background
cadence (``tick_s``), keeps a bounded history, publishes per-window
``slo.*`` gauges, serves ``report()`` to the telemetry ``/slo``
endpoint, and — on the False→True breach edge — fires an ``slo.burn``
incident through the flight recorder's trigger bus (utils/trace.py), so
a burning SLO freezes the last N request traces that caused it.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import metrics as _metrics
from . import trace as _trace

import time


@dataclass(frozen=True)
class SLO:
    """One declared objective.  Use the ``latency_slo``/``ratio_slo``
    constructors; the dataclass itself is the engine's internal shape.

    ``kind`` is "latency" (bad = timer observations over
    ``objective_s``; budget = 1 − quantile/100) or "ratio" (bad/total =
    counter sums; budget declared directly)."""

    name: str
    kind: str
    #: budgeted bad fraction (latency: 1 − quantile/100; ratio: given)
    budget: float
    #: latency kind: the metrics timer the objective binds to
    timer: str = ""
    #: latency kind: the objective in seconds
    objective_s: float = 0.0
    #: latency kind: the quantile the objective is stated at ([0,100])
    quantile: float = 99.0
    #: ratio kind: counter names summed into the bad numerator
    bad: Tuple[str, ...] = field(default_factory=tuple)
    #: ratio kind: counter names summed into the total denominator
    #: (bad counters NOT implicitly included — list them if they are
    #: not already part of the total)
    total: Tuple[str, ...] = field(default_factory=tuple)


def latency_slo(
    name: str, timer: str, objective_ms: float, quantile: float = 99.0
) -> SLO:
    """"p<quantile> of <timer> ≤ objective_ms" — at most
    (1 − quantile/100) of observations may exceed the objective."""
    if not 0.0 < quantile < 100.0:
        raise ValueError(f"quantile must be in (0, 100), got {quantile}")
    return SLO(
        name=name, kind="latency", budget=1.0 - quantile / 100.0,
        timer=timer, objective_s=objective_ms / 1000.0, quantile=quantile,
    )


def ratio_slo(
    name: str, bad: Sequence[str], total: Sequence[str], budget: float
) -> SLO:
    """"sum(bad) ≤ budget × sum(total)" over each window."""
    if not 0.0 < budget <= 1.0:
        raise ValueError(f"budget must be in (0, 1], got {budget}")
    return SLO(
        name=name, kind="ratio", budget=budget,
        bad=tuple(bad), total=tuple(total),
    )


def default_slos() -> Tuple[SLO, ...]:
    """The serving stack's stock objectives — deliberately generous (an
    SLO that pages on a CPU proxy's ordinary jitter teaches operators to
    ignore it); override per deployment via ``with_telemetry(slos=…)``.

    - per-surface latency: direct checks (``checks.dispatch``), the
      coalesced serving path (``serve.request_s``), and the pinned
      latency tier (``latency.dispatch_s`` — the north-star surface,
      held to a tighter objective);
    - shed budget: sheds across the admission gate and the serve queue
      vs. offered work;
    - transient-fault budget: retry-envelope activity vs. requested
      checks (a fault storm burns this one — scripts/slo_smoke.sh's
      subject);
    - denial-rate budget: denied verdicts vs. all verdicts (the
      per-strategy ``check.verdicts.*`` counters, utils/decisions.py) —
      a sustained denial spike is the authorization-domain anomaly an
      operator wants paged on (bad schema push, revoked-edges sweep,
      token confusion), and the breach edge freezes the flight ring
      with the deciding traces AND the last-N decisions in the bundle.
      Generous on purpose: burn-threshold 2 × budget 0.25 ⇒ a sustained
      ≥50% denial fraction pages, ordinary deny-heavy traffic doesn't.
    """
    return (
        latency_slo("check.dispatch", "checks.dispatch", objective_ms=50.0),
        latency_slo("serve.request", "serve.request_s", objective_ms=50.0),
        latency_slo("latency.dispatch", "latency.dispatch_s",
                    objective_ms=20.0),
        ratio_slo(
            "shed",
            bad=("admission.sheds", "serve.sheds"),
            total=("checks.requested", "serve.submissions"),
            budget=0.05,
        ),
        ratio_slo(
            "transient_faults",
            bad=("retry.retries",),
            total=("checks.requested", "serve.submissions"),
            budget=0.01,
        ),
        ratio_slo(
            "denial_rate",
            bad=("check.verdicts.denied",),
            total=("check.verdicts.allowed", "check.verdicts.denied"),
            budget=0.25,
        ),
    )


class SLOEngine:
    """Multi-window burn-rate evaluator over the live metrics registry.

    ``windows`` (seconds, ascending) are evaluated per SLO per tick; an
    SLO is **breached** when EVERY window's burn ≥ ``burn_threshold``
    (the multi-window AND).  Gauges per tick:

    - ``slo.<name>.burn_<w>s`` — burn rate per window
    - ``slo.<name>.breached`` — 0/1
    - ``slo.breached`` — count of breached SLOs (0 ⇒ healthy)

    On the False→True breach edge the engine fires an ``slo.burn``
    incident through the flight-recorder trigger bus and bumps
    ``slo.breaches``.  ``tick()`` is callable directly (tests drive the
    clock); ``start=True`` runs it on a daemon thread every ``tick_s``.
    """

    def __init__(
        self,
        slos: Optional[Sequence[SLO]] = None,
        registry: Optional[_metrics.Metrics] = None,
        windows: Sequence[float] = (30.0, 300.0),
        burn_threshold: float = 2.0,
        tick_s: float = 1.0,
        clock=time.monotonic,
        start: bool = True,
    ) -> None:
        self.slos: Tuple[SLO, ...] = tuple(
            slos if slos is not None else default_slos()
        )
        self._m = registry or _metrics.default
        self.windows = tuple(sorted(float(w) for w in windows))
        if not self.windows:
            raise ValueError("at least one window required")
        self.burn_threshold = float(burn_threshold)
        self.tick_s = float(tick_s)
        self._clock = clock
        self._lock = threading.Lock()
        # history per SLO: (t, bad_cum, total_cum), bounded to the
        # longest window (+ slack for jittered ticks)
        hist_len = int(self.windows[-1] / max(self.tick_s, 1e-3)) + 8
        self._hist: Dict[str, deque] = {
            s.name: deque(maxlen=hist_len) for s in self.slos
        }
        self._breached: Dict[str, bool] = {s.name: False for s in self.slos}
        self._last_report: Dict[str, Any] = {
            "healthy": True, "slos": [], "windows_s": list(self.windows),
            "burn_threshold": self.burn_threshold, "ticks": 0,
        }
        self._ticks = 0
        timer_objectives: Dict[str, float] = {}
        for s in self.slos:
            if s.kind == "latency":
                # the over-objective counter is PER TIMER: two latency
                # SLOs binding the same timer at different objectives
                # would silently share one threshold (last writer wins)
                # and compute at least one burn against the wrong
                # objective — reject the misconfiguration loudly
                prev = timer_objectives.get(s.timer)
                if prev is not None and prev != s.objective_s:
                    raise ValueError(
                        f"multiple latency SLOs bind timer {s.timer!r}"
                        f" at different objectives ({prev}s vs"
                        f" {s.objective_s}s) — one objective per timer"
                    )
                timer_objectives[s.timer] = s.objective_s
                # exact over-objective counting at observe() time — the
                # burn numerator is a counter delta, not a ring estimate
                self._m.set_timer_threshold(s.timer, s.objective_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # evaluate once up front: /slo must never serve an empty report
        # in the gap before the first background tick
        self.tick()
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="gochugaru-slo", daemon=True
            )
            self._thread.start()

    # -- sampling ----------------------------------------------------------
    def _cumulative(self, s: SLO) -> Tuple[float, float]:
        if s.kind == "latency":
            n, over = self._m.timer_counts(s.timer)
            return float(over), float(n)
        bad = sum(self._m.counter(c) for c in s.bad)
        total = sum(self._m.counter(c) for c in s.total)
        return bad, total

    @staticmethod
    def _window_delta(
        hist: deque, now: float, w: float
    ) -> Tuple[float, float, float]:
        """(bad_delta, total_delta, actual_window_s) between the newest
        sample and the oldest one inside the window (or the oldest held,
        while history is still shorter than the window)."""
        newest = hist[-1]
        base = hist[0]
        for item in hist:
            if now - item[0] <= w:
                base = item
                break
        return (
            newest[1] - base[1],
            newest[2] - base[2],
            max(newest[0] - base[0], 0.0),
        )

    # -- evaluation --------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Evaluate every SLO once; returns (and caches) the report the
        ``/slo`` endpoint serves."""
        now = self._clock() if now is None else now
        report_slos: List[Dict[str, Any]] = []
        breached_names: List[str] = []
        edges: List[Dict[str, Any]] = []
        with self._lock:
            self._ticks += 1
            for s in self.slos:
                bad, total = self._cumulative(s)
                hist = self._hist[s.name]
                hist.append((now, bad, total))
                row: Dict[str, Any] = {
                    "name": s.name,
                    "kind": s.kind,
                    "budget": s.budget,
                    "windows": {},
                }
                if s.kind == "latency":
                    row["timer"] = s.timer
                    row["objective_ms"] = round(s.objective_s * 1000.0, 3)
                    row["quantile"] = s.quantile
                else:
                    row["bad"] = list(s.bad)
                    row["total"] = list(s.total)
                breach = True
                for w in self.windows:
                    db, dt, actual = self._window_delta(hist, now, w)
                    frac = (db / dt) if dt > 0 else 0.0
                    burn = frac / s.budget
                    key = f"{format(w, 'g')}s"
                    # a window still WARMING (history shorter than the
                    # window) cannot confirm a breach: until the long
                    # window holds w seconds of history, every window
                    # computes the SAME delta off hist[0] and the
                    # multi-window AND de-noising is void — a cold-start
                    # compile blip would page instantly, the exact
                    # behavior the two-window rule exists to prevent
                    warmed = actual >= w - 1.5 * self.tick_s
                    row["windows"][key] = {
                        "burn": round(burn, 4),
                        "bad": db,
                        "total": dt,
                        "window_s": round(actual, 3),
                    }
                    if not warmed:
                        row["windows"][key]["warming"] = True
                        breach = False
                    self._m.set_gauge(f"slo.{s.name}.burn_{key}", burn)
                    if burn < self.burn_threshold:
                        breach = False
                # a window with zero traffic cannot burn; require traffic
                # in the short window for a breach (an idle process is
                # healthy, not silently failing its objectives)
                short = row["windows"][f"{format(self.windows[0], 'g')}s"]
                if short["total"] <= 0:
                    breach = False
                row["breached"] = breach
                self._m.set_gauge(f"slo.{s.name}.breached", float(breach))
                prev = self._breached[s.name]
                self._breached[s.name] = breach
                if breach:
                    breached_names.append(s.name)
                    if not prev:
                        self._m.inc("slo.breaches")
                        worst = max(
                            wv["burn"] for wv in row["windows"].values()
                        )
                        edges.append({
                            "slo": s.name, "burn": round(worst, 3),
                            "budget": s.budget,
                        })
                report_slos.append(row)
            self._m.set_gauge("slo.breached", float(len(breached_names)))
            report = {
                "healthy": not breached_names,
                "breached": breached_names,
                "slos": report_slos,
                "windows_s": list(self.windows),
                "burn_threshold": self.burn_threshold,
                "tick_s": self.tick_s,
                "ticks": self._ticks,
            }
            self._last_report = report
        # breach-edge incidents fire OUTSIDE the engine lock: the
        # capture-thread spawn must not serialize /slo and /healthz
        # readers on report() — the same hoist every other trigger site
        # (gate, breaker, batcher shed) applies
        for e in edges:
            _trace.trigger_incident("slo.burn", **e)
        return report

    def report(self) -> Dict[str, Any]:
        """The most recent tick's evaluation (the ``/slo`` payload)."""
        with self._lock:
            return self._last_report

    def breached(self) -> List[str]:
        with self._lock:
            return [n for n, b in self._breached.items() if b]

    # -- lifecycle ---------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # pragma: no cover - a tick must never kill
                self._m.inc("slo.tick_errors")  # the evaluator thread

    @property
    def closed(self) -> bool:
        """True once ``close()`` ran — endpoint holders (telemetry's
        ``/slo``, ``readiness_report``) check this so a client whose
        shared engine was later disabled reports "disabled" instead of
        serving the closed engine's frozen last report as live."""
        return self._stop.is_set()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        # a closed engine's verdict must not outlive it: a stale
        # slo.<name>.breached=1 on /metrics would page forever on a
        # breach that ended (a replacement engine republishes its own
        # set on its constructor tick)
        self._m.clear_gauges("slo.")


#: process-global engine (mirrors trace._TRACER / trace._RECORDER): the
#: gauges it writes and the timer thresholds it arms live on the shared
#: registry, so two engines evaluating independent histories would fight
#: over the same slo.* series and double-fire breach edges — one engine
#: per process, shared by every with_telemetry client
_ENGINE: Optional[SLOEngine] = None


def install_engine(engine: Optional[SLOEngine]) -> Optional[SLOEngine]:
    """Install (``None`` uninstalls) the process-global SLO engine; a
    previously installed engine is closed first — there must never be
    two evaluators racing over the same ``slo.*`` gauges.

    Replacement ordering is handled HERE, not by callers: in
    ``install_engine(SLOEngine(...))`` the new engine's constructor tick
    publishes gauges before the old engine's ``close()`` clears the
    ``slo.*`` prefix, so after closing the old one the new engine is
    re-ticked to republish — /metrics never loses the slo series for a
    tick window."""
    global _ENGINE
    prev = _ENGINE
    if prev is not None and prev is not engine:
        prev.close()
    _ENGINE = engine
    if engine is not None and prev is not None and prev is not engine:
        engine.tick()
    return engine


def get_engine() -> Optional[SLOEngine]:
    return _ENGINE


__all__ = [
    "SLO",
    "SLOEngine",
    "default_slos",
    "get_engine",
    "install_engine",
    "latency_slo",
    "ratio_slo",
]

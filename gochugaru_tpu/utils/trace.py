"""Request-scoped tracing: spans, head sampling with a keep-slow tail
rule, and profiler-correlated dispatch.

Every number this project shipped before this module was a
benchmark-harness aggregate; a serving system must answer "why was THIS
check slow" from the live process.  TpuGraphs (arXiv:2308.13490) shows
kernel/layout choices dominate TPU graph-workload cost — actionable only
when per-request spans line up with the device trace — and the Graphulo
measurement discipline (arXiv:1609.08642) the bench suite follows is
extended here to the always-on path.

Design constraints, in order (the same ordering utils/faults.py states):

1. **Zero cost when disabled.**  The span entry points sit on the
   latency dispatch path.  With no tracer installed, ``root_span``
   is one module-global load + branch returning the ``NOOP`` singleton;
   every method on ``NOOP`` is a no-op returning ``NOOP``; Context
   propagation (``ctx_with_span``) returns the SAME context — no dict
   churn, no allocation.  Tests assert the identity
   (``span is trace.NOOP``) and that ``spans_created()`` does not move.
2. **Head-based sampling, keep-slow tail rule.**  The keep/drop decision
   is made at trace START (``sample_rate``): unsampled requests run the
   NOOP path end-to-end.  The tail rule catches what head sampling
   misses: callers on the NOOP path report their measured duration via
   ``maybe_keep_slow``; a request slower than ``slow_threshold_s`` is
   recorded as a root-only trace flagged ``tail_kept`` — so "why was
   this check slow" always has an answer, even at a 1% sample rate.
   (A tail-kept trace has no child spans — the price of not paying span
   bookkeeping on the 99% — but carries the request attributes and
   duration; raise the sample rate to get full trees.)
3. **Bounded.**  Finished traces land in a ring (``capacity``); span
   events cap at ``MAX_EVENTS`` per span with a drop counter.  A
   long-lived serving process holds a bounded few hundred KB.

Spans form a tree: ``root_span`` starts a trace, ``span.child`` nests,
timestamps are ``time.perf_counter()`` so durations subtract exactly the
way the utils/metrics.py stage timers subtract — a stage span built from
the SAME t0/t1 the timer used agrees with the timer bit-for-bit.

Context propagation: the active span rides request Context values
(``Context.with_span`` / ``Context.span``, utils/context.py) across API
layers, and a thread-local "current span" (set by ``with span:``) lets
deep sites that never see a Context — the incremental closure advance,
the store write path — attach events via ``event_if_active`` without
plumbing a parameter through every signature.

Profiler correlation: when a profiler session is active (the
``GOCHUGARU_TRACE_DIR`` env var names its dump dir — tpu_watch.sh's
harvest step and ``bench_tpu_harvest --trace`` set it),
``annotate_dispatch(span)`` wraps dispatch in a
``jax.profiler.TraceAnnotation`` named by the trace id, so the XLA
device trace carries request attribution for free.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

#: events kept per span before dropping (the drop count is recorded on
#: the span as ``events_dropped``)
MAX_EVENTS = 128

#: Context value key the active span rides on (utils/context.py)
SPAN_KEY = "gochugaru.trace.span"

#: total real Span objects ever constructed in this process — the
#: zero-allocation contract's witness (tests assert it does not move
#: when sampling is off)
_SPANS_CREATED = 0

#: module-level fast path: None ⇒ every entry point is one load + branch
_TRACER: Optional["Tracer"] = None

#: cached profiler-session dir (GOCHUGARU_TRACE_DIR), refreshed by
#: profiler_session()/refresh_profiler() — not re-read per dispatch
_PROFILER_DIR: Optional[str] = os.environ.get("GOCHUGARU_TRACE_DIR") or None

#: pid hex for trace ids, read ONCE — os.getpid() is a syscall per call
#: (~46 µs under this container's sandbox; it dominated the traced-path
#: profile).  Refreshed after fork so children don't reuse the parent's.
_PID_HEX = f"{os.getpid():x}"


def _refresh_pid() -> None:
    global _PID_HEX
    _PID_HEX = f"{os.getpid():x}"


if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_refresh_pid)

_tls = threading.local()


class _NoopSpan:
    """The disabled/unsampled span: every method is a no-op returning
    the singleton itself, so traced code needs no ``if span:`` guards
    and allocates nothing.  Identity (``span is NOOP``) is the
    zero-cost contract tests assert."""

    __slots__ = ()

    sampled = False
    trace_id = ""
    span_id = 0
    name = ""

    def child(self, name: str, t: Optional[float] = None, **attrs) -> "_NoopSpan":
        return self

    def child_at(self, name: str, t: float) -> "_NoopSpan":
        return self

    def event(self, name: str, t: Optional[float] = None, **attrs) -> "_NoopSpan":
        return self

    def set_attr(self, key: str, value: Any) -> "_NoopSpan":
        return self

    def end(self, t: Optional[float] = None) -> None:
        return None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def __bool__(self) -> bool:
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<NoopSpan>"


#: the singleton every disabled path returns
NOOP = _NoopSpan()


class Span:
    """One node of a sampled trace: name, parent link, monotonic start,
    attributes, bounded events.  ``end()`` freezes the duration and
    (for the root) hands the finished trace to the tracer's ring.

    Allocation discipline: a sampled dispatch constructs six of these
    and the marginal tail cost of tracing is GC pressure, not CPU — so
    ``attrs``/``events`` stay ``None`` until something is stored, the
    trace id renders lazily at export, and ``child_at`` takes no kwargs
    (a ``**attrs`` signature allocates a dict per call even when
    empty)."""

    __slots__ = (
        "_rec", "span_id", "parent_id", "name",
        "t0", "t1", "attrs", "events", "_dropped", "_tls_prev",
    )

    sampled = True

    def __init__(
        self,
        rec: "_TraceRec",
        name: str,
        parent_id: int,
        t: Optional[float] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        global _SPANS_CREATED
        _SPANS_CREATED += 1
        self._rec = rec
        # id allocation + registration inlined (single-writer per
        # request, so no lock): this constructor runs six times per
        # sampled dispatch and call overhead was the profile's top line
        self.span_id = rec._next_id
        rec._next_id += 1
        rec.spans.append(self)
        self.parent_id = parent_id
        self.name = name
        self.t0 = time.perf_counter() if t is None else t
        self.t1: Optional[float] = None
        self.attrs: Optional[Dict[str, Any]] = attrs
        self.events: Optional[List[Dict[str, Any]]] = None
        self._dropped = 0
        self._tls_prev: Any = None

    @property
    def trace_id(self) -> str:
        return self._rec.trace_id

    # -- tree --------------------------------------------------------------
    def child(self, name: str, t: Optional[float] = None, **attrs) -> "Span":
        """Start a child span.  ``t`` backdates the start (stage spans
        rebuilt from already-taken perf_counter timestamps)."""
        return Span(self._rec, name, self.span_id, t=t, attrs=attrs or None)

    def child_at(self, name: str, t: float) -> "Span":
        """Attribute-less child backdated to ``t`` — the stage-span fast
        path (no kwargs dict)."""
        return Span(self._rec, name, self.span_id, t=t)

    def event(self, name: str, t: Optional[float] = None, **attrs) -> "Span":
        """Attach a point-in-time event (bounded; drops are counted)."""
        evs = self.events
        if evs is None:
            evs = self.events = []
        elif len(evs) >= MAX_EVENTS:
            self._dropped += 1
            return self
        # raw float here; rounding happens once at export (as_dict) —
        # round() costs ~1 µs each under this container and events sit
        # on the request path
        ev: Dict[str, Any] = {
            "name": name,
            "t_s": (time.perf_counter() if t is None else t) - self._rec.t0,
        }
        if attrs:
            ev.update(attrs)
        evs.append(ev)
        return self

    def set_attr(self, key: str, value: Any) -> "Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    # -- lifecycle ---------------------------------------------------------
    def end(self, t: Optional[float] = None) -> None:
        if self.t1 is not None:
            return  # idempotent: `with` + explicit end must not double-finish
        self.t1 = time.perf_counter() if t is None else t
        if self._dropped:
            self.set_attr("events_dropped", self._dropped)
        if self.span_id == 0:
            self._rec.finish(self.t1)

    def __enter__(self) -> "Span":
        # thread-local activation: deep sites (closure advance, store
        # write internals) attach events via event_if_active without a
        # span parameter reaching them
        self._tls_prev = getattr(_tls, "span", None)
        _tls.span = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _tls.span = self._tls_prev
        if exc is not None and (self.attrs is None or "error" not in self.attrs):
            self.set_attr("error", type(exc).__name__)
        self.end()
        return False

    def duration_s(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def as_dict(self, default_t1: Optional[float] = None) -> Dict[str, Any]:
        """Render for export.  Runs at dump/scrape time, NOT on the
        request path — rounding lives here.  ``default_t1`` stands in
        for a child that was never explicitly ended (the root's end
        time, so an unclosed child can't grow until export)."""
        t1 = self.t1
        if t1 is None:
            t1 = default_t1 if default_t1 is not None else time.perf_counter()
        d: Dict[str, Any] = {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "t0_s": round(self.t0 - self._rec.t0, 9),
            "dur_s": round(t1 - self.t0, 9),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        if self.events:
            d["events"] = [
                {**ev, "t_s": round(ev["t_s"], 9)} for ev in self.events
            ]
        return d


class _TraceRec:
    """Book-keeping for one in-flight sampled trace (root + registered
    descendants).  Spans of one request may be touched from the request
    thread only — the same single-writer discipline a Context has — so
    the only lock here is the tracer ring's.

    The trace id string renders lazily (``trace_id``): the eager
    sequence number is one atomic ``next()`` and the string only exists
    when something reads it — export, or ``annotate_dispatch`` inside a
    profiler session.  The render is deterministic from (pid, seq,
    tracer salt), so concurrent readers agree without a lock."""

    __slots__ = ("tracer", "seq", "_tid", "name", "t0", "wall_t0", "spans", "_next_id")

    def __init__(self, tracer: "Tracer", name: str) -> None:
        self.tracer = tracer
        self.seq = next(tracer._seq)
        self._tid: Optional[str] = None
        self.name = name
        self.t0 = time.perf_counter()
        self.wall_t0 = time.time()
        self.spans: List[Span] = []
        self._next_id = 0

    @property
    def trace_id(self) -> str:
        tid = self._tid
        if tid is None:
            tid = self._tid = _render_trace_id(self.tracer._salt, self.seq)
        return tid

    def finish(self, t1: float) -> None:
        self.tracer._record(self, t1)


def _render_trace_id(salt: int, seq: int) -> str:
    """pid-seq-mix: unique within a process lifetime via seq, unique
    across restarts via the tracer's per-construction random salt —
    deterministic given (salt, seq) so lazy rendering is race-free."""
    return f"{_PID_HEX}-{seq:08x}-{(seq * 0x9E3779B1 ^ salt) & 0xFFFFFFFF:08x}"


class Tracer:
    """Head-sampling tracer with a bounded ring of finished traces.

    ``sample_rate`` in [0, 1] is the head decision; ``slow_threshold_s``
    is the tail rule (``maybe_keep_slow``); ``capacity`` bounds the
    ring.  Counters ride the shared metrics registry:
    ``trace.started`` / ``trace.kept`` / ``trace.tail_kept`` /
    ``trace.unsampled``."""

    def __init__(
        self,
        sample_rate: float = 1.0,
        slow_threshold_s: Optional[float] = 0.100,
        capacity: int = 512,
        registry: Optional[_metrics.Metrics] = None,
        seed: Optional[int] = None,
    ) -> None:
        import itertools

        self.sample_rate = float(sample_rate)
        self.slow_threshold_s = slow_threshold_s
        self._m = registry or _metrics.default
        self._rng = random.Random(seed)
        self._salt = self._rng.getrandbits(32)
        self._seq = itertools.count(1)  # GIL-atomic next(); no hot-path lock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(capacity), 1))

    # -- trace start -------------------------------------------------------
    def start_trace(self, name: str, **attrs) -> Span:
        if self.sample_rate <= 0.0 or (
            self.sample_rate < 1.0 and self._rng.random() >= self.sample_rate
        ):
            self._m.inc("trace.unsampled")
            return NOOP
        self._m.inc("trace.started")
        rec = _TraceRec(self, name)
        return Span(rec, name, parent_id=-1, t=rec.t0, attrs=attrs or None)

    # -- tail rule ---------------------------------------------------------
    def keep_slow(self, name: str, duration_s: float, **attrs) -> bool:
        """Record a root-only trace for an unsampled-but-slow request.
        Returns True when kept (duration ≥ slow_threshold_s)."""
        thr = self.slow_threshold_s
        if thr is None or duration_s < thr:
            return False
        self._m.inc("trace.tail_kept")
        attrs["tail_kept"] = True
        with self._lock:
            self._ring.append({
                "trace_id": _render_trace_id(self._salt, next(self._seq)),
                "name": name,
                "start_unix_s": round(time.time() - duration_s, 6),
                "duration_s": round(duration_s, 9),
                "tail_kept": True,
                "spans": [{
                    "span_id": 0, "parent_id": -1, "name": name,
                    "t0_s": 0.0, "dur_s": round(duration_s, 9),
                    "attrs": attrs,
                }],
            })
        return True

    # -- retention ---------------------------------------------------------
    def _record(self, rec: _TraceRec, t1: float) -> None:
        """Root ended: retain the live record.  Rendering (span dicts,
        rounding) is deferred to ``traces()`` — a finished trace's spans
        never mutate again, so export-time rendering reads frozen data,
        and the request path pays one deque append."""
        self._m.inc("trace.kept")
        with self._lock:
            self._ring.append((rec, t1))

    # -- export ------------------------------------------------------------
    def traces(self) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._ring)
        out: List[Dict[str, Any]] = []
        for it in items:
            if isinstance(it, dict):  # tail-kept: pre-rendered root-only
                out.append(it)
                continue
            rec, t1 = it
            out.append({
                "trace_id": rec.trace_id,
                "name": rec.name,
                "start_unix_s": round(rec.wall_t0, 6),
                "duration_s": round(t1 - rec.t0, 9),
                "spans": [sp.as_dict(default_t1=t1) for sp in rec.spans],
            })
        return out

    def dump_jsonl(self, path: Optional[str] = None) -> str:
        """One JSON object per line per finished trace (newest last).
        With ``path``, also writes the dump there."""
        out = "\n".join(json.dumps(t) for t in self.traces())
        if out:
            out += "\n"
        if path is not None:
            with open(path, "w") as f:
                f.write(out)
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# ---------------------------------------------------------------------------
# Module-level surface (the hot-path entry points)
# ---------------------------------------------------------------------------


def configure(
    sample_rate: float = 1.0,
    slow_threshold_s: Optional[float] = 0.100,
    capacity: int = 512,
    registry: Optional[_metrics.Metrics] = None,
    seed: Optional[int] = None,
) -> Tracer:
    """Install (and return) the process-global tracer.  ``sample_rate``
    is the head decision; ``slow_threshold_s=None`` disables the tail
    rule."""
    global _TRACER
    _TRACER = Tracer(
        sample_rate=sample_rate, slow_threshold_s=slow_threshold_s,
        capacity=capacity, registry=registry, seed=seed,
    )
    return _TRACER


def disable() -> None:
    """Remove the global tracer: every entry point returns to the
    one-branch NOOP path."""
    global _TRACER
    _TRACER = None


def install(tracer: Optional[Tracer]) -> None:
    """Install an existing tracer (or ``None`` to disable) without
    constructing a new one — the overhead harness flips one tracer
    in and out per rep and must not allocate while doing so."""
    global _TRACER
    _TRACER = tracer


def get() -> Optional[Tracer]:
    return _TRACER


def enabled() -> bool:
    return _TRACER is not None


def spans_created() -> int:
    """Process-lifetime count of real Span allocations — the witness for
    the zero-cost-when-disabled contract."""
    return _SPANS_CREATED


def root_span(name: str, **attrs) -> Span:
    """Start a request trace, or return ``NOOP`` in one branch when no
    tracer is installed / the head sample says no."""
    tr = _TRACER
    if tr is None:
        return NOOP
    return tr.start_trace(name, **attrs)


def tail_clock() -> float:
    """perf_counter() when a tracer with a tail rule is active, else 0.0
    — callers on the NOOP path feed the result to ``maybe_keep_slow``
    without paying the clock read when tracing is off."""
    tr = _TRACER
    if tr is None or tr.slow_threshold_s is None:
        return 0.0
    return time.perf_counter()


def maybe_keep_slow(name: str, t0: float, **attrs) -> None:
    """Tail rule for NOOP-path requests: ``t0`` from ``tail_clock()``
    (0.0 ⇒ tracing was off at request start — nothing to do)."""
    if t0 == 0.0:
        return
    tr = _TRACER
    if tr is None or tr.slow_threshold_s is None:
        return
    tr.keep_slow(name, time.perf_counter() - t0, **attrs)


# -- Context propagation ----------------------------------------------------


def ctx_with_span(ctx, span):
    """The span rides the request Context — but the NOOP span rides for
    free: the SAME context comes back (no child-context dict)."""
    if span is NOOP:
        return ctx
    return ctx.with_value(SPAN_KEY, span)


def span_of(ctx) -> Any:
    """The context's span, or ``NOOP``.  One branch when tracing is
    disabled (the context chain is not even walked)."""
    if _TRACER is None:
        return NOOP
    sp = ctx.value(SPAN_KEY)
    return sp if sp is not None else NOOP


# -- thread-local current span (deep sites without a Context) ---------------


def current() -> Any:
    """The span most recently activated via ``with span:`` on this
    thread, or ``NOOP``."""
    if _TRACER is None:
        return NOOP
    sp = getattr(_tls, "span", None)
    return sp if sp is not None else NOOP


def event_if_active(name: str, **attrs) -> None:
    """Attach an event to the thread's active span, if any — the hook
    for sites that never see a Context (closure advance, store write
    internals).  One load + branch when tracing is disabled."""
    if _TRACER is None:
        return
    sp = getattr(_tls, "span", None)
    if sp is not None:
        sp.event(name, **attrs)


# -- profiler correlation ---------------------------------------------------


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


def refresh_profiler() -> Optional[str]:
    """Re-read GOCHUGARU_TRACE_DIR (the profiler-session marker) into
    the cached module flag; returns the active dir or None."""
    global _PROFILER_DIR
    _PROFILER_DIR = os.environ.get("GOCHUGARU_TRACE_DIR") or None
    return _PROFILER_DIR


def profiler_active() -> bool:
    return _PROFILER_DIR is not None


def annotate_dispatch(span) -> Any:
    """A context manager for the kernel-execution window: when a
    GOCHUGARU_TRACE_DIR profiler session is active, a
    ``jax.profiler.TraceAnnotation`` named by the request's trace id
    (``gochugaru:<trace_id>``, or ``gochugaru:untraced`` for unsampled
    requests), so the harvested device trace carries request
    attribution.  Otherwise a shared null context — no allocation."""
    if _PROFILER_DIR is None:
        return _NULL_CTX
    import jax

    name = f"gochugaru:{span.trace_id}" if span is not NOOP else "gochugaru:untraced"
    return jax.profiler.TraceAnnotation(name)


class profiler_session:
    """Marks a profiler session active for this process (sets
    GOCHUGARU_TRACE_DIR and the cached flag) for the duration —
    ``bench_tpu_harvest --trace`` wraps its ``jax.profiler.trace``
    window in this so every dispatch inside is request-annotated."""

    def __init__(self, trace_dir: str) -> None:
        self.trace_dir = trace_dir
        self._prev: Optional[str] = None

    def __enter__(self) -> "profiler_session":
        global _PROFILER_DIR
        self._prev = os.environ.get("GOCHUGARU_TRACE_DIR")
        os.environ["GOCHUGARU_TRACE_DIR"] = self.trace_dir
        _PROFILER_DIR = self.trace_dir
        return self

    def __exit__(self, *exc) -> bool:
        global _PROFILER_DIR
        if self._prev is None:
            os.environ.pop("GOCHUGARU_TRACE_DIR", None)
        else:
            os.environ["GOCHUGARU_TRACE_DIR"] = self._prev
        _PROFILER_DIR = self._prev
        return False

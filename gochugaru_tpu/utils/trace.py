"""Request-scoped tracing: spans, head sampling with a keep-slow tail
rule, and profiler-correlated dispatch.

Every number this project shipped before this module was a
benchmark-harness aggregate; a serving system must answer "why was THIS
check slow" from the live process.  TpuGraphs (arXiv:2308.13490) shows
kernel/layout choices dominate TPU graph-workload cost — actionable only
when per-request spans line up with the device trace — and the Graphulo
measurement discipline (arXiv:1609.08642) the bench suite follows is
extended here to the always-on path.

Design constraints, in order (the same ordering utils/faults.py states):

1. **Zero cost when disabled.**  The span entry points sit on the
   latency dispatch path.  With no tracer installed, ``root_span``
   is one module-global load + branch returning the ``NOOP`` singleton;
   every method on ``NOOP`` is a no-op returning ``NOOP``; Context
   propagation (``ctx_with_span``) returns the SAME context — no dict
   churn, no allocation.  Tests assert the identity
   (``span is trace.NOOP``) and that ``spans_created()`` does not move.
2. **Head-based sampling, keep-slow tail rule.**  The keep/drop decision
   is made at trace START (``sample_rate``): unsampled requests run the
   NOOP path end-to-end.  The tail rule catches what head sampling
   misses: callers on the NOOP path report their measured duration via
   ``maybe_keep_slow``; a request slower than ``slow_threshold_s`` is
   recorded as a root-only trace flagged ``tail_kept`` — so "why was
   this check slow" always has an answer, even at a 1% sample rate.
   (A tail-kept trace has no child spans — the price of not paying span
   bookkeeping on the 99% — but carries the request attributes and
   duration; raise the sample rate to get full trees.)
3. **Bounded.**  Finished traces land in a ring (``capacity``); span
   events cap at ``MAX_EVENTS`` per span with a drop counter.  A
   long-lived serving process holds a bounded few hundred KB.

Spans form a tree: ``root_span`` starts a trace, ``span.child`` nests,
timestamps are ``time.perf_counter()`` so durations subtract exactly the
way the utils/metrics.py stage timers subtract — a stage span built from
the SAME t0/t1 the timer used agrees with the timer bit-for-bit.

Context propagation: the active span rides request Context values
(``Context.with_span`` / ``Context.span``, utils/context.py) across API
layers, and a thread-local "current span" (set by ``with span:``) lets
deep sites that never see a Context — the incremental closure advance,
the store write path — attach events via ``event_if_active`` without
plumbing a parameter through every signature.

Profiler correlation: when a profiler session is active (the
``GOCHUGARU_TRACE_DIR`` env var names its dump dir — tpu_watch.sh's
harvest step and ``bench_tpu_harvest --trace`` set it),
``annotate_dispatch(span)`` wraps dispatch in a
``jax.profiler.TraceAnnotation`` named by the trace id, so the XLA
device trace carries request attribution for free.

Flight recorder (this round): head sampling answers "why was THIS check
slow" but not "what was the system doing when the breaker tripped" — by
the time an anomaly fires, the interesting requests are the ones head
sampling already dropped.  ``FlightRecorder`` is a second, always-on
bounded ring: when a recorder is installed (``install_recorder``), every
request gets a REAL span tree even when the head sample says no
(``flight_only`` traces — retained in the recorder's ring at full
fidelity, never exported to ``/traces`` unless they trip the slow-tail
threshold), so the last N finished root spans are always available at
full fidelity regardless of the sample rate.  A **trigger bus** rides on
top: anomaly sites — SLO burn (utils/slo.py), a CircuitBreaker trip
(utils/admission.py), a shed-rate spike (``note_anomaly``), a pinned-path
recompile (engine/latency.py), a watch resume storm (client.py) — call
``trigger_incident(name)``, which freezes the ring and dumps an
**incident bundle** (the retained traces, a full typed metrics snapshot,
registered context providers like the admission cost model) as JSONL
under the incident dir, rate-limited per trigger.  utils/telemetry.py
serves the bundles at ``/debug/incidents``.  The disabled path is
unchanged: no tracer installed ⇒ every entry point is one load + branch,
recorder or not.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

#: events kept per span before dropping (the drop count is recorded on
#: the span as ``events_dropped``)
MAX_EVENTS = 128

#: Context value key the active span rides on (utils/context.py)
SPAN_KEY = "gochugaru.trace.span"

#: total real Span objects ever constructed in this process — the
#: zero-allocation contract's witness (tests assert it does not move
#: when sampling is off)
_SPANS_CREATED = 0

#: module-level fast path: None ⇒ every entry point is one load + branch
_TRACER: Optional["Tracer"] = None

#: the installed flight recorder (None ⇒ anomaly sites are one load +
#: branch; requests the head sample drops stay on the NOOP path)
_RECORDER: Optional["FlightRecorder"] = None

#: cached profiler-session dir (GOCHUGARU_TRACE_DIR), refreshed by
#: profiler_session()/refresh_profiler() — not re-read per dispatch
_PROFILER_DIR: Optional[str] = os.environ.get("GOCHUGARU_TRACE_DIR") or None

#: pid hex for trace ids, read ONCE — os.getpid() is a syscall per call
#: (~46 µs under this container's sandbox; it dominated the traced-path
#: profile).  Refreshed after fork so children don't reuse the parent's.
_PID_HEX = f"{os.getpid():x}"


def _refresh_pid() -> None:
    global _PID_HEX
    _PID_HEX = f"{os.getpid():x}"


if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_refresh_pid)

_tls = threading.local()


class _NoopSpan:
    """The disabled/unsampled span: every method is a no-op returning
    the singleton itself, so traced code needs no ``if span:`` guards
    and allocates nothing.  Identity (``span is NOOP``) is the
    zero-cost contract tests assert."""

    __slots__ = ()

    sampled = False
    trace_id = ""
    span_id = 0
    name = ""

    def child(self, name: str, t: Optional[float] = None, **attrs) -> "_NoopSpan":
        return self

    def child_at(self, name: str, t: float) -> "_NoopSpan":
        return self

    def event(self, name: str, t: Optional[float] = None, **attrs) -> "_NoopSpan":
        return self

    def set_attr(self, key: str, value: Any) -> "_NoopSpan":
        return self

    def end(self, t: Optional[float] = None) -> None:
        return None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def __bool__(self) -> bool:
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<NoopSpan>"


#: the singleton every disabled path returns
NOOP = _NoopSpan()


class Span:
    """One node of a sampled trace: name, parent link, monotonic start,
    attributes, bounded events.  ``end()`` freezes the duration and
    (for the root) hands the finished trace to the tracer's ring.

    Allocation discipline: a sampled dispatch constructs six of these
    and the marginal tail cost of tracing is GC pressure, not CPU — so
    ``attrs``/``events`` stay ``None`` until something is stored, the
    trace id renders lazily at export, and ``child_at`` takes no kwargs
    (a ``**attrs`` signature allocates a dict per call even when
    empty)."""

    __slots__ = (
        "_rec", "span_id", "parent_id", "name",
        "t0", "t1", "attrs", "events", "_dropped", "_tls_prev",
    )

    sampled = True

    def __init__(
        self,
        rec: "_TraceRec",
        name: str,
        parent_id: int,
        t: Optional[float] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        global _SPANS_CREATED
        _SPANS_CREATED += 1
        self._rec = rec
        # id allocation + registration inlined (single-writer per
        # request, so no lock): this constructor runs six times per
        # sampled dispatch and call overhead was the profile's top line
        self.span_id = rec._next_id
        rec._next_id += 1
        rec.spans.append(self)
        self.parent_id = parent_id
        self.name = name
        self.t0 = time.perf_counter() if t is None else t
        self.t1: Optional[float] = None
        self.attrs: Optional[Dict[str, Any]] = attrs
        self.events: Optional[List[Dict[str, Any]]] = None
        self._dropped = 0
        self._tls_prev: Any = None

    @property
    def trace_id(self) -> str:
        return self._rec.trace_id

    # -- tree --------------------------------------------------------------
    def child(self, name: str, t: Optional[float] = None, **attrs) -> "Span":
        """Start a child span.  ``t`` backdates the start (stage spans
        rebuilt from already-taken perf_counter timestamps)."""
        return Span(self._rec, name, self.span_id, t=t, attrs=attrs or None)

    def child_at(self, name: str, t: float) -> "Span":
        """Attribute-less child backdated to ``t`` — the stage-span fast
        path (no kwargs dict)."""
        return Span(self._rec, name, self.span_id, t=t)

    def event(self, name: str, t: Optional[float] = None, **attrs) -> "Span":
        """Attach a point-in-time event (bounded; drops are counted)."""
        evs = self.events
        if evs is None:
            evs = self.events = []
        elif len(evs) >= MAX_EVENTS:
            self._dropped += 1
            return self
        # raw float here; rounding happens once at export (as_dict) —
        # round() costs ~1 µs each under this container and events sit
        # on the request path
        ev: Dict[str, Any] = {
            "name": name,
            "t_s": (time.perf_counter() if t is None else t) - self._rec.t0,
        }
        if attrs:
            ev.update(attrs)
        evs.append(ev)
        return self

    def set_attr(self, key: str, value: Any) -> "Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    # -- lifecycle ---------------------------------------------------------
    def end(self, t: Optional[float] = None) -> None:
        if self.t1 is not None:
            return  # idempotent: `with` + explicit end must not double-finish
        self.t1 = time.perf_counter() if t is None else t
        if self._dropped:
            self.set_attr("events_dropped", self._dropped)
        if self.span_id == 0:
            self._rec.finish(self.t1)

    def __enter__(self) -> "Span":
        # thread-local activation: deep sites (closure advance, store
        # write internals) attach events via event_if_active without a
        # span parameter reaching them
        self._tls_prev = getattr(_tls, "span", None)
        _tls.span = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _tls.span = self._tls_prev
        if exc is not None and (self.attrs is None or "error" not in self.attrs):
            self.set_attr("error", type(exc).__name__)
        self.end()
        return False

    def duration_s(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def as_dict(self, default_t1: Optional[float] = None) -> Dict[str, Any]:
        """Render for export.  Runs at dump/scrape time, NOT on the
        request path — rounding lives here.  ``default_t1`` stands in
        for a child that was never explicitly ended (the root's end
        time, so an unclosed child can't grow until export)."""
        t1 = self.t1
        if t1 is None:
            t1 = default_t1 if default_t1 is not None else time.perf_counter()
        d: Dict[str, Any] = {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "t0_s": round(self.t0 - self._rec.t0, 9),
            "dur_s": round(t1 - self.t0, 9),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        if self.events:
            d["events"] = [
                {**ev, "t_s": round(ev["t_s"], 9)} for ev in self.events
            ]
        return d


class _TraceRec:
    """Book-keeping for one in-flight sampled trace (root + registered
    descendants).  Spans of one request may be touched from the request
    thread only — the same single-writer discipline a Context has — so
    the only lock here is the tracer ring's.

    The trace id string renders lazily (``trace_id``): the eager
    sequence number is one atomic ``next()`` and the string only exists
    when something reads it — export, or ``annotate_dispatch`` inside a
    profiler session.  The render is deterministic from (pid, seq,
    tracer salt), so concurrent readers agree without a lock."""

    __slots__ = ("tracer", "seq", "_tid", "name", "t0", "wall_t0", "spans",
                 "_next_id", "flight_only", "tail_kept")

    def __init__(self, tracer: "Tracer", name: str) -> None:
        self.tracer = tracer
        self.seq = next(tracer._seq)
        self._tid: Optional[str] = None
        self.name = name
        self.t0 = time.perf_counter()
        self.wall_t0 = time.time()
        self.spans: List[Span] = []
        self._next_id = 0
        #: True ⇒ the head sample said no and this trace exists only for
        #: the flight recorder's ring (never the /traces export ring,
        #: unless it trips the slow-tail threshold at finish)
        self.flight_only = False
        #: True ⇒ a flight-only trace that blew the slow threshold and
        #: exported anyway — rendered as ``tail_kept`` so /traces
        #: consumers filtering on the documented flag still see it
        self.tail_kept = False

    @property
    def trace_id(self) -> str:
        tid = self._tid
        if tid is None:
            tid = self._tid = _render_trace_id(self.tracer._salt, self.seq)
        return tid

    def finish(self, t1: float) -> None:
        self.tracer._record(self, t1)


def _render_trace_id(salt: int, seq: int) -> str:
    """pid-seq-mix: unique within a process lifetime via seq, unique
    across restarts via the tracer's per-construction random salt —
    deterministic given (salt, seq) so lazy rendering is race-free."""
    return f"{_PID_HEX}-{seq:08x}-{(seq * 0x9E3779B1 ^ salt) & 0xFFFFFFFF:08x}"


def render_finished(item) -> Dict[str, Any]:
    """One retained ring item → its export dict.  Items are either
    pre-rendered dicts (tail-kept root-only traces) or (rec, t1) live
    records; the SAME renderer serves the tracer's /traces ring and the
    flight recorder's incident bundles, so the two cannot disagree about
    what a trace looks like."""
    if isinstance(item, dict):
        return item
    rec, t1 = item
    d: Dict[str, Any] = {
        "trace_id": rec.trace_id,
        "name": rec.name,
        "start_unix_s": round(rec.wall_t0, 6),
        "duration_s": round(t1 - rec.t0, 9),
        "spans": [sp.as_dict(default_t1=t1) for sp in rec.spans],
    }
    if rec.flight_only:
        d["flight_only"] = True
    if rec.tail_kept:
        d["tail_kept"] = True
    return d


class Tracer:
    """Head-sampling tracer with a bounded ring of finished traces.

    ``sample_rate`` in [0, 1] is the head decision; ``slow_threshold_s``
    is the tail rule (``maybe_keep_slow``); ``capacity`` bounds the
    ring.  Counters ride the shared metrics registry:
    ``trace.started`` / ``trace.kept`` / ``trace.tail_kept`` /
    ``trace.unsampled``."""

    def __init__(
        self,
        sample_rate: float = 1.0,
        slow_threshold_s: Optional[float] = 0.100,
        capacity: int = 512,
        registry: Optional[_metrics.Metrics] = None,
        seed: Optional[int] = None,
    ) -> None:
        import itertools

        self.sample_rate = float(sample_rate)
        self.slow_threshold_s = slow_threshold_s
        self._m = registry or _metrics.default
        self._rng = random.Random(seed)
        self._salt = self._rng.getrandbits(32)
        self._seq = itertools.count(1)  # GIL-atomic next(); no hot-path lock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(capacity), 1))

    # -- trace start -------------------------------------------------------
    def start_trace(self, name: str, **attrs) -> Span:
        if self.sample_rate <= 0.0 or (
            self.sample_rate < 1.0 and self._rng.random() >= self.sample_rate
        ):
            self._m.inc("trace.unsampled")
            if _RECORDER is None:
                return NOOP
            # flight-recorder path: the head sample dropped this request
            # from the EXPORT ring, but the always-on recorder retains
            # the last N finished roots at full fidelity regardless —
            # so "what was happening when the breaker tripped" has an
            # answer even at a 0% sample rate
            rec = _TraceRec(self, name)
            rec.flight_only = True
            return Span(rec, name, parent_id=-1, t=rec.t0, attrs=attrs or None)
        self._m.inc("trace.started")
        rec = _TraceRec(self, name)
        return Span(rec, name, parent_id=-1, t=rec.t0, attrs=attrs or None)

    # -- tail rule ---------------------------------------------------------
    def keep_slow(self, name: str, duration_s: float, **attrs) -> bool:
        """Record a root-only trace for an unsampled-but-slow request.
        Returns True when kept (duration ≥ slow_threshold_s)."""
        thr = self.slow_threshold_s
        if thr is None or duration_s < thr:
            return False
        self._m.inc("trace.tail_kept")
        attrs["tail_kept"] = True
        item = {
            "trace_id": _render_trace_id(self._salt, next(self._seq)),
            "name": name,
            "start_unix_s": round(time.time() - duration_s, 6),
            "duration_s": round(duration_s, 9),
            "tail_kept": True,
            "spans": [{
                "span_id": 0, "parent_id": -1, "name": name,
                "t0_s": 0.0, "dur_s": round(duration_s, 9),
                "attrs": attrs,
            }],
        }
        with self._lock:
            self._ring.append(item)
        r = _RECORDER
        if r is not None:
            r.record(item)
        return True

    # -- retention ---------------------------------------------------------
    def _record(self, rec: _TraceRec, t1: float) -> None:
        """Root ended: retain the live record.  Rendering (span dicts,
        rounding) is deferred to ``traces()`` — a finished trace's spans
        never mutate again, so export-time rendering reads frozen data,
        and the request path pays one deque append (two with a flight
        recorder installed).  Flight-only traces stay out of the export
        ring — unless they blow the slow-tail threshold, in which case
        the FULL tree exports (strictly better than the root-only
        tail-kept record the NOOP path produces)."""
        r = _RECORDER
        if rec.flight_only:
            self._m.inc("trace.flight_kept")
            thr = self.slow_threshold_s
            if thr is not None and t1 - rec.t0 >= thr:
                self._m.inc("trace.tail_kept")
                rec.tail_kept = True
                with self._lock:
                    self._ring.append((rec, t1))
        else:
            self._m.inc("trace.kept")
            with self._lock:
                self._ring.append((rec, t1))
        if r is not None:
            r.record((rec, t1))

    # -- export ------------------------------------------------------------
    def traces(self) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._ring)
        return [render_finished(it) for it in items]

    def dump_jsonl(self, path: Optional[str] = None) -> str:
        """One JSON object per line per finished trace (newest last).
        With ``path``, also writes the dump there."""
        out = "\n".join(json.dumps(t) for t in self.traces())
        if out:
            out += "\n"
        if path is not None:
            with open(path, "w") as f:
                f.write(out)
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# ---------------------------------------------------------------------------
# Flight recorder: always-on retention + anomaly-triggered incident dumps
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded always-on ring of the last N finished root traces, plus
    the anomaly trigger bus that freezes it into incident bundles.

    Retention is fed by the installed tracer (``Tracer._record`` routes
    every finished root here, including the flight-only trees built for
    requests the head sample dropped).  ``trigger(name)`` captures an
    incident: the ring is snapshotted SYNCHRONOUSLY at trigger time (the
    "freeze" — under load, post-anomaly traffic would otherwise evict
    the very traces the trigger fired about), then rendering, the
    metrics dump, and the file write run on a short-lived daemon thread
    so no anomaly site ever blocks a request on disk I/O.  After a short
    ``grace_s`` the capture ALSO appends roots that finished since the
    freeze — usually the failing request itself, whose root span was
    still open when the breaker tripped mid-dispatch.

    Per-trigger cooldown rate-limits dump storms; ``max_incidents``
    bounds the files kept on disk; the last few bundles are additionally
    kept in memory so ``/debug/incidents`` serves them without a
    configured directory.

    ``note(kind)`` is the spike detector: anomaly sites that are normal
    in ones (a shed) but an incident in bursts call it per event, and a
    burst of ``spike_threshold`` within ``spike_window_s`` fires a
    ``<kind>.spike`` trigger.

    ``add_context(name, fn)`` registers extra state providers dumped
    into every bundle (the client wires the admission cost model and
    gate/breaker state here)."""

    def __init__(
        self,
        incident_dir: Optional[str] = None,
        capacity: int = 64,
        cooldown_s: float = 30.0,
        grace_s: float = 0.25,
        max_incidents: int = 32,
        keep_bundles: int = 4,
        spike_threshold: int = 32,
        spike_window_s: float = 1.0,
        registry: Optional[_metrics.Metrics] = None,
        clock=time.monotonic,
    ) -> None:
        import itertools

        #: bundles dump here (created lazily); None ⇒ in-memory only.
        #: GOCHUGARU_INCIDENT_DIR is the zero-plumbing default so bench
        #: children inside a tpu_watch.sh harvest window dump without
        #: any wiring of their own
        self.incident_dir = (
            incident_dir
            if incident_dir is not None
            else (os.environ.get("GOCHUGARU_INCIDENT_DIR") or None)
        )
        self.capacity = max(int(capacity), 1)
        self.cooldown_s = cooldown_s
        self.grace_s = grace_s
        self.max_incidents = max(int(max_incidents), 1)
        self.keep_bundles = max(int(keep_bundles), 1)
        self.spike_threshold = max(int(spike_threshold), 1)
        self.spike_window_s = spike_window_s
        self._m = registry or _metrics.default
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._last_fire: Dict[str, float] = {}
        self._notes: Dict[str, deque] = {}
        self._seq = itertools.count(1)
        self._context: Dict[str, Any] = {}
        self._pending: List[threading.Thread] = []
        self._paths: List[str] = []
        #: incident metadata, oldest first (mutated in place by the
        #: capture thread once the bundle lands)
        self.incidents: List[Dict[str, Any]] = []
        self._bundles: Dict[str, str] = {}
        self._bundle_order: List[str] = []

    # -- retention (called by the tracer per finished root) --------------
    def record(self, item) -> None:
        with self._lock:
            self._ring.append(item)

    def traces(self) -> List[Dict[str, Any]]:
        """Render the current ring (newest last) — debugging surface and
        the test hook; bundles render from a trigger-time snapshot."""
        with self._lock:
            items = list(self._ring)
        return [render_finished(it) for it in items]

    def add_context(self, name: str, fn) -> None:
        """Register a zero-arg provider whose result is dumped into every
        incident bundle under ``context.<name>`` (exceptions are caught
        and recorded — a broken provider must not lose the bundle)."""
        with self._lock:
            self._context[name] = fn

    def add_context_group(self, providers: Dict[str, Any], cap: int = 8) -> bool:
        """Register a RELATED set of providers atomically under
        collision-free keys: the first group gets the bare names, later
        groups a ``#N`` suffix (keyed off the first name's existing
        registrations on THIS recorder).  Returns False once ``cap``
        groups are registered — providers are never unregistered, so an
        unbounded registrant pattern (a client per job) must not grow
        the context or pin its registrants' state forever."""
        if not providers:
            return False
        with self._lock:
            first = next(iter(providers))
            n = sum(
                1 for k in self._context
                if k == first or k.startswith(first + "#")
            )
            if n >= cap:
                return False
            suffix = "" if n == 0 else f"#{n + 1}"
            for name, fn in providers.items():
                self._context[f"{name}{suffix}"] = fn
        return True

    # -- spike detection --------------------------------------------------
    def note(self, kind: str) -> Optional[str]:
        """One anomaly event of ``kind`` (e.g. a shed).  Fires a
        ``<kind>.spike`` trigger when ``spike_threshold`` events land
        within ``spike_window_s`` — events are normal in ones and an
        incident in bursts."""
        now = self._clock()
        with self._lock:
            dq = self._notes.get(kind)
            if dq is None:
                dq = self._notes[kind] = deque()
            dq.append(now)
            while dq and now - dq[0] > self.spike_window_s:
                dq.popleft()
            n = len(dq)
            if n < self.spike_threshold:
                return None
            dq.clear()  # one spike per burst; cooldown guards refires
        return self.trigger(
            f"{kind}.spike", count=n, window_s=self.spike_window_s
        )

    # -- the trigger bus ---------------------------------------------------
    def trigger(self, name: str, **info) -> Optional[str]:
        """Fire one anomaly trigger: freeze the ring and capture an
        incident bundle (on a daemon thread).  Returns the incident id,
        or None when the per-trigger cooldown suppressed it."""
        now = self._clock()
        with self._lock:
            last = self._last_fire.get(name)
            if last is not None and now - last < self.cooldown_s:
                self._m.inc("incidents.suppressed")
                return None
            self._last_fire[name] = now
            seq = next(self._seq)
        self._m.inc("incidents.triggered")
        self._m.inc(f"incidents.triggered.{name}")
        iid = f"{int(time.time() * 1000):013d}-{seq:03d}-{name}"
        meta: Dict[str, Any] = {
            "id": iid,
            "trigger": name,
            "unix_s": round(time.time(), 6),
            "info": info,
            "state": "capturing",
        }
        # the FREEZE is synchronous: snapshot the ring NOW, at the
        # moment of the anomaly — under load, waiting even the short
        # capture grace would let post-anomaly traffic evict the very
        # traces the trigger fired about (the capture thread appends
        # roots that finish DURING the grace on top of this snapshot)
        with self._lock:
            frozen = list(self._ring)
        t = threading.Thread(
            target=self._capture, args=(meta, frozen),
            name="gochugaru-incident", daemon=True,
        )
        with self._lock:
            self.incidents.append(meta)
            del self.incidents[: -4 * self.max_incidents]
            # prune only threads that RAN and finished: a created-but-
            # not-yet-started thread (ident is None) reports not-alive
            # too, and dropping it here would let flush() return before
            # a concurrent trigger's capture ever starts
            self._pending = [
                x for x in self._pending
                if x.is_alive() or x.ident is None
            ]
            self._pending.append(t)
        t.start()
        return iid

    def flush(self, timeout: float = 10.0) -> None:
        """Wait for in-flight capture threads (tests and drain paths).
        Polls rather than bare-joining: a concurrent trigger may hold a
        created-but-not-yet-started thread (join would raise), and new
        captures may start while we wait."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                live = [
                    x for x in self._pending
                    if x.is_alive() or x.ident is None
                ]
            if not live:
                return
            for t in live:
                if t.ident is not None:
                    t.join(timeout=max(
                        0.0, min(0.25, deadline - time.monotonic())
                    ))
            time.sleep(0.002)

    # -- capture -----------------------------------------------------------
    def _capture(self, meta: Dict[str, Any], frozen: list) -> None:
        try:
            if self.grace_s > 0:
                # let roots in flight AT the trigger (usually the failing
                # request itself — a breaker trips mid-dispatch, before
                # its root span ends) finish into the ring
                time.sleep(self.grace_s)
            with self._lock:
                ring_now = list(self._ring)
                providers = list(self._context.items())
            # trigger-time snapshot PLUS roots that finished during the
            # grace — the frozen traces can never be displaced by
            # post-anomaly traffic, however hot the ring runs
            seen = {id(it) for it in frozen}
            items = frozen + [it for it in ring_now if id(it) not in seen]
            traces = [render_finished(it) for it in items]
            counters, gauges, timers = self._m.typed_snapshot()
            hists = self._m.hist_snapshot()
            context: Dict[str, Any] = {}
            for k, fn in providers:
                try:
                    context[k] = fn()
                except Exception as e:  # a broken provider loses itself only
                    context[k] = {"provider_error": type(e).__name__}
            # decision provenance: every bundle carries the last-N
            # authorization DECISIONS (utils/decisions.py) — "what was
            # being decided when the breaker tripped / the denial-rate
            # SLO burned" ships inside the bundle, not in a separate
            # store an operator has to correlate by timestamp
            decisions = None
            try:
                from . import decisions as _decisions

                dlog = _decisions.get()
                if dlog is not None:
                    decisions = dlog.tail(32)
            except Exception:  # provenance must never lose the bundle
                decisions = None
            head = {
                "kind": "incident",
                "id": meta["id"],
                "trigger": meta["trigger"],
                "unix_s": meta["unix_s"],
                "info": meta["info"],
                "trace_ids": [t.get("trace_id") for t in traces],
                # the headline process state an operator reads first —
                # all re-dumped in full inside the metrics line below
                "breaker_state": gauges.get("breaker.state"),
                "admission_inflight": gauges.get("admission.inflight"),
                "serve_queue_depth": gauges.get("serve.queue_depth"),
                "device_bytes": gauges.get("snapshot.device_bytes"),
                "context": context,
            }
            if decisions is not None:
                head["decisions"] = decisions
            # default=repr: a provider returning a numpy scalar (or a
            # span attr holding one) must degrade to its repr, not lose
            # the whole bundle to a TypeError mid-capture
            lines = [json.dumps(head, default=repr)]
            for tr in traces:
                lines.append(json.dumps({"kind": "trace", **tr},
                                        default=repr))
            # timers dump as count/total + the shared quantiles, not raw
            # rings — a bundle is a diagnosis artifact, not a data lake
            tdump = {}
            for k, (n, total, samples) in timers.items():
                row = {"count": n, "total_s": round(total, 9)}
                if samples:
                    for q in _metrics.SNAPSHOT_QUANTILES:
                        row[_metrics.quantile_suffix(q)] = round(
                            _metrics.nearest_rank(samples, q), 9
                        )
                tdump[k] = row
            lines.append(json.dumps({
                "kind": "metrics",
                "counters": counters,
                "gauges": gauges,
                "timers": tdump,
            }, default=repr))
            if hists:
                lines.append(json.dumps({
                    "kind": "hists",
                    "hists": {
                        k: {
                            "buckets": list(bs), "counts": counts,
                            "count": n, "sum": round(total, 9),
                            "exemplars": ex,
                        }
                        for k, (bs, counts, n, total, ex) in hists.items()
                    },
                }, default=repr))
            bundle = "\n".join(lines) + "\n"
            path = None
            if self.incident_dir:
                try:
                    os.makedirs(self.incident_dir, exist_ok=True)
                    path = os.path.join(
                        self.incident_dir, f"incident_{meta['id']}.jsonl"
                    )
                    with open(path, "w") as f:
                        f.write(bundle)
                except OSError as e:
                    meta["write_error"] = type(e).__name__
                    path = None
            evict: List[str] = []
            with self._lock:
                meta.update(
                    state="captured", path=path, traces=len(traces),
                    trace_ids=head["trace_ids"],
                )
                self._bundles[meta["id"]] = bundle
                self._bundle_order.append(meta["id"])
                while len(self._bundle_order) > self.keep_bundles:
                    self._bundles.pop(self._bundle_order.pop(0), None)
                if path is not None:
                    self._paths.append(path)
                    while len(self._paths) > self.max_incidents:
                        evict.append(self._paths.pop(0))
            # unlink OUTSIDE the lock: record() contends on it from
            # every finished root span, and a slow filesystem must not
            # stall request threads in span end() behind an os.remove
            for old in evict:
                try:
                    os.remove(old)
                except OSError:
                    pass
            self._m.inc("incidents.captured")
        except Exception as e:  # pragma: no cover - capture must not raise
            meta["state"] = f"failed:{type(e).__name__}"
            self._m.inc("incidents.capture_errors")

    # -- read side (telemetry /debug/incidents) ---------------------------
    def incident_index(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(m) for m in self.incidents]

    def bundle(self, iid: str) -> Optional[str]:
        """The JSONL bundle for an incident id: in-memory when still
        retained, else re-read from its file."""
        with self._lock:
            b = self._bundles.get(iid)
            path = next(
                (m.get("path") for m in self.incidents if m["id"] == iid),
                None,
            )
        if b is not None:
            return b
        if path:
            try:
                with open(path) as f:
                    return f.read()
            except OSError:
                return None
        return None


# ---------------------------------------------------------------------------
# Module-level surface (the hot-path entry points)
# ---------------------------------------------------------------------------


def configure(
    sample_rate: float = 1.0,
    slow_threshold_s: Optional[float] = 0.100,
    capacity: int = 512,
    registry: Optional[_metrics.Metrics] = None,
    seed: Optional[int] = None,
) -> Tracer:
    """Install (and return) the process-global tracer.  ``sample_rate``
    is the head decision; ``slow_threshold_s=None`` disables the tail
    rule."""
    global _TRACER
    _TRACER = Tracer(
        sample_rate=sample_rate, slow_threshold_s=slow_threshold_s,
        capacity=capacity, registry=registry, seed=seed,
    )
    return _TRACER


def disable() -> None:
    """Remove the global tracer AND the flight recorder: every entry
    point returns to the one-branch NOOP path (a recorder without a
    tracer would retain nothing anyway — flight-only spans are built by
    the tracer)."""
    global _TRACER, _RECORDER
    _TRACER = None
    _RECORDER = None


def install_recorder(rec: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Install (``None`` uninstalls) the process-global flight recorder.
    Requires an installed tracer to retain traces — ``with_telemetry``
    (client.py) installs a 0%-head-sample tracer when none exists, so
    flight recording costs span bookkeeping but exports nothing to
    ``/traces`` except slow-tail trees."""
    global _RECORDER
    _RECORDER = rec
    return rec


def recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def trigger_incident(name: str, **info) -> Optional[str]:
    """Anomaly sites call this: one load + branch when no recorder is
    installed, else fire the named trigger (rate-limited per name by the
    recorder's cooldown).  Returns the incident id when one captures."""
    r = _RECORDER
    if r is None:
        return None
    return r.trigger(name, **info)


def note_anomaly(kind: str) -> None:
    """Windowed anomaly event (e.g. one shed): one load + branch when no
    recorder is installed, else feeds the recorder's spike detector —
    a burst fires a ``<kind>.spike`` incident."""
    r = _RECORDER
    if r is not None:
        r.note(kind)


def install(tracer: Optional[Tracer]) -> None:
    """Install an existing tracer (or ``None`` to disable) without
    constructing a new one — the overhead harness flips one tracer
    in and out per rep and must not allocate while doing so."""
    global _TRACER
    _TRACER = tracer


def get() -> Optional[Tracer]:
    return _TRACER


def enabled() -> bool:
    return _TRACER is not None


def spans_created() -> int:
    """Process-lifetime count of real Span allocations — the witness for
    the zero-cost-when-disabled contract."""
    return _SPANS_CREATED


def root_span(name: str, **attrs) -> Span:
    """Start a request trace, or return ``NOOP`` in one branch when no
    tracer is installed / the head sample says no."""
    tr = _TRACER
    if tr is None:
        return NOOP
    return tr.start_trace(name, **attrs)


def tail_clock() -> float:
    """perf_counter() when a tracer with a tail rule is active, else 0.0
    — callers on the NOOP path feed the result to ``maybe_keep_slow``
    without paying the clock read when tracing is off."""
    tr = _TRACER
    if tr is None or tr.slow_threshold_s is None:
        return 0.0
    return time.perf_counter()


def maybe_keep_slow(name: str, t0: float, **attrs) -> None:
    """Tail rule for NOOP-path requests: ``t0`` from ``tail_clock()``
    (0.0 ⇒ tracing was off at request start — nothing to do)."""
    if t0 == 0.0:
        return
    tr = _TRACER
    if tr is None or tr.slow_threshold_s is None:
        return
    tr.keep_slow(name, time.perf_counter() - t0, **attrs)


# -- Context propagation ----------------------------------------------------


def ctx_with_span(ctx, span):
    """The span rides the request Context — but the NOOP span rides for
    free: the SAME context comes back (no child-context dict)."""
    if span is NOOP:
        return ctx
    return ctx.with_value(SPAN_KEY, span)


def span_of(ctx) -> Any:
    """The context's span, or ``NOOP``.  One branch when tracing is
    disabled (the context chain is not even walked)."""
    if _TRACER is None:
        return NOOP
    sp = ctx.value(SPAN_KEY)
    return sp if sp is not None else NOOP


# -- thread-local current span (deep sites without a Context) ---------------


def current() -> Any:
    """The span most recently activated via ``with span:`` on this
    thread, or ``NOOP``."""
    if _TRACER is None:
        return NOOP
    sp = getattr(_tls, "span", None)
    return sp if sp is not None else NOOP


def event_if_active(name: str, **attrs) -> None:
    """Attach an event to the thread's active span, if any — the hook
    for sites that never see a Context (closure advance, store write
    internals).  One load + branch when tracing is disabled."""
    if _TRACER is None:
        return
    sp = getattr(_tls, "span", None)
    if sp is not None:
        sp.event(name, **attrs)


# -- profiler correlation ---------------------------------------------------


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


def refresh_profiler() -> Optional[str]:
    """Re-read GOCHUGARU_TRACE_DIR (the profiler-session marker) into
    the cached module flag; returns the active dir or None."""
    global _PROFILER_DIR
    _PROFILER_DIR = os.environ.get("GOCHUGARU_TRACE_DIR") or None
    return _PROFILER_DIR


def profiler_active() -> bool:
    return _PROFILER_DIR is not None


def annotate_dispatch(span) -> Any:
    """A context manager for the kernel-execution window: when a
    GOCHUGARU_TRACE_DIR profiler session is active, a
    ``jax.profiler.TraceAnnotation`` named by the request's trace id
    (``gochugaru:<trace_id>``, or ``gochugaru:untraced`` for unsampled
    requests), so the harvested device trace carries request
    attribution.  Otherwise a shared null context — no allocation."""
    if _PROFILER_DIR is None:
        return _NULL_CTX
    import jax

    name = f"gochugaru:{span.trace_id}" if span is not NOOP else "gochugaru:untraced"
    return jax.profiler.TraceAnnotation(name)


class profiler_session:
    """Marks a profiler session active for this process (sets
    GOCHUGARU_TRACE_DIR and the cached flag) for the duration —
    ``bench_tpu_harvest --trace`` wraps its ``jax.profiler.trace``
    window in this so every dispatch inside is request-annotated."""

    def __init__(self, trace_dir: str) -> None:
        self.trace_dir = trace_dir
        self._prev: Optional[str] = None

    def __enter__(self) -> "profiler_session":
        global _PROFILER_DIR
        self._prev = os.environ.get("GOCHUGARU_TRACE_DIR")
        os.environ["GOCHUGARU_TRACE_DIR"] = self.trace_dir
        _PROFILER_DIR = self.trace_dir
        return self

    def __exit__(self, *exc) -> bool:
        global _PROFILER_DIR
        if self._prev is None:
            os.environ.pop("GOCHUGARU_TRACE_DIR", None)
        else:
            os.environ["GOCHUGARU_TRACE_DIR"] = self._prev
        _PROFILER_DIR = self._prev
        return False

"""Cross-cutting utilities: Context, error taxonomy, retry, metrics."""

from .context import Context, background, todo
from .errors import (
    DeadlineExceededError,
    PermanentError,
    PreconditionFailedError,
    AlreadyExistsError,
    RevisionUnavailableError,
    UnavailableError,
)
from .retry import retry_retriable_errors

__all__ = [
    "Context",
    "background",
    "todo",
    "UnavailableError",
    "DeadlineExceededError",
    "PermanentError",
    "PreconditionFailedError",
    "AlreadyExistsError",
    "RevisionUnavailableError",
    "retry_retriable_errors",
]

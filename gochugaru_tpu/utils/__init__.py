"""Cross-cutting utilities: Context, error taxonomy, retry, metrics,
fault injection, admission control."""

from .context import Context, background, todo
from .errors import (
    DeadlineExceededError,
    PermanentError,
    PreconditionFailedError,
    AlreadyExistsError,
    RevisionUnavailableError,
    ShedError,
    UnavailableError,
    classify_dispatch_exception,
)
from .retry import retry_retriable_errors

__all__ = [
    "Context",
    "background",
    "todo",
    "UnavailableError",
    "ShedError",
    "DeadlineExceededError",
    "PermanentError",
    "PreconditionFailedError",
    "AlreadyExistsError",
    "RevisionUnavailableError",
    "classify_dispatch_exception",
    "retry_retriable_errors",
]

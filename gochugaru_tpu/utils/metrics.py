"""First-class counters and timers.

The reference has no observability at all (SURVEY.md §5); the north-star
metric here demands measurement, so the client and engine publish counters
(checks dispatched, batch occupancy, closure/BFS overflow fallbacks, device
dispatch time) through this registry.  ``jax.profiler`` remains the deep
tool; these are the cheap always-on numbers.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._timings: Dict[str, list] = defaultdict(lambda: [0, 0.0])  # [n, total_s]

    def inc(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += delta

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            t = self._timings[name]
            t[0] += 1
            t[1] += seconds

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            for k, (n, total) in self._timings.items():
                out[f"{k}.count"] = n
                out[f"{k}.total_s"] = total
                if n:
                    out[f"{k}.mean_s"] = total / n
            return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timings.clear()


#: Process-global default registry.
default = Metrics()

"""First-class counters and timers.

The reference has no observability at all (SURVEY.md §5); the north-star
metric here demands measurement, so the client and engine publish counters
(checks dispatched, batch occupancy, closure/BFS overflow fallbacks, device
dispatch time) through this registry.  ``jax.profiler`` remains the deep
tool; these are the cheap always-on numbers.

Timers keep a bounded ring of raw samples alongside the running
count/total, so tail latency is a first-class readout: ``percentile``
answers "what is my p99 right now" from the live process, and
``snapshot`` publishes ``.p50_s``/``.p90_s``/``.p99_s``/``.p999_s`` per
timer (one shared nearest-rank definition, one sorted pass).  The
telemetry exporter (utils/telemetry.py) renders the same registry as
Prometheus text, and utils/trace.py adds request-scoped spans on top —
counters stay the cheap always-on layer underneath.  The north-star
metric is a p99, and a mean cannot stand in for it — the latency-mode
dispatch path (engine/latency.py) publishes its per-stage budget through
these samples.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

#: the percentiles ``snapshot`` publishes per timer (one sorted pass)
SNAPSHOT_QUANTILES = (50.0, 90.0, 99.0, 99.9)


def nearest_rank(sorted_samples, q: float) -> float:
    """Nearest-rank percentile over an ascending-sorted sequence — the
    ONE definition ``percentile``, ``snapshot`` and the telemetry
    exporter (utils/telemetry.py) all share, so their p99s cannot
    disagree.  ``q`` in [0, 100]; no numpy dependency here."""
    n = len(sorted_samples)
    i = min(n - 1, max(0, int(round(q / 100.0 * (n - 1)))))
    return sorted_samples[i]


def quantile_suffix(q: float) -> str:
    """'p50_s'/'p90_s'/'p99_s'/'p999_s'-style key suffix for a [0,100]
    percentile (99.9 → 'p999_s')."""
    return "p" + format(q, "g").replace(".", "") + "_s"


class Metrics:
    #: per-timer sample-ring capacity: enough that a p99 is the ~20th
    #: worst sample (not the max of a handful), small enough that a
    #: long-lived serving process holds a few KB per timer
    SAMPLE_CAP = 2048

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._timings: Dict[str, list] = defaultdict(lambda: [0, 0.0])  # [n, total_s]
        self._samples: Dict[str, list] = defaultdict(list)  # ring of raw seconds
        #: explicit per-ring write cursor.  NOT derived from the timing
        #: count: an in-flight timer racing ``reset()`` recreates the
        #: ``_timings`` entry out of step with ``_samples`` (count says
        #: "overwrite slot n" while the ring is empty again) — the
        #: cursor lives and dies with its ring, so the two cannot skew
        self._scursor: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, float] = {}  # last-set values (breaker state)
        #: fixed-bucket histograms: name → [ascending bucket uppers,
        #: per-bucket counts (len+1, last = overflow), count, sum,
        #: per-bucket exemplars (len+1, last trace that landed in the
        #: bucket, or None)].  Buckets freeze at first observe — a
        #: histogram whose buckets drift mid-run cannot be merged or
        #: compared
        self._hists: Dict[str, list] = {}
        #: per-timer over-objective thresholds (utils/slo.py): observe()
        #: counts samples above the threshold into ``_over`` so an SLO
        #: burn rate is computed from EXACT per-window counts, not a
        #: quantile estimate over an unstamped ring
        self._thr: Dict[str, float] = {}
        self._over: Dict[str, int] = defaultdict(int)

    def inc(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += delta

    def set_gauge(self, name: str, value: float) -> None:
        """Last-write-wins instantaneous value (e.g. ``breaker.state``:
        0=closed, 1=half-open, 2=open; ``admission.inflight``)."""
        with self._lock:
            self._gauges[name] = value

    def gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def clear_gauges(self, prefix: str) -> None:
        """Drop every gauge under ``prefix`` (per-snapshot breakdowns
        republished wholesale each prepare — stale keys would survive a
        table being dropped from the snapshot)."""
        with self._lock:
            for k in [k for k in self._gauges if k.startswith(prefix)]:
                del self._gauges[k]

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            t = self._timings[name]
            t[0] += 1
            t[1] += seconds
            s = self._samples[name]
            if len(s) < self.SAMPLE_CAP:
                s.append(seconds)
            else:
                cur = self._scursor[name]
                s[cur] = seconds
                self._scursor[name] = (cur + 1) % self.SAMPLE_CAP
            thr = self._thr.get(name)
            if thr is not None and seconds > thr:
                self._over[name] += 1

    def set_timer_threshold(self, name: str, seconds: Optional[float]) -> None:
        """Arm (or with ``None`` disarm) over-objective counting for a
        timer: every ``observe(name, s)`` with ``s > seconds`` also bumps
        the timer's over-counter.  The SLO engine (utils/slo.py) reads
        (count, over) pairs per tick, so a latency burn rate is exact —
        "of the N requests observed this window, M blew the objective" —
        instead of estimated from the sample ring."""
        with self._lock:
            if seconds is None:
                self._thr.pop(name, None)
            else:
                self._thr[name] = float(seconds)

    def timer_counts(self, name: str) -> Tuple[int, int]:
        """(total observations, over-threshold observations) for a timer
        — both cumulative, both monotone, the SLO engine's raw feed."""
        with self._lock:
            return self._timings[name][0] if name in self._timings else 0, \
                self._over.get(name, 0)

    def observe_hist(
        self,
        name: str,
        value: float,
        buckets: Tuple[float, ...],
        trace_id: Optional[str] = None,
    ) -> None:
        """Count ``value`` into a fixed-bucket histogram (bucket uppers
        are inclusive, Prometheus ``le`` semantics; values past the last
        bucket land in the +Inf overflow slot).  The serving batcher's
        batch-occupancy distribution is the motivating consumer — a
        p99 summary can't show bimodality (half the batches full, half
        nearly empty averages to a lie), a histogram can.

        ``trace_id`` records an EXEMPLAR: the last trace that landed in
        the bucket, rendered by the telemetry exporter as an OpenMetrics
        exemplar — so a fat tail bucket links directly to a recorded
        trace instead of to a guess."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                bs = tuple(sorted(float(b) for b in buckets))
                h = self._hists[name] = [
                    bs, [0] * (len(bs) + 1), 0, 0.0, [None] * (len(bs) + 1)
                ]
            bs, counts = h[0], h[1]
            i = len(bs)
            for j, b in enumerate(bs):
                if value <= b:
                    i = j
                    break
            counts[i] += 1
            h[2] += 1
            h[3] += value
            if trace_id is not None:
                h[4][i] = (trace_id, float(value), time.time())

    def hist_snapshot(
        self,
    ) -> Dict[str, Tuple[Tuple[float, ...], List[int], int, float, list]]:
        """name → (bucket uppers, per-bucket counts incl. +Inf overflow,
        total count, sum, per-bucket exemplars) — the telemetry exporter
        renders these as Prometheus ``histogram`` series with cumulative
        ``le`` labels (exemplars attach in OpenMetrics mode).  Each
        exemplar is (trace_id, observed value, unix seconds) or None."""
        with self._lock:
            return {
                k: (h[0], list(h[1]), h[2], h[3], list(h[4]))
                for k, h in self._hists.items()
            }

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def counters_prefixed(self, prefix: str) -> Dict[str, float]:
        """Every counter under ``prefix`` — the tagged-family accessor
        (per-strategy verdict counters ``check.verdicts.*``, decision
        drop counters ``decisions.*``) for endpoints and tests that want
        one family without a full snapshot."""
        with self._lock:
            return {
                k: v for k, v in self._counters.items()
                if k.startswith(prefix)
            }

    def percentile(self, name: str, q: float) -> Optional[float]:
        """The q-th percentile (seconds) over the timer's sample ring, or
        None when the timer has no samples.  Honest within the ring: at
        ≥ SAMPLE_CAP observations it is the p-of-the-last-SAMPLE_CAP, a
        sliding window — exactly what a serving SLO wants."""
        with self._lock:
            s = self._samples.get(name)
            if not s:
                return None
            s = list(s)  # sort outside the lock observe() contends on
        return nearest_rank(sorted(s), q)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            samples = {k: list(v) for k, v in self._samples.items() if v}
            for k, (n, total) in self._timings.items():
                out[f"{k}.count"] = n
                out[f"{k}.total_s"] = total
                if n:
                    out[f"{k}.mean_s"] = total / n
            for k, h in self._hists.items():
                cum = 0
                for b, c in zip(h[0], h[1]):
                    cum += c
                    out[f"{k}.le_{format(b, 'g')}"] = cum
                out[f"{k}.count"] = h[2]
                out[f"{k}.sum"] = h[3]
        for k, s in samples.items():
            # one sorted pass per timer, every published quantile off it;
            # sorting happens outside the lock the latency path's
            # observe() contends on, off a ring copy
            s = sorted(s)
            for q in SNAPSHOT_QUANTILES:
                out[f"{k}.{quantile_suffix(q)}"] = nearest_rank(s, q)
        return out

    def typed_snapshot(
        self,
    ) -> Tuple[Dict[str, float], Dict[str, float], Dict[str, Tuple[int, float, List[float]]]]:
        """(counters, gauges, timers) with types preserved — the
        telemetry exporter needs to know a counter from a gauge from a
        timer to emit correct Prometheus TYPE lines.  Timers map to
        (count, total_s, ascending-sorted sample ring)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers = {
                k: (n, total, list(self._samples.get(k, ())))
                for k, (n, total) in self._timings.items()
            }
        # sort the ring copies AFTER releasing the lock: a /metrics
        # scrape sorting every 2048-sample ring must not stall the
        # latency path's observe() behind the registry lock
        return counters, gauges, {
            k: (n, total, sorted(s)) for k, (n, total, s) in timers.items()
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timings.clear()
            self._samples.clear()
            self._scursor.clear()
            self._gauges.clear()
            self._hists.clear()
            # thresholds are CONFIG (armed by the SLO engine) and survive
            # a reset; the over-counters are data and do not
            self._over.clear()


#: Process-global default registry.
default = Metrics()


def peak_rss_mb() -> float:
    """Process peak resident set size in MiB: the max of
    ``getrusage(RUSAGE_SELF).ru_maxrss`` (KiB on Linux) and
    ``/proc/self/status`` VmHWM.  The host-sharded build's memory claim
    is a MEASURED per-process number (benchmarks emit it as a
    ``peak_rss_mb`` column; parallel/multihost.py's RSS dryrun compares
    it across process counts) — a high-water mark, so capture readings
    at phase boundaries and difference them."""
    import resource

    peak_kib = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    peak_kib = max(peak_kib, float(line.split()[1]))
                    break
    except OSError:
        pass
    return round(peak_kib / 1024.0, 1)

"""First-class counters and timers.

The reference has no observability at all (SURVEY.md §5); the north-star
metric here demands measurement, so the client and engine publish counters
(checks dispatched, batch occupancy, closure/BFS overflow fallbacks, device
dispatch time) through this registry.  ``jax.profiler`` remains the deep
tool; these are the cheap always-on numbers.

Timers keep a bounded ring of raw samples alongside the running
count/total, so tail latency is a first-class readout: ``percentile``
answers "what is my p99 right now" from the live process, and
``snapshot`` publishes ``.p50_s``/``.p99_s`` per timer.  The north-star
metric is a p99, and a mean cannot stand in for it — the latency-mode
dispatch path (engine/latency.py) publishes its per-stage budget through
these samples.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional


class Metrics:
    #: per-timer sample-ring capacity: enough that a p99 is the ~20th
    #: worst sample (not the max of a handful), small enough that a
    #: long-lived serving process holds a few KB per timer
    SAMPLE_CAP = 2048

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._timings: Dict[str, list] = defaultdict(lambda: [0, 0.0])  # [n, total_s]
        self._samples: Dict[str, list] = defaultdict(list)  # ring of raw seconds
        self._gauges: Dict[str, float] = {}  # last-set values (breaker state)

    def inc(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += delta

    def set_gauge(self, name: str, value: float) -> None:
        """Last-write-wins instantaneous value (e.g. ``breaker.state``:
        0=closed, 1=half-open, 2=open; ``admission.inflight``)."""
        with self._lock:
            self._gauges[name] = value

    def gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            t = self._timings[name]
            t[0] += 1
            t[1] += seconds
            s = self._samples[name]
            if len(s) < self.SAMPLE_CAP:
                s.append(seconds)
            else:
                s[(t[0] - 1) % self.SAMPLE_CAP] = seconds

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def percentile(self, name: str, q: float) -> Optional[float]:
        """The q-th percentile (seconds) over the timer's sample ring, or
        None when the timer has no samples.  Honest within the ring: at
        ≥ SAMPLE_CAP observations it is the p-of-the-last-SAMPLE_CAP, a
        sliding window — exactly what a serving SLO wants."""
        with self._lock:
            s = self._samples.get(name)
            if not s:
                return None
            s = sorted(s)
        # nearest-rank on the sorted ring: no numpy dependency here
        i = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
        return s[i]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            samples = {k: sorted(v) for k, v in self._samples.items() if v}
            for k, (n, total) in self._timings.items():
                out[f"{k}.count"] = n
                out[f"{k}.total_s"] = total
                if n:
                    out[f"{k}.mean_s"] = total / n
        for k, s in samples.items():
            out[f"{k}.p50_s"] = s[int(round(0.50 * (len(s) - 1)))]
            out[f"{k}.p99_s"] = s[int(round(0.99 * (len(s) - 1)))]
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timings.clear()
            self._samples.clear()
            self._gauges.clear()


#: Process-global default registry.
default = Metrics()


def peak_rss_mb() -> float:
    """Process peak resident set size in MiB: the max of
    ``getrusage(RUSAGE_SELF).ru_maxrss`` (KiB on Linux) and
    ``/proc/self/status`` VmHWM.  The host-sharded build's memory claim
    is a MEASURED per-process number (benchmarks emit it as a
    ``peak_rss_mb`` column; parallel/multihost.py's RSS dryrun compares
    it across process counts) — a high-water mark, so capture readings
    at phase boundaries and difference them."""
    import resource

    peak_kib = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    peak_kib = max(peak_kib, float(line.split()[1]))
                    break
    except OSError:
        pass
    return round(peak_kib / 1024.0, 1)

"""Error taxonomy.

The reference classifies errors for its retry policy into retriable (gRPC
Unavailable / DeadlineExceeded, "retryable error", "try restarting
transaction", context deadline) and permanent (client/client.go:193-211).
Device-local evaluation maps the same classes: transient device conditions
(OOM-retryable dispatch, snapshot being swapped) → Unavailable; everything
else is permanent.
"""

from __future__ import annotations


class AuthzError(Exception):
    """Base class for framework errors."""


class UnavailableError(AuthzError):
    """Transient: the evaluator/snapshot is temporarily unavailable
    (the local analogue of gRPC ``codes.Unavailable``)."""


class ShedError(UnavailableError):
    """Admission control refused the request before dispatch (bounded
    in-flight gate full, or the deadline budget cannot cover a dispatch).
    A subclass of ``UnavailableError`` ON PURPOSE: a shed engages the
    existing retry/backoff envelope — load-shedding converts queue growth
    into client-side backoff instead of unbounded buffering, the same
    move gRPC servers make by returning ``codes.Unavailable`` under
    overload."""


class DeadlineExceededError(AuthzError):
    """The context deadline passed (gRPC ``codes.DeadlineExceeded``)."""


class CancelledError(AuthzError):
    """The context was cancelled."""


class PermanentError(AuthzError):
    """Wrapper marking an error as not retriable (backoff.Permanent,
    client/client.go:202)."""


class PreconditionFailedError(AuthzError):
    """A write/delete precondition (MustMatch/MustNotMatch) failed
    (rel/txn.go:15-29 semantics)."""

    def __init__(self, message: str = "precondition failed") -> None:
        super().__init__(message)


class AlreadyExistsError(AuthzError):
    """CREATE of a relationship that already exists (the local analogue of
    gRPC ``codes.AlreadyExists``, client/client.go:450)."""


class RevisionUnavailableError(AuthzError):
    """A Snapshot()/AtLeast() revision that is unknown or has been garbage
    collected."""


class SchemaError(AuthzError):
    """Schema parse/validation failure, including writes that would leave
    relationships unreferenced (client/client.go:426-427 doc contract)."""


class PartialDeletionError(AuthzError):
    """DeleteAtomic did not complete (client/client.go:331-333)."""


class BulkCheckItemError(AuthzError):
    """One item of a bulk Check failed to evaluate.  The reference's
    CheckBulkPermissions maps per-item errors by aborting the result walk
    and returning the results accumulated so far alongside the error
    (client/client.go:279-283); ``results`` carries those partial
    per-item booleans and ``index`` the failing item's position.

    Never retriable (``is_retriable`` short-circuits on the class): the
    reference retries the RPC, not the per-item mapping — and the
    substring classifier must not re-match retry phrases inside the
    embedded cause message.  Not a PermanentError subclass because the
    retry envelope unwraps those to their cause, which would lose the
    partial results."""

    def __init__(self, index: int, results, cause: BaseException) -> None:
        super().__init__(
            f"check item {index} failed: {type(cause).__name__}: {cause}"
        )
        self.index = index
        self.results = results
        self.__cause__ = cause


class OverlapKeyMissingError(RuntimeError):
    """Raised (the reference panics) when WithOverlapRequired is set and a
    request carries no overlap key (client/client.go:182-191)."""

    def __init__(self) -> None:
        super().__init__("failed to configure required overlap key for request")


#: Substrings marking a raw device/runtime failure as transient — the
#: XLA/jax analogues of gRPC Unavailable: allocator pressure and
#: backend/transfer hiccups retry; everything else is a real bug.
TRANSIENT_DISPATCH_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "ABORTED")

#: Cross-process transport failures (fleet serving, fleet/wire.py) — the
#: OS-level analogues of gRPC Unavailable.  A replica dying shows up on
#: the router's socket as one of these (ConnectionResetError and
#: BrokenPipeError are ConnectionError subclasses; ``socket.timeout`` is
#: an alias of TimeoutError since 3.10), and the retry envelope must
#: engage — reroute/backoff — instead of surfacing a raw OSError.
TRANSPORT_ERRORS = (ConnectionError, TimeoutError, EOFError)


def classify_dispatch_exception(err: BaseException):
    """Map a raw engine/JAX dispatch failure — or a cross-process
    transport failure — onto the retry taxonomy.

    Returns an ``UnavailableError`` (with ``err`` as cause) when the
    failure is a transport error or carries a transient marker, ``err``
    itself when it is already a classified ``AuthzError``, and None when
    it is neither — the caller re-raises unclassifiable errors unchanged
    so genuine bugs keep their tracebacks."""
    if isinstance(err, AuthzError):
        return err
    if isinstance(err, TRANSPORT_ERRORS):
        e = UnavailableError(f"{type(err).__name__}: {err}")
        e.__cause__ = err
        return e
    msg = str(err)
    if any(m in msg for m in TRANSIENT_DISPATCH_MARKERS):
        e = UnavailableError(msg)
        e.__cause__ = err
        return e
    return None


def is_retriable(err: BaseException) -> bool:
    """The retry classifier (client/client.go:193-203): Unavailable /
    DeadlineExceeded classes, the two SpiceDB compat strings, or a context
    deadline error; everything else is permanent."""
    if isinstance(err, (PermanentError, BulkCheckItemError)):
        return False
    if isinstance(err, (UnavailableError, DeadlineExceededError)):
        return True
    msg = str(err)
    return "retryable error" in msg or "try restarting transaction" in msg

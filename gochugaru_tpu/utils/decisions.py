"""Structured decision log: the authorization-domain audit surface.

The observability stack answers "why was this check *slow*" (spans,
flight recorder, perf ledgers) but kept no record of what was *decided*:
who asked, for what, what the verdict was, at which revision, under
which consistency strategy.  This module is that record — the per-tenant
audit surface the multi-tenant roadmap item names, and the first thing
an operator greps during an authorization incident.

Design follows the trace.py ordering of constraints:

1. **Zero cost when disarmed.**  No log installed ⇒ every ``record_*``
   entry point is one module-global load + branch.  The per-strategy
   VERDICT COUNTERS (``check.verdicts.{allowed,denied}`` plus
   ``.<strategy>`` and ``.cache_hit`` tags) are separate and always on —
   two to six counter bumps per *batch*, so denial-rate spikes are
   alertable (the stock ``denial_rate`` SLO in utils/slo.default_slos)
   even with no log armed.
2. **Sampled always-on ring, always-keep-denied.**  The head sample
   decides per decision; DENIED verdicts are kept regardless (the
   slow-tail analogue: "why was this user denied" must always have an
   answer), bounded per batch by ``denied_keep_max`` so a bulk denial
   sweep cannot flood the ring.
3. **Bounded everywhere.**  The ring is a deque; the optional JSONL sink
   rotates at ``rotate_bytes`` keeping ``rotate_keep`` files; entries a
   failed sink write loses are COUNTED (``decisions.dropped``), never
   silently gone — the bench_compare direction registry watches that
   counter.

Each entry records: client id, resource, permission, subject, verdict,
revision, consistency strategy, cache_hit / dedup_parked provenance,
latency, and the dispatch trace id (joining the decision to its span
tree and, through histogram exemplars, to /metrics).

Surfaces: ``/decisions`` (utils/telemetry.py) serves the ring as JSONL
with a counter summary head; incident bundles (utils/trace.py) carry the
last-N decisions so "what was being decided when the breaker tripped"
ships inside the bundle; vcache-served verdicts log ``cache_hit: true``
with the pinned revision — ``client.explain`` re-derives their trees
against that revision (engine/explain.py).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

__all__ = [
    "DecisionLog",
    "count_verdicts",
    "enabled",
    "get",
    "install",
    "record_cols",
    "record_rels",
    "strategy_name",
]

#: module-level fast path: None ⇒ record_* is one load + branch
_LOG: Optional["DecisionLog"] = None


def strategy_name(cs) -> str:
    """Short tag of a consistency Strategy (or None → "direct")."""
    if cs is None:
        return "direct"
    req = getattr(cs, "requirement", None)
    v = getattr(req, "value", None)
    return {
        "fully_consistent": "full",
        "minimize_latency": "min_latency",
        "at_least_as_fresh": "at_least",
        "at_exact_snapshot": "snapshot",
    }.get(v, v or "direct")


def count_verdicts(
    m: _metrics.Metrics,
    allowed: int,
    denied: int,
    strategy: str,
    cache_hits: int = 0,
) -> None:
    """Always-on verdict counters: plain totals (the denial-rate SLO's
    feed), per-strategy tags, and the cache-hit tag.  A handful of
    counter bumps per BATCH — never per check."""
    if allowed:
        m.inc("check.verdicts.allowed", allowed)
        m.inc(f"check.verdicts.allowed.{strategy}", allowed)
    if denied:
        m.inc("check.verdicts.denied", denied)
        m.inc(f"check.verdicts.denied.{strategy}", denied)
    if cache_hits:
        m.inc("check.verdicts.cache_hit", cache_hits)


class DecisionLog:
    """Bounded decision ring + optional rotating JSONL sink.

    ``sample_rate`` is the head decision per ALLOWED decision; denied
    decisions always record (up to ``denied_keep_max`` per batch).  The
    sink is written synchronously under the lock in small batches —
    decision volume is sampling-bounded, and a lost write counts into
    ``decisions.dropped`` instead of raising into a serving path."""

    def __init__(
        self,
        capacity: int = 2048,
        *,
        sample_rate: float = 1.0,
        sink_path: Optional[str] = None,
        rotate_bytes: int = 4 << 20,
        rotate_keep: int = 4,
        denied_keep_max: int = 64,
        registry: Optional[_metrics.Metrics] = None,
        seed: Optional[int] = None,
    ) -> None:
        self.capacity = max(int(capacity), 1)
        self.sample_rate = float(sample_rate)
        self.sink_path = sink_path
        self.rotate_bytes = int(rotate_bytes)
        self.rotate_keep = max(int(rotate_keep), 1)
        self.denied_keep_max = max(int(denied_keep_max), 1)
        self._m = registry or _metrics.default
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._sink = None
        self._sink_bytes = 0

    # -- recording -------------------------------------------------------
    def sampled(self) -> bool:
        r = self.sample_rate
        return r >= 1.0 or (r > 0.0 and self._rng.random() < r)

    def record(self, entries: List[Dict[str, Any]]) -> None:
        """Append already-built entries (ring + sink).  Entries are
        caller-sampled; this only stores and counts."""
        if not entries:
            return
        m = self._m
        lines: Optional[List[str]] = None
        with self._lock:
            for e in entries:
                self._ring.append(e)
            if self.sink_path is not None:
                lines = []
                for e in entries:
                    try:
                        lines.append(json.dumps(e, default=repr))
                    except (TypeError, ValueError):
                        m.inc("decisions.dropped")
                self._write_locked(lines)
        m.inc("decisions.recorded", len(entries))

    def _write_locked(self, lines: List[str]) -> None:
        if not lines:
            return
        try:
            if self._sink is None:
                self._sink = open(self.sink_path, "a")
                self._sink_bytes = self._sink.tell()
            buf = "\n".join(lines) + "\n"
            self._sink.write(buf)
            self._sink.flush()
            self._sink_bytes += len(buf)
            if self._sink_bytes >= self.rotate_bytes:
                self._rotate_locked()
        except OSError:
            self._m.inc("decisions.dropped", len(lines))
            try:
                if self._sink is not None:
                    self._sink.close()
            except OSError:
                pass
            self._sink = None

    def _rotate_locked(self) -> None:
        """path → path.1 → … → path.<rotate_keep> (oldest removed)."""
        self._sink.close()
        self._sink = None
        self._sink_bytes = 0
        oldest = f"{self.sink_path}.{self.rotate_keep}"
        try:
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self.rotate_keep - 1, 0, -1):
                src = f"{self.sink_path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.sink_path}.{i + 1}")
            os.replace(self.sink_path, f"{self.sink_path}.1")
            self._m.inc("decisions.rotated")
        except OSError:
            self._m.inc("decisions.rotate_errors")

    # -- read side -------------------------------------------------------
    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._ring)
        if n is None:
            return items
        n = int(n)
        # items[-0:] would be the WHOLE ring, and a negative n the head
        return items[-n:] if n > 0 else []

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def stats(self) -> Dict[str, Any]:
        m = self._m
        with self._lock:
            ring = len(self._ring)
        return {
            "ring": ring,
            "capacity": self.capacity,
            "sample_rate": self.sample_rate,
            "sink": self.sink_path,
            "recorded": m.counter("decisions.recorded"),
            "sampled_out": m.counter("decisions.sampled_out"),
            "denied_kept": m.counter("decisions.denied_kept"),
            "denied_capped": m.counter("decisions.denied_capped"),
            "dropped": m.counter("decisions.dropped"),
            "rotated": m.counter("decisions.rotated"),
        }

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None


# ---------------------------------------------------------------------------
# Module surface (the hot-path entry points)
# ---------------------------------------------------------------------------


def install(log: Optional[DecisionLog]) -> Optional[DecisionLog]:
    """Install (``None`` uninstalls) the process-global decision log —
    the trace.py tracer discipline: one per process, shared by every
    client, so /decisions and incident bundles see one stream."""
    global _LOG
    prev = _LOG
    _LOG = log
    if prev is not None and prev is not log:
        prev.close()
    return log


def set_recording(log: Optional[DecisionLog]) -> Optional[DecisionLog]:
    """Swap the installed log WITHOUT closing the previous one — the
    per-rep A/B toggle (explain_smoke, tpu_watch): ``install(None)``
    would close the JSONL sink, so every armed rep would pay a file
    reopen inside the timed window that a steady-state log never pays.
    Returns the previously installed log."""
    global _LOG
    prev = _LOG
    _LOG = log
    return prev


def get() -> Optional[DecisionLog]:
    return _LOG


def enabled() -> bool:
    return _LOG is not None


#: Process identity stamped on every entry (fleet serving: a replica
#: process sets its replica id at startup, so merged decision streams
#: attribute each verdict to the process that served it).  None (the
#: single-process default) adds nothing to entries.
_IDENTITY: Optional[str] = None


def set_identity(identity: Optional[str]) -> None:
    """Set (None clears) the ``replica`` label on subsequent entries."""
    global _IDENTITY
    _IDENTITY = identity


def identity() -> Optional[str]:
    return _IDENTITY


def _entry(
    resource: str, permission: str, subject: str, allowed: bool, *,
    revision, strategy: str, cache_hit: bool, dedup_parked: bool,
    latency_s: float, trace_id: Optional[str], client_id,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    e: Dict[str, Any] = {
        "unix_s": round(time.time() if now is None else now, 6),
        "resource": resource,
        "permission": permission,
        "subject": subject,
        "verdict": "allowed" if allowed else "denied",
        "strategy": strategy,
        "latency_ms": round(latency_s * 1000.0, 4),
    }
    if revision is not None:
        e["revision"] = int(revision)
    if cache_hit:
        e["cache_hit"] = True
    if dedup_parked:
        e["dedup_parked"] = True
    if trace_id:
        e["trace_id"] = trace_id
    if client_id is not None:
        e["client"] = str(client_id)
    if _IDENTITY is not None:
        e["replica"] = _IDENTITY
    return e


def record_rels(
    rels,
    verdicts,
    *,
    revision=None,
    strategy=None,
    cache_hits=None,
    dedup_parked: bool = False,
    latency_s: float = 0.0,
    trace_id: Optional[str] = None,
    client_id=None,
) -> None:
    """Record a relationship batch's decisions: sampled allowed entries
    plus every denied one (bounded), one load + branch when no log is
    installed.  ``cache_hits`` is an optional per-item bool sequence."""
    log = _LOG
    if log is None:
        return
    m = log._m
    sname = strategy if isinstance(strategy, str) else strategy_name(strategy)
    now = time.time()
    entries: List[Dict[str, Any]] = []
    denied_kept = 0
    denied_capped = 0
    sampled_out = 0
    for i, r in enumerate(rels):
        allowed = bool(verdicts[i])
        if not allowed:
            if denied_kept >= log.denied_keep_max:
                denied_capped += 1
                continue
            denied_kept += 1
        elif not log.sampled():
            sampled_out += 1
            continue
        entries.append(_entry(
            f"{r.resource_type}:{r.resource_id}",
            r.resource_relation,
            (f"{r.subject_type}:{r.subject_id}#{r.subject_relation}"
             if r.subject_relation else f"{r.subject_type}:{r.subject_id}"),
            allowed,
            revision=revision, strategy=sname,
            cache_hit=bool(cache_hits[i]) if cache_hits is not None else False,
            dedup_parked=dedup_parked, latency_s=latency_s,
            trace_id=trace_id, client_id=client_id, now=now,
        ))
    if denied_kept:
        m.inc("decisions.denied_kept", denied_kept)
    if denied_capped:
        # the always-keep-denied guarantee was CAPPED this batch — a
        # distinct counter, never folded into sampling, so the audit
        # hole is visible ("why was user X denied" may have no entry)
        m.inc("decisions.denied_capped", denied_capped)
    if sampled_out:
        m.inc("decisions.sampled_out", sampled_out)
    log.record(entries)


def record_cols(
    n: int,
    verdicts,
    decode,
    *,
    revision=None,
    strategy=None,
    cache_hits=None,
    latency_s: float = 0.0,
    trace_id: Optional[str] = None,
    client_id=None,
) -> None:
    """Columnar mirror: sample FIRST, decode interned ids only for the
    entries actually kept (``decode(i) -> (resource, permission,
    subject)``), so a 100k-row bulk batch pays string reconstruction for
    a handful of rows, not the batch."""
    log = _LOG
    if log is None:
        return
    m = log._m
    sname = strategy if isinstance(strategy, str) else strategy_name(strategy)
    now = time.time()
    entries: List[Dict[str, Any]] = []
    denied_kept = 0
    denied_capped = 0
    sampled_out = 0
    for i in range(n):
        allowed = bool(verdicts[i])
        if not allowed:
            if denied_kept >= log.denied_keep_max:
                denied_capped += 1
                continue
            denied_kept += 1
        elif not log.sampled():
            sampled_out += 1
            continue
        try:
            resource, permission, subject = decode(i)
        except Exception:
            m.inc("decisions.dropped")
            continue
        entries.append(_entry(
            resource, permission, subject, allowed,
            revision=revision, strategy=sname,
            cache_hit=bool(cache_hits[i]) if cache_hits is not None else False,
            dedup_parked=False, latency_s=latency_s,
            trace_id=trace_id, client_id=client_id, now=now,
        ))
    if denied_kept:
        m.inc("decisions.denied_kept", denied_kept)
    if denied_capped:
        m.inc("decisions.denied_capped", denied_capped)
    if sampled_out:
        m.inc("decisions.sampled_out", sampled_out)
    log.record(entries)

"""Performance attribution: where the bytes and microseconds go.

The observability stack answers "why was THIS check slow" (utils/trace.py)
and "what was happening when the breaker tripped" (the flight recorder);
this module answers the third question — "where do the bytes and the
wall time go" — with three legs, the way TpuGraphs treats per-program
cost as first-class data and Graphulo decomposes achieved rates against
machine ceilings:

1. **Device cost ledger.**  Every AOT-compiled executable the engine
   pins (latency-tier pins, the batch-path program, the frontier SpMV
   kernels) registers here: pinned executables record their XLA
   ``compiled.cost_analysis()`` (flops, bytes accessed) at pin time —
   the Compiled object is already in hand, so the capture is free —
   while jit-cached programs register a LAZY thunk over
   ``ShapeDtypeStruct`` avals that is only realized when a consumer
   explicitly asks (``/perf?compile=1``, the perf smoke, benches): a
   thunk realization is one extra AOT compile, which must never ride a
   serving dispatch or a unit test.  Backends whose ``cost_analysis``
   returns nothing (or raises) degrade to the meta model below with a
   ``perf.cost_analysis_unavailable`` gauge instead of erroring.

   Alongside the XLA numbers the ledger keeps the EXACT meta-driven
   gathered-bytes model (``gathered_bytes_model``): per-level,
   per-table HBM bytes gathered per check derived from the FlatMeta
   geometry — wildcard doubling, fold probes, the T-index fast path,
   and (new here; the old ``benchmarks/common.est_bytes_per_check``
   admitted it excluded them) the deeper recursion levels: flattened
   rc-closure probes and the arrow unroll at the snapshot's measured
   ``ar_data_depth``.  Pad-waste accounting (live lanes vs padded lanes
   per pinned-tier dispatch, fed from the batcher's occupancy through
   the latency path) completes the ledger: wasted lanes are gathered
   bytes too.

2. **Roofline meter.**  ``measure_bandwidth`` runs a one-shot on-device
   triad-style copy microbench (x + s·y over arrays far larger than
   cache: 2 streams read, 1 written) and caches the measured GB/s per
   backend fingerprint (jaxlib version + backend + device kind), the
   same discipline as bench.py's probe cache.  achieved GB/s =
   gathered bytes/check × measured true checks/s; ``roofline_frac`` =
   achieved / measured ceiling.  The first silicon number then ships
   its roofline note mechanically: ``tpu_watch.sh`` dumps
   ``roofline.json`` beside each XLA capture via ``python -m
   gochugaru_tpu.utils.perf``.

3. **Closed wall-time ledger.**  Per measurement window, 100%±ε of
   wall time is accounted into named buckets — form / queue-wait /
   host-prep / H2D / kernel / D2H / filter / backoff / idle — built
   from the SAME perf_counter stamps the stage timers publish.  Code
   reports (bucket, t0, t1) intervals through ``report_wall`` (a
   single None-check when no window is armed); ``WallLedger.stop``
   attributes every instant of the window to exactly ONE bucket by a
   fixed priority sweep (kernel > H2D > D2H > host-prep > filter >
   form > queue-wait > backoff; uncovered time is idle), so the ledger
   closes BY CONSTRUCTION — the closure property is pinned by tests,
   and bench9 emits the ledger as a row block: the "queue p99 is ~21×
   the quiet-window p99" question becomes a column, not a caveat.

Everything publishes three ways: ``perf.*`` gauges/counters on the
metrics registry, attrs on the existing dispatch spans, and a flight-
recorder context provider (``context_state``) so incident bundles carry
the cost state at the moment of the anomaly.  ``render_report`` backs
the ``/perf`` telemetry endpoint.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import metrics as _metrics

# ---------------------------------------------------------------------------
# device cost ledger: XLA cost_analysis capture
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
#: realized cost entries: (kind, key) → {flops, bytes_accessed, ...}
_COST: "Dict[Tuple[str, str], Dict[str, Any]]" = {}
#: lazy capture thunks: (kind, key) → () -> Compiled (realized on demand)
_COST_THUNKS: "Dict[Tuple[str, str], Callable[[], Any]]" = {}
#: bound on ledger entries — a qctx-shape-churning process must not grow
#: the ledger without end (FIFO, same discipline as the pin caches)
COST_LEDGER_MAX = 256


def _extract_cost(compiled) -> Optional[Dict[str, float]]:
    """Normalize ``compiled.cost_analysis()`` across backends: a dict,
    a list of per-device dicts, None, or a raise all reduce to
    {flops, bytes_accessed, transcendentals?} — or None when the
    backend declines (the caller then records an 'unavailable' entry
    and the meta model stays the roofline numerator)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    out: Dict[str, float] = {}
    for k in ("flops", "bytes accessed", "transcendentals"):
        v = ca.get(k)
        if isinstance(v, (int, float)):
            out[k.replace(" ", "_")] = float(v)
    if not out:
        return None
    return out


def _mem_stats(compiled) -> Dict[str, float]:
    try:
        ms = compiled.memory_analysis()
        return {
            "argument_bytes": float(ms.argument_size_in_bytes),
            "output_bytes": float(ms.output_size_in_bytes),
            "temp_bytes": float(ms.temp_size_in_bytes),
        }
    except Exception:
        return {}


def record_cost(
    kind: str, key: str, compiled, registry: Optional[_metrics.Metrics] = None,
    **extra,
) -> Dict[str, Any]:
    """Capture one executable's cost analysis into the ledger.  Called
    where a ``Compiled`` is already in hand (the latency pin path) or by
    thunk realization; graceful where the backend declines."""
    m = registry or _metrics.default
    cost = _extract_cost(compiled)
    entry: Dict[str, Any] = {
        "kind": kind, "key": key, "captured_unix_s": round(time.time(), 3),
        **extra,
    }
    if cost is None:
        entry["unavailable"] = True
        m.inc("perf.cost_analysis_unavailable_total")
        with _LOCK:
            m.set_gauge(
                "perf.cost_analysis_unavailable",
                m.gauge("perf.cost_analysis_unavailable", 0.0) + 1.0,
            )
    else:
        entry.update(cost)
        entry.update(_mem_stats(compiled))
        m.inc("perf.cost.captures")
        if "flops" in cost:
            m.set_gauge(f"perf.cost.{kind}.flops", cost["flops"])
        if "bytes_accessed" in cost:
            m.set_gauge(
                f"perf.cost.{kind}.bytes_accessed", cost["bytes_accessed"]
            )
    with _LOCK:
        while len(_COST) >= COST_LEDGER_MAX:
            _COST.pop(next(iter(_COST)))
        _COST[(kind, key)] = entry
    return entry


def cost_registered(kind: str, key: str) -> bool:
    """Whether (kind, key) already has an entry or a pending thunk —
    hot paths guard their (per-call) thunk construction on this."""
    with _LOCK:
        return (kind, key) in _COST or (kind, key) in _COST_THUNKS


def register_cost_thunk(kind: str, key: str, thunk: Callable[[], Any]) -> None:
    """Register a lazy capture: ``thunk()`` must return a Compiled.
    Realized only by ``cost_entries(realize=True)`` — never on a serving
    path (a realization is one AOT compile)."""
    with _LOCK:
        if (kind, key) in _COST or (kind, key) in _COST_THUNKS:
            return
        while len(_COST_THUNKS) >= COST_LEDGER_MAX:
            _COST_THUNKS.pop(next(iter(_COST_THUNKS)))
        _COST_THUNKS[(kind, key)] = thunk


def cost_entries(
    realize: bool = False, registry: Optional[_metrics.Metrics] = None
) -> List[Dict[str, Any]]:
    """The ledger's entries.  ``realize=True`` runs pending thunks first
    (each one AOT-compiles its program — benches and the perf smoke pay
    this; the /perf endpoint only on ``?compile=1``)."""
    if realize:
        with _LOCK:
            pending = list(_COST_THUNKS.items())
            _COST_THUNKS.clear()
        for (kind, key), thunk in pending:
            try:
                compiled = thunk()
            except Exception as e:
                record_cost(
                    kind, key, _Uncostable(), registry,
                    error=f"{type(e).__name__}: {e}"[:200],
                )
                continue
            record_cost(kind, key, compiled, registry)
    with _LOCK:
        return [dict(v) for v in _COST.values()] + [
            {"kind": k, "key": key, "pending": True}
            for (k, key) in _COST_THUNKS
        ]


class _Uncostable:
    """Stand-in whose cost_analysis declines — routes a failed thunk
    through the same graceful-decline path a backend refusal takes."""

    def cost_analysis(self):
        return None


def avals_of(args):
    """args pytree → ShapeDtypeStruct pytree: what a lazy cost thunk
    closes over instead of device buffers (holding the real args would
    pin multi-GB snapshots to the ledger)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        if not hasattr(x, "aval")
        else jax.ShapeDtypeStruct(x.aval.shape, x.aval.dtype),
        args,
    )


def reset_cost_ledger() -> None:
    """Test hygiene: drop every entry and pending thunk."""
    with _LOCK:
        _COST.clear()
        _COST_THUNKS.clear()


# ---------------------------------------------------------------------------
# gathered-bytes model: the exact meta-driven roofline numerator
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class BytesModel:
    """HBM bytes gathered per check, decomposed.

    ``per_table`` charges each device array; ``per_level`` splits the
    total by recursion level — level 0 is the root dispatch (the old
    ``est_bytes_per_check`` scope), level 1+ are the flattened
    rc-closure probes and the arrow unroll the old model excluded.
    ``total == sum(per_level) == sum(per_table.values())``."""

    per_table: Dict[str, float]
    per_level: Tuple[float, ...]
    total: float


def table_bytes(dsnap) -> int:
    """Resident device-table bytes of a DeviceSnapshot (the arrays
    actually shipped; HBM-lean snapshots keep raw columns host-side and
    those are correctly NOT counted — they never reach the device)."""
    return sum(int(getattr(v, "nbytes", 0)) for v in dsnap.arrays.values())


def gathered_bytes_model(dsnap) -> BytesModel:
    """Static estimate of HBM bytes GATHERED per check, per table and
    per recursion level, from the FlatMeta geometry and the ACTUAL
    device array widths/dtypes (so packed and unpacked layouts are
    compared by what truly crosses HBM).

    Level 0 mirrors the root dispatch sites: bucket-offset reads +
    candidate blocks at the e/T/KU/fold probes, wildcard doubling
    included.  Deeper levels close the old model's documented gap:

    - each flattened rc hierarchy (``meta.rc_slots``) adds ONE ancestor
      range probe + fan rows at level 1, then the rest-expression's
      leaf tests at the fan ancestors at level 2;
    - snapshots whose arrows did NOT fold into rc closure unroll to the
      measured ``meta.ar_data_depth``: each level probes the arrow
      range-group view and re-runs the leaf sites at a frontier widened
      by the per-slot arrow fanout (pow2-bucketed, exactly the lattice
      the kernel compiles).
    """
    meta = dsnap.flat_meta
    if meta is None:
        return BytesModel({}, (0.0,), 0.0)
    arrs = dsnap.arrays
    per_table: Dict[str, float] = {}

    def charge(key: str, nbytes: float) -> float:
        if nbytes:
            per_table[key] = per_table.get(key, 0.0) + float(nbytes)
        return float(nbytes)

    def row(k: str) -> int:
        """Bytes of one table row (packed lanes or int32 cols)."""
        a = arrs.get(k)
        if a is None:
            return 0
        return int(a.shape[-1]) * int(np.dtype(a.dtype).itemsize)

    def off(k: str) -> int:
        """One bucket-offset read (+ the int32 anchor when packed)."""
        a = arrs.get(k)
        if a is None:
            return 0
        return int(np.dtype(a.dtype).itemsize) + (
            4 if (k + "_a") in arrs else 0
        )

    wc = 2 if meta.has_wc_edges else 1
    wcc = 2 if meta.has_wc_closure else 1

    def e_block(width: float) -> float:
        """The direct-edge probe at ``width`` lattice nodes."""
        if not meta.e_slots:
            return 0.0
        al = arrs.get("ehx_al")
        if al is not None:
            b = int(al.shape[1]) * int(np.dtype(al.dtype).itemsize)
            # width-stratum ladder: one row gather per level
            extra = sum(
                int(arrs[k].shape[1]) * int(np.dtype(arrs[k].dtype).itemsize)
                for k in arrs
                if k.startswith("ehx_als")
            )
            return charge("ehx_al", wc * width * (b + extra))
        return charge("eh_off", wc * width * off("eh_off")) + charge(
            "ehx", wc * width * meta.e_cap * row("ehx")
        )

    def t_block(width: float) -> float:
        if not meta.has_tindex:
            return 0.0
        return charge("th_off", wcc * width * off("th_off")) + charge(
            "tx", wcc * width * meta.t_cap * row("tx")
        )

    def cl_block(width: float) -> float:
        """One closure-containment probe (per userset candidate)."""
        if not meta.has_closure:
            return 0.0
        return charge("clh_off", wcc * width * off("clh_off")) + charge(
            "clx", wcc * width * meta.cl_cap * row("clx")
        )

    def ku_block(width: float, fan: int) -> float:
        """The userset (KU) expansion: range probe + fan candidate rows,
        each candidate tested against the closure."""
        if fan <= 0:
            return 0.0
        return (
            charge("usr_off", width * off("usr_off"))
            + charge("usgx", width * meta.usr_cap * row("usgx"))
            + charge("usx", width * fan * row("usx"))
            + cl_block(width * fan)
        )

    def fold_block(width: float) -> float:
        if not meta.fold_pairs:
            return 0.0
        total = 0.0
        if meta.pf_has_e:
            total += charge("pfh_off", wc * width * off("pfh_off"))
            total += charge("pfx", wc * width * meta.pf_e_cap * row("pfx"))
        if meta.pf_has_u:
            if meta.pf_direct:
                total += charge("pfu_start", width * 2 * off("pfu_start"))
                total += charge(
                    "pfu_gk", width * meta.pf_u_fan * row("pfu_gk")
                )
                if not meta.pf_u_alllive:
                    total += charge(
                        "pfu_u", width * meta.pf_u_fan * row("pfu_u")
                    )
            else:
                total += charge("pfu_off", width * off("pfu_off"))
                total += charge(
                    "pfugx", width * meta.pf_u_cap * row("pfugx")
                )
                total += charge("pfux", width * meta.pf_u_fan * row("pfux"))
            # subject-side closure slice: once per dispatch, not per node
            if meta.pf_s_direct:
                total += charge("csr_start", 2 * off("csr_start"))
                total += charge("csr_gk", meta.pf_s_fan * row("csr_gk"))
                if not meta.pf_s_alllive:
                    total += charge("csr_d", meta.pf_s_fan * row("csr_d"))
                    total += charge("csr_p", meta.pf_s_fan * row("csr_p"))
            else:
                total += charge("csr_off", off("csr_off"))
                total += charge("csrgx", meta.pf_s_cap * row("csrgx"))
                total += charge("csrx", meta.pf_s_fan * row("csrx"))
        return total

    us_fan = max((f for _s, f in meta.us_fanout_by_slot), default=0)

    def leaf_sites(width: float) -> float:
        """The full leaf test battery at ``width`` lattice nodes: the
        direct edge probe, then the T fast path where it covers, else
        the KU expansion."""
        total = e_block(width)
        if meta.has_tindex:
            total += t_block(width)
            if meta.has_ovf and us_fan:
                # T incomplete for overflowed sources: the usr range
                # probe still runs to flag `used`
                total += charge("usr_off", width * off("usr_off"))
                total += charge("usgx", width * meta.usr_cap * row("usgx"))
        elif us_fan:
            total += ku_block(width, us_fan)
        return total

    levels: List[float] = []
    # ---- level 0: the root dispatch --------------------------------------
    levels.append(leaf_sites(1.0) + fold_block(1.0))

    # ---- level 1+: flattened rc hierarchies ------------------------------
    l1 = 0.0
    l2 = 0.0
    for ts_slot, cap, fan in meta.rc_slots:
        gx, x, o = f"rc{ts_slot}gx", f"rc{ts_slot}x", f"rc{ts_slot}_off"
        l1 += charge(o, off(o)) + charge(gx, cap * row(gx))
        l1 += charge(x, fan * row(x))
        # the rest expression evaluates at the fan ancestors
        l2 += leaf_sites(float(fan))
    if l1:
        levels.append(l1)
    if l2:
        levels.append(l2)

    # ---- level 1+: the arrow unroll (hierarchies NOT folded into rc) -----
    ar_fans = dict(meta.ar_fanout_by_slot)
    unrolled = {s for s in ar_fans if s not in {t for t, _, _ in meta.rc_slots}}
    depth = max(int(getattr(meta, "ar_data_depth", -1)), 0)
    if unrolled and depth > 0:
        fan = max(ar_fans[s] for s in unrolled)
        width = 1.0
        for lvl in range(1, depth + 1):
            a = (
                charge("arr_off", width * off("arr_off"))
                + charge("argx", width * meta.arr_cap * row("argx"))
                + charge("arx", width * fan * row("arx"))
            )
            width *= fan
            a += leaf_sites(width)
            if len(levels) <= lvl:
                levels.append(a)
            else:
                levels[lvl] += a
    total = float(sum(levels))
    return BytesModel(per_table, tuple(levels), total)


def est_bytes_per_check(dsnap) -> float:
    """The gathered-bytes model's total — the roofline numerator next
    to checks/s.  One implementation; ``benchmarks/common`` delegates
    here."""
    return gathered_bytes_model(dsnap).total


#: the last published model (per-process; the /perf endpoint and the
#: flight-recorder context read it)
_LAST_MODEL: "List[Tuple[float, BytesModel]]" = []


def publish_model(
    dsnap, registry: Optional[_metrics.Metrics] = None
) -> Optional[BytesModel]:
    """Compute + publish the snapshot's gathered-bytes model as
    ``perf.bytes_per_check`` (+ per-level gauges).  Called at prepare;
    never fails the prepare (a geometry the model can't read publishes
    nothing)."""
    try:
        model = gathered_bytes_model(dsnap)
    except Exception:
        return None
    m = registry or _metrics.default
    m.clear_gauges("perf.bytes_per_check")
    m.set_gauge("perf.bytes_per_check", model.total)
    for i, v in enumerate(model.per_level):
        m.set_gauge(f"perf.bytes_per_check.level{i}", v)
    with _LOCK:
        _LAST_MODEL.clear()
        _LAST_MODEL.append((time.time(), model))
    return model


def last_model() -> Optional[BytesModel]:
    with _LOCK:
        return _LAST_MODEL[0][1] if _LAST_MODEL else None


# ---------------------------------------------------------------------------
# Pallas one-pass delta model (engine/pallas.py fused probe backend)
# ---------------------------------------------------------------------------

#: block tables the fused kernel serves (pblock/psite sites in
#: engine/flat.py) and the bucket-offset arrays it pins VMEM-resident.
#: Tables outside this set (emission rows, csr slices, delta overlays)
#: keep the XLA path and honestly show saved == 0
_PALLAS_BLOCK_TBLS = frozenset(
    {"ehx", "ehx_al", "tx", "clx", "pusx", "ovfx", "pfx", "usgx", "argx"}
)
_PALLAS_OFF_TBLS = frozenset(
    {"eh_off", "th_off", "clh_off", "push_off", "ovfh_off", "pfh_off",
     "usr_off", "arr_off"}
)


def pallas_bytes_model(dsnap) -> Dict[str, Dict[str, float]]:
    """Per-table bytes-accessed before/after for the Pallas fused probe:
    ``{table: {"xla": b, "pallas": b', "saved": b - b'}}``.

    The model, stated so the tests can assert its structure (the silicon
    measurement is tpu_watch's priority-4.0 A/B, not this function):

    - the XLA chain charges the gathered source bytes
      (:func:`gathered_bytes_model`) PLUS one write+read of the decoded
      int32 block per probed block table — the gather-boundary
      intermediate XLA materializes between the block gather and the
      compare/gate consumers (packed tables inflate it by the
      int32-width/packed-lane ratio; that materialization is exactly
      what "one HBM pass" removes);
    - the fused kernel charges the raw block bytes ONCE (the bucket DMA)
      and zero per-probe bytes for VMEM-resident bucket offsets/anchors
      (``engine.pallas.vmem_plan``); offsets too big for the plan keep
      their XLA charge (the kernel declines those sites);
    - tables the kernel does not serve keep identical charges.
    """
    from ..engine.pallas import vmem_plan

    base = gathered_bytes_model(dsnap)
    meta = dsnap.flat_meta
    arrs = dsnap.arrays
    pk = dict(meta.packed) if meta is not None else {}
    rc_off = {f"rc{ts}_off" for ts, _c, _f in getattr(meta, "rc_slots", ())}
    rc_gx = {f"rc{ts}gx" for ts, _c, _f in getattr(meta, "rc_slots", ())}
    resident = set(vmem_plan(arrs))
    out: Dict[str, Dict[str, float]] = {}
    for t, b in base.per_table.items():
        if t in (_PALLAS_OFF_TBLS | rc_off):
            # the anchor rides the off charge; resident iff both fit
            ok = t in resident and (
                t + "_a" not in arrs or t + "_a" in resident
            )
            saved = b if ok else 0.0
            out[t] = {"xla": b, "pallas": b - saved, "saved": saved}
            continue
        if t in (_PALLAS_BLOCK_TBLS | rc_gx):
            a = arrs.get(t)
            spec = pk.get(t[:-3] if t.endswith("_al") else t)
            if a is None:
                out[t] = {"xla": b, "pallas": b, "saved": 0.0}
                continue
            if spec is not None:
                w_log = int(spec[0])
                lanes = spec[1]
                isz = int(np.dtype(a.dtype).itemsize)
                factor = (4.0 * w_log) / float(lanes * isz)
            else:
                factor = 1.0
            inter = 2.0 * b * factor  # decoded block: one write + read
            out[t] = {"xla": b + inter, "pallas": b, "saved": inter}
            continue
        out[t] = {"xla": b, "pallas": b, "saved": 0.0}
    return out


def publish_pallas_model(
    dsnap, registry: Optional[_metrics.Metrics] = None
) -> Optional[Dict[str, Dict[str, float]]]:
    """Publish the fused-probe delta next to the base model:
    ``perf.pallas.bytes_per_check`` / ``.bytes_saved_per_check`` totals
    + per-table ``perf.pallas.saved.<table>`` gauges.  Called at prepare
    when ``EngineConfig.pallas`` resolves on; never fails the prepare."""
    try:
        model = pallas_bytes_model(dsnap)
    except Exception:
        return None
    m = registry or _metrics.default
    m.clear_gauges("perf.pallas.")
    m.set_gauge(
        "perf.pallas.bytes_per_check",
        sum(v["pallas"] for v in model.values()),
    )
    m.set_gauge(
        "perf.pallas.bytes_saved_per_check",
        sum(v["saved"] for v in model.values()),
    )
    for t, v in model.items():
        if v["saved"]:
            m.set_gauge(f"perf.pallas.saved.{t}", v["saved"])
    return model


# ---------------------------------------------------------------------------
# pad-waste accounting (live vs padded lanes per pinned-tier dispatch)
# ---------------------------------------------------------------------------

#: tiers record_pad has seen — lets pad_stats read the per-tier
#: counters by NAME instead of snapshotting the whole registry (a
#: snapshot copies+sorts every timer ring; pad_stats runs inside the
#: "cheap by contract" incident context provider and per /perf scrape)
_PAD_TIERS: "set" = set()


def record_pad(
    tier: int, live: int, registry: Optional[_metrics.Metrics] = None
) -> None:
    """One pinned-tier dispatch padded ``live`` queries to ``tier``
    lanes.  Fed from the latency path, which serves both direct calls
    and the micro-batcher's formed batches — so the batcher's occupancy
    flows into the ledger per dispatch."""
    m = registry or _metrics.default
    m.inc("perf.pad.live_lanes", live)
    m.inc("perf.pad.total_lanes", tier)
    m.inc(f"perf.pad.live_lanes.t{tier}", live)
    m.inc(f"perf.pad.total_lanes.t{tier}", tier)
    if tier not in _PAD_TIERS:
        with _LOCK:
            _PAD_TIERS.add(int(tier))


def pad_stats(registry: Optional[_metrics.Metrics] = None) -> Dict[str, Any]:
    """{live_lanes, total_lanes, pad_fraction, per_tier} cumulative —
    ``pad_fraction`` is the share of dispatched lanes that carried
    padding, the roofline's wasted-bytes column (lower is better).
    Reads only the pad counters by name — never a full registry
    snapshot."""
    m = registry or _metrics.default
    live = m.counter("perf.pad.live_lanes")
    total = m.counter("perf.pad.total_lanes")
    with _LOCK:
        tiers = sorted(_PAD_TIERS)
    per_tier: Dict[str, Dict[str, float]] = {}
    for t in tiers:
        tt = m.counter(f"perf.pad.total_lanes.t{t}")
        if not tt:
            continue
        lt = m.counter(f"perf.pad.live_lanes.t{t}")
        per_tier[str(t)] = {
            "live": lt, "total": tt,
            "pad_fraction": round(1.0 - lt / tt, 4),
        }
    return {
        "live_lanes": live,
        "total_lanes": total,
        "pad_fraction": round(1.0 - live / total, 4) if total else 0.0,
        "per_tier": per_tier,
    }


# ---------------------------------------------------------------------------
# roofline meter: measured memory-bandwidth denominator
# ---------------------------------------------------------------------------

#: on-disk bandwidth cache, keyed by backend fingerprint (the probe-cache
#: discipline: a microbench re-run tells you nothing new about the same
#: silicon, and on a busy proxy it costs a second of full-core traffic)
ROOFLINE_CACHE_PATH = os.environ.get(
    "GOCHUGARU_ROOFLINE_CACHE_PATH", "/tmp/gochugaru_roofline.json"
)


#: the last fingerprint computed in THIS process — lets a plain /perf
#: scrape key its cache read without touching the backend (computing a
#: fingerprint calls jax.devices(), which INITIALIZES the backend: a
#: multi-second stall, or a hang on a dead axon tunnel, that a scrape
#: must never pay)
_LAST_FP: "List[str]" = []


def backend_fingerprint() -> str:
    """jaxlib version + backend + device kind + device count: the cache
    key under which one bandwidth measurement stands for a machine.
    Initializes the JAX backend — callers on scrape paths use the
    remembered in-process value instead (``_LAST_FP``)."""
    try:
        from importlib.metadata import version

        jaxlib = version("jaxlib")
    except Exception:
        jaxlib = "unknown"
    import jax

    devs = jax.devices()
    kind = devs[0].device_kind if devs else "none"
    fp = (
        f"jaxlib={jaxlib};backend={jax.default_backend()}"
        f";kind={kind};n={len(devs)}"
    )
    with _LOCK:
        _LAST_FP.clear()
        _LAST_FP.append(fp)
    return fp


def _bandwidth_cache_read(fp: str) -> Optional[Dict[str, Any]]:
    if os.environ.get("GOCHUGARU_ROOFLINE_CACHE", "1") == "0":
        return None
    try:
        with open(ROOFLINE_CACHE_PATH) as f:
            blob = json.load(f)
        if blob.get("fingerprint") != fp:
            return None
        # the blob persists with cached=False (it was fresh when
        # written); anything served FROM the cache must say so — a
        # /perf reader must not mistake a stale verdict for a
        # this-scrape measurement
        return {**blob, "cached": True}
    except (OSError, ValueError):
        return None


def _bandwidth_cache_write(blob: Dict[str, Any]) -> None:
    if os.environ.get("GOCHUGARU_ROOFLINE_CACHE", "1") == "0":
        return
    try:
        tmp = ROOFLINE_CACHE_PATH + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(blob, f)
        os.replace(tmp, ROOFLINE_CACHE_PATH)
    except OSError:
        pass  # best-effort; next run re-measures


def measure_bandwidth(
    refresh: bool = False,
    size_mb: float = 64.0,
    reps: int = 7,
    registry: Optional[_metrics.Metrics] = None,
) -> Dict[str, Any]:
    """The roofline denominator: measured device memory bandwidth via a
    triad-style copy (out = x + 0.5·y over float32 arrays far larger
    than any cache level — 2 streams read, 1 written, 12 B/element) —
    best-of-``reps`` blocked executions, cached per backend fingerprint.

    Returns {gbps, bytes_moved, reps, fingerprint, platform, cached};
    publishes ``perf.roofline_gbps``."""
    m = registry or _metrics.default
    fp = backend_fingerprint()
    if not refresh:
        cached = _bandwidth_cache_read(fp)
        if cached is not None and cached.get("gbps"):
            m.set_gauge("perf.roofline_gbps", cached["gbps"])
            return cached
    import jax
    import jax.numpy as jnp

    n = max(int(size_mb * 1e6 / 4), 1 << 16)
    x = jnp.arange(n, dtype=jnp.float32)
    y = x * jnp.float32(0.25)
    fn = jax.jit(lambda a, b: a + jnp.float32(0.5) * b)
    out = fn(x, y)
    jax.block_until_ready(out)
    # one fetch → synchronous stream (benchmarks/common._force_sync_mode
    # rationale: remote-attached platforms lie to enqueue-only timers)
    jax.device_get(out[:1])
    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(x, y))
        best = min(best, time.perf_counter() - t0)
    bytes_moved = 3 * n * 4  # 2 read + 1 written
    gbps = bytes_moved / best / 1e9
    blob = {
        "gbps": round(gbps, 2),
        "bytes_moved": bytes_moved,
        "best_s": round(best, 6),
        "reps": int(reps),
        "fingerprint": fp,
        "platform": jax.default_backend(),
        "measured_unix_s": round(time.time(), 3),
        "cached": False,
    }
    _bandwidth_cache_write(blob)
    m.set_gauge("perf.roofline_gbps", blob["gbps"])
    return blob


def roofline_columns(
    rate: float,
    dsnap=None,
    bytes_per_check: Optional[float] = None,
    registry: Optional[_metrics.Metrics] = None,
) -> Dict[str, float]:
    """The bench columns: achieved GB/s = gathered bytes/check × true
    checks/s against the MEASURED bandwidth ceiling.  Works from a
    DeviceSnapshot (model computed here) or a precomputed
    bytes_per_check.

    ``bytes_accessed_per_check`` is the ACTIVE backend's modeled HBM
    traffic: when the prepare that produced ``dsnap`` resolved the
    Pallas fused probe on, :func:`publish_pallas_model` left the fused
    per-check bytes in the ``perf.pallas.bytes_per_check`` gauge and
    the row carries that (plus the before/after delta in
    ``pallas_bytes_saved_per_check``); otherwise it equals the XLA
    gather model ``bytes_per_check`` and the delta column is absent —
    so one bench emits the A and the B rows of the same model."""
    if bytes_per_check is None:
        bytes_per_check = est_bytes_per_check(dsnap) if dsnap is not None else 0.0
    bw = measure_bandwidth(registry=registry)
    m = registry or _metrics.default
    fused = m.gauge("perf.pallas.bytes_per_check")
    saved = m.gauge("perf.pallas.bytes_saved_per_check")
    eff = fused if fused > 0 else float(bytes_per_check)
    achieved = eff * max(rate, 0.0) / 1e9
    ceiling = float(bw.get("gbps") or 0.0)
    m.set_gauge("perf.achieved_gbps", achieved)
    out = {
        "bytes_per_check": round(float(bytes_per_check), 1),
        "bytes_accessed_per_check": round(eff, 1),
        "achieved_gbps": round(achieved, 3),
        "roofline_gbps": round(ceiling, 2),
        "roofline_frac": round(achieved / ceiling, 4) if ceiling else 0.0,
    }
    if fused > 0:
        out["pallas_bytes_saved_per_check"] = round(saved, 1)
    return out


# ---------------------------------------------------------------------------
# closed wall-time ledger
# ---------------------------------------------------------------------------

#: attribution priority, highest first: an instant covered by several
#: reported intervals belongs to the FIRST listed bucket that covers it
#: (the device stages own their windows; host-side bookkeeping fills
#: around them; waiting only counts where nothing is running)
WALL_BUCKETS = (
    "kernel", "h2d", "d2h", "host_prep", "filter", "form", "queue_wait",
    "backoff",
)
_BUCKET_INDEX = {b: i for i, b in enumerate(WALL_BUCKETS)}

#: bound on reported intervals per window (a runaway window degrades to
#: a counted drop, never unbounded memory)
WALL_INTERVAL_MAX = 400_000

#: the armed window (one per process; benches own the lifecycle).  A
#: PLAIN reference assigned/cleared atomically — reporters on other
#: threads read it once, so a concurrent stop() can never race a
#: check-then-index (the reporter either sees the window or None)
_WALL: "Optional[WallLedger]" = None
#: the last CLOSED window's result (the /perf endpoint serves it);
#: same single-reference discipline
_LAST_WALL: "Optional[Dict[str, Any]]" = None


def report_wall(bucket: str, t0: float, t1: float) -> None:
    """Report one (bucket, start, end) interval on the perf_counter
    timeline.  A single reference-read + None-check when no window is
    armed — safe on the latency path's per-dispatch budget."""
    w = _WALL
    if w is not None:
        w._report(bucket, t0, t1)


def report_wall_stages(t0: float, t1: float, t2: float, t3: float, t4: float) -> None:
    """The latency path's four stage intervals from the SAME t0..t4
    stamps the DispatchBudget subtracts — ledger and budget agree
    exactly."""
    w = _WALL
    if w is not None:
        w._report("host_prep", t0, t1)
        w._report("h2d", t1, t2)
        w._report("kernel", t2, t3)
        w._report("d2h", t3, t4)


class WallLedger:
    """One measurement window's wall-time attribution.

    ``start()`` arms the process-global report hook; ``stop()`` disarms
    it and sweeps the reported intervals into per-bucket seconds by the
    fixed priority order — every instant of [start, stop] lands in
    exactly one bucket (uncovered time is ``idle``), so the buckets sum
    to the window length BY CONSTRUCTION (``closure_frac`` states it).
    Because idle is a residual, closure alone cannot catch LOST
    intervals — the accounting's real teeth are ``dropped == 0`` plus
    the named buckets the consumer expects being nonzero
    (``named_frac``); the tests and bench9 assert those too."""

    def __init__(self, registry: Optional[_metrics.Metrics] = None) -> None:
        self._m = registry or _metrics.default
        self._lock = threading.Lock()
        self._intervals: List[Tuple[int, float, float]] = []
        self.dropped = 0
        self.t_start: Optional[float] = None
        self.t_stop: Optional[float] = None
        self.result: Optional[Dict[str, Any]] = None

    def _report(self, bucket: str, t0: float, t1: float) -> None:
        bi = _BUCKET_INDEX.get(bucket)
        if bi is None or t1 <= t0:
            return
        with self._lock:
            if len(self._intervals) >= WALL_INTERVAL_MAX:
                self.dropped += 1
                return
            self._intervals.append((bi, t0, t1))

    def start(self) -> "WallLedger":
        global _WALL
        self.t_start = time.perf_counter()
        _WALL = self
        return self

    def stop(self) -> Dict[str, Any]:
        global _WALL, _LAST_WALL
        if _WALL is self:
            _WALL = None
        self.t_stop = time.perf_counter()
        with self._lock:
            intervals = list(self._intervals)
        self.result = _attribute_wall(
            intervals, self.t_start, self.t_stop, self.dropped
        )
        _publish_wall(self.result, self._m)
        _LAST_WALL = self.result
        return self.result


def _attribute_wall(
    intervals: List[Tuple[int, float, float]],
    t0: float,
    t1: float,
    dropped: int = 0,
) -> Dict[str, Any]:
    """Priority sweep: at every instant the highest-priority bucket with
    an active interval owns the time; no active bucket → idle."""
    W = max(t1 - t0, 1e-12)
    sec = {b: 0.0 for b in WALL_BUCKETS}
    events: List[Tuple[float, int, int]] = []
    for bi, s, e in intervals:
        s, e = max(s, t0), min(e, t1)
        if e > s:
            events.append((s, 1, bi))
            events.append((e, -1, bi))
    events.sort(key=lambda ev: ev[0])
    active = [0] * len(WALL_BUCKETS)
    prev = t0
    for t, d, bi in events:
        if t > prev:
            own = next((i for i, c in enumerate(active) if c > 0), None)
            if own is not None:
                sec[WALL_BUCKETS[own]] += t - prev
            prev = t
        active[bi] += d
    named = sum(sec.values())
    idle = max(W - named, 0.0)
    # closure from the UNROUNDED sums: rounding bucket seconds to a µs
    # quantum first would make a sub-100µs window's closure read
    # percent-level noise (a flaky test, not a property)
    closure = (named + idle) / W
    sec["idle"] = idle
    fracs = {b: round(v / W, 4) for b, v in sec.items()}
    return {
        "window_s": round(W, 6),
        "seconds": {b: round(v, 6) for b, v in sec.items()},
        "fracs": fracs,
        "closure_frac": round(closure, 4),
        "named_frac": round(named / W, 4),
        "intervals": len(intervals),
        "dropped": int(dropped),
    }


def _publish_wall(result: Dict[str, Any], m: _metrics.Metrics) -> None:
    m.clear_gauges("perf.wall.")
    m.set_gauge("perf.wall.window_s", result["window_s"])
    m.set_gauge("perf.wall.closure_frac", result["closure_frac"])
    for b, v in result["seconds"].items():
        m.set_gauge(f"perf.wall.{b}_s", v)
        m.set_gauge(f"perf.wall.{b}_frac", result["fracs"][b])


def last_wall() -> Optional[Dict[str, Any]]:
    return _LAST_WALL


# ---------------------------------------------------------------------------
# export surface: /perf report + flight-recorder context
# ---------------------------------------------------------------------------

def render_report(
    registry: Optional[_metrics.Metrics] = None,
    realize: bool = False,
    bench: bool = False,
) -> Dict[str, Any]:
    """The ``/perf`` payload: the whole ledger as one JSON document.
    ``realize`` runs pending cost thunks (AOT compiles — explicit
    opt-in); ``bench`` runs the bandwidth microbench when no cached
    verdict exists (otherwise the cached one is served)."""
    m = registry or _metrics.default
    model = last_model()
    with _LOCK:
        fp = _LAST_FP[0] if _LAST_FP else None
    bw = None
    try:
        # a plain scrape must never initialize the JAX backend (a
        # multi-second stall, or a hang on a dead axon tunnel): without
        # ?bench=1 the fingerprint only keys a cache read, so it uses
        # the value some in-process measurement already computed — a
        # process that never measured serves roofline: null until the
        # operator explicitly asks with ?bench=1
        if bench:
            bw = measure_bandwidth(registry=m)
            fp = bw.get("fingerprint", fp)
        elif fp is not None:
            bw = _bandwidth_cache_read(fp)
    except Exception:
        pass
    return {
        "cost": cost_entries(realize=realize, registry=m),
        "cost_analysis_unavailable": m.gauge(
            "perf.cost_analysis_unavailable", 0.0
        ),
        "bytes_model": None if model is None else {
            "total": round(model.total, 1),
            "per_level": [round(v, 1) for v in model.per_level],
            "per_table": {
                k: round(v, 1) for k, v in sorted(model.per_table.items())
            },
        },
        "pad": pad_stats(m),
        "roofline": bw,
        "fingerprint": fp,
        "wall": last_wall(),
        **{
            k: _safe_section(fn) for k, fn in sorted(_EXTRA_REPORT.items())
        },
    }


#: extra /perf report sections registered by other subsystems (the
#: verdict cache registers its stats here — engine/vcache.py — so one
#: scrape answers "where do the checks go" AND "what never reached the
#: device").  Cheap-by-contract, same rule as context providers
_EXTRA_REPORT: Dict[str, Any] = {}


def register_report_section(name: str, fn) -> None:
    """Attach a callable whose result rides /perf under ``name``
    (last registration per name wins)."""
    _EXTRA_REPORT[name] = fn


def _safe_section(fn):
    try:
        return fn()
    except Exception as e:  # a broken section must not break the scrape
        return {"error": repr(e)}


def context_state() -> Dict[str, Any]:
    """Flight-recorder context provider: the cost state an incident
    bundle carries.  Cheap by contract — realized entries only, cached
    bandwidth only, no compiles, no microbench."""
    m = _metrics.default
    model = last_model()
    entries = cost_entries(realize=False)
    return {
        "bytes_per_check": None if model is None else round(model.total, 1),
        "bytes_per_level": None if model is None else [
            round(v, 1) for v in model.per_level
        ],
        "pad": pad_stats(m),
        "cost_entries": len(entries),
        "cost_pending": sum(1 for e in entries if e.get("pending")),
        "cost_analysis_unavailable": m.gauge(
            "perf.cost_analysis_unavailable", 0.0
        ),
        "roofline_gbps": m.gauge("perf.roofline_gbps", 0.0) or None,
        "wall": last_wall(),
    }


def _main() -> int:
    """``python -m gochugaru_tpu.utils.perf``: run (or read) the
    bandwidth microbench and print the roofline JSON — tpu_watch.sh
    dumps this beside each XLA capture so the first silicon number
    ships its roofline note."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--refresh", action="store_true",
                    help="re-measure even with a cached verdict")
    ap.add_argument("--size-mb", type=float, default=64.0)
    ap.add_argument("--reps", type=int, default=7)
    args = ap.parse_args()
    if os.environ.get("GOCHUGARU_FORCE_CPU") == "1":
        from .platform import force_cpu_platform

        force_cpu_platform()
    bw = measure_bandwidth(
        refresh=args.refresh, size_mb=args.size_mb, reps=args.reps
    )
    print(json.dumps({**bw, "cache_path": ROOFLINE_CACHE_PATH}, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())

"""Telemetry export: Prometheus/OpenMetrics rendering of the live
Metrics registry, JSONL trace dump, SLO burn report, incident bundles,
and a stdlib HTTP daemon serving all of it.

The bench suite measures offline (Graphulo discipline, arXiv:1609.08642);
a serving process for millions of users must expose the SAME numbers
live.  This module is deliberately dependency-free: ``http.server`` on a
daemon thread, the exposition formats by hand — the container bakes no
prometheus_client, and the formats are a few dozen lines of code.

Surface:

- ``render_prometheus(registry)`` — counters as ``counter``, gauges as
  ``gauge``, timer rings as ``summary`` quantile series (p50/p90/p99/
  p999 via the shared ``metrics.nearest_rank``) plus ``_count``/``_sum``,
  fixed-bucket histograms as cumulative ``le`` series.  With
  ``openmetrics=True`` the output is OpenMetrics 1.0 text instead:
  histogram buckets carry **exemplars** — the last trace id that landed
  in the bucket (``Metrics.observe_hist(trace_id=…)``) — so a fat tail
  bucket links directly to a recorded trace; terminated by ``# EOF``.
  The HTTP handler negotiates via the Accept header (scrapers ask for
  ``application/openmetrics-text``) or a ``?openmetrics=1`` query.
- ``render_traces(tracer)`` — the tracer ring as JSONL.
- ``TelemetryServer`` — ``/metrics`` (exposition text), ``/traces``
  (JSONL), ``/slo`` (burn-rate report, utils/slo.py), ``/tune``
  (self-tuning posture: live knob values, frozen knobs, ``tune.*``
  trajectory counters when an OnlineController is attached), ``/perf``
  (the
  performance-attribution ledger, utils/perf.py: cost_analysis
  entries, gathered-bytes model, pad waste, measured roofline,
  wall-time ledger — ``?compile=1``/``?bench=1`` opt into the
  expensive captures), ``/debug/incidents`` (flight-recorder bundle
  index; ``/debug/incidents/<id>`` serves one bundle as JSONL),
  ``/healthz`` (readiness report: breaker state, admission in-flight,
  serve queue depth, SLO status — degraded states say why instead of a
  flat ok).  Bound to localhost by default; ``port=0`` picks an
  ephemeral port (read ``.port`` back).
- ``client.with_telemetry(port=..., incident_dir=...)`` (client.py)
  starts one per client; ``scripts/telemetryd.py`` runs one standalone.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional

from . import metrics as _metrics
from . import trace as _trace

#: every exported series is namespaced (dots/dashes → underscores after)
PROM_PREFIX = "gochugaru_"

CONTENT_TYPE_PROM = "text/plain; version=0.0.4; charset=utf-8"
CONTENT_TYPE_OPENMETRICS = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: quantile-label values for the timer summaries, paired with the
#: shared snapshot percentiles (50 → "0.5", 99.9 → "0.999")
_QUANTILE_LABELS = tuple(
    (q, format(q / 100.0, "g")) for q in _metrics.SNAPSHOT_QUANTILES
)


def prom_name(name: str, suffix: str = "") -> str:
    """'checks.dispatch' → 'gochugaru_checks_dispatch<suffix>'."""
    return PROM_PREFIX + _NAME_RE.sub("_", name) + suffix


def _fmt(v: float) -> str:
    # Prometheus wants plain decimal/scientific; repr of a float is fine
    return repr(float(v))


def _exemplar(ex) -> str:
    """One OpenMetrics exemplar suffix: ``# {trace_id="…"} value ts``.
    Exemplars are only legal in OpenMetrics text, only on histogram
    ``_bucket`` lines — the 0.0.4 renderer never calls this."""
    tid, value, ts = ex
    return f' # {{trace_id="{tid}"}} {_fmt(value)} {round(ts, 3)}'


def render_prometheus(
    registry: Optional[_metrics.Metrics] = None, *, openmetrics: bool = False
) -> str:
    """The registry as exposition text.  Counters/gauges map directly;
    each timer ring becomes a summary — quantile series from the SAME
    nearest-rank math ``Metrics.snapshot`` publishes, so the scraped p99
    and the in-process p99 cannot disagree.  ``openmetrics=True``
    switches dialect: TYPE lines name the metric family without the
    ``_total`` suffix, ``le`` labels are canonical floats, histogram
    buckets carry trace-id exemplars, and the text ends with ``# EOF``."""
    m = registry or _metrics.default
    counters, gauges, timers = m.typed_snapshot()
    hists = m.hist_snapshot()
    lines: List[str] = []
    for name in sorted(counters):
        pn = prom_name(name)
        # OpenMetrics: the TYPE line names the family, samples add _total;
        # 0.0.4 scrapers expect the TYPE line to match the sample name
        lines.append(f"# TYPE {pn if openmetrics else pn + '_total'} counter")
        lines.append(f"{pn}_total {_fmt(counters[name])}")
    for name in sorted(gauges):
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(gauges[name])}")
    for name in sorted(timers):
        n, total, samples = timers[name]
        base = _NAME_RE.sub("_", name)
        # timer names already end in '_s' by convention; normalize the
        # exported unit suffix to _seconds either way
        base = base[:-2] if base.endswith("_s") else base
        pn = PROM_PREFIX + base + "_seconds"
        lines.append(f"# TYPE {pn} summary")
        if samples:
            for q, label in _QUANTILE_LABELS:
                lines.append(
                    f'{pn}{{quantile="{label}"}} '
                    f"{_fmt(_metrics.nearest_rank(samples, q))}"
                )
        lines.append(f"{pn}_count {n}")
        lines.append(f"{pn}_sum {_fmt(total)}")
    for name in sorted(hists):
        buckets, counts, n, total, exemplars = hists[name]
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for i, (b, c) in enumerate(zip(buckets, counts)):
            cum += c
            le = _fmt(b) if openmetrics else format(b, "g")
            ex = exemplars[i] if openmetrics else None
            lines.append(
                f'{pn}_bucket{{le="{le}"}} {cum}'
                + (_exemplar(ex) if ex is not None else "")
            )
        ex = exemplars[-1] if openmetrics else None
        lines.append(
            f'{pn}_bucket{{le="+Inf"}} {n}'
            + (_exemplar(ex) if ex is not None else "")
        )
        lines.append(f"{pn}_count {n}")
        lines.append(f"{pn}_sum {_fmt(total)}")
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_traces(tracer: Optional[_trace.Tracer] = None) -> str:
    """The tracer's finished-trace ring as JSONL ('' when tracing is
    disabled or nothing was kept)."""
    tr = tracer if tracer is not None else _trace.get()
    if tr is None:
        return ""
    return tr.dump_jsonl()


def _live_slo(slo):
    """The engine whose verdict is CURRENT: the given one while it is
    open, else the process-global engine (the bound engine may have been
    closed — disabled or replaced — after its holder captured it; a
    frozen report must not pose as live status).  One rule shared by
    ``/slo`` and ``readiness_report`` so the two cannot disagree about
    which engine is live."""
    if slo is not None and getattr(slo, "closed", False):
        from . import slo as _slo_mod

        return _slo_mod.get_engine()
    return slo


#: how long after an incident /healthz keeps naming it a degradation
#: reason — long enough for a poller to notice, short enough that one
#: transient blip doesn't keep a recovered process drained (breaker and
#: SLO state cover LIVE anomalies; this reason covers recent history)
RECENT_INCIDENT_S = 60.0


def readiness_report(
    registry: Optional[_metrics.Metrics] = None,
    slo=None,
    recorder: Optional[_trace.FlightRecorder] = None,
    uptime_s: float = 0.0,
    recent_incident_s: float = RECENT_INCIDENT_S,
) -> dict:
    """The ``/healthz`` payload: liveness grown into readiness.  A bare
    200 says a thread is alive; an operator (and the serve smoke's load
    balancer stand-in) needs "is this process actually fit to take
    traffic" — breaker state, in-flight admission, serve queue depth,
    and the SLO engine's verdict.  Degraded states answer
    ``"status": "degraded"`` with machine-readable reasons instead of a
    flat ok (still HTTP 200: degraded-but-alive is a routing decision
    for the caller, not an error)."""
    m = registry or _metrics.default
    slo = _live_slo(slo)
    breaker = m.gauge("breaker.state", 0.0)
    reasons: List[str] = []
    if breaker == 2.0:
        reasons.append("breaker_open")
    elif breaker == 1.0:
        reasons.append("breaker_half_open")
    slo_status = None
    if slo is not None:
        rep = slo.report()
        slo_status = {
            "healthy": bool(rep.get("healthy", True)),
            "breached": list(rep.get("breached", ())),
        }
        for name in slo_status["breached"]:
            reasons.append(f"slo_burn:{name}")
    incidents = None
    if recorder is not None:
        idx = recorder.incident_index()
        incidents = len(idx)
        recent = [
            mi for mi in idx
            if time.time() - mi.get("unix_s", 0.0) < recent_incident_s
        ]
        if recent:
            reasons.append(f"recent_incidents:{len(recent)}")
    return {
        "status": "degraded" if reasons else "ok",
        "reasons": reasons,
        "uptime_s": round(uptime_s, 3),
        "tracing": _trace.enabled(),
        "breaker_state": int(breaker),
        "admission_inflight": int(m.gauge("admission.inflight", 0.0)),
        "serve_queue_depth": int(m.gauge("serve.queue_depth", 0.0)),
        "slo": slo_status,
        "incidents": incidents,
    }


class TelemetryServer:
    """``/metrics`` + ``/traces`` + ``/slo`` + ``/perf`` +
    ``/debug/incidents`` + ``/healthz`` on a daemon thread.

    Read-only by construction: the handlers render from the registry,
    the tracer ring, the SLO engine's cached report, and the recorder's
    bundle store, never mutate them — safe to point a scraper at a
    serving process.  ``close()`` shuts the listener down; the client
    never calls it implicitly (a dropped Client must not tear telemetry
    out from under a scraper mid-poll; the daemon thread dies with the
    process)."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: Optional[_metrics.Metrics] = None,
        tracer: Optional[_trace.Tracer] = None,
        slo=None,
        recorder: Optional[_trace.FlightRecorder] = None,
        controller=None,
    ) -> None:
        self._registry = registry or _metrics.default
        self._tracer = tracer  # None → follow the global tracer live
        self._slo = slo
        self._recorder = recorder  # None → follow the global recorder live
        self._controller = controller  # tune.OnlineController, optional
        self._t0 = time.monotonic()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # silence per-request noise
                pass

            def _reply(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                path, _, query = self.path.partition("?")
                try:
                    if path == "/metrics":
                        from urllib.parse import parse_qs

                        om = parse_qs(query).get("openmetrics") == ["1"] or (
                            "application/openmetrics-text"
                            in (self.headers.get("Accept") or "")
                        )
                        self._reply(
                            200,
                            render_prometheus(
                                outer._registry, openmetrics=om
                            ),
                            CONTENT_TYPE_OPENMETRICS if om
                            else CONTENT_TYPE_PROM,
                        )
                    elif path == "/traces":
                        self._reply(
                            200, render_traces(outer._tracer),
                            "application/x-ndjson; charset=utf-8",
                        )
                    elif path == "/perf":
                        from urllib.parse import parse_qs

                        from . import perf as _perf

                        q = parse_qs(query)
                        # ?compile=1 realizes pending cost thunks (one
                        # AOT compile each); ?bench=1 runs the bandwidth
                        # microbench when no cached verdict exists —
                        # both explicit: a scrape must never surprise a
                        # serving process with compiles or a 100-ms
                        # full-bandwidth burn
                        self._reply(
                            200,
                            json.dumps(
                                _perf.render_report(
                                    outer._registry,
                                    realize=q.get("compile") == ["1"],
                                    bench=q.get("bench") == ["1"],
                                ),
                                default=repr,
                            ),
                            "application/json",
                        )
                    elif path == "/decisions":
                        from urllib.parse import parse_qs

                        from . import decisions as _decisions

                        q = parse_qs(query)
                        try:
                            n = max(0, int(q.get("n", ["256"])[0]))
                        except ValueError:
                            n = 256
                        log = _decisions.get()
                        head = {
                            "kind": "summary",
                            "enabled": log is not None,
                            "verdicts": outer._registry.counters_prefixed(
                                "check.verdicts."
                            ),
                        }
                        if log is not None:
                            head["stats"] = log.stats()
                        lines = [json.dumps(head, default=repr)]
                        if log is not None:
                            lines.extend(
                                json.dumps(e, default=repr)
                                for e in log.tail(n)
                            )
                        self._reply(
                            200, "\n".join(lines) + "\n",
                            "application/x-ndjson; charset=utf-8",
                        )
                    elif path == "/slo":
                        slo = _live_slo(outer._slo)
                        body = (
                            {"enabled": False} if slo is None
                            else {"enabled": True, **slo.report()}
                        )
                        self._reply(
                            200, json.dumps(body), "application/json"
                        )
                    elif path == "/debug/incidents":
                        rec = outer._recorder or _trace.recorder()
                        idx = (
                            rec.incident_index() if rec is not None else []
                        )
                        self._reply(
                            200,
                            json.dumps({
                                "incident_dir": (
                                    rec.incident_dir
                                    if rec is not None else None
                                ),
                                "incidents": idx,
                            }),
                            "application/json",
                        )
                    elif path.startswith("/debug/incidents/"):
                        rec = outer._recorder or _trace.recorder()
                        iid = path[len("/debug/incidents/"):]
                        bundle = (
                            rec.bundle(iid) if rec is not None else None
                        )
                        if bundle is None:
                            self._reply(
                                404, "no such incident\n", "text/plain"
                            )
                        else:
                            self._reply(
                                200, bundle,
                                "application/x-ndjson; charset=utf-8",
                            )
                    elif path == "/tune":
                        # self-tuning posture: the controller's live
                        # knob values + trajectory counters, read-only
                        # (revert stays an in-process call on purpose —
                        # a GET must never move a knob)
                        ctl = outer._controller
                        body = {
                            "enabled": ctl is not None,
                            "counters": outer._registry.counters_prefixed(
                                "tune."
                            ),
                        }
                        if ctl is not None:
                            body["status"] = ctl.status()
                        self._reply(
                            200, json.dumps(body, default=repr),
                            "application/json",
                        )
                    elif path == "/healthz":
                        self._reply(
                            200,
                            json.dumps(readiness_report(
                                outer._registry, outer._slo,
                                outer._recorder or _trace.recorder(),
                                uptime_s=time.monotonic() - outer._t0,
                            )),
                            "application/json",
                        )
                    else:
                        self._reply(404, "not found\n", "text/plain")
                except BrokenPipeError:  # scraper went away mid-write
                    pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name=f"gochugaru-telemetry-{self.port}",
            daemon=True,
        )
        self._thread.start()
        _metrics.default.set_gauge("telemetry.port", self.port)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


__all__ = [
    "CONTENT_TYPE_OPENMETRICS",
    "CONTENT_TYPE_PROM",
    "PROM_PREFIX",
    "TelemetryServer",
    "prom_name",
    "readiness_report",
    "render_prometheus",
    "render_traces",
]

"""Telemetry export: Prometheus text rendering of the live Metrics
registry, JSONL trace dump, and a stdlib HTTP daemon serving both.

The bench suite measures offline (Graphulo discipline, arXiv:1609.08642);
a serving process for millions of users must expose the SAME numbers
live.  This module is deliberately dependency-free: ``http.server`` on a
daemon thread, Prometheus exposition text v0.0.4 by hand — the container
bakes no prometheus_client, and the format is ten lines of code.

Surface:

- ``render_prometheus(registry)`` — counters as ``counter``, gauges as
  ``gauge``, timer rings as ``summary`` quantile series (p50/p90/p99/
  p999 via the shared ``metrics.nearest_rank``) plus ``_count``/``_sum``.
- ``render_traces(tracer)`` — the tracer ring as JSONL.
- ``TelemetryServer`` — ``/metrics`` (Prometheus text), ``/traces``
  (JSONL), ``/healthz`` (JSON liveness).  Bound to localhost by
  default; ``port=0`` picks an ephemeral port (read ``.port`` back).
- ``client.with_telemetry(port=...)`` (client.py) starts one per client;
  ``scripts/telemetryd.py`` runs one standalone.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from . import metrics as _metrics
from . import trace as _trace

#: every exported series is namespaced (dots/dashes → underscores after)
PROM_PREFIX = "gochugaru_"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: quantile-label values for the timer summaries, paired with the
#: shared snapshot percentiles (50 → "0.5", 99.9 → "0.999")
_QUANTILE_LABELS = tuple(
    (q, format(q / 100.0, "g")) for q in _metrics.SNAPSHOT_QUANTILES
)


def prom_name(name: str, suffix: str = "") -> str:
    """'checks.dispatch' → 'gochugaru_checks_dispatch<suffix>'."""
    return PROM_PREFIX + _NAME_RE.sub("_", name) + suffix


def _fmt(v: float) -> str:
    # Prometheus wants plain decimal/scientific; repr of a float is fine
    return repr(float(v))


def render_prometheus(registry: Optional[_metrics.Metrics] = None) -> str:
    """The registry as Prometheus exposition text.  Counters/gauges map
    directly; each timer ring becomes a summary — quantile series from
    the SAME nearest-rank math ``Metrics.snapshot`` publishes, so the
    scraped p99 and the in-process p99 cannot disagree."""
    m = registry or _metrics.default
    counters, gauges, timers = m.typed_snapshot()
    hists = m.hist_snapshot()
    lines = []
    for name in sorted(counters):
        pn = prom_name(name, "_total")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt(counters[name])}")
    for name in sorted(gauges):
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(gauges[name])}")
    for name in sorted(timers):
        n, total, samples = timers[name]
        base = _NAME_RE.sub("_", name)
        # timer names already end in '_s' by convention; normalize the
        # exported unit suffix to _seconds either way
        base = base[:-2] if base.endswith("_s") else base
        pn = PROM_PREFIX + base + "_seconds"
        lines.append(f"# TYPE {pn} summary")
        if samples:
            for q, label in _QUANTILE_LABELS:
                lines.append(
                    f'{pn}{{quantile="{label}"}} '
                    f"{_fmt(_metrics.nearest_rank(samples, q))}"
                )
        lines.append(f"{pn}_count {n}")
        lines.append(f"{pn}_sum {_fmt(total)}")
    for name in sorted(hists):
        buckets, counts, n, total = hists[name]
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for b, c in zip(buckets, counts):
            cum += c
            lines.append(f'{pn}_bucket{{le="{format(b, "g")}"}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {n}')
        lines.append(f"{pn}_count {n}")
        lines.append(f"{pn}_sum {_fmt(total)}")
    return "\n".join(lines) + "\n"


def render_traces(tracer: Optional[_trace.Tracer] = None) -> str:
    """The tracer's finished-trace ring as JSONL ('' when tracing is
    disabled or nothing was kept)."""
    tr = tracer if tracer is not None else _trace.get()
    if tr is None:
        return ""
    return tr.dump_jsonl()


class TelemetryServer:
    """``/metrics`` + ``/traces`` + ``/healthz`` on a daemon thread.

    Read-only by construction: the handlers render from the registry and
    the tracer ring, never mutate them — safe to point a scraper at a
    serving process.  ``close()`` shuts the listener down; the client
    never calls it implicitly (a dropped Client must not tear telemetry
    out from under a scraper mid-poll; the daemon thread dies with the
    process)."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: Optional[_metrics.Metrics] = None,
        tracer: Optional[_trace.Tracer] = None,
    ) -> None:
        self._registry = registry or _metrics.default
        self._tracer = tracer  # None → follow the global tracer live
        self._t0 = time.monotonic()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # silence per-request noise
                pass

            def _reply(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._reply(
                            200, render_prometheus(outer._registry),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/traces":
                        self._reply(
                            200, render_traces(outer._tracer),
                            "application/x-ndjson; charset=utf-8",
                        )
                    elif path == "/healthz":
                        self._reply(
                            200,
                            json.dumps({
                                "status": "ok",
                                "uptime_s": round(
                                    time.monotonic() - outer._t0, 3
                                ),
                                "tracing": _trace.enabled(),
                            }),
                            "application/json",
                        )
                    else:
                        self._reply(404, "not found\n", "text/plain")
                except BrokenPipeError:  # scraper went away mid-write
                    pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name=f"gochugaru-telemetry-{self.port}",
            daemon=True,
        )
        self._thread.start()
        _metrics.default.set_gauge("telemetry.port", self.port)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


__all__ = [
    "PROM_PREFIX",
    "TelemetryServer",
    "prom_name",
    "render_prometheus",
    "render_traces",
]

"""Deterministic fault injection: a process-global registry of named
injection sites.

The retry taxonomy (utils/errors.py, utils/retry.py) mirrors the
reference's failure envelope exactly — but until this module existed no
code path ever *raised* the transient errors it classifies, so the
backoff envelope, the partial-result semantics of BulkCheckItemError,
and the watch cursor-resume contract were dead wiring.  Production graph
stores treat failure handling as a benchmarked surface (PAPERS.md:
Graphulo measures degraded-mode throughput explicitly; Samyama leans on
admission control to keep accelerated paths honest under overload); this
registry is the lever that lets tests and benches exercise those paths
end-to-end, deterministically.

Design constraints, in order:

1. **Zero cost when disarmed.**  ``fire(site)`` is called from hot
   dispatch paths (device dispatch, snapshot selection, per-update watch
   delivery).  A module-level ``_ACTIVE`` flag makes the disarmed call a
   single attribute load + branch; no dict lookup, no lock.
2. **Deterministic.**  Every armed site owns its own ``random.Random``
   seeded at arm time, so a chaos run with a fixed seed injects the same
   fault sequence every time — flaky-by-construction tests are worse
   than no tests.
3. **Policy per site.**  Probability (coin per hit), ``times`` (fire at
   most N times), ``after`` (skip the first N hits), or any combination:
   ``arm("device.dispatch", times=1, after=2)`` is "the third dispatch
   fails once".
4. **Classified errors only.**  The default injected error is
   ``UnavailableError`` — the transient class the retry envelope
   understands — so an injection exercises the *production* recovery
   path, not a synthetic one.  Sites may arm any error factory.

Injection sites threaded through the tree (grep ``faults.fire``):

    store.snapshot_for       snapshot-generation selection (store/store.py)
    store.materialize        snapshot swap / rebuild (store/store.py)
    snapshot.finish          snapshot column finalization (store/snapshot.py)
    device.prepare           device-resident snapshot build (engine/device.py)
    prepare.build            staged first-prepare pipeline (engine/flat.py)
    prepare.partition        partition-first stacked/feed build
                             (engine/flat.py sharded builder,
                             engine/partition.py partition_feed)
    closure.delta            incremental closure advance (store/closure.py)
                             AND the group-commit pre-commit point
                             (store/store.py write_group: fires after
                             group formation/collapse, before any state
                             mutates — an armed fault aborts the whole
                             group at its base revision with no zookies
                             minted, and a retry is idempotent)
    device.dispatch          batched check dispatch (engine/device.py)
    lookup.dispatch          frontier-SpMV lookup hop dispatch
                             (engine/spmv.py; the client's lookup
                             surface retries these under the envelope)
    spmm.dispatch            fused K-hop SpMM lookup dispatch
                             (engine/spmm.py; fires BEFORE the fused
                             program launches, so the client retry
                             re-runs the whole fixpoint cleanly; the
                             fused launch also fires lookup.dispatch —
                             it IS one — so coverage armed on either
                             site reaches it)
    latency.dispatch         pinned small-batch dispatch (engine/latency.py)
    pallas.dispatch          Pallas fused-probe dispatch (engine/device.py
                             check paths + engine/latency.py pinned path;
                             fires ONLY when EngineConfig.pallas resolves
                             on, right after the site's own dispatch fault
                             — a fused-kernel failure classifies through
                             the same retry envelope and reroutes exactly
                             like a latency-path one, which the breaker
                             re-form chaos test proves)
    sharded.dispatch         sharded query partition (parallel/sharded.py)
    sharded.collective       shard_map kernel launch (parallel/sharded.py)
    watch.stream             per-update watch delivery (client.py)
    batcher.form             micro-batch formation (serve/batcher.py; a
                             form fault leaves the queue INTACT — the
                             former retries, zero requests lost)
    batcher.dispatch         formed-batch dispatch (serve/batcher.py;
                             classified onto the futures, so the
                             submitters' retry envelopes re-submit)
    cache.lookup             verdict-cache read (engine/vcache.py)
    explain.walk             explain-tree derivation (engine/explain.py;
                             fires BEFORE any tree state exists, so the
                             client envelope's retry can never observe
                             a torn tree)
    router.dispatch          fleet sub-batch dispatch (fleet/router.py;
                             fires before the wire request, so a reroute
                             to a surviving replica re-runs the whole
                             group — idempotent reads, nothing lost)
    router.health            fleet health probe (fleet/router.py; enough
                             consecutive fires on one replica drives the
                             eviction/failover path without killing
                             anything)
    replica.apply            replication-tail entry apply
                             (fleet/replica.py; fires BEFORE
                             apply_replicated, so the resumed tail
                             redelivers the entry from the local-head
                             cursor — exactly-once)
    replica.kill             replica crash (fleet/replica.py; fires on
                             ANY served op and makes the replica die
                             hard — reset sockets, failed probes — the
                             seeded kill the chaos soak's failover story
                             runs on)
"""

from __future__ import annotations

import random
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional, Union

from . import metrics as _metrics
from .errors import UnavailableError

ErrorFactory = Union[BaseException, type, Callable[[str], BaseException]]

#: module-level fast path: False ⇒ fire() returns after one branch.
_ACTIVE = False


class FaultSpec:
    """One armed injection site and its firing policy (mutable counters
    are read back by tests: ``hits`` = times the site was reached while
    armed, ``fired`` = faults actually raised)."""

    __slots__ = ("site", "error", "probability", "times", "after", "rng",
                 "hits", "fired")

    def __init__(
        self,
        site: str,
        error: ErrorFactory,
        probability: float,
        times: Optional[int],
        after: int,
        seed: Optional[int],
    ) -> None:
        self.site = site
        self.error = error
        self.probability = probability
        self.times = times
        self.after = after
        self.rng = random.Random(seed)
        self.hits = 0
        self.fired = 0

    def make_error(self) -> BaseException:
        e = self.error
        if isinstance(e, BaseException):
            return e
        if isinstance(e, type) and issubclass(e, BaseException):
            return e(f"injected fault at {self.site}")
        return e(self.site)  # callable factory

    def should_fire(self) -> bool:
        """Policy decision for one hit (``hits`` already incremented)."""
        if self.hits <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.probability < 1.0 and self.rng.random() >= self.probability:
            return False
        return True


class FaultRegistry:
    """Named injection sites with per-site policies.  One process-global
    ``default`` instance exists; the module-level ``fire``/``arm``/
    ``disarm``/``reset`` helpers operate on it."""

    def __init__(self, registry: Optional[_metrics.Metrics] = None) -> None:
        self._lock = threading.Lock()
        self._specs: Dict[str, FaultSpec] = {}
        self._m = registry or _metrics.default

    # -- arming ----------------------------------------------------------
    def arm(
        self,
        site: str,
        *,
        error: ErrorFactory = UnavailableError,
        probability: float = 1.0,
        times: Optional[int] = None,
        after: int = 0,
        seed: Optional[int] = None,
    ) -> FaultSpec:
        """Arm ``site``.  Defaults inject an ``UnavailableError`` on every
        hit; combine ``probability``/``times``/``after`` for policies
        ("one-shot on the 3rd hit" = ``times=1, after=2``)."""
        spec = FaultSpec(site, error, probability, times, after, seed)
        with self._lock:
            self._specs[site] = spec
        _recompute_active()
        return spec

    def disarm(self, site: str) -> None:
        with self._lock:
            self._specs.pop(site, None)
        _recompute_active()

    def reset(self) -> None:
        """Disarm every site (test teardown)."""
        with self._lock:
            self._specs.clear()
        _recompute_active()

    @contextmanager
    def armed(self, site: str, **kw: Any):
        """``with faults.default.armed("device.dispatch", times=2) as spec:``
        — arm for the block, disarm on exit, yield the spec for counter
        assertions."""
        spec = self.arm(site, **kw)
        try:
            yield spec
        finally:
            self.disarm(site)

    # -- introspection ---------------------------------------------------
    def active(self) -> bool:
        with self._lock:
            return bool(self._specs)

    def spec(self, site: str) -> Optional[FaultSpec]:
        with self._lock:
            return self._specs.get(site)

    def hits(self, site: str) -> int:
        s = self.spec(site)
        return s.hits if s is not None else 0

    def fired(self, site: str) -> int:
        s = self.spec(site)
        return s.fired if s is not None else 0

    # -- the injection point --------------------------------------------
    def maybe_fire(self, site: str) -> None:
        """Raise the armed error for ``site`` if its policy triggers.
        The error is constructed under the lock but raised outside it."""
        with self._lock:
            spec = self._specs.get(site)
            if spec is None:
                return
            spec.hits += 1
            if not spec.should_fire():
                return
            spec.fired += 1
            err = spec.make_error()
        self._m.inc("faults.injected")
        self._m.inc(f"faults.injected.{site}")
        raise err


#: Process-global default registry (mirrors utils/metrics.py ``default``).
default = FaultRegistry()


def _recompute_active() -> None:
    global _ACTIVE
    _ACTIVE = default.active()


def fire(site: str) -> None:
    """The injection point production code calls.  Disarmed cost: one
    module-global load and a branch."""
    if not _ACTIVE:
        return
    default.maybe_fire(site)


def arm(site: str, **kw: Any) -> FaultSpec:
    return default.arm(site, **kw)


def disarm(site: str) -> None:
    default.disarm(site)


def reset() -> None:
    default.reset()


def armed(site: str, **kw: Any):
    return default.armed(site, **kw)

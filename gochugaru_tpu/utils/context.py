"""A Go-style Context: cancellation, deadline, and request-scoped values.

The reference API passes ``context.Context`` as the first argument of every
client method and carries the SpiceDB overlap key in outgoing gRPC metadata
(consistency/consistency.go:21-23, client/client.go:182-191).  This is the
structural equivalent so the client surface keeps the same shape: methods
take ``ctx`` first, cancellation stops streams, and ``with_value`` carries
request metadata such as the overlap key.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Mapping, Optional


class Context:
    """Immutable-ish context chain with cancellation and deadline."""

    def __init__(
        self,
        parent: Optional["Context"] = None,
        *,
        deadline: Optional[float] = None,
        values: Optional[Mapping[str, Any]] = None,
        _root: bool = False,
    ) -> None:
        self._parent = parent
        self._deadline = deadline
        self._values = dict(values or {})
        self._cancelled = threading.Event()
        self._root = _root

    # -- values ------------------------------------------------------------
    def value(self, key: str) -> Any:
        if key in self._values:
            return self._values[key]
        if self._parent is not None:
            return self._parent.value(key)
        return None

    def with_value(self, key: str, val: Any) -> "Context":
        return Context(self, values={key: val})

    # -- tracing (utils/trace.py) ------------------------------------------
    def with_span(self, span) -> "Context":
        """Carry a request-scoped trace span (utils/trace.py) down the
        context chain — the structural analogue of the overlap key riding
        ``with_value``.  The NOOP span rides for free: the SAME context
        comes back, so the disabled-tracing path creates no child
        context (zero dict churn on the latency path)."""
        from . import trace as _trace

        return _trace.ctx_with_span(self, span)

    def span(self):
        """The active trace span carried by this context chain, or the
        NOOP singleton (one branch when tracing is disabled)."""
        from . import trace as _trace

        return _trace.span_of(self)

    # -- cancellation ------------------------------------------------------
    def with_cancel(self) -> "Context":
        return Context(self)

    def with_deadline(self, deadline: float) -> "Context":
        return Context(self, deadline=deadline)

    def with_timeout(self, seconds: float) -> "Context":
        return self.with_deadline(time.monotonic() + seconds)

    def cancel(self) -> None:
        # The background root is uncancellable, like Go's context.Background();
        # cancelling it would poison every context in the process.
        if self._root:
            return
        self._cancelled.set()

    def deadline(self) -> Optional[float]:
        own = self._deadline
        parent = self._parent.deadline() if self._parent is not None else None
        if own is None:
            return parent
        if parent is None:
            return own
        return min(own, parent)

    def done(self) -> bool:
        if self._cancelled.is_set():
            return True
        dl = self.deadline()
        if dl is not None and time.monotonic() >= dl:
            return True
        return self._parent.done() if self._parent is not None else False

    def err(self) -> Optional[BaseException]:
        from .errors import CancelledError, DeadlineExceededError

        if self._cancelled.is_set() or (self._parent is not None and self._parent.done()):
            if self._is_deadline_hit():
                return DeadlineExceededError("context deadline exceeded")
            return CancelledError("context cancelled")
        if self._is_deadline_hit():
            return DeadlineExceededError("context deadline exceeded")
        return None

    def _is_deadline_hit(self) -> bool:
        dl = self.deadline()
        return dl is not None and time.monotonic() >= dl

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until this context is done (cancelled anywhere in the chain,
        or past its deadline).  Returns True if done, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.done():
                return True
            step = 0.05
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self.done()
                step = min(step, remaining)
            dl = self.deadline()
            if dl is not None:
                step = min(step, max(dl - time.monotonic(), 0.0) + 0.001)
            # Wake promptly on own cancellation; parent cancellation and
            # deadlines are caught by the poll above.
            self._cancelled.wait(step)


_BACKGROUND = Context(_root=True)


def background() -> Context:
    return _BACKGROUND


def todo() -> Context:
    return _BACKGROUND

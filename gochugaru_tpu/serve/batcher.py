"""Continuous-batching serving front-end: an async micro-batch former
over the pinned tier ladder.

Every headline number so far was measured on pre-formed giant batches,
but the north-star workload arrives as thousands of concurrent small
Check/CheckMany calls — request-shaped, not batch-shaped.  This module
closes that gap with the idiom inference servers use (continuous
batching): concurrent submissions coalesce into the next pow2 tier slot
of the AOT-pinned latency ladder (engine/latency.py), so the device
always sees one of the shapes it already has a pinned executable for —
no retrace by construction, whatever the traffic does.

Two daemon threads per batcher, so batch FORMATION overlaps in-flight
device DISPATCH (form tier N+1 while N runs):

- the **former** watches the submission queues and flushes a batch when
  (a) the target tier slot fills, (b) the deadline-aware hold-back says
  waiting longer would miss the earliest queued deadline (expected cost
  per tier from the SHARED ``utils/admission.CostModel`` — the same
  estimate the deadline shed uses, no duplicated EWMA), or (c) the
  max-hold timer expires.  Formation drains per-client FIFO queues
  round-robin — **per-client fair admission**: one bulk caller cannot
  starve interactive clients out of a formed batch, because every
  client with pending work gets a turn per rotation.
- the **dispatcher** pops formed batches from a depth-1 queue and runs
  them through the injected dispatch callables (the client's
  ``_evaluate_rels``/``_evaluate_columns`` — breaker-gated, classified
  failures, host-oracle resolution), then slices verdicts back onto
  each submission's future.

Overload sheds, never queues unboundedly: a submission that would push
the pending-check depth past ``queue_max`` raises ``ShedError`` (an
``UnavailableError``, so the caller's retry envelope backs off — the
same contract the admission gate states), and a submission whose
deadline cannot cover the expected queue+dispatch cost sheds before it
ever queues.  When the latency-path CircuitBreaker is OPEN, the former
RE-FORMS for the batch path: target sizing switches from the pinned
tier ladder to ``batch_path_max`` (re-tier, don't replay the pinned
shapes), and the client evaluation reroutes onto the throughput path —
zero requests lost or duplicated across the transition (each future
resolves exactly once; rejected futures re-submit through the caller's
envelope).

Fault sites ``batcher.form`` (fires BEFORE any dequeue — a form fault
leaves the queue intact and the former retries) and
``batcher.dispatch`` (classified onto the batch's futures) ride the
chaos registry (utils/faults.py).
"""

from __future__ import annotations

import heapq
import queue as _queue
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..engine import vcache as _vcache
from ..engine.latency import tier_for
from ..utils import faults
from ..utils import metrics as _metrics
from ..utils import perf as _perf
from ..utils import trace as _trace
from ..utils.admission import OPEN, CostModel
from ..utils.errors import (
    BulkCheckItemError,
    DeadlineExceededError,
    ShedError,
    UnavailableError,
    classify_dispatch_exception,
)


@dataclass(frozen=True)
class ServeConfig:
    """Tuning for the micro-batch former."""

    #: max seconds a queued submission may wait before a partial batch
    #: flushes anyway (the hold-back ceiling)
    hold_max_s: float = 0.002
    #: pending CHECKS (not submissions) before submit() sheds with
    #: ``ShedError`` — the queue-depth shed path
    queue_max: int = 16_384
    #: safety slack subtracted from deadline budgets in the hold-back
    #: decision (clock granularity + wakeup jitter)
    deadline_margin_s: float = 0.0005
    #: formed-batch size cap while the breaker routes to the batch
    #: path (re-tier target; must be ≥ the top latency tier)
    batch_path_max: int = 8_192
    #: ask the client evaluation for the pinned latency path (engines
    #: whose latency path declines still serve on the throughput path)
    use_latency: bool = True
    #: formed batches buffered between former and dispatcher: 1 means
    #: one batch forms while one dispatches (the overlap)
    form_queue_depth: int = 1
    #: seconds close() waits for the drain before rejecting leftovers
    drain_timeout_s: float = 10.0
    #: check deduplication (engine/vcache.py): identical checks in one
    #: formed batch dispatch once (the evaluate layer collapses them and
    #: fans verdicts back out), a submission duplicating a batch already
    #: in flight parks on that batch's resolution (no queue slot, no
    #: tier lane), and the residual unique misses land on the SMALLEST
    #: covering pinned tier — effective tier occupancy counts unique
    #: work and padding shrinks with it, while the former keeps forming
    #: the next batch from the queue in parallel.  False restores the
    #: pre-dedup former byte-for-byte (the bench A/B baseline lever)
    dedup: bool = True


#: guards lazy waiter-event creation on SubmitFuture (module-global: a
#: per-future lock would put the allocation back on the submit path)
_FUT_EV_LOCK = threading.Lock()


class SubmitFuture:
    """The coalesced-result handle one submission awaits.  Resolves
    exactly once (a double resolve is a bug, asserted); ``result``
    honors context cancellation/deadline while waiting.

    The wakeup Event is created LAZILY by the first waiter: a
    threading.Event costs ~8µs to build, and at serving rates most
    futures resolve before anyone blocks on them — the submit path
    (front-end critical on the 1-core proxy) must not pay for a wait
    that usually never happens."""

    __slots__ = ("_done", "_ev", "_value", "_error", "t_submit", "t_done",
                 "dedup_parked")

    def __init__(self, t_submit: float) -> None:
        self._done = False
        self._ev: Optional[threading.Event] = None
        self._value = None
        self._error: Optional[BaseException] = None
        self.t_submit = t_submit
        self.t_done: Optional[float] = None
        #: True when this submission PARKED on an in-flight twin batch
        #: (engine/vcache.Singleflight) — decision-log provenance: its
        #: verdicts never passed the evaluate layer themselves, so the
        #: serving handle records them with ``dedup_parked: true``
        self.dedup_parked = False

    def done(self) -> bool:
        return self._done

    def _settle(self) -> None:
        self._done = True
        ev = self._ev
        if ev is None:
            # a waiter may be creating its event right now: re-check
            # under the same lock the waiter holds while creating it
            with _FUT_EV_LOCK:
                ev = self._ev
        if ev is not None:
            ev.set()

    def _resolve(self, value, t_done: float) -> None:
        assert not self._done, "future resolved twice"
        self._value = value
        self.t_done = t_done
        self._settle()

    def _reject(self, err: BaseException, t_done: float) -> None:
        assert not self._done, "future resolved twice"
        self._error = err
        self.t_done = t_done
        self._settle()

    def result(self, ctx=None, timeout: Optional[float] = None):
        """Block until the coalesced answer (or its error) arrives.
        ``ctx`` cancellation/deadline interrupts the wait."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if not self._done and self._ev is None:
            with _FUT_EV_LOCK:
                if self._ev is None:
                    self._ev = threading.Event()
        while not self._done:
            if ctx is not None:
                err = ctx.err()
                if err is not None:
                    raise err
            step = 0.05
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise DeadlineExceededError(
                        "timed out waiting for coalesced result"
                    )
                step = min(step, remaining)
            self._ev.wait(step)
        if self._error is not None:
            raise self._error
        return self._value


class _Submission:
    """One queued Check/CheckMany: either a list of Relationships or a
    pre-interned column triple, atomic in formation (a submission's
    checks never split across formed batches — its future gets one
    contiguous verdict slice)."""

    __slots__ = (
        "client_id", "kind", "rels", "cols", "n", "deadline", "future",
        "queued",
    )

    def __init__(self, client_id, kind, rels, cols, n, deadline, future):
        self.client_id = client_id
        self.kind = kind  # "rels" | "cols"
        self.rels = rels
        self.cols = cols
        self.n = n
        self.deadline = deadline  # absolute monotonic, or None
        self.future = future
        self.queued = True


class _FormedBatch:
    __slots__ = ("subs", "total", "kind", "target", "reason", "t_formed",
                 "tier")

    def __init__(self, subs, total, kind, target, reason, t_formed, tier):
        self.subs = subs
        self.total = total
        self.kind = kind
        self.target = target
        self.reason = reason
        self.t_formed = t_formed
        self.tier = tier  # ladder tier the batch lands on, or None


#: flush reasons → counter names (serve.flush_*)
_FLUSH_FULL = "full"
_FLUSH_DEADLINE = "deadline"
_FLUSH_MAXHOLD = "maxhold"
_FLUSH_DRAIN = "drain"

#: ``serve.request_latency`` histogram uppers (seconds, submit→resolve).
#: The serve.request_s timer ring gives sliding-window quantiles; the
#: histogram gives the bucket-resolved tail — cumulative, mergeable, and
#: (through the exporter's OpenMetrics exemplars) each bucket links to
#: the last dispatch trace that landed in it
REQUEST_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)


class MicroBatcher:
    """The former/dispatcher pair.  Dispatch is injected so the batcher
    serves any engine shape (single-chip, latency-mode, partitioned
    mesh) and unit tests can drive formation deterministically
    (``start=False`` + ``form_batch``/``dispatch_batch``).

    ``cost`` is the SHARED ``utils/admission.CostModel`` (the client's
    ``AdmissionController.cost``): the hold-back reads per-tier
    expected dispatch cost from it and the dispatcher feeds measured
    batch costs back, so the deadline shed and the hold-back can never
    disagree about what a dispatch costs."""

    def __init__(
        self,
        *,
        tiers: Sequence[int],
        cost: Optional[CostModel] = None,
        breaker=None,
        admission=None,
        config: Optional[ServeConfig] = None,
        dispatch_rels: Optional[Callable] = None,
        dispatch_cols: Optional[Callable] = None,
        registry: Optional[_metrics.Metrics] = None,
        start: bool = True,
        inflight_dedup: bool = True,
    ) -> None:
        self.config = config or ServeConfig()
        self.tiers = tuple(sorted(int(t) for t in tiers))
        if not self.tiers:
            raise ValueError("empty tier ladder")
        self._top = self.tiers[-1]
        if self.config.batch_path_max < self._top:
            raise ValueError("batch_path_max must cover the top tier")
        self._cost = cost if cost is not None else CostModel()
        self._breaker = breaker
        self._adm = admission
        self._dispatch_rels = dispatch_rels
        self._dispatch_cols = dispatch_cols
        self._m = registry or _metrics.default
        #: cross-batch singleflight window (engine/vcache.py) — built
        #: whenever the pinned strategy tolerates serving a duplicate
        #: from its in-flight twin (everything but Full); whether it is
        #: USED is read from ``self.config.dedup`` at each submit/
        #: dispatch, so the online tuner can toggle dedup by swapping
        #: the config without rebuilding the batcher
        self._sf = _vcache.Singleflight(self._m) if inflight_dedup else None
        #: occupancy histogram buckets: the ladder itself plus half/
        #: quarter marks, so "flushed at 61 of 256" is visible
        self._fill_buckets = tuple(sorted(
            {t for t in self.tiers}
            | {max(1, t // 2) for t in self.tiers}
            | {max(1, t // 4) for t in self.tiers}
        ))
        #: per-tier occupancy buckets (``serve.occupancy.t{tier}``):
        #: live-lane counts at fixed fractions of the tier, precomputed
        #: here because a histogram's buckets freeze at first observe —
        #: the tuner reads these to place a tighter (possibly non-pow2)
        #: tier where the occupancy mass actually sits
        self._occ_buckets = {
            t: tuple(sorted({
                max(1, round(t * f))
                for f in (0.0625, 0.125, 0.1875, 0.25, 0.375, 0.5,
                          0.625, 0.75, 0.875, 1.0)
            }))
            for t in self.tiers
        }
        self._cond = threading.Condition()
        #: client_id → FIFO of _Submission (insertion-ordered dict: the
        #: round-robin rotation walks it)
        self._queues: "OrderedDict[Any, deque]" = OrderedDict()
        self._depth = 0  # queued CHECKS
        self._rr = 0  # round-robin rotation cursor
        self._dl_heap: List[Tuple[float, int, _Submission]] = []
        self._dl_seq = 0
        self._closed = False
        self._form_q: "_queue.Queue" = _queue.Queue(
            maxsize=max(1, self.config.form_queue_depth)
        )
        self._threads: List[threading.Thread] = []
        self._former_t: Optional[threading.Thread] = None
        self._disp_t: Optional[threading.Thread] = None
        if start:
            self._former_t = threading.Thread(
                target=self._former_loop,
                name="gochugaru-serve-former", daemon=True,
            )
            self._disp_t = threading.Thread(
                target=self._dispatcher_loop,
                name="gochugaru-serve-dispatcher", daemon=True,
            )
            self._threads = [self._former_t, self._disp_t]
            for t in self._threads:
                t.start()

    # -- submission ------------------------------------------------------
    @property
    def depth(self) -> int:
        with self._cond:
            return self._depth

    def submit_rels(self, client_id, rels, ctx=None) -> SubmitFuture:
        return self._submit(client_id, "rels", rels=list(rels),
                            n=len(rels), ctx=ctx)

    def submit_columns(
        self, client_id, q_res, q_perm, q_subj, ctx=None
    ) -> SubmitFuture:
        cols = (
            np.ascontiguousarray(q_res, np.int32),
            np.ascontiguousarray(q_perm, np.int32),
            np.ascontiguousarray(q_subj, np.int32),
        )
        return self._submit(client_id, "cols", cols=cols,
                            n=int(cols[0].shape[0]), ctx=ctx)

    def _submit(self, client_id, kind, *, rels=None, cols=None, n=0,
                ctx=None) -> SubmitFuture:
        t_submit = time.perf_counter()
        fut = SubmitFuture(t_submit)
        if n == 0:
            fut._resolve([] if kind == "rels" else np.zeros(0, bool), t_submit)
            return fut
        if n > self._top:
            raise ValueError(
                f"submission of {n} checks exceeds the top tier"
                f" {self._top} — batch-shaped work belongs on the"
                " throughput path, not the micro-batcher"
            )
        self._m.inc("serve.submissions")
        span = _trace.span_of(ctx) if ctx is not None else _trace.NOOP
        deadline = None
        if ctx is not None:
            dl = ctx.deadline()
            if dl is not None:
                # context deadlines are time.monotonic-based; queue
                # bookkeeping runs on perf_counter — convert once here
                deadline = t_submit + (dl - time.monotonic())
            # deadline-budget shed through the admission controller:
            # the SAME cost model + counters as the caller-formed path
            if self._adm is not None:
                self._adm.check_deadline(ctx, span=span)
        sf = self._sf if self.config.dedup else None
        if sf is not None and sf.active:
            # cross-batch singleflight: a submission whose rows ALL
            # duplicate the currently-dispatching batch's checks parks
            # on that batch's resolution — no queue slot, no tier lane.
            # One Python-scalar probe rules out the common non-dup case
            # before any per-row key packing happens
            if kind == "cols":
                k0 = _vcache.pack_one(
                    int(cols[1][0]), int(cols[0][0]), int(cols[2][0])
                )
            else:
                k0 = _vcache.rel_key(rels[0])
            if sf.probe(k0):
                if kind == "cols":
                    keys = _vcache.pack_cols(cols[1], cols[0], cols[2])
                else:
                    keys = [_vcache.rel_key(r) for r in rels]
                if sf.try_park(keys, fut, kind, n):
                    fut.dedup_parked = True
                    span.event("serve.dedup_parked", checks=n)
                    return fut
        shed_depth = None
        with self._cond:
            if self._closed:
                raise UnavailableError("serving handle is closed")
            if self._depth + n > self.config.queue_max:
                self._m.inc("serve.sheds")
                shed_depth = self._depth
            else:
                sub = _Submission(
                    client_id, kind, rels, cols, n, deadline, fut
                )
                was_empty = self._depth == 0
                q = self._queues.get(client_id)
                if q is None:
                    q = self._queues[client_id] = deque()
                q.append(sub)
                self._depth += n
                self._m.set_gauge("serve.queue_depth", self._depth)
                if deadline is not None:
                    self._dl_seq += 1
                    heapq.heappush(
                        self._dl_heap, (deadline, self._dl_seq, sub)
                    )
                # wake the former only when this submission can CHANGE
                # its decision: first work after idle, a full target
                # tier, or a new deadline that may tighten the
                # hold-back.  Every other submission rides the former's
                # own timed wait — at tens of thousands of
                # submissions/s, notify-per-submit is the front-end's
                # biggest avoidable cost
                if (
                    was_empty or deadline is not None
                    or self._depth >= self._top
                ):
                    self._cond.notify_all()
        if shed_depth is not None:
            # shed bookkeeping OUTSIDE the condition lock: the spike-
            # threshold-crossing note() spawns an incident capture
            # thread, and that spawn must not serialize submitters and
            # the former/dispatcher loops on the hottest lock at peak
            # load (same hoist as the admission gate's shed path)
            _trace.note_anomaly("shed")
            span.event(
                "serve.shed", depth=shed_depth, submitting=n,
                queue_max=self.config.queue_max,
            )
            raise ShedError(
                f"serve queue depth {shed_depth} + {n} >"
                f" queue_max {self.config.queue_max}"
            )
        return fut

    # -- formation -------------------------------------------------------
    def _batch_path_mode(self) -> bool:
        """OPEN breaker → the pinned latency shapes lost trust: re-form
        for the batch path (HALF_OPEN keeps the ladder — probes must
        land on the pinned shapes to close the breaker)."""
        return self._breaker is not None and self._breaker.state == OPEN

    def _target_cap(self) -> int:
        return (
            self.config.batch_path_max if self._batch_path_mode()
            else self._top
        )

    def _earliest_deadline_locked(self, now: float) -> Optional[float]:
        h = self._dl_heap
        while h and not h[0][2].queued:
            heapq.heappop(h)
        return h[0][0] if h else None

    def _oldest_submit_locked(self) -> Optional[float]:
        # each client queue is FIFO, so the global oldest is among heads
        heads = [q[0].future.t_submit for q in self._queues.values() if q]
        return min(heads) if heads else None

    def _flush_decision_locked(self, now: float):
        """(flush?, reason, wait_s) for the current queue state."""
        cfg = self.config
        cap = self._target_cap()
        if self._closed:
            return True, _FLUSH_DRAIN, 0.0
        if self._depth >= cap:
            return True, _FLUSH_FULL, 0.0
        wait = cfg.hold_max_s
        oldest = self._oldest_submit_locked()
        if oldest is not None:
            held = now - oldest
            if held >= cfg.hold_max_s:
                return True, _FLUSH_MAXHOLD, 0.0
            wait = cfg.hold_max_s - held
        dl = self._earliest_deadline_locked(now)
        if dl is not None:
            # deadline-aware hold-back: flush the moment waiting longer
            # would put the earliest deadline inside the expected
            # dispatch cost for the tier this queue would land on
            tier = tier_for(self.tiers, min(self._depth, self._top))
            est = self._cost.expected_s(tier)
            slack = (dl - now) - est - cfg.deadline_margin_s
            if slack <= 0:
                return True, _FLUSH_DEADLINE, 0.0
            wait = min(wait, slack)
        return False, "", max(wait, 1e-4)

    def form_batch(self) -> Optional[_FormedBatch]:
        """Block until a batch is due, then form and return it (None
        when closed and drained).  The former thread's body; tests call
        it directly for deterministic formation."""
        with self._cond:
            while True:
                if self._depth == 0:
                    if self._closed:
                        return None
                    self._cond.wait(0.05)
                    continue
                now = time.perf_counter()
                flush, reason, wait_s = self._flush_decision_locked(now)
                if not flush:
                    # hold-back with work queued: the wall ledger calls
                    # this queue-wait (submissions sit while the former
                    # deliberately holds) — reported around the wait so
                    # the 21× question shows up as a bucket, not idle
                    self._cond.wait(wait_s)
                    _perf.report_wall("queue_wait", now, time.perf_counter())
                    continue
                # the injection point sits BEFORE any dequeue: a form
                # fault leaves every submission queued — the former
                # pauses and retries, zero requests lost
                try:
                    faults.fire("batcher.form")
                except Exception:
                    self._m.inc("serve.form_faults")
                    self._cond.wait(0.002)
                    # form-fault retry pause: attributed to formation,
                    # not lost to idle (the chaos closure test's subject)
                    _perf.report_wall("form", now, time.perf_counter())
                    continue
                batch = self._form_locked(reason, now)
                t_f1 = time.perf_counter()
                _perf.report_wall("form", now, t_f1)
                self._m.observe("serve.form_s", t_f1 - now)
                return batch

    def _form_locked(self, reason: str, now: float) -> _FormedBatch:
        cfg = self.config
        # deadline-heap hygiene: formed/settled entries are popped only
        # when they surface at the heap head, so sustained
        # deadline-bearing traffic would otherwise grow it without
        # bound — compact when stale entries dominate
        if len(self._dl_heap) > 64:
            live = sum(len(q) for q in self._queues.values())
            if len(self._dl_heap) > max(64, 4 * live):
                self._dl_heap = [
                    e for e in self._dl_heap if e[2].queued
                ]
                heapq.heapify(self._dl_heap)
        cap = self._target_cap()
        batch_path = cap > self._top
        target = (
            cap if batch_path
            else (tier_for(self.tiers, min(self._depth, self._top))
                  or self._top)
        )
        picked: List[_Submission] = []
        total = 0
        kind: Optional[str] = None
        clients = list(self._queues.keys())
        start = self._rr % len(clients)
        order = clients[start:] + clients[:start]
        self._rr += 1
        progress = True
        while progress and total < target:
            progress = False
            for cid in order:
                q = self._queues.get(cid)
                if not q:
                    continue
                head = q[0]
                if head.deadline is not None and head.deadline <= now:
                    # already dead: reject now instead of burning a slot
                    q.popleft()
                    if not q:
                        self._queues.pop(cid, None)
                    head.queued = False
                    self._depth -= head.n
                    self._m.inc("serve.deadline_expired")
                    head.future._reject(
                        DeadlineExceededError(
                            "deadline passed while queued for a batch"
                        ),
                        now,
                    )
                    progress = True
                    continue
                if kind is not None and head.kind != kind:
                    continue
                if total + head.n > target:
                    continue
                q.popleft()
                if not q:
                    self._queues.pop(cid, None)
                head.queued = False
                if kind is None:
                    kind = head.kind
                picked.append(head)
                total += head.n
                self._depth -= head.n
                progress = True
                if total >= target:
                    break
        self._m.set_gauge("serve.queue_depth", self._depth)
        tier = tier_for(self.tiers, total) if not batch_path else None
        if picked:
            m = self._m
            m.inc(f"serve.flush_{reason}")
            if batch_path:
                m.inc("serve.reformed_batchpath")
            for s in picked:
                m.observe("serve.queue_wait_s", now - s.future.t_submit)
            oldest = min(s.future.t_submit for s in picked)
            m.observe("serve.hold_s", now - oldest)
            m.observe_hist("serve.batch_fill", total, self._fill_buckets)
            if tier is not None:
                m.observe_hist(
                    "serve.occupancy", total / tier,
                    (0.25, 0.5, 0.75, 0.9, 1.0),
                )
                # per-tier live-lane histogram — the tuner's primary
                # input ("tier 1024 p90 occupancy 131" reads off this)
                m.observe_hist(
                    f"serve.occupancy.t{tier}", total,
                    self._occ_buckets[tier],
                )
        return _FormedBatch(picked, total, kind, target, reason, now, tier)

    # -- dispatch --------------------------------------------------------
    def dispatch_batch(self, batch: _FormedBatch) -> None:
        """Run one formed batch through the injected evaluation and
        settle every future exactly once.  Dispatch failures classify
        onto the retry taxonomy and reject the batch's futures — the
        submitters' envelopes re-submit, so a transient fault (or the
        breaker tripping mid-queue) loses nothing.

        With dedup on, the batch's key→row map opens a singleflight
        WINDOW for the duration of the dispatch: submissions arriving
        meanwhile whose rows all duplicate in-flight checks park on it
        and settle here, from the same verdicts (engine/vcache.py
        Singleflight) — the window closes on every exit path."""
        m = self._m
        if not batch.subs:
            return
        t0 = time.perf_counter()
        # wall ledger: formed→dispatch-start is the formed batch's queue
        # wait; the dispatch window itself reports as ``filter`` (host
        # concat/slice/settle) with the device stages — reported by the
        # latency path from the same stamps its budget uses — overlaying
        # it at higher priority, so filter ends up the host-side residue
        _perf.report_wall("queue_wait", batch.t_formed, t0)
        sp = _trace.root_span(
            "serve.dispatch",
            batch=batch.total, target=batch.target, reason=batch.reason,
            kind=batch.kind, submissions=len(batch.subs),
            occupancy=round(batch.total / batch.target, 4),
        )
        sf = self._sf if self.config.dedup else None
        window_open = False
        verdicts = None
        try:
            try:
                faults.fire("batcher.dispatch")
                use_latency = self.config.use_latency and batch.tier is not None
                if batch.kind == "cols":
                    if len(batch.subs) == 1:
                        q_res, q_perm, q_subj = batch.subs[0].cols
                    else:
                        q_res = np.concatenate([s.cols[0] for s in batch.subs])
                        q_perm = np.concatenate([s.cols[1] for s in batch.subs])
                        q_subj = np.concatenate([s.cols[2] for s in batch.subs])
                    if sf is not None:
                        keys = _vcache.pack_cols(q_perm, q_res, q_subj)
                        if isinstance(keys, np.ndarray):
                            ks = np.sort(keys)
                            # unique-work count off the same sort the
                            # window probes use — effective occupancy
                            unique = int(
                                1 + (ks[1:] != ks[:-1]).sum()
                            ) if ks.shape[0] else 0
                            sf.open_cols(keys, ks)
                        else:
                            key_map = dict(zip(keys, range(len(keys))))
                            unique = len(key_map)
                            sf.open_map(key_map)
                        sp.set_attr("unique", unique)
                        m.inc("serve.unique_checks", unique)
                        window_open = True
                    verdicts = self._dispatch_cols(
                        q_res, q_perm, q_subj, use_latency, sp
                    )
                else:
                    rels = [r for s in batch.subs for r in s.rels]
                    if sf is not None:
                        kl = [_vcache.rel_key(r) for r in rels]
                        key_map = dict(zip(kl, range(len(kl))))
                        sp.set_attr("unique", len(key_map))
                        m.inc("serve.unique_checks", len(key_map))
                        sf.open_map(key_map)
                        window_open = True
                    verdicts = self._dispatch_rels(rels, use_latency, sp)
            except BulkCheckItemError as e:
                # a per-item oracle failure is batch-relative: slice it
                # back onto submissions.  Fully-evaluated submissions
                # resolve normally, the failing one gets ITS OWN
                # submission-relative BulkCheckItemError (no
                # cross-submitter verdict leakage, no out-of-range
                # index), and never-evaluated ones reject retriable so
                # their envelopes re-submit — they weren't at fault
                m.inc("serve.dispatch_errors")
                sp.set_attr("error", "BulkCheckItemError")
                t1 = time.perf_counter()
                off = 0
                for s in batch.subs:
                    if off + s.n <= e.index:
                        s.future._resolve(e.results[off:off + s.n], t1)
                    elif off <= e.index:
                        s.future._reject(
                            BulkCheckItemError(
                                e.index - off, e.results[off:e.index],
                                e.__cause__ or e,
                            ),
                            t1,
                        )
                    else:
                        s.future._reject(UnavailableError(
                            "batch aborted by another submission's"
                            " per-item failure"
                        ), t1)
                    off += s.n
                return
            except Exception as e:
                classified = classify_dispatch_exception(e)
                err = classified if classified is not None else e
                m.inc("serve.dispatch_errors")
                sp.set_attr("error", type(err).__name__)
                t1 = time.perf_counter()
                for s in batch.subs:
                    s.future._reject(err, t1)
                return
            dt = time.perf_counter() - t0
            # feed the shared cost model at this batch's ladder tier —
            # the hold-back's estimate learns from real coalesced
            # dispatches, not just caller-formed ones.  Batch-path
            # (breaker-open) batches have no ladder tier; they tag with
            # their target cap instead of the tier-less channel, which
            # is reserved for CALLER-formed dispatch costs (see
            # CostModel.observe)
            self._cost.observe(
                dt, tier=batch.tier if batch.tier is not None else batch.target
            )
            m.observe("serve.dispatch_s", dt)
            t1 = time.perf_counter()
            # exemplar: the batch's dispatch trace id, so a fat latency
            # bucket on /metrics links straight to a recorded trace
            # (flight-only spans carry ids too — the recorder retains
            # them even when the head sample dropped the trace)
            tid = sp.trace_id if sp.sampled else None
            off = 0
            for s in batch.subs:
                s.future._resolve(verdicts[off:off + s.n], t1)
                lat = t1 - s.future.t_submit
                m.observe("serve.request_s", lat)
                m.observe_hist(
                    "serve.request_latency", lat,
                    REQUEST_LATENCY_BUCKETS, trace_id=tid,
                )
                off += s.n
            m.inc("serve.batches")
            m.inc("serve.checks", batch.total)
        finally:
            # settle-exactly-once backstop: a BaseException escaping the
            # paths above (interpreter shutdown, a settle-path bug) must
            # not strand futures mid-dispatch — whoever is still waiting
            # gets a classified rejection instead of a hang.  The
            # singleflight window settles the same way: on success the
            # parked futures resolve from this batch's verdicts, on any
            # failure they reject retriable and their envelopes
            # re-submit
            for s in batch.subs:
                if not s.future.done():
                    s.future._reject(
                        UnavailableError("serve dispatch aborted"),
                        time.perf_counter(),
                    )
            if window_open:
                sf.close(
                    verdicts,
                    None if verdicts is not None else UnavailableError(
                        "deduplicated twin's batch failed; re-submit"
                    ),
                    time.perf_counter(),
                )
            _perf.report_wall("filter", t0, time.perf_counter())
            sp.end()

    # -- threads ---------------------------------------------------------
    def _reject_batch(self, batch: _FormedBatch, err: BaseException) -> None:
        now = time.perf_counter()
        for s in batch.subs:
            if not s.future.done():
                s.future._reject(err, now)

    def _former_loop(self) -> None:
        try:
            while True:
                batch = self.form_batch()
                if batch is None:
                    break
                # hand off without blocking forever: if the dispatcher
                # died, this thread — not close(), which can't reach an
                # in-hand batch — must settle the batch's futures.  The
                # handoff wait (former blocked behind a busy dispatcher)
                # is attributed to the ``form`` wall bucket: it is a
                # formation stall, and leaving it to the idle residual
                # would make the tuner read dispatch backpressure as
                # headroom
                t_h0 = time.perf_counter()
                while True:
                    try:
                        self._form_q.put(batch, timeout=0.25)
                        break
                    except _queue.Full:
                        d = self._disp_t
                        if d is not None and not d.is_alive():
                            self._reject_batch(batch, UnavailableError(
                                "serve dispatcher thread died"
                            ))
                            break
                _perf.report_wall("form", t_h0, time.perf_counter())
        except BaseException:  # never leave submitters hanging on a
            self._emergency_stop()  # dead former — close() rejects them
            raise
        finally:
            try:  # drain sentinel; a full queue is fine — the
                self._form_q.put_nowait(None)  # dispatcher also polls
            except _queue.Full:  # _closed + former-dead as its exit
                pass

    def _dispatcher_loop(self) -> None:
        try:
            while True:
                try:
                    batch = self._form_q.get(timeout=0.25)
                except _queue.Empty:
                    # sentinel-less exit: a dead/finished former sends
                    # nothing more, so closed + empty queue = done
                    f = self._former_t
                    if self._closed and (f is None or not f.is_alive()):
                        return
                    continue
                if batch is None:
                    return
                self.dispatch_batch(batch)
        except BaseException:
            self._emergency_stop()
            raise

    def _emergency_stop(self) -> None:
        self._m.inc("serve.thread_crashes")
        threading.Thread(target=self.close, daemon=True).start()

    # -- lifecycle -------------------------------------------------------
    def apply_config(self, config: ServeConfig) -> None:
        """Swap the serve config atomically (the online tuner's apply
        path).  ServeConfig is frozen and ``self.config`` is read fresh
        at every decision point, so a single attribute store is the
        whole transaction; the former is woken so a SHORTER hold-back
        takes effect on the batch it is currently holding rather than
        one hold later.  Dedup toggles the same way: the singleflight
        window object persists, ``config.dedup`` gates its use."""
        if config.batch_path_max < self._top:
            raise ValueError("batch_path_max must cover the top tier")
        self.config = config
        with self._cond:
            self._cond.notify_all()

    def close(self) -> None:
        """Drain: flush everything queued, stop both threads, reject
        any straggler futures (classified, so callers back off rather
        than hang)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=self.config.drain_timeout_s)
        leftovers: List[_Submission] = []
        while True:  # formed-but-undispatched batches (a dead dispatcher)
            try:
                b = self._form_q.get_nowait()
            except _queue.Empty:
                break
            if b is not None:
                leftovers.extend(s for s in b.subs if not s.future.done())
        if self._sf is not None:
            # a window left open by a killed dispatcher: fail its parked
            # futures closed instead of stranding them
            self._sf.close(None, UnavailableError(
                "serving handle closed before dispatch"
            ), time.perf_counter())
        with self._cond:
            for q in self._queues.values():
                leftovers.extend(s for s in q if not s.future.done())
            self._queues.clear()
            self._depth = 0
            self._m.set_gauge("serve.queue_depth", 0)
        now = time.perf_counter()
        for s in leftovers:
            s.queued = False
            s.future._reject(
                UnavailableError("serving handle closed before dispatch"),
                now,
            )

"""Continuous-batching serving front-end (the ``serve`` subsystem).

``Client.with_serving(...)`` (client.py) opens a ``ServingHandle`` over
a client: concurrent Check/CheckMany submissions coalesce into pinned
pow2 tier slots through the ``MicroBatcher`` (serve/batcher.py), with
per-client fairness, deadline-aware hold-back, and the admission
gate/breaker as the shed path.  ``benchmarks/bench9_serve.py`` is the
open-loop traffic bench over this surface.
"""

from .batcher import MicroBatcher, ServeConfig, SubmitFuture
from .handle import ServingHandle

__all__ = ["MicroBatcher", "ServeConfig", "ServingHandle", "SubmitFuture"]

"""ServingHandle: the client-facing surface of the micro-batcher.

``handle.check(ctx, *rels)`` submits into the batcher and blocks on the
coalesced result; transient faults (a shed, an injected dispatch fault,
the breaker tripping mid-queue) reject the submission's future with a
classified error and the reference retry envelope RE-SUBMITS — so every
call resolves exactly once, through however many re-formed batches it
takes.  ``submit``/``submit_columns`` return the raw futures for
open-loop callers that must not block on their own traffic
(benchmarks/bench9_serve.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

import numpy as np

from ..engine.plan import EngineConfig
from ..rel.relationship import RelationshipLike, as_relationship
from ..utils import decisions as _decisions
from ..utils import trace as _trace
from ..utils.retry import retry_retriable_errors
from .batcher import MicroBatcher, ServeConfig, SubmitFuture


class ServingHandle:
    """One continuous-batching front-end over one Client, pinned to one
    consistency strategy (every formed batch evaluates at a single
    snapshot).  Context-manager friendly: closing drains the queue and
    stops the former/dispatcher threads."""

    def __init__(
        self, client, cs, config: Optional[ServeConfig] = None,
        *, use_cache: bool = True,
    ) -> None:
        self._client = client
        self._cs = cs
        #: with_serving(cache=False) forces this handle's evaluates
        #: cache-off even when the client carries a verdict cache (the
        #: bench A/B lever); the pinned strategy is otherwise the
        #: cache's read policy (full() bypasses by policy)
        self._use_cache = use_cache
        ecfg = client._engine_config or EngineConfig()
        adm = client._admission
        from ..consistency import Requirement

        self.batcher = MicroBatcher(
            tiers=ecfg.latency_tiers,
            cost=adm.cost,
            breaker=adm.breaker,
            admission=adm,
            config=config,
            dispatch_rels=self._dispatch_rels,
            dispatch_cols=self._dispatch_cols,
            # cross-batch singleflight parks a duplicate on its in-
            # flight twin's resolution — sound for MinLatency (the twin
            # is at least as fresh as if the duplicate had arrived when
            # its twin did), AtLeast (the twin's revision is >= the
            # floor) and Snapshot (same pinned revision); Full must see
            # the head at its own dispatch, so it never parks
            inflight_dedup=cs.requirement != Requirement.FULL,
        )

    # -- batch evaluation (called from the dispatcher thread) ------------
    def _dispatch_rels(self, rels, latency, span):
        client = self._client
        snap = client._store.snapshot_for(self._cs)
        span.set_attr("revision", int(snap.revision))
        return client._evaluate_rels(
            snap, rels, latency=latency, span=span,
            cs=self._cs if self._use_cache else None,
            dedup=self.batcher.config.dedup,
        )

    def _dispatch_cols(self, q_res, q_perm, q_subj, latency, span):
        client = self._client
        snap = client._store.snapshot_for(self._cs)
        span.set_attr("revision", int(snap.revision))
        return client._evaluate_columns(
            snap, q_res, q_perm, q_subj, latency=latency, span=span,
            cs=self._cs if self._use_cache else None,
            dedup=self.batcher.config.dedup,
        )

    # -- blocking check surface ------------------------------------------
    @staticmethod
    def _client_id(client_id) -> Any:
        # fairness key defaults to the calling thread: each concurrent
        # caller is its own admission class unless it names one
        return client_id if client_id is not None else threading.get_ident()

    def check(
        self, ctx, *rs: RelationshipLike, client_id=None,
        explain: bool = False,
    ) -> List[bool]:
        """Batched permission check through the micro-batcher: submits
        into the next formed tier slot and awaits the coalesced result,
        under the same retry envelope ``client.check`` uses (a shed or
        a transient batch fault re-submits).

        ``explain=True`` additionally re-derives each verdict's typed
        resolution tree at the handle's pinned strategy — ONE snapshot
        for the whole batch's trees (witness codes extracted in one
        armed dispatch), returning ``List[ExplainedCheck]``: the
        coalesced verdict plus the tree.  The verdict came from the
        batcher's own dispatch snapshot; under ``min_latency`` a write
        landing between the coalesced dispatch and the explain can move
        the head, so a tree disagreeing with its served verdict is
        flagged ``verdict_skew`` (the tree's ``revision`` names the
        world it describes) instead of silently posing as the verdict's
        derivation."""
        self._client._check_overlap(ctx)
        rels = [as_relationship(r) for r in rs]
        if not rels:
            return []
        cid = self._client_id(client_id)
        root = _trace.root_span("serve.check", batch=len(rels))
        ctx = _trace.ctx_with_span(ctx, root)
        pre_snap = pre_ents = None
        if explain:
            # cache residency probed BEFORE submitting: entries the
            # coalesced dispatch itself inserts are fresh work, not
            # cache-served provenance
            pre_snap = self._client._store.snapshot_for(self._cs)
            pre_ents = self._client._peek_cached(pre_snap, rels, self._cs)

        def attempt():
            fut = self.batcher.submit_rels(cid, rels, ctx)
            out = fut.result(ctx)
            if fut.dedup_parked:
                # parked on an in-flight twin: these verdicts never ran
                # the evaluate layer themselves, so their provenance is
                # recorded HERE — counted, and logged dedup_parked
                _decisions.count_verdicts(
                    self.batcher._m,
                    sum(1 for v in out if v),
                    sum(1 for v in out if not v),
                    _decisions.strategy_name(self._cs),
                )
                if _decisions.enabled():
                    _decisions.record_rels(
                        rels, out, strategy=self._cs, dedup_parked=True,
                        latency_s=(
                            (fut.t_done or time.perf_counter())
                            - fut.t_submit
                        ),
                        trace_id=root.trace_id if root.sampled else None,
                        client_id=cid,
                    )
            return out

        with root:
            verdicts = retry_retriable_errors(ctx, attempt)
            if not explain:
                return verdicts
            client = self._client

            def derive():
                sp = _trace.span_of(ctx)
                snap = client._store.snapshot_for(self._cs)
                # if a write moved the head since the pre-submit probe,
                # its entries describe another revision: treat every
                # item as uncached rather than mislabel provenance
                ents = (
                    pre_ents
                    if pre_snap is not None
                    and snap.revision == pre_snap.revision
                    else [None] * len(rels)
                )
                # the witness extraction is a real device dispatch: it
                # runs under the client's admission envelope (deadline
                # shed + in-flight gate), same as client explain
                codes = client._admitted(
                    ctx, sp, lambda: client._witness_batch(snap, rels)
                )
                return client._explain_batch(
                    snap, rels, verdicts, self._cs, cache_ents=ents,
                    codes=codes,
                )

            return retry_retriable_errors(ctx, derive)

    def check_one(self, ctx, r: RelationshipLike, *, client_id=None) -> bool:
        return self.check(ctx, r, client_id=client_id)[0]

    def check_many(
        self, ctx, rs, *, client_id=None
    ) -> List[bool]:
        return self.check(ctx, *rs, client_id=client_id)

    def check_columns(
        self, ctx, q_res, q_perm, q_subj, *, client_id=None
    ) -> np.ndarray:
        """Columnar mirror of ``check``: pre-interned int32 columns in,
        bool verdicts out, coalesced with everything else in flight."""
        self._client._check_overlap(ctx)
        cid = self._client_id(client_id)
        root = _trace.root_span("serve.check", batch=int(q_res.shape[0]))
        ctx = _trace.ctx_with_span(ctx, root)

        def attempt():
            fut = self.batcher.submit_columns(cid, q_res, q_perm, q_subj, ctx)
            return fut.result(ctx)

        with root:
            return retry_retriable_errors(ctx, attempt)

    # -- open-loop surface -----------------------------------------------
    def submit(self, ctx, *rs: RelationshipLike, client_id=None) -> SubmitFuture:
        """Fire-and-await-later: returns the submission's future without
        blocking (sheds raise immediately — the open-loop caller counts
        them instead of retrying)."""
        self._client._check_overlap(ctx)
        rels = [as_relationship(r) for r in rs]
        return self.batcher.submit_rels(self._client_id(client_id), rels, ctx)

    def submit_columns(
        self, ctx, q_res, q_perm, q_subj, *, client_id=None
    ) -> SubmitFuture:
        self._client._check_overlap(ctx)
        return self.batcher.submit_columns(
            self._client_id(client_id), q_res, q_perm, q_subj, ctx
        )

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        self.batcher.close()

    def __enter__(self) -> "ServingHandle":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

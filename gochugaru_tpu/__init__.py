"""gochugaru_tpu — a TPU-native authorization framework.

A brand-new framework with the client-visible capabilities of
``authzed/gochugaru`` (the ergonomic SpiceDB Go client,
``/root/reference/gochugaru.go:1-9``): the same Check/Write/Read/Delete/
Watch/Schema/Import/Export/Lookup surface and consistency strategies —
but instead of RPC-ing to a SpiceDB server, permission evaluation runs
locally on TPU.  SpiceDB-style schemas are compiled into JAX reachability
programs; relationships are interned to integer columns held as sorted
columnar snapshots on device; bulk checks are a vmap batch axis; multi-hop
userset-rewrite expansion lowers to capped frontier BFS plus dense boolean
fixpoint iteration, shardable over a ``jax.sharding.Mesh`` with
all-reduce(OR) collectives.

Package layout (mirrors the reference's layer map, SURVEY.md §1):

- ``rel``          — the data model (reference ``rel/``)
- ``consistency``  — consistency strategies (reference ``consistency/``)
- ``schema``       — SpiceDB schema-language parser + IR compiler
- ``caveats``      — CEL-subset caveat expression compiler
- ``store``        — interners, MVCC tuple log, columnar snapshots
- ``engine``       — the evaluators: host oracle + JAX device engine
- ``parallel``     — mesh/sharding helpers, multi-chip bulk check
- ``serve``        — continuous-batching front-end (micro-batch former
  over the pinned tier ladder; ``Client.with_serving``)
- ``client``       — the ergonomic Client facade (reference ``client/``)
- ``utils``        — context, retry/backoff, errors, metrics
"""

__version__ = "0.1.0"

from . import consistency, rel  # noqa: F401  (re-exported subpackages)

import importlib.util as _ilu

if _ilu.find_spec(".client", __package__) is not None:
    # The client facade pulls in jax; the data model above stays importable
    # without it.  Import errors inside the client itself must surface.
    from .client import Client, new_tpu_evaluator, new_with_opts  # noqa: F401

"""The Client: the ergonomic facade with the reference's full 18-method
surface (client/client.go §2.1 of SURVEY.md), backed by the local TPU
evaluation engine instead of a SpiceDB server.

Where the reference dials gRPC (``NewPlaintext``/``NewSystemTLS``,
client/client.go:38-61), this framework evaluates in-process: the
constructors build a local store + engine.  Everything else keeps the same
shape and semantics — consistency strategies select snapshot generations,
``Check`` batches onto the device the way ``CheckBulkPermissions`` batches
onto the wire, the retry taxonomy wraps the dispatch (transient device
conditions play the role of gRPC Unavailable), the overlap-key guard
raises on the same set of methods, and streaming methods are generators
(Python's ``iter.Seq``).

Check resolution is a three-tier cascade:
1. **Device** (fast path): batched two-phase evaluation; definite answers
   return immediately.
2. **Host oracle** for the slice the device flagged: conditional results
   (caveats needing context evaluation) and static-cap overflows.
3. Schemas the device cannot evaluate at all (permission-valued userset
   subjects) run entirely on the oracle.
"""

from __future__ import annotations

import contextlib
import dataclasses as _dataclasses
import threading
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from . import consistency as _consistency
from .consistency import OVERLAP_KEY, Strategy
from .engine.device import DeviceEngine, DeviceSnapshot
from .engine.oracle import Oracle, SnapshotOracle, T, U
from .engine.plan import EngineConfig
from .engine import vcache as _vcache
from .rel.filter import Filter, PreconditionedFilter
from .rel.relationship import (
    Relationship,
    RelationshipLike,
    as_relationship,
    must_from_triple as rel_must_from_triple,
)
from .rel.strings import parse_object_set, parse_typed_relation
from .rel.txn import Txn
from .rel.update import Update, UpdateFilter
from .store.snapshot import Snapshot
from .store.store import Store, parse_revision
from .utils import decisions as _decisions
from .utils import faults
from .utils import metrics as _metrics
from .utils import trace as _trace
from .utils.admission import AdmissionConfig, AdmissionController
from .utils.context import Context
from .utils.errors import (
    AlreadyExistsError,
    BulkCheckItemError,
    OverlapKeyMissingError,
    PartialDeletionError,
    UnavailableError,
    classify_dispatch_exception,
)
from .utils.retry import retry_retriable_errors

#: Batch/page sizes mirroring the reference's wire tuning
#: (client/client.go:166,295,348,448).
CHECK_CHUNK = 1000
READ_PAGE = 512
DELETE_BATCH = 10_000
#: Import accumulation before flushing to the store: at least the store's
#: columnar threshold (store/store.py COLUMNAR_IMPORT_MIN), so bulk
#: restores land as immutable column segments instead of per-object dict
#: entries — the reference streams chunks of 1000 over gRPC
#: (client/client.go:448), but our "wire" is a function call, so the
#: buffer can be as large as segment efficiency wants.  Each flush
#: re-probes the accumulated base for duplicates, so fewer/larger
#: flushes win: 2M-row buffers import 2.5x faster than 256k at 10M
#: edges (the chunk list holds references, not copies — the transient
#: cost is the flush's own O(buffer) columns).
IMPORT_BUFFER = 2_097_152


@_dataclasses.dataclass(frozen=True)
class WatchConfig:
    """Tuning for ``updates`` / ``updates_since_revision`` subscriptions.

    The defaults are the interactive-subscriber posture (mirroring the
    class attributes they replace); a replica tailing a busy stream
    (fleet/replica.py) raises both budgets — on a link that faults under
    sustained load, eight consecutive no-progress resumes is routine
    churn there, not a storm worth an incident bundle."""

    #: consecutive no-progress resumes before the stream surfaces the
    #: UnavailableError to its consumer
    max_resumes: int = 64
    #: consecutive no-progress resumes that fire the
    #: ``watch.resume_storm`` incident (carrying the stream cursor)
    storm_resumes: int = 8
    #: store poll cadence while the stream is idle
    poll_interval: float = 0.05


class LookupPage(NamedTuple):
    """One page of a cursored lookup (lookup_resources_page /
    lookup_subjects_page): result ids in stable stream order, plus the
    opaque resume cursor (None = stream exhausted)."""

    ids: List[str]
    cursor: Optional[str]


class ExplainedCheck(NamedTuple):
    """One ``check(..., explain=True)`` item: the boolean verdict plus
    its full resolution tree (engine/explain.py — the reference's
    CheckPermission debug-trace shape)."""

    allowed: bool
    explanation: Dict[str, Any]


class _Options:
    def __init__(self) -> None:
        self.overlap_required = False
        self.engine_config: Optional[EngineConfig] = None
        self.store: Optional[Store] = None
        self.use_device = True
        self.profile_dir: Optional[str] = None
        self.latency_mode = False
        self.admission: Optional[AdmissionConfig] = None
        self.mesh = None  # jax.sharding.Mesh → sharded engine
        self.mesh_partitioned = False  # partitioned (owner-routed) serve
        self.telemetry_port: Optional[int] = None
        self.telemetry_host = "127.0.0.1"
        self.trace_sample_rate: Optional[float] = None
        self.trace_slow_ms: Optional[float] = 100.0
        self.incident_dir: Optional[str] = None
        self.slos = None  # None → utils/slo.default_slos(); () disables
        self.verdict_cache = None  # VerdictCache | max_bytes int | None
        self.decision_log = None  # (spec, kwargs) from with_decision_log
        self.group_commit = None  # GroupCommitConfig | True | None


Option = Callable[[_Options], None]


def with_overlap_required() -> Option:
    """Raise if a request lacks an overlap key (the reference panics,
    client/client.go:84-86,182-191)."""

    def opt(o: _Options) -> None:
        o.overlap_required = True

    return opt


def with_engine_config(cfg: EngineConfig) -> Option:
    """Tune the device evaluator's static caps — the local analogue of
    WithDialOpts' escape hatch (client/client.go:95-97)."""

    def opt(o: _Options) -> None:
        o.engine_config = cfg

    return opt


def with_store(store: Store) -> Option:
    """Share a Store between clients (e.g. one writer, many checkers)."""

    def opt(o: _Options) -> None:
        o.store = store

    return opt


def with_host_only_evaluation() -> Option:
    """Disable the device engine; evaluate every check on the host oracle.
    Useful for debugging and differential testing."""

    def opt(o: _Options) -> None:
        o.use_device = False

    return opt


def with_latency_mode() -> Option:
    """Route interactive-sized Check batches through the latency-mode
    execution path (engine/latency.py): warm pinned kernels at fixed
    small-batch tiers, preallocated staging buffers, and a per-stage
    budget breakdown published as ``latency.*`` metrics with live
    p50/p99 — the serving shape for the p99 < 2 ms half of the north
    star.  Batches the path cannot serve (beyond the top tier, too many
    distinct permissions, non-flat worlds) fall back to the throughput
    path transparently."""

    def opt(o: _Options) -> None:
        o.latency_mode = True

    return opt


def with_mesh(mesh, *, partitioned: bool = False) -> Option:
    """Evaluate checks over a (data × model) device mesh: the client
    builds a ShardedEngine (parallel/sharded.py) — query batches split
    along the data axis, the bucket-sharded tables along the model axis
    — instead of the single-chip DeviceEngine.  The multichip serving
    shape; dispatch faults and the partitioned-prepare fault site
    (``prepare.partition``) retry under the same client envelope as the
    single-chip sites.

    ``partitioned=True`` prepares snapshots through the bucket-
    partitioned feed (engine/partition.py partition_feed with
    serve="routed"): the primary/fold point tables live model-split —
    O(E/M) HBM per device — membership/group tables whole per device,
    and eligible Check batches owner-route to their shards with no
    collective in the compiled program.  Fold-bearing schemas serve on
    this path (the fold/rc derivations are partition-composable since
    this round); worlds the feed cannot partition (keys past the int32
    pack) fall back to the ordinary sharded prepare transparently."""

    def opt(o: _Options) -> None:
        o.mesh = mesh
        o.mesh_partitioned = partitioned

    return opt


def with_verdict_cache(cache=True) -> Option:
    """Enable the revision-pinned verdict cache (engine/vcache.py) on
    this client's check paths: definite verdicts key on (snapshot
    revision, slot, resource, subject, query-context fingerprint) under
    a byte-bounded LRU, and the consistency strategy of each call is the
    read policy — ``snapshot``/``at_least`` hit the resolved revision's
    shard, ``min_latency`` the freshest resident one, ``full`` bypasses
    entirely.  Caveated verdicts that read live query context are never
    cached; time-gated verdicts cache with a pinned now_us.

    ``cache`` may be ``True`` (default 64 MB cache), an int byte budget,
    or a prebuilt ``VerdictCache`` (shared between clients)."""

    def opt(o: _Options) -> None:
        o.verdict_cache = cache

    return opt


def with_decision_log(log=True, **kw) -> Option:
    """Arm the structured decision log (utils/decisions.py): a sampled
    always-on ring (+ optional rotating JSONL sink) of authorization
    DECISIONS — client id, resource, permission, subject, verdict,
    revision, consistency strategy, cache_hit/dedup_parked provenance,
    latency, trace id — with an always-keep-denied rule (the slow-tail
    analogue: "why was this user denied" always has an answer).  Served
    live at ``/decisions`` (with_telemetry), carried in incident
    bundles, and feeding the per-strategy verdict counters the stock
    ``denial_rate`` SLO alerts on.

    ``log`` may be ``True`` (defaults) or a prebuilt ``DecisionLog``;
    keyword arguments (``capacity``, ``sample_rate``, ``sink_path``,
    ``rotate_bytes``, ``rotate_keep``) pass through to the constructor.
    The log is process-global (the trace.py tracer discipline) — one
    stream per process however many clients arm it."""

    def opt(o: _Options) -> None:
        o.decision_log = (log, kw)

    return opt


def with_group_commit(config=True) -> Option:
    """Route this client's writes through the group-commit pipeline
    (store/group.py): concurrent ``write`` calls coalesce into ONE
    collapsed delta committed as one log entry — one closure advance,
    one device reship, one replication frame per group — while each
    transaction still gets its own zookie (base+1..base+k inside the
    group).  Also starts the background delta-chain compactor, which
    materializes long LSM chains off the request path so probe depth
    stays bounded under sustained write load.

    ``config`` may be ``True`` (defaults) or a ``GroupCommitConfig``
    (store/group.py) to tune group size, hold-back, and the compactor's
    poll cadence.  Without this option, ``write`` stays byte-for-byte
    on the direct one-revision-per-transaction store path."""

    def opt(o: _Options) -> None:
        o.group_commit = config

    return opt


def with_admission_control(config: AdmissionConfig) -> Option:
    """Tune the dispatch admission controller (utils/admission.py): the
    bounded in-flight gate, the deadline-budget shed, and the latency-path
    circuit breaker.  Admission is ON by default with generous limits;
    this option tightens or disables it (``max_inflight=0`` no gate,
    ``breaker_threshold=0`` no breaker, ``deadline_shed=False`` no
    deadline-budget shedding)."""

    def opt(o: _Options) -> None:
        o.admission = config

    return opt


def with_telemetry(
    port: int = 0,
    *,
    host: str = "127.0.0.1",
    trace_sample_rate: Optional[float] = None,
    trace_slow_ms: Optional[float] = 100.0,
    incident_dir: Optional[str] = None,
    slos=None,
) -> Option:
    """Serve live telemetry from this client's process: a stdlib HTTP
    daemon thread (utils/telemetry.py) with ``/metrics`` (Prometheus or
    OpenMetrics text — counters, gauges, every timer ring as p50/p90/
    p99/p999 quantiles, histograms with trace-id exemplars), ``/traces``
    (JSONL dump of sampled request traces), ``/slo`` (multi-window
    burn-rate report, utils/slo.py), ``/perf`` (the performance-
    attribution ledger, utils/perf.py: cost_analysis entries, the
    gathered-bytes model, pad waste, measured roofline, wall-time
    ledger), ``/debug/incidents`` (flight-recorder bundles), and
    ``/healthz`` (readiness: breaker state, in-flight admission, serve
    queue depth, SLO status).  ``port=0`` picks an ephemeral port; read
    it back from ``client.telemetry.port``.

    This option also arms the anomaly-diagnosis loop with zero further
    configuration: a process-global **flight recorder** (utils/trace.py)
    retains the last N finished request traces at full fidelity
    regardless of the sample rate, and an **SLO engine** evaluates burn
    rates on a background cadence — an SLO burn, a breaker trip, a shed
    spike, a pinned-path recompile, or a watch resume storm freezes the
    ring and dumps an incident bundle.  ``incident_dir`` lands the
    bundles on disk as JSONL (otherwise the last few stay in memory,
    served at ``/debug/incidents``); ``slos`` overrides the stock
    objectives (``utils/slo.default_slos``; pass ``()`` to disable the
    engine).

    ``trace_sample_rate`` additionally installs the process-global
    request tracer (utils/trace.py) at that head-sampling rate with a
    ``trace_slow_ms`` keep-slow tail rule (None disables the tail
    rule).  Left at None, whatever tracer the process already has stays
    in force — or, when none exists, a 0%-head-sample tracer is
    installed so the flight recorder has traces to retain (``/traces``
    then only exports slow-tail trees; raise the rate for full export)."""

    def opt(o: _Options) -> None:
        o.telemetry_port = port
        o.telemetry_host = host
        o.trace_sample_rate = trace_sample_rate
        o.trace_slow_ms = trace_slow_ms
        o.incident_dir = incident_dir
        o.slos = slos

    return opt


def with_profiling(trace_dir: str) -> Option:
    """Capture a ``jax.profiler`` trace around every check dispatch into
    ``trace_dir`` and publish a ``checks.device_time_s`` timer — the deep
    analogue of the interceptors the reference admits through WithDialOpts
    (client/client.go:95-97; SURVEY.md §5 tracing/profiling)."""

    def opt(o: _Options) -> None:
        o.profile_dir = trace_dir

    return opt


class Client:
    """An in-process authorization client with the gochugaru surface."""

    def __init__(self, *opts: Option) -> None:
        o = _Options()
        for opt in opts:
            opt(o)
        # identity check, NOT truthiness: Store.__len__ counts only the
        # live-dict rows, so a store populated purely through columnar
        # imports is falsy — `o.store or Store()` silently dropped a
        # shared store and built a fresh empty one
        self._store = o.store if o.store is not None else Store()
        self._overlap_required = o.overlap_required
        self._engine_config = o.engine_config
        if o.engine_config is not None:
            # host-side LSM materialization floor rides the engine config
            # (the tuner's lsm_compact_min knob) down to the store
            self._store.lsm_compact_min = o.engine_config.lsm_compact_min
        #: group-commit write pipeline + background chain compactor
        #: (store/group.py), armed by with_group_commit(); None keeps
        #: write() on the direct store path
        self._committer = None
        self._compactor = None
        if o.group_commit is not None and o.group_commit is not False:
            from .store.group import (
                ChainCompactor,
                GroupCommitConfig,
                GroupCommitter,
            )

            gcfg = (
                o.group_commit
                if isinstance(o.group_commit, GroupCommitConfig)
                else GroupCommitConfig()
            )
            self._committer = GroupCommitter(
                self._store, gcfg, registry=_metrics.default
            )
            self._compactor = ChainCompactor(
                self._store, gcfg, registry=_metrics.default
            )
        self._use_device = o.use_device
        self._profile_dir = o.profile_dir
        self._latency_mode = o.latency_mode
        self._mesh = o.mesh
        self._mesh_partitioned = o.mesh_partitioned
        # jax.profiler allows one active trace per process: profiled
        # dispatches serialize so concurrent check() calls don't collide
        self._profile_lock = threading.Lock()
        self._lock = threading.Lock()
        self._engine: Optional[DeviceEngine] = None
        self._engine_schema = None  # CompiledSchema the engine was built for
        self._dsnap_cache: Dict[int, DeviceSnapshot] = {}
        self._oracle_cache: Dict[int, Oracle] = {}
        self._metrics = _metrics.default
        #: dispatch admission: bounded in-flight gate + deadline budget +
        #: latency-path circuit breaker (utils/admission.py)
        self._admission = AdmissionController(o.admission)
        #: revision-pinned verdict cache (engine/vcache.py) — None keeps
        #: every check path byte-for-byte on the pre-cache code
        self._vcache = self._make_vcache(o.verdict_cache)
        #: structured decision log (utils/decisions.py): process-global,
        #: installed by with_decision_log(); None ⇒ recording is one
        #: load + branch (verdict counters stay on regardless)
        if o.decision_log is not None:
            spec, kw = o.decision_log
            if spec is True:
                # bare arming REUSES an already-installed log (the
                # slo.install_engine discipline): a second client must
                # not silently close the first one's configured sink.
                # Explicit kwargs are an explicit reconfiguration.
                if kw or _decisions.get() is None:
                    _decisions.install(_decisions.DecisionLog(**kw))
            elif spec:
                _decisions.install(spec)
        #: telemetry endpoint (utils/telemetry.py), via with_telemetry()
        self.telemetry = None
        #: flight recorder + SLO engine (armed by with_telemetry)
        self.recorder = None
        self.slo = None
        if o.telemetry_port is not None:
            slow_s = (
                None if o.trace_slow_ms is None else o.trace_slow_ms / 1000.0
            )
            if o.trace_sample_rate is not None:
                _trace.configure(
                    sample_rate=o.trace_sample_rate, slow_threshold_s=slow_s
                )
            elif not _trace.enabled():
                # the flight recorder needs a tracer to build span trees;
                # a 0% head sample keeps /traces lean (slow-tail trees
                # only) while the recorder retains everything
                _trace.configure(sample_rate=0.0, slow_threshold_s=slow_s)
            rec = _trace.recorder()
            if rec is None:
                rec = _trace.install_recorder(
                    _trace.FlightRecorder(incident_dir=o.incident_dir)
                )
            elif o.incident_dir is not None:
                # an explicit caller dir WINS over whatever the shared
                # recorder inherited (env default, an earlier client) —
                # silently keeping the old dir would strand this
                # caller's own incident-dir polling
                rec.incident_dir = o.incident_dir
            self.recorder = rec
            # incident bundles carry the admission state that explains
            # shed/breaker behavior at the moment of the anomaly.  The
            # recorder is process-shared, so each telemetry client
            # registers its providers as an atomic GROUP on the current
            # recorder — suffixed keys, so client B never clobbers
            # client A's state, counted per recorder (a fresh recorder
            # starts over) and capped so a client-per-job pattern can't
            # grow the context or pin dead controllers without bound
            from .utils import perf as _perf

            rec.add_context_group(
                {
                    "cost_model": self._admission.cost.state,
                    "admission": lambda adm=self._admission: {
                        "inflight": adm.gate.inflight,
                        "max_inflight": adm.gate.max_inflight,
                        "breaker_state": adm.breaker.state,
                    },
                    # the perf ledger's cost state (gathered-bytes
                    # model, pad waste, realized cost entries, cached
                    # roofline, last wall-time window) — cheap by
                    # contract: no compiles, no microbench
                    "perf": _perf.context_state,
                    # verdict-cache state (read at capture time, so a
                    # cache attached later by with_serving(cache=...)
                    # still shows up in bundles)
                    "vcache": lambda c=self: (
                        None if c._vcache is None else c._vcache.stats()
                    ),
                },
                cap=self.TELEMETRY_CONTEXT_MAX,
            )
            from .utils import slo as _slo

            if o.slos is not None and len(o.slos) == 0:
                # explicit disable: an already-installed engine must
                # actually STOP (install_engine closes it) — leaving it
                # ticking behind an "/slo disabled" surface would keep
                # firing slo.burn incidents nothing reports on
                _slo.install_engine(None)
            else:
                # ONE engine per process (it writes shared slo.* gauges
                # and arms shared timer thresholds): reuse the installed
                # one unless this caller declares its own objectives, in
                # which case the old engine is closed and replaced
                eng = _slo.get_engine()
                if eng is None or o.slos is not None:
                    # install_engine closes any previous engine and
                    # republishes the replacement's gauges
                    eng = _slo.install_engine(
                        _slo.SLOEngine(slos=o.slos, registry=self._metrics)
                    )
                self.slo = eng
            from .utils.telemetry import TelemetryServer

            self.telemetry = TelemetryServer(
                port=o.telemetry_port, host=o.telemetry_host,
                registry=self._metrics, slo=self.slo, recorder=rec,
            )

    @staticmethod
    def _make_vcache(spec):
        """Normalize the with_verdict_cache / with_serving(cache=...)
        spec: None/False → off, True → default cache, int → byte
        budget, VerdictCache → shared instance."""
        if spec is None or spec is False:
            return None
        if spec is True:
            return _vcache.VerdictCache()
        if isinstance(spec, int):
            return _vcache.VerdictCache(max_bytes=spec)
        return spec

    # -- store access (shared by watch etc.) -----------------------------
    @property
    def store(self) -> Store:
        return self._store

    # -- overlap guard (client/client.go:182-191) ------------------------
    def _check_overlap(self, ctx: Context) -> None:
        if self._overlap_required and ctx.value(OVERLAP_KEY) is None:
            raise OverlapKeyMissingError()

    # -- engine / oracle plumbing ----------------------------------------
    def _engine_for(self, snap: Snapshot) -> Optional[DeviceEngine]:
        """Permission-valued userset subjects no longer evict the whole
        schema: the engine marks grants through them possible-not-definite
        (us_perm / pus leaf flags), so only the affected queries fall back
        to the host (checks.fallback_conditional)."""
        if not self._use_device:
            return None
        with self._lock:
            if self._engine is None or self._engine_schema is not snap.compiled:
                if self._mesh is not None:
                    from .parallel.sharded import ShardedEngine

                    self._engine = ShardedEngine(
                        snap.compiled, self._mesh, self._engine_config
                    )
                else:
                    self._engine = DeviceEngine(
                        snap.compiled, self._engine_config
                    )
                self._engine_schema = snap.compiled
                self._dsnap_cache.clear()
            return self._engine

    #: prepared-snapshot / oracle cache capacity per client
    SNAPSHOT_CACHE_MAX = 4

    #: max with_telemetry clients whose admission/cost-model state rides
    #: incident bundles on one recorder (providers are never
    #: unregistered — clients have no close — so registration is capped;
    #: later clients serve telemetry but skip bundle context)
    TELEMETRY_CONTEXT_MAX = 8

    @staticmethod
    def _lru_get(cache: Dict[int, Any], key: int):
        """LRU access: move the hit to the back (dicts preserve order)."""
        v = cache.pop(key, None)
        if v is not None:
            cache[key] = v
        return v

    @classmethod
    def _lru_put(cls, cache: Dict[int, Any], key: int, v: Any) -> List[int]:
        """Insert + evict least-recently-USED (round-2 Weak #5: evicting
        the lowest revision thrashed Snapshot-pinned readers under head
        writes — a pinned generation stays warm because every read
        refreshes it).  Returns the evicted keys so dependent caches
        (the verdict cache's revision shards) can drop with them."""
        cache[key] = v
        evicted: List[int] = []
        while len(cache) > cls.SNAPSHOT_CACHE_MAX:
            k = next(iter(cache))
            cache.pop(k)
            evicted.append(k)
        return evicted

    def _dsnap_for(self, engine: DeviceEngine, snap: Snapshot) -> DeviceSnapshot:
        with self._lock:
            ds = self._lru_get(self._dsnap_cache, snap.revision)
            if ds is None or (
                ds.snapshot is not snap
                and getattr(ds, "source_snapshot", None) is not snap
            ):
                # incremental prepare when the previous revision is still
                # resident: base tables stay on device, only the delta
                # overlay ships (engine/device.py _prepare_delta)
                di = getattr(snap, "delta_info", None)
                prev = (
                    self._dsnap_cache.get(di.prev_revision)
                    if di is not None
                    else None
                )
                if self._mesh_partitioned and hasattr(
                    engine, "prepare_snapshot_partitioned"
                ):
                    ds = engine.prepare_snapshot_partitioned(snap, prev=prev)
                else:
                    ds = engine.prepare(snap, prev=prev)
                evicted = self._lru_put(self._dsnap_cache, snap.revision, ds)
                # dsnap-LRU eviction drops the matching verdict shard:
                # a no-longer-resident revision's cached verdicts would
                # only pin bytes (pinned readers fail upstream anyway)
                if self._vcache is not None:
                    for r in evicted:
                        self._vcache.drop_revision(r)
            return ds

    def _oracle_for(self, snap: Snapshot) -> Oracle:
        """O(1)-construction fallback oracle: SnapshotOracle binary-searches
        the snapshot's sorted columns lazily, so the first conditional or
        overflowed check costs O(log E), not an O(E) Python prebuild."""
        with self._lock:
            o = self._lru_get(self._oracle_cache, snap.revision)
            if o is None:
                o = SnapshotOracle(
                    snap,
                    {
                        name: self._store.caveat_program(name)
                        for name in snap.compiled.schema.caveats
                    },
                )
                self._lru_put(self._oracle_cache, snap.revision, o)
            return o

    # ------------------------------------------------------------------
    # Writes (client/client.go:117-126 — deliberately NO retry wrapper)
    # ------------------------------------------------------------------
    def write(self, ctx: Context, txn: Txn) -> str:
        """Atomically perform a transaction on relationships; returns the
        revision it was written at.  Under with_group_commit() the
        transaction coalesces into the next commit group (same zookie
        contract, one log entry per group); otherwise it commits alone."""
        if self._committer is not None:
            return self._committer.write(txn, ctx)
        return self._store.write(txn)

    # ------------------------------------------------------------------
    # The Check family (client/client.go:128-180,238-284)
    # ------------------------------------------------------------------
    def check_one(self, ctx: Context, cs: Strategy, r: RelationshipLike) -> bool:
        return self.check(ctx, cs, r)[0]

    def check_any(self, ctx: Context, cs: Strategy, *rs: RelationshipLike) -> bool:
        return any(self.check(ctx, cs, *rs))

    def check_all(self, ctx: Context, cs: Strategy, *rs: RelationshipLike) -> bool:
        return all(self.check(ctx, cs, *rs))

    def check_iter(
        self,
        ctx: Context,
        cs: Strategy,
        rs: Iterable[RelationshipLike],
        *,
        chunk_size: int = CHECK_CHUNK,
    ) -> Iterator[bool]:
        """Batched streaming checks (client/client.go:164-180)."""
        batch: List[RelationshipLike] = []
        for r in rs:
            batch.append(r)
            if len(batch) >= chunk_size:
                yield from self.check(ctx, cs, *batch)
                batch.clear()
        if batch:
            yield from self.check(ctx, cs, *batch)

    def check(
        self, ctx: Context, cs: Strategy, *rs: RelationshipLike,
        explain: bool = False,
    ) -> List[bool]:
        """Batched permission check — the core path.  The reference folds N
        checks into one CheckBulkPermissions RPC (client/client.go:238-266);
        here they fold into one device dispatch, with host-oracle resolution
        for conditional/overflowed items, wrapped in the same retry
        envelope.

        ``explain=True`` returns ``List[ExplainedCheck]`` instead:
        verdicts AND their typed resolution trees (engine/explain.py),
        evaluated + explained at ONE pinned snapshot — the device
        witness seeds each allowed tree's walk, cache-served verdicts
        re-derive against the pinned revision."""
        self._check_overlap(ctx)
        rels = [as_relationship(r) for r in rs]
        if not rels:
            return []
        if explain:
            self._metrics.inc("checks.requested", len(rels))
            root = _trace.root_span("check.explain", batch=len(rels))
            ectx = _trace.ctx_with_span(ctx, root)

            def run() -> List[ExplainedCheck]:
                import time as _time

                sp = _trace.span_of(ectx)
                # ONE snapshot for the verdicts and every tree: explain
                # must describe the world the verdict was computed in,
                # not whatever head a later write minted
                snap = self._store.snapshot_for(cs)
                # cache residency is probed BEFORE the dispatch: the
                # entries this very dispatch inserts must not masquerade
                # as cache-served provenance
                cache_ents = self._peek_cached(snap, rels, cs)
                # ... and ONE evaluation instant: the walks' expiry
                # gates pin to the dispatch time, not tree-build time
                now_us = int(_time.time() * 1_000_000)
                # the SAME admission envelope as a plain check, covering
                # the evaluate dispatch AND the one batched witness
                # dispatch; only the host-oracle walks run outside it
                verdicts, codes = self._admitted(ectx, sp, lambda: (
                    self._evaluate_rels(
                        snap, rels, latency=self._latency_mode,
                        span=sp, cs=cs,
                    ),
                    self._witness_batch(snap, rels),
                ))
                return self._explain_batch(
                    snap, rels, verdicts, cs, now_us=now_us,
                    cache_ents=cache_ents, codes=codes,
                )

            with root:
                return retry_retriable_errors(ectx, run)
        self._metrics.inc("checks.requested", len(rels))
        # request-scoped tracing (utils/trace.py): head-sampled root
        # span riding the context chain.  The unsampled/disabled path is
        # the NOOP singleton — same context object back, no span
        # allocation anywhere below (tests assert the identity)
        root = _trace.root_span("check", batch=len(rels))
        ctx = _trace.ctx_with_span(ctx, root)

        def dispatch() -> List[bool]:
            sp = _trace.span_of(ctx)
            return self._admitted(
                ctx, sp,
                lambda: self._dispatch_admitted(ctx, cs, rels, span=sp),
            )

        if root is _trace.NOOP:
            # keep-slow tail rule: even unsampled requests leave a
            # root-only trace behind when they blow the slow threshold
            t0 = _trace.tail_clock()
            try:
                return retry_retriable_errors(ctx, dispatch)
            finally:
                _trace.maybe_keep_slow("check", t0, batch=len(rels))
        # Span.__exit__ records the exception type as the `error` attr
        with root:  # activates the thread-local current span + ends it
            return retry_retriable_errors(ctx, dispatch)

    def _admitted(self, ctx: Context, span, work):
        """The ONE admission envelope every device-dispatching request
        path runs under: deadline-budget shed before any device work,
        the bounded in-flight gate around ``work()``, and the cost-model
        observation feeding the shared per-tier EWMA after — plain
        checks, explain batches, and the serving handle's explain
        derivation all call this, so a change to admission behavior
        cannot silently miss one of them."""
        import time as _time

        adm = self._admission
        adm.check_deadline(ctx, span=span)
        t_disp = _time.perf_counter()
        with adm.gate.admit(span=span):
            out = work()
        adm.observe_cost(_time.perf_counter() - t_disp)
        return out

    def _dispatch_admitted(
        self,
        ctx: Context,
        cs: Strategy,
        rels: List[Relationship],
        span=_trace.NOOP,
    ) -> List[bool]:
        """One admitted check dispatch (inside the gate, one retry
        attempt): snapshot selection, device dispatch with classified
        failures feeding the circuit breaker, host-oracle resolution.
        A sampled ``span`` grows a ``dispatch`` child per attempt whose
        subtree covers snapshot selection, the device/latency stage
        spans, and host-oracle fallbacks; ``with dsp`` also activates
        the thread-local current span so deep write-path work reached
        from here (incremental closure advance during a delta prepare)
        attaches its events to this request."""
        dsp = span.child("dispatch")
        with dsp:
            snap = self._store.snapshot_for(cs)
            dsp.set_attr("revision", int(snap.revision))
            return self._evaluate_rels(
                snap, rels, latency=self._latency_mode, span=dsp, cs=cs
            )

    def _evaluate_rels(
        self,
        snap: Snapshot,
        rels: List[Relationship],
        *,
        latency: bool,
        span=_trace.NOOP,
        cs: Optional[Strategy] = None,
        dedup: bool = False,
    ) -> List[bool]:
        """Evaluate a formed batch at one snapshot, through the verdict
        cache and in-batch dedup when enabled: cache hits answer without
        touching the evaluator (read policy = the call's consistency
        strategy, engine/vcache.policy_for), remaining unique rows
        dispatch once (``dedup``, the serving batcher's flag) and
        verdicts fan back out, definite results populate the revision's
        shard.  Items carrying live query caveat context NEVER read or
        write the cache.  With no cache attached and dedup off this is
        byte-for-byte the pre-cache path (``_evaluate_rels_direct``).

        Decision provenance rides every exit: per-strategy verdict
        counters always (utils/decisions.count_verdicts — the stock
        denial-rate SLO's feed), and when a decision log is installed,
        sampled + always-keep-denied entries carrying revision,
        strategy, cache_hit and the evaluate latency."""
        import time as _time

        t_ev = _time.perf_counter()
        vc = self._vcache
        pol = _vcache.policy_for(cs) if vc is not None else _vcache.CACHE_OFF
        if not (pol.read or pol.write) and not dedup:
            out = self._evaluate_rels_direct(
                snap, rels, latency=latency, span=span
            )
            self._provenance_rels(
                rels, out, snap, cs, None, _time.perf_counter() - t_ev, span
            )
            return out

        B = len(rels)
        keys = [_vcache.rel_key(r) for r in rels]
        # live-context items (non-empty query caveat_context) bypass the
        # cache entirely — their caveat may read the live context
        cacheable = [k[1] == _vcache.EMPTY_CTX_FP for k in keys]
        out: List[Optional[bool]] = [None] * B
        now_us = int(_time.time() * 1_000_000)
        if pol.read:
            vals = vc.lookup_rels(
                snap.revision,
                [k if cacheable[i] else None for i, k in enumerate(keys)],
            )
            for i, v in enumerate(vals):
                if v is not None:
                    out[i] = v[0]
        pend = [i for i in range(B) if out[i] is None]
        hitflags = [out[i] is not None for i in range(B)]
        nh = B - len(pend)
        if nh:
            span.event("cache.hits", items=nh)
            span.set_attr("cache_hits", nh)
        if not pend:
            res = [bool(v) for v in out]
            self._provenance_rels(
                rels, res, snap, cs, hitflags,
                _time.perf_counter() - t_ev, span,
            )
            return res
        if dedup and len(pend) > 1:
            first: Dict[Any, int] = {}
            uidx: List[int] = []
            inverse: List[int] = []
            for i in pend:
                u = first.get(keys[i])
                if u is None:
                    u = first[keys[i]] = len(uidx)
                    uidx.append(i)
                inverse.append(u)
            dups = len(pend) - len(uidx)
            if dups:
                self._metrics.inc("dedup.batch_dups", dups)
        else:
            uidx = pend
            inverse = list(range(len(pend)))
        try:
            sub = self._evaluate_rels_direct(
                snap, [rels[i] for i in uidx], latency=latency, span=span
            )
        except BulkCheckItemError as e:
            raise self._remap_bulk_error(
                e, out, pend, inverse, lambda vs: list(vs)
            ) from (e.__cause__ or e)
        for j, i in enumerate(pend):
            out[i] = bool(sub[inverse[j]])
        if pol.write:
            vc.insert_rels(
                snap.revision,
                [(keys[i], sub[j]) for j, i in enumerate(uidx)
                 if cacheable[i]],
                now_us,
            )
        res = [bool(v) for v in out]
        self._provenance_rels(
            rels, res, snap, cs, hitflags, _time.perf_counter() - t_ev, span
        )
        return res

    @staticmethod
    def _remap_bulk_error(e, out, pend, inverse, as_seq):
        """Translate a unique-space BulkCheckItemError (from the deduped
        direct dispatch) back to caller-space: unique verdicts [0,
        e.index) scatter onto their duplicate rows, and the error is
        re-anchored at the first caller row that is NOT fully resolved
        (cache hits resolved rows past it stay unreported — the prefix
        contract only promises rows before the index)."""
        part = e.results
        first_bad = None
        for j, i in enumerate(pend):
            if inverse[j] < e.index:
                out[i] = bool(part[inverse[j]])
            elif first_bad is None or i < first_bad:
                first_bad = i
        if first_bad is None:  # defensive: nothing unresolved
            first_bad = pend[-1]
        prefix = as_seq(out[:first_bad])
        return BulkCheckItemError(first_bad, prefix, e.__cause__ or e)

    def _provenance_rels(
        self, rels, out, snap, cs, cache_hits, dt, span
    ) -> None:
        """Decision provenance for one relationship batch: always-on
        verdict counters (cheap, per batch), plus decision-log entries
        when a log is installed (one load + branch otherwise)."""
        sname = _decisions.strategy_name(cs)
        allowed = sum(1 for v in out if v)
        _decisions.count_verdicts(
            self._metrics, allowed, len(out) - allowed, sname,
            cache_hits=sum(cache_hits) if cache_hits is not None else 0,
        )
        if _decisions.enabled():
            _decisions.record_rels(
                rels, out, revision=snap.revision, strategy=sname,
                cache_hits=cache_hits, latency_s=dt,
                trace_id=span.trace_id if span.sampled else None,
            )

    def _provenance_cols(
        self, snap, q_res, q_perm, q_subj, res, cs, cache_resolved, dt, span
    ) -> None:
        """Columnar mirror: counters from numpy reductions; decision-log
        entries decode interned ids ONLY for the sampled/denied rows the
        log actually keeps."""
        sname = _decisions.strategy_name(cs)
        allowed = int(res.sum())
        _decisions.count_verdicts(
            self._metrics, allowed, int(res.shape[0]) - allowed, sname,
            cache_hits=int(cache_resolved.sum())
            if cache_resolved is not None else 0,
        )
        if _decisions.enabled():
            name_of_slot = snap.compiled.name_of_slot
            interner = snap.interner

            def decode(i: int):
                rt, rid = interner.key_of(int(q_res[i]))
                st, sid = interner.key_of(int(q_subj[i]))
                return (
                    f"{rt}:{rid}", name_of_slot[int(q_perm[i])],
                    f"{st}:{sid}",
                )

            _decisions.record_cols(
                int(res.shape[0]), res, decode,
                revision=snap.revision, strategy=sname,
                cache_hits=cache_resolved, latency_s=dt,
                trace_id=span.trace_id if span.sampled else None,
            )

    def _evaluate_rels_direct(
        self,
        snap: Snapshot,
        rels: List[Relationship],
        *,
        latency: bool,
        span=_trace.NOOP,
    ) -> List[bool]:
        """Evaluate a formed batch at one snapshot: device dispatch with
        classified failures feeding the circuit breaker, host-oracle
        resolution of conditional/overflow items.  ``latency`` asks for
        the pinned-tier path (the breaker may still reroute).  Shared by
        the per-request path above and the serving batcher
        (serve/batcher.py), so breaker semantics cannot drift between
        caller-formed and coalesced batches."""
        adm = self._admission
        dsp = span
        engine = self._engine_for(snap)
        with self._metrics.timer("checks.dispatch"):
            if engine is None:
                self._metrics.inc("checks.oracle", len(rels))
                with dsp.child("oracle.check", items=len(rels)):
                    oracle = self._oracle_for(snap)
                    return [
                        oracle.check_relationship(r) == T for r in rels
                    ]
            dsnap = self._dsnap_for(engine, snap)
            dsp.event("snapshot.prepared")
            if self._profile_dir is not None:
                import jax

                self._profile_lock.acquire()
                prof = jax.profiler.trace(self._profile_dir)
                unlock = self._profile_lock.release
            else:
                prof = contextlib.nullcontext()
                unlock = lambda: None
            # circuit breaker: after consecutive transient dispatch
            # failures, latency-mode traffic reroutes onto the batch
            # path until the breaker half-opens a probe
            use_latency = latency and adm.breaker.allow_latency()
            if latency and not use_latency:
                self._metrics.inc("breaker.latency_rerouted")
                dsp.event("breaker.latency_rerouted")
            # a latency-mode call may silently fall back to the batch path
            # (batch beyond the top tier, no flat tables, ...): the probe
            # flag fed to the breaker must reflect whether the latency
            # path actually SERVED, so read its dispatch counter around
            # the call (per-snapshot counter; a concurrent same-snapshot
            # dispatch can inflate it, which at worst closes the breaker
            # on that other dispatch's success — still a latency success)
            lp = dsnap.latency_path if use_latency else None
            lp_n = lp.dispatch_count if lp is not None else 0
            try:
                with prof, self._metrics.timer("checks.device_time_s"):
                    d, p, ovf = engine.check_batch(
                        dsnap, rels, latency=use_latency, span=dsp
                    )
            except Exception as e:  # classify device dispatch failures
                classified = classify_dispatch_exception(e)
                if isinstance(classified, UnavailableError):
                    adm.breaker.record_failure()
                    if classified is e:
                        raise
                    raise classified
                raise
            else:
                lp2 = dsnap.latency_path
                served_latency = (
                    use_latency
                    and lp2 is not None
                    and lp2.dispatch_count > lp_n
                )
                adm.breaker.record_success(probe=served_latency)
            finally:
                unlock()
            needs_host = (p & ~d) | ovf
            if not needs_host.any():
                self._metrics.inc("checks.device_definite", len(rels))
                return [bool(x) for x in d]
            osp = dsp.child(
                "oracle.fallback", items=int(needs_host.sum()),
                overflow=int(ovf.sum()),
            )
            try:
                oracle = self._oracle_for(snap)
                out = []
                for i, r in enumerate(rels):
                    if needs_host[i]:
                        self._metrics.inc(
                            "checks.fallback_overflow"
                            if ovf[i]
                            else "checks.fallback_conditional"
                        )
                        try:
                            out.append(oracle.check_relationship(r) == T)
                        except Exception as e:
                            # per-item error: abort with partial results,
                            # mirroring the reference's bulk mapping loop
                            # (client/client.go:279-283).  Not retriable —
                            # the reference retries the RPC, not the
                            # per-item mapping
                            raise BulkCheckItemError(i, out, e) from e
                    else:
                        out.append(bool(d[i]))
                return out
            finally:
                osp.end()

    def _evaluate_columns(
        self,
        snap: Snapshot,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        *,
        latency: bool,
        span=_trace.NOOP,
        cs: Optional[Strategy] = None,
        dedup: bool = False,
    ) -> np.ndarray:
        """Columnar mirror of ``_evaluate_rels``' cache/dedup layer.
        The columnar path carries no live query context by construction,
        so every verdict is cacheable (expiry gates pin now_us on the
        entry).  Cache hits and duplicate rows never reach the device —
        only the unique misses dispatch, at whatever (smaller) pow2 tier
        they land on.  With no cache and dedup off this is byte-for-byte
        the pre-cache path."""
        import time as _time

        t_ev = _time.perf_counter()
        vc = self._vcache
        pol = _vcache.policy_for(cs) if vc is not None else _vcache.CACHE_OFF
        if not (pol.read or pol.write) and not dedup:
            out = self._evaluate_columns_direct(
                snap, q_res, q_perm, q_subj, latency=latency, span=span
            )
            self._provenance_cols(
                snap, q_res, q_perm, q_subj, np.asarray(out, bool), cs,
                None, _time.perf_counter() - t_ev, span,
            )
            return out

        B = int(q_res.shape[0])
        keys = _vcache.pack_cols(q_perm, q_res, q_subj)
        res = np.zeros(B, bool)
        resolved = np.zeros(B, bool)
        now_us = int(_time.time() * 1_000_000)
        if pol.read:
            arr = vc.lookup_cols(snap.revision, keys)
            if arr is not None:
                resolved = arr >= 0
                res = (arr & 1).astype(bool)
                res[~resolved] = False
        pend = np.nonzero(~resolved)[0]
        nh = B - int(pend.shape[0])
        if nh:
            span.event("cache.hits", items=nh)
            span.set_attr("cache_hits", nh)
        if pend.shape[0] == 0:
            self._provenance_cols(
                snap, q_res, q_perm, q_subj, res, cs, resolved,
                _time.perf_counter() - t_ev, span,
            )
            return res
        if dedup and pend.shape[0] > 1:
            if isinstance(keys, np.ndarray):
                _, uix, inverse = np.unique(
                    keys[pend], return_index=True, return_inverse=True
                )
                uidx = pend[uix]
            else:
                first: Dict[Any, int] = {}
                ulist: List[int] = []
                inverse = np.empty(pend.shape[0], np.int64)
                for j, i in enumerate(pend):
                    k = keys[i]
                    u = first.get(k)
                    if u is None:
                        u = first[k] = len(ulist)
                        ulist.append(int(i))
                    inverse[j] = u
                uidx = np.asarray(ulist, np.int64)
            dups = int(pend.shape[0] - uidx.shape[0])
            if dups:
                self._metrics.inc("dedup.batch_dups", dups)
        else:
            uidx = pend
            inverse = np.arange(pend.shape[0])
        try:
            sub = self._evaluate_columns_direct(
                snap, np.ascontiguousarray(q_res[uidx]),
                np.ascontiguousarray(q_perm[uidx]),
                np.ascontiguousarray(q_subj[uidx]),
                latency=latency, span=span,
            )
        except BulkCheckItemError as e:
            # unique-space → caller-space: scatter the resolved unique
            # prefix onto its duplicates, re-anchor at the first
            # unresolved caller row (everything before it IS resolved)
            part = np.asarray(e.results, bool)
            ok = inverse < e.index
            res[pend[ok]] = part[inverse[ok]]
            resolved[pend[ok]] = True
            first_bad = int(np.nonzero(~resolved)[0][0])
            raise BulkCheckItemError(
                first_bad, res[:first_bad], e.__cause__ or e
            ) from (e.__cause__ or e)
        res[pend] = np.asarray(sub, bool)[inverse]
        if pol.write:
            ku = keys[uidx] if isinstance(keys, np.ndarray) else [
                keys[int(i)] for i in uidx
            ]
            vc.insert_cols(snap.revision, ku, np.asarray(sub, bool), now_us)
        self._provenance_cols(
            snap, q_res, q_perm, q_subj, res, cs, resolved,
            _time.perf_counter() - t_ev, span,
        )
        return res

    def _evaluate_columns_direct(
        self,
        snap: Snapshot,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        *,
        latency: bool,
        span=_trace.NOOP,
    ) -> np.ndarray:
        """The columnar mirror of ``_evaluate_rels`` for the serving
        batcher: pre-interned int32 columns straight onto the pinned
        tier ladder (breaker-gated, classified failures feed it), with
        conditional/overflow items resolved on the host oracle by id
        reconstruction.  Returns a bool verdict array of len(q_res)."""
        adm = self._admission
        B = int(q_res.shape[0])
        engine = self._engine_for(snap)
        if engine is None:
            self._metrics.inc("checks.oracle", B)
            oracle = self._oracle_for(snap)
            return np.fromiter(
                (
                    self._check_interned(
                        oracle, snap, q_res[i], q_perm[i], q_subj[i]
                    )
                    for i in range(B)
                ),
                bool, count=B,
            )
        dsnap = self._dsnap_for(engine, snap)
        use_latency = latency and adm.breaker.allow_latency()
        if latency and not use_latency:
            self._metrics.inc("breaker.latency_rerouted")
            span.event("breaker.latency_rerouted")
        # deliberately NO with_profiling (jax.profiler.trace) wrapper
        # here, unlike _evaluate_rels: the process allows one active
        # profiler trace, so per-batch traces would serialize the
        # serving dispatcher behind _profile_lock — profiler
        # correlation for serving dispatches goes through the
        # GOCHUGARU_TRACE_DIR annotation path (trace.annotate_dispatch)
        lp = engine.latency_path(dsnap) if use_latency else None
        lp_n = lp.dispatch_count if lp is not None else 0
        try:
            with self._metrics.timer("checks.device_time_s"):
                out = None
                if lp is not None:
                    out = lp.dispatch_columns(q_res, q_perm, q_subj, span=span)
                if out is None:
                    out = engine.check_columns(dsnap, q_res, q_perm, q_subj)
        except Exception as e:
            classified = classify_dispatch_exception(e)
            if isinstance(classified, UnavailableError):
                adm.breaker.record_failure()
                if classified is e:
                    raise
                raise classified
            raise
        else:
            adm.breaker.record_success(
                probe=lp is not None and lp.dispatch_count > lp_n
            )
        d, p, ovf = out
        res = np.asarray(d, bool).copy()
        needs_host = (p & ~d) | ovf
        if needs_host.any():
            oracle = self._oracle_for(snap)
            idx = np.nonzero(needs_host)[0]
            span.event("oracle.fallback", items=int(idx.shape[0]))
            for i in idx:
                self._metrics.inc(
                    "checks.fallback_overflow" if ovf[i]
                    else "checks.fallback_conditional"
                )
                try:
                    res[i] = self._check_interned(
                        oracle, snap, q_res[i], q_perm[i], q_subj[i]
                    )
                except Exception as e:
                    # same per-item isolation as _evaluate_rels: idx is
                    # ascending, so every item before i is fully
                    # resolved (device-definite or already host-checked)
                    # — the serving batcher slices this back onto the
                    # co-batched submissions instead of failing them all
                    raise BulkCheckItemError(int(i), res[:int(i)], e) from e
        else:
            self._metrics.inc("checks.device_definite", B)
        return res

    def _check_interned(
        self, oracle: Oracle, snap: Snapshot, res_id, perm_slot, subj_id
    ) -> bool:
        """One host-oracle check from interned ids (the columnar path's
        fallback): reconstruct the (resource, permission, subject)
        triple through the snapshot's interner and slot names."""
        rtype, rid = snap.interner.key_of(int(res_id))
        stype, sid = snap.interner.key_of(int(subj_id))
        perm = snap.compiled.name_of_slot[int(perm_slot)]
        r = rel_must_from_triple(f"{rtype}:{rid}", perm, f"{stype}:{sid}")
        return oracle.check_relationship(r) == T

    # ------------------------------------------------------------------
    # Decision provenance (engine/explain.py)
    # ------------------------------------------------------------------
    def explain(
        self, ctx: Context, cs: Strategy, r: RelationshipLike
    ) -> Dict[str, Any]:
        """Full resolution tree for ONE check at the strategy's pinned
        revision — the reference's CheckPermission debug-trace surface.
        The device witness (engine/flat.py armed kernel) seeds the walk
        toward the branch the kernel proved winning; verdicts the
        verdict cache would have served are re-derived against the
        pinned revision and flagged ``cached``.  Runs under the same
        retry envelope as checks (the ``explain.walk`` chaos site
        classifies into it)."""
        self._check_overlap(ctx)
        rel_ = as_relationship(r)

        def run() -> Dict[str, Any]:
            snap = self._store.snapshot_for(cs)
            return self._explain_at(snap, rel_, cs)

        return retry_retriable_errors(ctx, run)

    _WITNESS_UNSET = object()

    def _witness_batch(self, snap: Snapshot, rels) -> Optional[Any]:
        """Best-effort device witness codes for a whole batch (ONE armed
        dispatch, not one per item) — a hint, never a failure: any error
        degrades to the unseeded walk."""
        engine = self._engine_for(snap)
        if engine is None:
            return None
        try:
            dsnap = self._dsnap_for(engine, snap)
            return engine.witness_codes(dsnap, rels)
        except Exception:
            self._metrics.inc("explain.witness_errors")
            return None

    def _peek_cached(
        self, snap: Snapshot, rels, cs: Optional[Strategy]
    ) -> List[Optional[tuple]]:
        """Per-rel verdict-cache entries ``(verdict, pinned now_us)`` or
        None — a metric-free residency probe for explain provenance.
        Must run BEFORE the evaluate dispatch: an entry that exists only
        because this request's dispatch inserted it is fresh work, not a
        cache-served verdict."""
        from .engine import vcache as _vc

        vc = self._vcache
        if vc is None or not _vc.policy_for(cs).read:
            return [None] * len(rels)
        out: List[Optional[tuple]] = []
        for r in rels:
            key = _vc.rel_key(r)
            out.append(
                vc.peek_rel(snap.revision, key)
                if key[1] == _vc.EMPTY_CTX_FP else None
            )
        return out

    def _explain_batch(
        self, snap: Snapshot, rels, verdicts, cs: Optional[Strategy],
        *, now_us: Optional[int] = None, cache_ents=None,
        codes=_WITNESS_UNSET,
    ) -> List["ExplainedCheck"]:
        """Derive one explain tree per already-computed verdict at one
        pinned snapshot — the ONE implementation behind both
        ``check(explain=True)`` and ``ServingHandle.check(explain=True)``.
        A tree disagreeing with its served verdict (head moved, entry
        expired) is flagged ``verdict_skew`` instead of silently posing
        as the verdict's derivation."""
        if codes is Client._WITNESS_UNSET:
            codes = self._witness_batch(snap, rels)
        out = []
        for i, (v, r) in enumerate(zip(verdicts, rels)):
            tree = self._explain_at(
                snap, r, cs,
                witness=None if codes is None else int(codes[i]),
                now_us=now_us,
                cache_ent=(
                    cache_ents[i] if cache_ents is not None
                    else Client._WITNESS_UNSET
                ),
            )
            if (tree["result"] == "allowed") != bool(v):
                tree["verdict_skew"] = True
            out.append(ExplainedCheck(bool(v), tree))
        return out

    def _explain_at(
        self, snap: Snapshot, r: Relationship, cs: Optional[Strategy],
        witness=_WITNESS_UNSET, now_us: Optional[int] = None,
        cache_ent=_WITNESS_UNSET,
    ) -> Dict[str, Any]:
        """One explain tree at one pinned snapshot: witness extraction
        (unless the caller already extracted a batch's worth), cache
        provenance, then the instrumented oracle walk.  ``now_us`` pins
        the walk's expiry gates to the instant the verdict was computed;
        a cache-served verdict re-derives at its ENTRY's pinned now_us
        (overriding the caller's), so the tree describes the world the
        cached verdict saw, not wall clock at explain time.
        ``cache_ent`` is the pre-dispatch residency probe result (None =
        known uncached); left unset, the probe runs here — only correct
        when no verdict dispatch preceded this call (``client.explain``)."""
        from .engine import explain as _explain

        if witness is Client._WITNESS_UNSET:
            codes = self._witness_batch(snap, [r])
            wit = int(codes[0]) if codes is not None else None
        else:
            wit = witness
        if cache_ent is Client._WITNESS_UNSET:
            cache_ent = self._peek_cached(snap, [r], cs)[0]
        cached = cache_ent is not None
        if cached:
            now_us = cache_ent[1]
        self._metrics.inc("explain.requests")
        oracle = self._oracle_for(snap)
        return _explain.explain_relationship(
            oracle, r, witness=wit, revision=snap.revision, cached=cached,
            now_us=now_us, strategy=_decisions.strategy_name(cs),
        )

    # ------------------------------------------------------------------
    # Continuous-batching serving front-end (serve/batcher.py)
    # ------------------------------------------------------------------
    def with_serving(
        self, cs: Optional[Strategy] = None, config=None, cache=None
    ) -> "Any":
        """Open a continuous-batching serving handle over this client:
        an async micro-batch former that coalesces concurrent Check /
        CheckMany submissions into the next pinned pow2 tier slot
        (engine/latency.py ladder) under a deadline-aware hold-back,
        with per-client fair admission and queue-depth shedding through
        the admission controller's ``ShedError`` path.  The handle's
        ``check(ctx, *rels)`` blocks on its coalesced result (the
        retry envelope re-submits on transient faults); ``submit`` /
        ``submit_columns`` return futures for open-loop callers
        (benchmarks/bench9_serve.py).  Works over single-chip,
        latency-mode, and ``with_mesh(partitioned=True)`` engines —
        engines whose latency path declines a batch serve it on the
        throughput path, same answers.

        ``cs`` pins the handle's consistency strategy (default
        ``min_latency()``): coalesced requests in one formed batch
        evaluate at one snapshot, the same revision discipline the
        reference's bulk RPCs have.  Close the handle (or use it as a
        context manager) to drain and stop its threads.

        ``cache`` arms the revision-pinned verdict cache on this
        client's evaluate paths (``True`` = default 64 MB, an int byte
        budget, a shared ``VerdictCache``, or ``False`` to force this
        handle cache-off even when the client carries one); the
        handle's pinned strategy is the read policy (``full()``
        bypasses).  In-flight/in-batch check deduplication is governed
        by ``ServeConfig.dedup`` and is on by default."""
        from .serve import ServingHandle

        if cache is not None and cache is not False:
            # True reuses an already-attached cache; an explicit
            # instance or byte budget replaces it
            if self._vcache is None or cache is not True:
                self._vcache = self._make_vcache(cache)
        return ServingHandle(
            self, cs if cs is not None else _consistency.min_latency(),
            config, use_cache=cache is not False,
        )

    # ------------------------------------------------------------------
    # Reads (client/client.go:286-315)
    # ------------------------------------------------------------------
    def read_relationships(
        self, ctx: Context, cs: Strategy, f: Filter
    ) -> Iterator[Relationship]:
        """Stream the relationships matching the filter.  The reference
        pages server-side at 512 (client/client.go:295); locally the scan
        is vectorized, and the generator honors context cancellation at
        page boundaries."""
        self._check_overlap(ctx)
        count = 0
        for r in self._store.read(cs, f):
            err = ctx.err()
            if err is not None and count % READ_PAGE == 0:
                raise err
            count += 1
            yield r

    # ------------------------------------------------------------------
    # Deletes (client/client.go:317-358)
    # ------------------------------------------------------------------
    @staticmethod
    def _as_preconditioned(pf) -> PreconditionedFilter:
        """Accept a bare Filter where the reference's signature takes a
        *PreconditionedFilter (client/client.go:319,340) — Go's type system
        makes the wrapping explicit; here a filter with no preconditions
        means the same thing, so wrap instead of failing deep in the
        store."""
        if isinstance(pf, PreconditionedFilter):
            return pf
        if isinstance(pf, Filter):
            return PreconditionedFilter(pf)
        raise TypeError(
            f"expected Filter or PreconditionedFilter, got {type(pf).__name__}"
        )

    def delete_atomic(self, ctx: Context, pf: PreconditionedFilter) -> str:
        """Remove all matching relationships in one transaction.
        Explicitly NO retry (client/client.go:322)."""
        self._check_overlap(ctx)
        pf = self._as_preconditioned(pf)
        revision, complete = self._store.delete_by_filter(pf, limit=0)
        if not complete:
            raise PartialDeletionError(
                "delete disallowing partial deletion did not complete"
            )
        return revision

    def delete(self, ctx: Context, pf: PreconditionedFilter) -> None:
        """Remove all matching relationships in batches of 10,000 with
        retry (client/client.go:340-358)."""
        self._check_overlap(ctx)
        pf = self._as_preconditioned(pf)
        while True:
            _, complete = retry_retriable_errors(
                ctx, lambda: self._store.delete_by_filter(pf, limit=DELETE_BATCH)
            )
            if complete:
                return

    # ------------------------------------------------------------------
    # Watch (client/client.go:360-413)
    # ------------------------------------------------------------------
    def updates(
        self, ctx: Context, f: UpdateFilter,
        config: Optional["WatchConfig"] = None,
    ) -> Iterator[Update]:
        return self.updates_since_revision(ctx, f, "", config=config)

    #: consecutive no-progress stream faults tolerated before the watch
    #: surfaces the UnavailableError to its consumer — bounded so a
    #: permanently-faulted stream classifies instead of spinning forever
    WATCH_MAX_RESUMES = 64
    #: consecutive no-progress resumes that count as a resume STORM —
    #: fires a flight-recorder incident (utils/trace.py) well before the
    #: stream gives up at WATCH_MAX_RESUMES, so the bundle captures the
    #: storm in progress
    WATCH_STORM_RESUMES = 8

    def updates_since_revision(
        self, ctx: Context, f: UpdateFilter, revision: str,
        *, config: Optional["WatchConfig"] = None,
    ) -> Iterator[Update]:
        """Subscribe to ordered, filtered, resumable updates.  Cancel via
        the context, exactly like the reference's Watch loop
        (client/client.go:394-411).

        Resume-on-fault: a transient stream failure (``UnavailableError``
        from the store or the ``watch.stream`` injection site) does not
        surface to the consumer — the subscription re-subscribes from the
        last delivered cursor with exactly-once delivery.  The cursor is
        (last fully-delivered revision, raw updates delivered of the
        partially-delivered revision), tracked pre-filter so filtered
        streams resume at the right raw position; redelivered prefixes
        are skipped, so no event is lost or duplicated across stream
        breaks.

        ``config`` tunes the resume budget (WatchConfig): an interactive
        subscriber keeps the defaults; a replica tailing a busy stream
        raises ``storm_resumes``/``max_resumes`` so routine churn on a
        faulted link doesn't page."""
        self._check_overlap(ctx)
        cfg = config if config is not None else WatchConfig(
            max_resumes=self.WATCH_MAX_RESUMES,
            storm_resumes=self.WATCH_STORM_RESUMES,
        )
        if f.object_types and f.relationship_filters:
            raise ValueError(
                "UpdateFilter.object_types and relationship_filters are mutually"
                " exclusive"
            )
        # no cursor → subscribe from the current head, exactly like Watch
        # with no OptionalStartCursor (client/client.go:379-387); a cursor
        # replays everything after it
        since = parse_revision(revision) if revision else self._store.head_revision
        stop = threading.Event()

        def gen() -> Iterator[Update]:
            # one sampled span per subscription (not per update): resumes
            # are events, delivery volume is an attribute at close —
            # bounded trace weight however long the stream lives.  Started
            # lazily on first iteration so a subscription that is never
            # consumed records no span (gen()'s finally is its only end)
            wsp = _trace.root_span("watch", since=int(since))
            base = since  # every revision ≤ base fully delivered
            part_rev: Optional[int] = None  # revision partially delivered
            part_n = 0  # raw updates of part_rev already delivered
            no_progress = 0
            delivered = 0
            try:
                while True:
                    if ctx.done():
                        return
                    skip_rev, to_skip, skipped = part_rev, part_n, 0
                    try:
                        for rev, u in self._store.updates_since(
                            base, stop=stop, poll_interval=cfg.poll_interval,
                            cancelled=ctx.done,
                        ):
                            if ctx.done():
                                return
                            if rev != part_rev:
                                if part_rev is not None:
                                    # moved past it → fully delivered
                                    base = part_rev
                                part_rev, part_n = rev, 0
                            if rev == skip_rev and skipped < to_skip:
                                # redelivered prefix of the partially-
                                # delivered revision: already consumed
                                skipped += 1
                                continue
                            faults.fire("watch.stream")
                            part_n += 1
                            no_progress = 0
                            if f.admits(u):
                                delivered += 1
                                yield u
                        return  # stream ended: stop set or ctx cancelled
                    except UnavailableError:
                        self._metrics.inc("watch.resumes")
                        wsp.event(
                            "watch.resume",
                            error="UnavailableError",
                            no_progress=no_progress + 1,
                            cursor_rev=int(base),
                            cursor_offset=part_n,
                        )
                        no_progress += 1
                        if no_progress == cfg.storm_resumes:
                            # a resume is routine; storm_resumes
                            # consecutive no-progress resumes is a storm
                            # — freeze the flight ring while the
                            # faulting stream's spans are still in it
                            # (fires once per storm: the counter resets
                            # on progress).  The incident carries the
                            # full cursor — (revision, raw offset) — so
                            # the bundle pinpoints where the stream is
                            # stuck
                            _trace.trigger_incident(
                                "watch.resume_storm",
                                no_progress=no_progress,
                                cursor_rev=int(base),
                                cursor_offset=part_n,
                            )
                        if no_progress > cfg.max_resumes:
                            raise
                        # brief context-aware pause, then re-subscribe
                        # from the (base, part_n) cursor
                        ctx.wait(min(0.002 * no_progress, 0.05))
            finally:
                stop.set()
                wsp.set_attr("delivered", delivered)
                wsp.end()

        return gen()

    # ------------------------------------------------------------------
    # Schema (client/client.go:415-434)
    # ------------------------------------------------------------------
    def read_schema(self, ctx: Context) -> Tuple[str, str]:
        """Read the current schema with full consistency; returns
        (schema_text, revision)."""
        return self._store.read_schema()

    def write_schema(self, ctx: Context, schema: str) -> str:
        """Apply the schema.  A schema leaving live relationships
        unreferenced raises (client/client.go:426-427)."""
        return self._store.write_schema(schema)

    # ------------------------------------------------------------------
    # Bulk import/export (client/client.go:436-499)
    # ------------------------------------------------------------------
    def import_relationships(
        self, ctx: Context, rs: Iterable[RelationshipLike]
    ) -> None:
        """Bulk restore, optimized over Write.  Accumulates IMPORT_BUFFER
        relationships per store flush so restores land on the columnar
        bulk path (store/store.py COLUMNAR_IMPORT_MIN); a batch that
        already exists falls back to a retried TOUCH import — the same
        recovery the reference performs on AlreadyExists
        (client/client.go:448-463)."""
        chunk: List[Relationship] = []

        def flush() -> None:
            if not chunk:
                return
            try:
                self._store.import_relationships(chunk)
            except AlreadyExistsError:
                retry_retriable_errors(
                    ctx,
                    lambda: self._store.import_relationships(chunk, touch=True),
                )
            chunk.clear()

        for r in rs:
            chunk.append(as_relationship(r))
            if len(chunk) >= IMPORT_BUFFER:
                flush()
        flush()

    def import_relationship_columns(
        self,
        ctx: Context,
        *,
        resource_type: str,
        resource_ids: Sequence[str],
        resource_relation: str,
        subject_type: str,
        subject_ids: Sequence[str],
        subject_relation: str = "",
    ) -> None:
        """Columnar bulk restore: one relationship shape, ids as parallel
        string columns — the native-path complement of
        ``import_relationships`` for the plain rows that dominate
        restores (no per-edge objects; batch interning; one validation).
        Falls back to a retried TOUCH import on AlreadyExists, like the
        reference's recovery (client/client.go:448-463)."""
        self._check_overlap(ctx)
        kw = dict(
            resource_type=resource_type, resource_ids=resource_ids,
            resource_relation=resource_relation,
            subject_type=subject_type, subject_ids=subject_ids,
            subject_relation=subject_relation,
        )
        try:
            self._store.import_columns(**kw)
        except AlreadyExistsError:
            retry_retriable_errors(
                ctx, lambda: self._store.import_columns(**kw, touch=True)
            )

    def export_relationships(
        self, ctx: Context, revision: str
    ) -> Iterator[Relationship]:
        """Stream every relationship at an exact snapshot revision — the
        backup half of backup/restore (client/client.go:467-499).
        Cancellation is honored at page boundaries (READ_PAGE rows),
        like read_relationships and the reference's server stream — a
        per-row ctx check costs more than the row decode itself."""
        self._check_overlap(ctx)
        count = 0
        for r in self._store.export_at(revision):
            if count % READ_PAGE == 0:
                err = ctx.err()
                if err is not None:
                    raise err
            count += 1
            yield r

    def export_relationship_columns(
        self, ctx: Context, revision: str
    ) -> Iterator[Dict[str, list]]:
        """Columnar export at an exact snapshot revision: yields chunks
        of parallel string/value lists — the backup mirror of
        ``import_relationship_columns``, for restore pipelines that
        don't want per-edge objects (~4× the object path's rate).
        Cancellation is honored between chunks."""
        self._check_overlap(ctx)
        for chunk in self._store.export_columns_at(revision):
            err = ctx.err()
            if err is not None:
                raise err
            yield chunk

    def import_relationship_id_columns(
        self,
        ctx: Context,
        *,
        resource_ids,
        resource_relation: str,
        subject_ids,
        subject_relation: str = "",
    ) -> None:
        """Pre-interned columnar bulk restore: int node-id columns from
        THIS store's interner (``export_relationship_id_columns``
        chunks, or ``Interner.node_batch`` results) — no string work at
        all, the fastest restore path (~5x the string-columnar rate).
        Rows may mix resource/subject types.  Falls back to a retried
        TOUCH import on AlreadyExists, like the reference's recovery
        (client/client.go:448-463)."""
        self._check_overlap(ctx)
        kw = dict(
            resource_ids=resource_ids, resource_relation=resource_relation,
            subject_ids=subject_ids, subject_relation=subject_relation,
        )
        try:
            self._store.import_interned_columns(**kw)
        except AlreadyExistsError:
            retry_retriable_errors(
                ctx,
                lambda: self._store.import_interned_columns(
                    **kw, touch=True
                ),
            )

    def export_relationship_id_columns(
        self, ctx: Context, revision: str
    ) -> Iterator[Dict[str, Any]]:
        """Interned columnar export at an exact snapshot revision: yields
        chunks of int32 node-id columns (one (relation, subject-relation)
        shape per chunk) — the zero-string mirror of
        ``import_relationship_id_columns`` for restore pipelines staying
        within this store's interner.  Cancellation is honored between
        chunks."""
        self._check_overlap(ctx)
        for chunk in self._store.export_interned_columns_at(revision):
            err = ctx.err()
            if err is not None:
                raise err
            yield chunk

    # ------------------------------------------------------------------
    # Lookups (client/client.go:501-599)
    # ------------------------------------------------------------------
    def lookup_resources(
        self, ctx: Context, cs: Strategy, permission: str, subject: str
    ) -> Iterator[str]:
        """Stream resource IDs the subject can access.
        ``permission`` = "type#perm", ``subject`` = "type:id[#rel]"
        (client/client.go:501-552).

        Device path: masked frontier SpMV over the reverse-CSR tables
        (engine/spmv.py; host-walker fallback for layouts without them)
        + batched exact forward checks; host-oracle scan only for
        schemas the device can't evaluate.  Transient dispatch faults
        (``lookup.dispatch`` site) retry under the reference's backoff
        envelope like checks do."""
        self._check_overlap(ctx)
        subj_type, subj_id, subj_rel = parse_object_set(subject)
        obj_type, obj_rel = parse_typed_relation(permission)
        snap = self._store.snapshot_for(cs)
        engine = self._engine_for(snap)
        if engine is not None:
            from .engine.lookup import lookup_resources_device

            self._metrics.inc("lookups.resources_device")
            ids = retry_retriable_errors(
                ctx,
                lambda: lookup_resources_device(
                    engine, self._dsnap_for(engine, snap),
                    obj_type, obj_rel, subj_type, subj_id, subj_rel,
                    oracle_factory=lambda: self._oracle_for(snap),
                ),
            )
        else:
            self._metrics.inc("lookups.resources_oracle")
            ids = self._oracle_for(snap).lookup_resources(
                obj_type, obj_rel, subj_type, subj_id, subj_rel
            )
        for rid in ids:
            err = ctx.err()
            if err is not None:
                raise err
            yield rid

    def lookup_subjects(
        self, ctx: Context, cs: Strategy, resource: str, permission: str, subject: str
    ) -> Iterator[str]:
        """Stream subject IDs holding the permission on the resource.
        ``resource`` = "type:id", ``subject`` = "type[#rel]"
        (client/client.go:554-599).

        Device path mirrors lookup_resources: forward frontier expansion
        bounds the candidates, batched device checks filter exactly."""
        self._check_overlap(ctx)
        res_type, res_id, _ = parse_object_set(resource)
        subj_type, _, subj_rel = subject.partition("#")
        snap = self._store.snapshot_for(cs)
        engine = self._engine_for(snap)
        if engine is not None:
            from .engine.lookup import lookup_subjects_device

            self._metrics.inc("lookups.subjects_device")
            ids = retry_retriable_errors(
                ctx,
                lambda: lookup_subjects_device(
                    engine, self._dsnap_for(engine, snap),
                    res_type, res_id, permission, subj_type, subj_rel,
                    oracle_factory=lambda: self._oracle_for(snap),
                ),
            )
        else:
            self._metrics.inc("lookups.subjects_oracle")
            ids = self._oracle_for(snap).lookup_subjects(
                res_type, res_id, permission, subj_type, subj_rel
            )
        for sid in ids:
            err = ctx.err()
            if err is not None:
                raise err
            yield sid

    def lookup_resources_page(
        self, ctx: Context, cs: Strategy, permission: str, subject: str,
        *, page_size: int = 1_000, cursor: Optional[str] = None,
    ) -> "LookupPage":
        """One cursor-paginated page of LookupResources — the reference's
        cursored lookup surface (SURVEY §2).  Results arrive in stable
        discovery order as the frontier expands, so the first page of a
        huge answer returns before the fixpoint completes; the returned
        ``cursor`` is revision-pinned and resumes EXACTLY (no duplicate
        or lost IDs), as long as the pinned revision's prepared snapshot
        is still resident (``PreconditionFailedError`` otherwise)."""
        self._check_overlap(ctx)
        subj_type, subj_id, subj_rel = parse_object_set(subject)
        obj_type, obj_rel = parse_typed_relation(permission)

        def run_page(engine, dsnap, snap, cur):
            from .engine.lookup import lookup_resources_page as page

            return page(
                engine, dsnap, obj_type, obj_rel, subj_type, subj_id,
                subj_rel, page_size=page_size, cursor=cur,
                oracle_factory=lambda: self._oracle_for(snap),
            )

        return self._lookup_page(
            ctx, cs, cursor, "lookup_resources_page",
            ("res", obj_type, obj_rel, subj_type, subj_id, subj_rel),
            run_page,
            lambda snap, now_us: self._pinned_oracle(
                snap, now_us
            ).lookup_resources(
                obj_type, obj_rel, subj_type, subj_id, subj_rel
            ),
            page_size,
        )

    def lookup_subjects_page(
        self, ctx: Context, cs: Strategy, resource: str, permission: str,
        subject: str, *, page_size: int = 1_000,
        cursor: Optional[str] = None,
    ) -> "LookupPage":
        """One cursor-paginated page of LookupSubjects (see
        lookup_resources_page for the cursor contract)."""
        self._check_overlap(ctx)
        res_type, res_id, _ = parse_object_set(resource)
        subj_type, _, subj_rel = subject.partition("#")

        def run_page(engine, dsnap, snap, cur):
            from .engine.lookup import lookup_subjects_page as page

            return page(
                engine, dsnap, res_type, res_id, permission, subj_type,
                subj_rel, page_size=page_size, cursor=cur,
                oracle_factory=lambda: self._oracle_for(snap),
            )

        return self._lookup_page(
            ctx, cs, cursor, "lookup_subjects_page",
            ("subj", res_type, res_id, permission, subj_type, subj_rel),
            run_page,
            lambda snap, now_us: self._pinned_oracle(
                snap, now_us
            ).lookup_subjects(
                res_type, res_id, permission, subj_type, subj_rel
            ),
            page_size,
        )

    def _pinned_oracle(self, snap: Snapshot, now_us: int) -> Oracle:
        """A SnapshotOracle pinned to one evaluation time (cursor-paged
        oracle fallbacks) — the shared LRU oracle stays wall-clocked for
        ordinary conditional-check fallbacks."""
        return SnapshotOracle(
            snap,
            {
                name: self._store.caveat_program(name)
                for name in snap.compiled.schema.caveats
            },
            now_us=now_us,
        )

    def _lookup_page(self, ctx, cs, cursor, metric, token_parts, run_page,
                     run_oracle, page_size):
        """Shared paged-lookup plumbing: cursor decode + revision
        pinning, the retry envelope around the device dispatch, and a
        sorted-scan fallback for engine-less schemas."""
        from .engine.spmv import LookupCursor, query_token
        from .utils.errors import PreconditionFailedError

        cur = LookupCursor.decode(cursor) if cursor is not None else None
        snap = self._store.snapshot_for(cs)
        if cur is not None and cur.revision != snap.revision:
            # revision-pinned resume: serve from the pinned revision's
            # still-resident prepared snapshot, never silently from a
            # different revision
            with self._lock:
                ds = self._lru_get(self._dsnap_cache, cur.revision)
            if ds is None:
                raise PreconditionFailedError(
                    f"lookup cursor pinned to revision {cur.revision},"
                    " which is no longer resident — restart the lookup"
                )
            snap = ds.source_snapshot or ds.snapshot
        engine = self._engine_for(snap)
        self._metrics.inc(f"lookups.{metric}")
        if engine is None:
            # oracle fallback: deterministic sorted scan, cursor = offset.
            # The evaluation time resolves ONCE and rides the token +
            # cursor (a resume after cache eviction must slice the SAME
            # list, not one recomputed at a later wall clock), and the
            # full answer caches on the snapshot keyed by the token —
            # paging a 100k-result answer must not re-run the oracle
            # scan + sort once per page
            from .engine.spmv import resolve_now_us

            now_us = resolve_now_us(cur, None)
            token = query_token("oracle", snap.revision, now_us,
                                *token_parts)
            if cur is not None and cur.token != token:
                raise PreconditionFailedError(
                    "lookup cursor does not match this query"
                )
            pages = snap.__dict__.setdefault("_oracle_lookup_pages", {})
            ids_all = pages.get(token)
            if ids_all is None:
                ids_all = sorted(run_oracle(snap, now_us))
                pages[token] = ids_all
                while len(pages) > 4:
                    pages.pop(next(iter(pages)))
            pos = cur.pos if cur is not None else 0
            ids = ids_all[pos : pos + page_size]
            nxt = None
            if pos + len(ids) < len(ids_all):
                nxt = LookupCursor(
                    snap.revision, token, pos + len(ids), now_us
                )
            return LookupPage(ids, nxt.encode() if nxt else None)
        dsnap = self._dsnap_for(engine, snap)
        ids, nxt = retry_retriable_errors(
            ctx, lambda: run_page(engine, dsnap, snap, cur)
        )
        return LookupPage(ids, nxt.encode() if nxt is not None else None)


# ---------------------------------------------------------------------------
# Constructors (client/client.go:35-77)
# ---------------------------------------------------------------------------


def new_tpu_evaluator(*opts: Option) -> Client:
    """Create a client backed by the local TPU evaluation engine — the
    constructor BASELINE.json names as the north star."""
    return Client(*opts)


def new_with_opts(*opts: Option) -> Client:
    """Create a client with defaults overridden by options
    (client/client.go:63-77)."""
    return Client(*opts)


def new_plaintext(endpoint: str = "", preshared_key: str = "", *opts: Option) -> Client:
    """API-parity constructor (client/client.go:38-44).  The reference
    dials an insecure gRPC channel; this framework evaluates locally, so
    the endpoint and key are accepted for drop-in compatibility and
    ignored."""
    return Client(*opts)


def new_system_tls(endpoint: str = "", preshared_key: str = "", *opts: Option) -> Client:
    """API-parity constructor (client/client.go:50-61); see new_plaintext."""
    return Client(*opts)


# Go-parity aliases.
NewTPUEvaluator = new_tpu_evaluator
NewWithOpts = new_with_opts
NewPlaintext = new_plaintext
NewSystemTLS = new_system_tls
WithOverlapRequired = with_overlap_required
WithLatencyMode = with_latency_mode
WithAdmissionControl = with_admission_control
WithGroupCommit = with_group_commit

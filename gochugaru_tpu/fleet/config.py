"""Shared fleet tuning knobs (router and replica both read these)."""

from __future__ import annotations

from dataclasses import dataclass

from .zookie import DEFAULT_KEY


@dataclass(frozen=True)
class FleetConfig:
    """One config object for the whole fleet story; the defaults are the
    single-box test/bench posture (sub-second failure detection, bounded
    freshness waits)."""

    #: virtual nodes per ring member — smooths placement so one replica
    #: death re-spreads its keyspace across the survivors
    vnodes: int = 32
    #: health-probe cadence; with ``kill_threshold`` consecutive misses
    #: this bounds kill-detection latency at roughly their product
    probe_interval_s: float = 0.25
    probe_timeout_s: float = 1.0
    kill_threshold: int = 2
    #: bounded block on reads requiring a revision no ring member has
    #: reached yet (read-your-writes catchup); on expiry the request
    #: sheds with a retriable UnavailableError
    freshness_wait_s: float = 5.0
    freshness_poll_s: float = 0.05
    #: catchup lag (revisions behind upstream head) beyond which a
    #: replica reports not-ready and the router drains it from the ring;
    #: generous so steady write load doesn't flap membership
    ready_lag: int = 64
    #: idle heartbeat cadence on the replication stream — a quiescent
    #: replica still learns the upstream head this often
    heartbeat_s: float = 0.25
    io_timeout_s: float = 30.0
    connect_timeout_s: float = 2.0
    #: relationships per bootstrap-export frame
    bootstrap_chunk: int = 2048
    #: router-side parallel dispatch lanes (per-owner sub-batches)
    dispatch_workers: int = 8
    #: HMAC key zookies are minted/verified with — every front sharing
    #: traffic must share it
    zookie_key: bytes = DEFAULT_KEY

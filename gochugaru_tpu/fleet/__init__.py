"""Fleet serving: replicated processes around the shared watch stream.

One process is one failure domain.  This package splits the engine into
an authoritative **router** (owns the store, mints revisions and
zookies, serves the replication stream, routes checks over a
consistent-hash ring with freshness overrides and failover) and N
**replicas** (bootstrap a world export, tail the stream exactly-once,
serve checks through a full local Client with verdict cache and
admission control).  See fleet/router.py and fleet/replica.py for the
protocol details, scripts/fleetd.py for the process entrypoints, and
BENCHMARKS.md "Fleet serving" for topology and failover methodology.
"""

from .config import FleetConfig
from .replica import Replica
from .router import FleetRouter, HashRing
from .zookie import InvalidZookieError

__all__ = [
    "FleetConfig",
    "FleetRouter",
    "HashRing",
    "Replica",
    "InvalidZookieError",
]

"""Front router: consistent-hash placement with freshness overrides.

The router owns the authoritative ``Store`` — every write lands here,
mints a revision, and is pushed to replicas over the replication stream
(``Store.entries_since`` served by the router's wire server).  Reads
route to replicas:

- **Placement** — a consistent-hash ring (virtual nodes) keyed by the
  resource id, so a check batch splits into per-owner sub-batches and
  each replica's verdict cache sees a stable keyspace slice.
- **Freshness override** — ``consistency.policy_for`` maps the caller's
  strategy (plus any zookie) to a minimum revision; an owner whose
  resident head hasn't reached it is overridden to any sufficiently
  fresh ring member, and when *no* member is fresh enough the dispatch
  blocks (bounded, probing as it waits) for catchup — block-or-redirect,
  never stale.
- **Failover** — health probes (``kill_threshold`` consecutive misses)
  and classified transport errors on the dispatch path evict a replica
  from the ring, fire the ``fleet.failover`` incident trigger, and
  re-route the affected sub-batch to a survivor within the same attempt;
  the client-facing retry envelope (``retry_retriable_errors``) is the
  outer backstop.  Checks are idempotent reads, so re-dispatch loses and
  duplicates nothing.  A restarted replica re-enters the ring only when
  its health reports ready (caught up past the ready-lag gate).

Fault sites on this path: ``router.dispatch`` (fires before each
sub-batch dispatch) and ``router.health`` (fires before each probe) —
both armed by the chaos soak.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .. import consistency
from ..rel.relationship import as_relationship
from ..rel.txn import Txn
from ..rel.update import UpdateType
from ..store.store import RevisionToken, Store, parse_revision
from ..utils import faults
from ..utils import metrics as _metrics
from ..utils import trace as _trace
from ..utils.context import Context, background
from ..utils.errors import (
    PermanentError,
    RevisionUnavailableError,
    TRANSPORT_ERRORS,
    UnavailableError,
    classify_dispatch_exception,
    is_retriable,
)
from ..utils.retry import retry_retriable_errors
from .config import FleetConfig
from . import wire as _wire
from . import zookie as _zookie


def _hash64(data: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(data.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring with virtual nodes.  Not thread-safe; the
    router mutates it under its own lock."""

    def __init__(self, vnodes: int = 32) -> None:
        self._vnodes = vnodes
        self._points: List[Tuple[int, str]] = []  # sorted (hash, member)
        self._members: Set[str] = set()

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.add(member)
        for v in range(self._vnodes):
            bisect.insort(self._points, (_hash64(f"{member}#{v}"), member))

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.discard(member)
        self._points = [p for p in self._points if p[1] != member]

    def members(self) -> Set[str]:
        return set(self._members)

    def owner(self, key: str) -> Optional[str]:
        if not self._points:
            return None
        h = _hash64(key)
        i = bisect.bisect_right(self._points, (h, "\uffff"))
        if i == len(self._points):
            i = 0
        return self._points[i][1]


class _ReplicaHandle:
    """Router-side view of one replica: address, pooled connections, and
    the last-probed health (head / lag / readiness / residency)."""

    def __init__(self, addr: Tuple[str, int], cfg: FleetConfig) -> None:
        self.id = ""
        self.addr = addr
        self.cfg = cfg
        self.in_ring = False
        self.fails = 0
        self.head = 0
        self.lag = 0
        self.ready = False
        self.resident: List[int] = []
        self._pool: List[_wire.Conn] = []
        self._lock = threading.Lock()

    def request(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            conn = self._pool.pop() if self._pool else None
        if conn is None:
            conn = _wire.Conn(
                self.addr,
                connect_timeout=self.cfg.connect_timeout_s,
                io_timeout=self.cfg.io_timeout_s,
            )
        try:
            out = conn.request(msg)
        except BaseException:
            conn.close()
            raise
        with self._lock:
            if len(self._pool) < 4:
                self._pool.append(conn)
            else:
                conn.close()
        return out

    def probe(self, timeout: float) -> Dict[str, Any]:
        """Health check on a fresh short-timeout connection — probe
        latency must not ride the (long) dispatch io timeout."""
        c = _wire.Conn(self.addr, connect_timeout=timeout, io_timeout=timeout)
        try:
            return c.request({"op": "health"})
        finally:
            c.close()

    def close(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, []
        for c in pool:
            c.close()


class FleetRouter:
    """The authority + front: owns the store, serves the replication
    stream, and routes checks across the replica ring."""

    def __init__(
        self,
        store: Optional[Store] = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        config: Optional[FleetConfig] = None,
        registry: Optional[_metrics.Metrics] = None,
    ) -> None:
        self._store = store if store is not None else Store()
        self._cfg = config or FleetConfig()
        self._m = registry or _metrics.default
        self._replicas: Dict[str, _ReplicaHandle] = {}
        self._ring = HashRing(self._cfg.vnodes)
        self._lock = threading.RLock()
        self._closed = False
        self._pool = ThreadPoolExecutor(
            max_workers=self._cfg.dispatch_workers,
            thread_name_prefix="fleet-dispatch",
        )
        self._server = _wire.WireServer(
            self._serve, host=host, port=port, name="fleet-router"
        )
        self.host, self.port = self._server.host, self._server.port
        self._prober = threading.Thread(
            target=self._probe_loop, daemon=True, name="fleet-prober"
        )
        self._prober.start()

    # -- properties -------------------------------------------------------
    @property
    def store(self) -> Store:
        return self._store

    @property
    def head_revision(self) -> int:
        return self._store.head_revision

    # -- write path (authority) ------------------------------------------
    def write_schema(self, ctx: Context, schema: str) -> str:
        return self._store.write_schema(schema)

    def write(self, ctx: Context, txn: Txn) -> str:
        """Apply a transaction on the authority and mint the zookie the
        client presents for read-your-writes."""
        token = self._store.write(txn)
        self._m.inc("fleet.writes")
        return _zookie.mint(token, self._cfg.zookie_key)

    def write_group(self, ctx: Context, txns: Sequence[Txn]) -> List[object]:
        """Group-commit on the authority (store/group.py semantics): the
        whole group lands as ONE log entry, so the watch stream carries
        it to every replica as ONE frame and each replica applies it as
        one advance under the same exactly-once cursor discipline as a
        single write.  Returns per-transaction outcomes in order: a
        minted zookie for survivors, the ejecting exception otherwise."""
        outcomes = self._store.write_group(txns)
        minted = 0
        for i, out in enumerate(outcomes):
            if not isinstance(out, BaseException):
                outcomes[i] = _zookie.mint(out, self._cfg.zookie_key)
                minted += 1
        self._m.inc("fleet.writes", minted)
        if minted:
            self._m.inc("fleet.write_groups")
        return outcomes

    # -- membership -------------------------------------------------------
    def add_replica(
        self, host: str, port: int, *, wait_ready_s: Optional[float] = None
    ) -> str:
        """Register a replica; it joins the ring on its first ready
        probe.  ``wait_ready_s`` blocks until then (bench/smoke setup)."""
        h = _ReplicaHandle((host, port), self._cfg)
        r = h.probe(self._cfg.probe_timeout_s)
        h.id = str(r["replica"])
        with self._lock:
            self._replicas[h.id] = h
        self._apply_probe(h, r)
        if wait_ready_s:
            deadline = time.monotonic() + wait_ready_s
            while not h.in_ring and time.monotonic() < deadline:
                time.sleep(0.02)
                try:
                    self._apply_probe(h, h.probe(self._cfg.probe_timeout_s))
                except Exception:
                    pass
            if not h.in_ring:
                raise UnavailableError(
                    f"replica {h.id} did not become ready in {wait_ready_s}s"
                )
        self._publish_ring()
        return h.id

    def remove_replica(self, replica_id: str) -> None:
        with self._lock:
            h = self._replicas.pop(replica_id, None)
            if h is not None and h.in_ring:
                self._ring.remove(h.id)
                h.in_ring = False
        if h is not None:
            h.close()
        self._publish_ring()

    def status(self) -> Dict[str, Any]:
        with self._lock:
            handles = list(self._replicas.values())
            ring = sorted(self._ring.members())
        return {
            "head": self.head_revision,
            "ring": ring,
            "replicas": {
                h.id: {
                    "head": h.head,
                    "lag": h.lag,
                    "ready": h.ready,
                    "in_ring": h.in_ring,
                    "fails": h.fails,
                }
                for h in handles
            },
        }

    # -- health probing ---------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._closed:
            with self._lock:
                handles = list(self._replicas.values())
            for h in handles:
                if self._closed:
                    return
                self._probe_once(h)
            if handles:
                self._m.set_gauge(
                    "fleet.max_catchup_lag",
                    float(max(h.lag for h in handles)),
                )
            time.sleep(self._cfg.probe_interval_s)

    def _probe_once(self, h: _ReplicaHandle) -> None:
        try:
            faults.fire("router.health")
            r = h.probe(self._cfg.probe_timeout_s)
        except BaseException as e:
            h.fails += 1
            self._m.inc("fleet.probe_failures")
            if h.fails >= self._cfg.kill_threshold and h.in_ring:
                self._evict(
                    h,
                    cause=f"{h.fails} consecutive probe failures: {e!r}",
                    kill=True,
                )
            return
        self._apply_probe(h, r)

    def _apply_probe(self, h: _ReplicaHandle, r: Dict[str, Any]) -> None:
        h.fails = 0
        h.head = max(h.head, int(r.get("head", 0)))
        h.lag = int(r.get("lag", 0))
        h.ready = bool(r.get("ready"))
        h.resident = [int(x) for x in r.get("resident", ())]
        if r.get("dead"):
            if h.in_ring:
                self._evict(h, cause="replica reports dead", kill=True)
            return
        if h.ready and not h.in_ring:
            self._join(h)
        elif not h.ready and h.in_ring:
            # catching up or shedding — drain without the failover alarm
            self._evict(
                h, cause=f"not ready (lag={h.lag})", kill=False
            )

    def _join(self, h: _ReplicaHandle) -> None:
        with self._lock:
            self._ring.add(h.id)
            h.in_ring = True
        self._m.inc("fleet.rejoins")
        self._publish_ring()

    def _evict(self, h: _ReplicaHandle, *, cause: str, kill: bool) -> None:
        with self._lock:
            if not h.in_ring:
                return
            self._ring.remove(h.id)
            h.in_ring = False
            survivors = sorted(self._ring.members())
        self._m.inc("fleet.evictions")
        self._publish_ring()
        if kill:
            self._m.inc("fleet.kill_detections")
            _trace.trigger_incident(
                "fleet.failover", replica=h.id, cause=cause, ring=survivors
            )

    def _publish_ring(self) -> None:
        with self._lock:
            self._m.set_gauge("fleet.ring_size", float(len(self._ring.members())))
            self._m.set_gauge("fleet.replicas", float(len(self._replicas)))

    # -- routed check -----------------------------------------------------
    def check(
        self, ctx: Context, cs: consistency.Strategy, *rs,
        zookie: Optional[str] = None,
    ) -> List[bool]:
        """Routed batched check.  ``zookie`` raises the freshness floor
        to the write that minted it (read-your-writes); an invalid token
        fails permanently before any dispatch."""
        rels = [as_relationship(r) for r in rs]
        if not rels:
            return []
        zrev = (
            _zookie.parse(zookie, self._cfg.zookie_key)
            if zookie is not None
            else None
        )
        with self._m.timer("fleet.check_s"):
            return retry_retriable_errors(
                ctx, lambda: self._dispatch(ctx, cs, zrev, rels)
            )

    def _dispatch(
        self,
        ctx: Context,
        cs: consistency.Strategy,
        zrev: Optional[int],
        rels: List,
    ) -> List[bool]:
        mode, rev_tok = consistency.policy_for(cs)
        head = self._store.head_revision
        if mode == "head":
            min_rev = head
        elif mode == "any":
            min_rev = 0
        else:
            min_rev = parse_revision(rev_tok or "")
        fwd = cs
        if mode == "head":
            # FULL pins "the head at dispatch": replicas evaluate
            # at-least that revision, which is read-your-writes for
            # every write committed before this call
            fwd = consistency.at_least(RevisionToken(min_rev))
        if zrev is not None and mode != "exact":
            if zrev > min_rev:
                min_rev = zrev
                fwd = consistency.at_least(RevisionToken(min_rev))
        if min_rev > head:
            # mirrors Store.snapshot_for's AT_LEAST contract: a token
            # from the future is a permanent client error, not a wait
            raise RevisionUnavailableError(
                f"revision {min_rev} is in the future (head {head})"
            )

        with self._lock:
            groups: Dict[Optional[str], List[int]] = {}
            for i, r in enumerate(rels):
                owner = self._ring.owner(f"{r.resource_type}:{r.resource_id}")
                groups.setdefault(owner, []).append(i)
        out: List[Optional[bool]] = [None] * len(rels)
        self._m.inc("fleet.dispatches", len(groups))
        futures = [
            (
                idxs,
                self._pool.submit(
                    self._dispatch_group, ctx, owner, mode, min_rev, fwd,
                    [rels[i] for i in idxs],
                ),
            )
            for owner, idxs in groups.items()
        ]
        for idxs, fut in futures:
            verdicts = fut.result()
            for i, v in zip(idxs, verdicts):
                out[i] = v
        return [bool(v) for v in out]

    def _dispatch_group(
        self,
        ctx: Context,
        owner_id: Optional[str],
        mode: str,
        min_rev: int,
        fwd: consistency.Strategy,
        sub: List,
    ) -> List[bool]:
        """One sub-batch: owner-preferred, freshness-overridden, with
        in-attempt failover.  ``failed`` accumulates replicas this
        attempt already saw fail — a transport failure also feeds the
        eviction path immediately instead of waiting out the prober."""
        failed: Set[str] = set()
        wait_deadline = time.monotonic() + self._cfg.freshness_wait_s
        waited = False
        msg = {
            "op": "check",
            "cs": _wire.strategy_to_wire(fwd),
            "rels": [_wire.rel_to_wire(r) for r in sub],
        }
        while True:
            err = ctx.err()
            if err is not None:
                raise err
            h = self._select(owner_id, mode, min_rev, failed)
            if h is None:
                if time.monotonic() >= wait_deadline:
                    raise UnavailableError(
                        f"no replica fresh enough for revision {min_rev}"
                        f" (mode={mode}, failed={sorted(failed)})"
                    )
                if not waited:
                    waited = True
                    self._m.inc("fleet.fresh_waits")
                # block-or-redirect, never stale: probe for catchup at
                # the poll cadence instead of trusting the (slower)
                # background prober
                with self._lock:
                    candidates = [
                        self._replicas[m]
                        for m in self._ring.members()
                        if m not in failed
                    ]
                for c in candidates:
                    try:
                        self._apply_probe(
                            c, c.probe(self._cfg.probe_timeout_s)
                        )
                    except Exception:
                        pass
                ctx.wait(self._cfg.freshness_poll_s)
                continue
            try:
                faults.fire("router.dispatch")
                resp = h.request(msg)
            except BaseException as e:
                classified = classify_dispatch_exception(e)
                if classified is None:
                    raise
                if not is_retriable(classified):
                    raise classified
                if isinstance(e, TRANSPORT_ERRORS):
                    # a reset/refused socket IS the death signal — don't
                    # wait for the prober to notice
                    h.fails += 1
                    if (
                        h.fails >= self._cfg.kill_threshold and h.in_ring
                    ):
                        self._evict(
                            h,
                            cause=f"transport failure on dispatch: {e!r}",
                            kill=True,
                        )
                failed.add(h.id)
                self._m.inc("fleet.reroutes")
                continue
            h.head = max(h.head, int(resp.get("head", 0)))
            return [bool(v) for v in resp["verdicts"]]

    def _select(
        self,
        owner_id: Optional[str],
        mode: str,
        min_rev: int,
        failed: Set[str],
    ) -> Optional[_ReplicaHandle]:
        with self._lock:
            members = [
                self._replicas[m]
                for m in self._ring.members()
                if m not in failed
            ]
        if mode == "exact":
            eligible = [
                h for h in members
                if min_rev in h.resident or h.head == min_rev
            ]
        else:
            eligible = [h for h in members if h.head >= min_rev]
        if not eligible:
            return None
        for h in eligible:
            if h.id == owner_id:
                return h
        if owner_id is not None and any(h.id == owner_id for h in members):
            # the owner is alive but not fresh enough: freshness override
            self._m.inc("fleet.freshness_redirects")
        return max(eligible, key=lambda h: h.head)

    # -- wire front (replica bootstrap/stream + remote clients) ----------
    def _serve(self, msg: Dict[str, Any], sock) -> Optional[Dict[str, Any]]:
        op = msg.get("op")
        if op == "bootstrap":
            snap = self._store.snapshot_for(consistency.full())
            schema, _ = self._store.read_schema()
            return {"ok": True, "schema": schema, "revision": snap.revision}
        if op == "export":
            rev = int(msg["revision"])
            batch: List[Dict[str, Any]] = []
            for r in self._store.export_at(RevisionToken(rev)):
                batch.append(_wire.rel_to_wire(r))
                if len(batch) >= self._cfg.bootstrap_chunk:
                    _wire.send_frame(sock, {"rels": batch})
                    batch = []
            if batch:
                _wire.send_frame(sock, {"rels": batch})
            _wire.send_frame(sock, {"ok": True, "eof": True})
            return None
        if op == "stream":
            since = int(msg.get("since", 0))
            for rev, ups in self._store.entries_since(
                since,
                heartbeats=True,
                poll_interval=self._cfg.heartbeat_s,
                cancelled=lambda: self._closed,
            ):
                if ups is None:
                    _wire.send_frame(sock, {"head": rev})
                else:
                    _wire.send_frame(
                        sock,
                        {
                            "rev": rev,
                            "head": self._store.head_revision,
                            "updates": [_wire.update_to_wire(u) for u in ups],
                        },
                    )
            _wire.send_frame(sock, {"ok": True, "eof": True})
            return None
        if op == "join":
            # self-service membership (scripts/fleetd.py --join): the
            # replica asks to be admitted; it enters the ring on its
            # first ready probe like any other member
            rid = self.add_replica(
                str(msg["host"]), int(msg["port"]),
                wait_ready_s=msg.get("wait_ready_s"),
            )
            return {"ok": True, "replica": rid, "ring": self.status()["ring"]}
        if op == "write":
            txn = Txn()
            for d in msg.get("updates", ()):
                u = _wire.update_from_wire(d)
                if u.update_type == UpdateType.CREATE:
                    txn.create(u.relationship)
                elif u.update_type == UpdateType.TOUCH:
                    txn.touch(u.relationship)
                else:
                    txn.delete(u.relationship)
            zk = self.write(background(), txn)
            return {
                "ok": True,
                "zookie": zk,
                "revision": RevisionToken(self._store.head_revision),
            }
        if op == "check":
            cs = _wire.strategy_from_wire(msg["cs"])
            rels = [_wire.rel_from_wire(d) for d in msg["rels"]]
            ctx = background().with_timeout(
                float(msg.get("deadline_s") or self._cfg.io_timeout_s)
            )
            verdicts = self.check(ctx, cs, *rels, zookie=msg.get("zookie"))
            return {
                "ok": True,
                "verdicts": verdicts,
                "head": self._store.head_revision,
            }
        if op == "health":
            st = self.status()
            st["ok"] = True
            st["role"] = "router"
            return st
        raise PermanentError(f"unknown router op {op!r}")

    # -- lifecycle --------------------------------------------------------
    def close(self) -> None:
        self._closed = True
        self._server.close(abort=True)
        self._pool.shutdown(wait=False)
        with self._lock:
            handles = list(self._replicas.values())
        for h in handles:
            h.close()
        self._prober.join(2.0)

"""Framed-JSON wire protocol for the fleet: router ⇄ replica ⇄ client.

Stdlib-only (socket/struct/json/threading) by constraint — the container
bakes no RPC framework, and a length-prefixed JSON frame is all the
fleet needs: requests are small (a check batch, a health probe), the
bulk paths (bootstrap export, log stream) are streamed as frame
sequences, and every error crosses the wire as a *classified* frame
that re-raises as the same ``AuthzError`` subclass on the caller's side
— so the retry envelope (utils/retry.py) treats a remote shed exactly
like a local one.

Frame format: 4-byte big-endian length + UTF-8 JSON.  A connection that
dies mid-frame raises ``WireClosed`` (a ``ConnectionError`` subclass,
so ``classify_dispatch_exception`` maps it to a retriable
``UnavailableError`` — the router's failover trigger).
"""

from __future__ import annotations

import datetime as _dt
import json
import socket
import struct
import threading
from typing import Any, Dict, Iterator, Optional, Tuple

from ..consistency import Requirement, Strategy
from ..rel.relationship import Relationship, expiration_micros
from ..rel.update import Update, UpdateType
from ..utils import errors as _errors

#: Frame size ceiling — a corrupted length prefix must not allocate GBs.
FRAME_MAX = 64 << 20


class WireClosed(ConnectionError):
    """The peer closed the connection (mid-frame or mid-request)."""


# ---------------------------------------------------------------------------
# Framing
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int, *, eof_ok: bool) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if eof_ok and not buf:
                return None
            raise WireClosed("connection closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Optional[Any]:
    """One frame, or None on clean EOF at a frame boundary."""
    head = _recv_exact(sock, 4, eof_ok=True)
    if head is None:
        return None
    (n,) = struct.unpack(">I", head)
    if n > FRAME_MAX:
        raise ValueError(f"frame of {n} bytes exceeds FRAME_MAX")
    body = _recv_exact(sock, n, eof_ok=False)
    return json.loads(body.decode("utf-8"))


# ---------------------------------------------------------------------------
# Classified errors over the wire
# ---------------------------------------------------------------------------

#: AuthzError classes that survive a wire crossing by name.  Anything not
#: listed deserializes as PermanentError — unknown remote failures must
#: not retry blindly.
_ERROR_TYPES: Dict[str, type] = {
    cls.__name__: cls
    for cls in (
        _errors.UnavailableError,
        _errors.ShedError,
        _errors.DeadlineExceededError,
        _errors.CancelledError,
        _errors.PermanentError,
        _errors.PreconditionFailedError,
        _errors.AlreadyExistsError,
        _errors.RevisionUnavailableError,
        _errors.SchemaError,
        _errors.PartialDeletionError,
    )
}


def register_error(cls: type) -> type:
    """Let modules above this one (fleet/zookie.py) add their own
    classified error to the wire vocabulary."""
    _ERROR_TYPES[cls.__name__] = cls
    return cls


def error_frame(err: BaseException) -> Dict[str, Any]:
    return {"ok": False, "error": type(err).__name__, "msg": str(err)}


def raise_error_frame(frame: Dict[str, Any]) -> None:
    cls = _ERROR_TYPES.get(frame.get("error", ""), _errors.PermanentError)
    raise cls(frame.get("msg", frame.get("error", "remote error")))


# ---------------------------------------------------------------------------
# Relationship / update / strategy codecs
# ---------------------------------------------------------------------------


def rel_to_wire(r: Relationship) -> Dict[str, Any]:
    d: Dict[str, Any] = {
        "rt": r.resource_type, "ri": r.resource_id, "rr": r.resource_relation,
        "st": r.subject_type, "si": r.subject_id,
    }
    if r.subject_relation:
        d["sr"] = r.subject_relation
    if r.caveat_name:
        d["cv"] = r.caveat_name
        if r.caveat_context:
            d["cc"] = dict(r.caveat_context)
    exp = expiration_micros(r.expiration)
    if exp:
        d["ex"] = exp
    return d


def rel_from_wire(d: Dict[str, Any]) -> Relationship:
    exp = None
    if d.get("ex"):
        exp = _dt.datetime.fromtimestamp(d["ex"] / 1e6, tz=_dt.timezone.utc)
    return Relationship(
        resource_type=d["rt"], resource_id=d["ri"],
        resource_relation=d["rr"],
        subject_type=d["st"], subject_id=d["si"],
        subject_relation=d.get("sr", ""),
        caveat_name=d.get("cv", ""),
        caveat_context=d.get("cc", {}),
        expiration=exp,
    )


def update_to_wire(u: Update) -> Dict[str, Any]:
    return {"t": u.update_type.value, "r": rel_to_wire(u.relationship)}


def update_from_wire(d: Dict[str, Any]) -> Update:
    return Update(UpdateType(d["t"]), rel_from_wire(d["r"]))


def strategy_to_wire(cs: Strategy) -> Dict[str, Any]:
    d: Dict[str, Any] = {"req": cs.requirement.value}
    if cs.revision is not None:
        d["rev"] = cs.revision
    return d


def strategy_from_wire(d: Dict[str, Any]) -> Strategy:
    return Strategy(Requirement(d["req"]), d.get("rev"))


# ---------------------------------------------------------------------------
# Client connection
# ---------------------------------------------------------------------------


class Conn:
    """One connection to a wire server; requests are serialized under a
    lock (one outstanding request per Conn — callers wanting parallelism
    open more Conns, which the router's per-replica handles do)."""

    def __init__(
        self, addr: Tuple[str, int], *,
        connect_timeout: float = 2.0, io_timeout: float = 30.0,
    ) -> None:
        self.addr = addr
        self._sock = socket.create_connection(addr, timeout=connect_timeout)
        self._sock.settimeout(io_timeout)
        self._lock = threading.Lock()

    def request(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            send_frame(self._sock, msg)
            out = recv_frame(self._sock)
        if out is None:
            raise WireClosed("connection closed before response")
        if isinstance(out, dict) and out.get("ok") is False:
            raise_error_frame(out)
        return out

    def stream(self, msg: Dict[str, Any]) -> Iterator[Dict[str, Any]]:
        """Send one request, yield response frames until a frame carries
        ``eof`` or the connection closes.  The lock is held for the whole
        stream — a streaming Conn is single-purpose."""
        with self._lock:
            send_frame(self._sock, msg)
            while True:
                out = recv_frame(self._sock)
                if out is None:
                    return
                if isinstance(out, dict) and out.get("ok") is False:
                    raise_error_frame(out)
                if isinstance(out, dict) and out.get("eof"):
                    return
                yield out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class WireServer:
    """Threaded framed-JSON server: one accept loop, one thread per
    connection.  ``handler(msg, sock)`` returns a response dict, or None
    when it already streamed its own frames on ``sock``.  A handler
    exception becomes a classified error frame; the connection stays up
    (one bad request must not sever a router's replica handle)."""

    def __init__(
        self, handler, *, host: str = "127.0.0.1", port: int = 0,
        name: str = "wire",
    ) -> None:
        self._handler = handler
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.25)  # accept loop polls the closed flag
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = False
        self._conns: set = set()
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"{name}-accept"
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                c, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                if self._closed:
                    c.close()
                    return
                self._conns.add(c)
            threading.Thread(
                target=self._serve, args=(c,), daemon=True
            ).start()

    def _serve(self, c: socket.socket) -> None:
        try:
            while not self._closed:
                msg = recv_frame(c)
                if msg is None:
                    return
                try:
                    out = self._handler(msg, c)
                except (WireClosed, OSError):
                    return  # handler aborted the connection (kill path)
                except BaseException as e:
                    out = error_frame(e)
                if out is not None:
                    send_frame(c, out)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            with self._lock:
                self._conns.discard(c)
            try:
                c.close()
            except OSError:
                pass

    def close(self, *, abort: bool = False) -> None:
        """Stop accepting.  ``abort=True`` hard-closes live connections —
        the crash-simulation path (fleet/replica.py ``die``): peers see
        a reset mid-request, exactly what a killed process looks like."""
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        if abort:
            with self._lock:
                conns = list(self._conns)
            for c in conns:
                try:
                    c.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0),
                    )
                except OSError:
                    pass
                try:
                    c.close()
                except OSError:
                    pass

"""Replica: one serving process that tails the shared watch stream.

A replica bootstraps a full world from the router (schema + columnar
export at a pinned revision), aligns its local revision counter to the
upstream numbering, then tails the router's replication stream —
``Store.entries_since`` on the authority side, ``apply_replicated`` on
this side — so every applied entry lands at its upstream revision and
zookies minted on write resolve identically everywhere.  The tail
cursor is the local head: a resume after any stream break re-subscribes
from it and ``apply_replicated``'s dup guard makes redelivered prefixes
no-ops (the same exactly-once discipline ``Client.updates_since_revision``
proved out, one layer down).

Serving: a framed-JSON wire server (fleet/wire.py) answering
``health`` / ``check`` / ``kill``.  Checks run through a full local
``Client`` — verdict cache, admission gate/breaker, deadline shed — so
a replica sheds exactly like a single-process server and the router
treats the shed as per-replica backpressure.  ``health`` reports the
resident revision range (store snapshots + verdict-cache shards),
catchup lag, and the admission state; the router's ring membership and
freshness overrides are computed from it.

Crash realism: the ``replica.kill`` fault site (and the explicit
``kill`` op) makes the replica drop every connection mid-request and
stop serving — with ``exit_on_death`` (subprocess mode) the process
exits non-zero.  The router sees exactly what a SIGKILL looks like:
reset sockets and failed probes.

Run as a process: ``python -m gochugaru_tpu.fleet.replica --upstream
HOST:PORT`` (scripts/fleetd.py wraps this).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from .. import consistency
from ..client import (
    Client,
    new_tpu_evaluator,
    with_host_only_evaluation,
    with_latency_mode,
    with_store,
    with_verdict_cache,
)
from ..store.store import Store
from ..utils import faults
from ..utils import metrics as _metrics
from ..utils.context import background
from ..utils.errors import (
    PermanentError,
    UnavailableError,
    classify_dispatch_exception,
)
from .config import FleetConfig
from . import wire as _wire


class Replica:
    """One fleet member: bootstrapped store + tailing thread + wire
    server.  In-process construction is what the tier-1 tests use; the
    module's ``main`` wraps the same object as a standalone process."""

    def __init__(
        self,
        upstream: Tuple[str, int],
        *,
        replica_id: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        config: Optional[FleetConfig] = None,
        client_options: Optional[tuple] = None,
        exit_on_death: bool = False,
        registry: Optional[_metrics.Metrics] = None,
    ) -> None:
        self._cfg = config or FleetConfig()
        self._m = registry or _metrics.default
        self._upstream = upstream
        self._exit_on_death = exit_on_death
        self._dead = False
        self._stop = threading.Event()
        self._tail_gate = threading.Event()  # cleared = paused (tests)
        self._tail_gate.set()
        self._tail_err: Optional[BaseException] = None

        self._store = Store()
        base = self._bootstrap()
        self._upstream_head = base
        self._client: Client = new_tpu_evaluator(
            with_store(self._store),
            *(client_options if client_options is not None
              else (with_verdict_cache(),)),
        )
        # materialize the bootstrap world so MIN_LATENCY reads serve
        # immediately and the residency report starts at the base revision
        self._store.snapshot_for(consistency.full())

        self.id = replica_id or f"replica-{os.getpid()}"
        self._server = _wire.WireServer(
            self._handle, host=host, port=port, name=f"fleet-{self.id}"
        )
        self.host, self.port = self._server.host, self._server.port
        self._tail_thread = threading.Thread(
            target=self._tail_loop, daemon=True, name=f"{self.id}-tail"
        )
        self._tail_thread.start()

    # -- bootstrap --------------------------------------------------------
    def _bootstrap(self) -> int:
        boot = _wire.Conn(
            self._upstream,
            connect_timeout=self._cfg.connect_timeout_s,
            io_timeout=self._cfg.io_timeout_s,
        )
        try:
            meta = boot.request({"op": "bootstrap"})
            base = int(meta["revision"])
            self._store.write_schema(meta["schema"])
            for frame in boot.stream({"op": "export", "revision": base}):
                rels = [_wire.rel_from_wire(d) for d in frame.get("rels", ())]
                if rels:
                    self._store.import_relationships(rels, touch=True)
            # local schema/import revisions were provisional numbering;
            # from here on this store counts in upstream revisions
            self._store.align_replica_head(base)
            return base
        finally:
            boot.close()

    # -- replication tail -------------------------------------------------
    def _tail_loop(self) -> None:
        resumes = 0
        while not self._stop.is_set():
            conn = None
            try:
                conn = _wire.Conn(
                    self._upstream,
                    connect_timeout=self._cfg.connect_timeout_s,
                    io_timeout=max(self._cfg.heartbeat_s * 20, 10.0),
                )
                # cursor = local head: apply_replicated's dup guard makes
                # any redelivered prefix a no-op (exactly-once)
                since = self._store.head_revision
                paused_skips = False
                for frame in conn.stream({"op": "stream", "since": since}):
                    if self._stop.is_set():
                        return
                    gate_open = self._tail_gate.is_set()
                    if gate_open and paused_skips:
                        # entries were skipped while paused: resubscribe
                        # from the local head so they are redelivered
                        # (dup guard keeps the overlap exactly-once)
                        break
                    head = frame.get("head")
                    if head is not None:
                        self._upstream_head = max(
                            self._upstream_head, int(head)
                        )
                    if frame.get("rev") is not None:
                        if not gate_open:
                            # paused (test lag induction): keep tracking
                            # the upstream head but apply nothing
                            paused_skips = True
                        else:
                            ups = [
                                _wire.update_from_wire(d)
                                for d in frame.get("updates", ())
                            ]
                            faults.fire("replica.apply")
                            local = self._store.head_revision
                            self._store.apply_replicated(
                                int(frame["rev"]), ups
                            )
                            resumes = 0
                            self._m.inc("fleet.applied_entries")
                            if int(frame["rev"]) - local > 1:
                                # a group-committed entry: one frame,
                                # one advance, head jumps base→base+k
                                self._m.inc("fleet.group_applies")
                            self._advance_serving()
                    self._m.set_gauge(
                        f"fleet.catchup_lag.{self.id}", float(self.lag())
                    )
            except BaseException as e:
                if self._stop.is_set():
                    return
                if classify_dispatch_exception(e) is None:
                    # an unclassified tail failure is a real bug: stop
                    # advancing and let health report it (the router
                    # drains a stalled replica via the ready gate)
                    self._tail_err = e
                    return
                resumes += 1
                self._m.inc("fleet.tail_resumes")
            finally:
                if conn is not None:
                    conn.close()
            self._stop.wait(min(0.002 * resumes, 0.1))

    def _advance_serving(self) -> None:
        """Make the just-applied head the generation MIN_LATENCY serves,
        and retire verdict-cache shards for generations the store no
        longer keeps.

        ``apply_replicated`` advances the live table and the head
        revision but materializes nothing, and ``snapshot_for`` under
        MinLatency serves the freshest MATERIALIZED generation — so
        without this step a replica keeps answering from its
        bootstrap-era world (and that world's cached verdicts) no matter
        how many deltas it applies.  Materializing here is the
        watch-driven re-index discipline: a delta advance off the
        previous generation, not a rebuild.  The shard drop mirrors the
        client's snapshot-LRU eviction hook — a verdict-cache revision
        whose store generation is gone can never be pin-validated again,
        it is pure dead weight — and counts each retirement as
        ``fleet.vcache_invalidations``."""
        self._store.snapshot_for(consistency.full())
        vc = self._client._vcache
        if vc is None:
            return
        resident = set(self._store.resident_revisions())
        for rev in vc.resident_revisions:
            if rev not in resident:
                vc.drop_revision(rev)
                self._m.inc("fleet.vcache_invalidations")

    # -- state ------------------------------------------------------------
    @property
    def head(self) -> int:
        return self._store.head_revision

    def lag(self) -> int:
        return max(0, self._upstream_head - self._store.head_revision)

    def ready(self) -> bool:
        return (
            not self._dead
            and self._tail_err is None
            and self.lag() <= self._cfg.ready_lag
        )

    def health(self) -> Dict[str, Any]:
        vc = self._client._vcache
        return {
            "ok": True,
            "replica": self.id,
            "head": self.head,
            "upstream_head": self._upstream_head,
            "lag": self.lag(),
            "ready": self.ready(),
            "dead": self._dead,
            "tail_error": repr(self._tail_err) if self._tail_err else None,
            # residency: materialized store generations + verdict-cache
            # revision shards — what the router's exact-snapshot
            # placement reads
            "resident": self._store.resident_revisions(),
            "cache": None if vc is None else vc.residency(),
            "admission": self._client._admission.report(),
        }

    # -- test hooks -------------------------------------------------------
    def pause_tail(self) -> None:
        """Stop applying streamed entries (lag induction for tests)."""
        self._tail_gate.clear()

    def resume_tail(self) -> None:
        self._tail_gate.set()

    # -- serving ----------------------------------------------------------
    def _handle(self, msg: Dict[str, Any], sock) -> Optional[Dict[str, Any]]:
        try:
            # the kill site fires on ANY op — a dead replica fails health
            # probes and checks alike, which is what drives the router's
            # eviction path in the chaos soak
            faults.fire("replica.kill")
        except BaseException:
            self.die()
            raise _wire.WireClosed("replica killed by fault injection")
        if self._dead:
            raise _wire.WireClosed("replica is dead")
        op = msg.get("op")
        if op == "health":
            return self.health()
        if op == "check":
            if not self.ready():
                raise UnavailableError(
                    f"replica {self.id} catching up (lag={self.lag()})"
                )
            cs = _wire.strategy_from_wire(msg["cs"])
            rels = [_wire.rel_from_wire(d) for d in msg["rels"]]
            ctx = background().with_timeout(
                float(msg.get("deadline_s") or self._cfg.io_timeout_s)
            )
            with self._m.timer("fleet.replica_check_s"):
                verdicts = self._client.check(ctx, cs, *rels)
            return {
                "ok": True,
                "replica": self.id,
                "head": self.head,
                "verdicts": [bool(v) for v in verdicts],
            }
        if op == "kill":
            self.die()
            raise _wire.WireClosed("replica killed")
        raise PermanentError(f"unknown replica op {op!r}")

    # -- lifecycle --------------------------------------------------------
    def die(self) -> None:
        """Crash, not shutdown: stop serving and hard-close every
        connection so peers see resets mid-request."""
        if self._dead:
            return
        self._dead = True
        self._stop.set()
        self._tail_gate.set()
        self._m.inc("fleet.replica_deaths")
        self._server.close(abort=True)
        if self._exit_on_death:
            os._exit(1)

    def close(self) -> None:
        """Graceful teardown (tests, clean process exit)."""
        self._dead = True
        self._stop.set()
        self._tail_gate.set()
        self._server.close(abort=True)
        self._tail_thread.join(2.0)


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(description="gochugaru fleet replica")
    ap.add_argument("--upstream", required=True, help="router HOST:PORT")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--id", default=None)
    ap.add_argument("--ready-lag", type=int, default=None)
    ap.add_argument(
        "--host-only", action="store_true",
        help="host-path evaluation (no device dispatch)",
    )
    ap.add_argument(
        "--latency-mode", action="store_true",
        help="pinned small-batch dispatch path",
    )
    ap.add_argument(
        "--join", action="store_true",
        help="ask the router to admit this replica (its 'join' op) once"
             " serving starts",
    )
    args = ap.parse_args(argv)

    host, _, port = args.upstream.rpartition(":")
    cfg = FleetConfig()
    if args.ready_lag is not None:
        from dataclasses import replace

        cfg = replace(cfg, ready_lag=args.ready_lag)
    opts = [with_verdict_cache()]
    if args.host_only:
        opts.append(with_host_only_evaluation())
    if args.latency_mode:
        opts.append(with_latency_mode())

    from ..utils import decisions as _decisions

    replica_id = args.id or f"replica-{os.getpid()}"
    # satellite: every decision-log entry this process emits carries its
    # replica identity
    _decisions.set_identity(replica_id)
    r = Replica(
        (host, int(port)),
        replica_id=replica_id,
        host=args.host,
        port=args.port,
        config=cfg,
        client_options=tuple(opts),
        exit_on_death=True,
    )
    print(
        "REPLICA-READY "
        + json.dumps({"id": r.id, "host": r.host, "port": r.port}),
        flush=True,
    )
    if args.join:
        jc = _wire.Conn((host, int(port)))
        try:
            jr = jc.request({
                "op": "join", "host": r.host, "port": r.port,
                "wait_ready_s": 60.0,
            })
            print(f"JOINED ring={jr['ring']}", flush=True)
        finally:
            jc.close()
    try:
        while not r._stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        r.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

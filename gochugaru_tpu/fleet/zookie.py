"""Zookies: client-held freshness tokens (Zanzibar §2.4).

A zookie is minted by the front router on every write and handed back to
the client; presenting it on a later Check/Lookup guarantees
read-your-writes — the router routes to any replica whose resident head
has reached the zookie's revision, or blocks (bounded) until one
catches up.  The token is opaque to clients and *authenticated*: an
HMAC over the revision keeps a client from forging "fresher" tokens to
force head reads (the DoS vector Zanzibar's encrypted zookies close).

Format: ``zk1.<revision>.<hex-mac-20>`` — HMAC-SHA256 over the version
tag + revision, truncated to 80 bits.  Tampered, truncated, or garbage
tokens raise ``InvalidZookieError`` (permanent, never retriable: a bad
token cannot become valid by retrying).
"""

from __future__ import annotations

import hashlib
import hmac

from .. import consistency
from ..store.store import RevisionToken, parse_revision
from ..utils.errors import AuthzError
from . import wire as _wire

_PREFIX = "zk1"
_MAC_HEX = 20

#: Dev/test default.  A real deployment passes its own key through
#: ``FleetConfig.zookie_key`` — router and any token-validating front
#: must share it.
DEFAULT_KEY = b"gochugaru-fleet-dev-key"


@_wire.register_error
class InvalidZookieError(AuthzError):
    """A zookie that fails parsing or MAC verification.  Permanent."""


def _mac(revision: int, key: bytes) -> str:
    body = f"{_PREFIX}.{revision}".encode("utf-8")
    return hmac.new(key, body, hashlib.sha256).hexdigest()[:_MAC_HEX]


def mint(revision, key: bytes = DEFAULT_KEY) -> str:
    """Token for a revision (int or ``gtz1.N`` token string)."""
    rev = revision if isinstance(revision, int) else parse_revision(revision)
    return f"{_PREFIX}.{rev}.{_mac(rev, key)}"


def parse(token: str, key: bytes = DEFAULT_KEY) -> int:
    """Verify and return the revision; raises InvalidZookieError on any
    malformed or tampered token."""
    if not isinstance(token, str):
        raise InvalidZookieError(f"zookie must be a string, got {type(token).__name__}")
    parts = token.split(".")
    if len(parts) != 3 or parts[0] != _PREFIX:
        raise InvalidZookieError(f"malformed zookie: {token!r}")
    try:
        rev = int(parts[1])
    except ValueError:
        raise InvalidZookieError(f"malformed zookie revision: {token!r}") from None
    if rev < 0:
        raise InvalidZookieError(f"malformed zookie revision: {token!r}")
    if not hmac.compare_digest(parts[2], _mac(rev, key)):
        raise InvalidZookieError("zookie failed verification (tampered or wrong key)")
    return rev


def revision_token(token: str, key: bytes = DEFAULT_KEY) -> str:
    """The store revision token (``gtz1.N``) a zookie names."""
    return RevisionToken(parse(token, key))


def strategy(token: str, key: bytes = DEFAULT_KEY) -> consistency.Strategy:
    """The consistency strategy a bare zookie implies: at-least-as-fresh
    as the write that minted it — read-your-writes for single-store
    clients (the router composes zookies with the caller's strategy
    itself; this is the convenience for direct ``Client`` use)."""
    return consistency.at_least(revision_token(token, key))

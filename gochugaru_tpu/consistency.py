"""Consistency strategies (reference: ``consistency/consistency.go``).

A ``Strategy`` selects which materialized graph snapshot a read/check
evaluates against — the PACELC speed-vs-freshness trade-off the reference
documents (consistency/consistency.go:10-17).  Revisions are ZedToken-style
opaque strings minted by writes; here a revision names a materialized
snapshot generation of the tuple store (SURVEY.md §5 "Checkpoint / resume").

- ``full()``        — evaluate at the latest revision, materializing any
                      pending writes first (consistency/consistency.go:29-35).
- ``min_latency()`` — evaluate at the store's preferred (already
                      materialized) revision; the default and fastest
                      (consistency/consistency.go:42-48).
- ``at_least(rev)`` — at least as fresh as ``rev``; read-after-write
                      (consistency/consistency.go:54-62).
- ``snapshot(rev)`` — exactly ``rev`` (consistency/consistency.go:69-77).

The strategy is also the **verdict cache's read policy**
(engine/vcache.policy_for): a check made with ``snapshot``/``at_least``
reads and populates the cache shard of the exact revision the store
resolved, ``min_latency`` hits the freshest resident revision's shard,
and ``full`` bypasses the cache entirely — cached verdicts are always
revision-exact, so no strategy can ever observe a verdict from a
revision it would not have evaluated at.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

from .utils.context import Context

#: Context key carrying the overlap key (requestmeta.RequestOverlapKey
#: analogue, consistency/consistency.go:21-23).
OVERLAP_KEY = "io.gochugaru-tpu.overlap-key"


class Requirement(enum.Enum):
    FULL = "fully_consistent"
    MIN_LATENCY = "minimize_latency"
    AT_LEAST = "at_least_as_fresh"
    SNAPSHOT = "at_exact_snapshot"


@dataclass(frozen=True)
class Strategy:
    """The strategy a request uses to trade off freshness with latency
    (consistency/consistency.go:15-17)."""

    requirement: Requirement
    revision: Optional[str] = None


def with_overlap_key(ctx: Context, key: str) -> Context:
    """Attach the hotspot-mitigation overlap key to a context; subsequent
    requests made with the returned context carry it
    (consistency/consistency.go:21-23)."""
    return ctx.with_value(OVERLAP_KEY, key)


def full() -> Strategy:
    """Evaluate at the most recent revision; least performant, guarantees
    read consistency (consistency/consistency.go:29-35)."""
    return Strategy(Requirement.FULL)


def min_latency() -> Strategy:
    """Evaluate at the store's preferred revision; optimal performance and
    the default (consistency/consistency.go:42-48)."""
    return Strategy(Requirement.MIN_LATENCY)


def at_least(revision: str) -> Strategy:
    """Evaluate at the provided revision or newer — avoids read-after-write
    inconsistencies (consistency/consistency.go:54-62)."""
    return Strategy(Requirement.AT_LEAST, revision)


def snapshot(revision: str) -> Strategy:
    """Evaluate at exactly the provided revision
    (consistency/consistency.go:69-77)."""
    return Strategy(Requirement.SNAPSHOT, revision)


def policy_for(strategy: Strategy) -> tuple:
    """Map a strategy onto the fleet *placement* policy (SURVEY §L2b):
    once revisions live on different replica processes, the consistency
    strategy decides which replicas are eligible to serve the read.

    Returns ``(mode, revision)`` where ``revision`` is the strategy's
    revision token (or None) and ``mode`` is one of:

    - ``"head"``     — FULL: only a replica at the authoritative head at
                       dispatch time is fresh enough;
    - ``"any"``      — MIN_LATENCY: any ring member serves (fastest);
    - ``"at_least"`` — AT_LEAST: any replica whose resident head has
                       reached ``revision`` (read-your-writes; zookies
                       raise the floor the same way);
    - ``"exact"``    — SNAPSHOT: the replica must hold exactly
                       ``revision`` (forwarded unchanged — the store's
                       own RevisionUnavailableError semantics apply).
    """
    req = strategy.requirement
    if req == Requirement.FULL:
        return "head", None
    if req == Requirement.MIN_LATENCY:
        return "any", None
    if req == Requirement.AT_LEAST:
        return "at_least", strategy.revision
    if req == Requirement.SNAPSHOT:
        return "exact", strategy.revision
    raise ValueError(f"unknown consistency requirement {req}")


# Go-parity aliases.
Full = full
MinLatency = min_latency
AtLeast = at_least
Snapshot = snapshot
WithOverlapKey = with_overlap_key

"""Filters for matching relationships (reference: ``rel/filter.go``).

The reference wraps ``*v1.RelationshipFilter`` protos; here a filter is a
plain dataclass the store matches against directly.  Empty string means
"match anything" for every field except ``resource_type``, which is required
(rel/filter.go:12-15).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import List, Optional

from .relationship import Relationship


@dataclass
class SubjectFilter:
    subject_type: str = ""
    optional_subject_id: str = ""
    #: None = any subject relation; "" = must have NO subject relation;
    #: non-empty = must equal.  Mirrors v1.SubjectFilter.RelationFilter
    #: semantics (rel/filter.go:27-37).
    optional_relation: Optional[str] = None


@dataclass
class Filter:
    """A filter matched against the Resource (and optionally Subject) of
    relationships (rel/filter.go:6-23)."""

    resource_type: str = ""
    optional_resource_id: str = ""
    optional_relation: str = ""
    optional_subject_filter: Optional[SubjectFilter] = None

    def with_subject_filter(
        self, subject_type: str, optional_id: str = "", optional_relation: str = ""
    ) -> "Filter":
        """Also match against the Subject (rel/filter.go:27-37).  As in the
        reference, an empty ``optional_relation`` here means "any relation"
        (the RelationFilter is only attached when non-empty)."""
        self.optional_subject_filter = SubjectFilter(
            subject_type=subject_type,
            optional_subject_id=optional_id,
            optional_relation=optional_relation if optional_relation != "" else None,
        )
        return self

    def matches(self, r: Relationship) -> bool:
        if self.resource_type != "" and r.resource_type != self.resource_type:
            return False
        if self.optional_resource_id != "" and r.resource_id != self.optional_resource_id:
            return False
        if self.optional_relation != "" and r.resource_relation != self.optional_relation:
            return False
        sf = self.optional_subject_filter
        if sf is not None:
            if sf.subject_type != "" and r.subject_type != sf.subject_type:
                return False
            if sf.optional_subject_id != "" and r.subject_id != sf.optional_subject_id:
                return False
            if sf.optional_relation is not None and r.subject_relation != sf.optional_relation:
                return False
        return True


def new_filter(resource_type: str, optional_id: str = "", optional_relation: str = "") -> Filter:
    """Create a Filter; a resource type is required, empty string foregoes
    filtering on the resource id / relation (rel/filter.go:15-23)."""
    return Filter(
        resource_type=resource_type,
        optional_resource_id=optional_id,
        optional_relation=optional_relation,
    )


@dataclass
class Precondition:
    must_match: bool = True
    filter: Filter = dc_field(default_factory=Filter)


@dataclass
class PreconditionedFilter:
    """A filter plus preconditions gating another action
    (rel/filter.go:41-70)."""

    filter: Filter = dc_field(default_factory=Filter)
    preconditions: List[Precondition] = dc_field(default_factory=list)

    def must_match(self, f: Filter) -> "PreconditionedFilter":
        self.preconditions.append(Precondition(must_match=True, filter=f))
        return self

    def must_not_match(self, f: Filter) -> "PreconditionedFilter":
        self.preconditions.append(Precondition(must_match=False, filter=f))
        return self


def new_preconditioned_filter(f: Filter) -> PreconditionedFilter:
    return PreconditionedFilter(filter=f)

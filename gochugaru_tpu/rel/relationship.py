"""Relationship: the flattened 9-field tuple at the heart of the data model.

Reference: ``rel/relationship.go:28-38`` (struct), ``:51-90`` (canonical
string format), ``:93-120`` (copy-with builders), ``:220-265`` (parsers with
sentinel errors).  The reference keeps ``Relationship`` as a flattened native
struct with lazy proto lowering; here the analogous lazy lowering is string →
interned int32 columns, owned by ``store.Interner`` — this type stays pure
Python and hashable so user code can put relationships in sets/dicts.
"""

from __future__ import annotations

import datetime as _dt
import json
from dataclasses import dataclass, field, replace
from typing import Any, Iterable, Mapping, Optional

#: The "ellipsis" subject relation — a subject with no relation (direct).
ELLIPSIS = ""

#: The wildcard object id (``user:*`` grants every subject of the type).
WILDCARD_ID = "*"


class InvalidResourceError(ValueError):
    """Catch-all error when a resource is invalid (rel/relationship.go:17)."""


class InvalidRelationError(ValueError):
    """Catch-all error when a relation is invalid (rel/relationship.go:20)."""


class InvalidSubjectError(ValueError):
    """Catch-all error when a subject is invalid (rel/relationship.go:23)."""


def _canonical_caveat_json(context: Mapping[str, Any]) -> str:
    """Serialize caveat context the way protobuf Struct JSON does: compact
    separators, map keys sorted, integral floats printed as integers
    (rel/relationship.go:66-83)."""

    def norm(v: Any) -> Any:
        if isinstance(v, bool) or v is None or isinstance(v, str):
            return v
        if isinstance(v, float) and v.is_integer():
            return int(v)
        if isinstance(v, (int, float)):
            return v
        if isinstance(v, Mapping):
            return {str(k): norm(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [norm(x) for x in v]
        raise TypeError(f"caveat context value not representable: {v!r}")

    return json.dumps(norm(dict(context)), separators=(",", ":"), sort_keys=True)


def expiration_micros(t: Optional[_dt.datetime]) -> int:
    """Expiration as epoch microseconds; 0 = none.  Naive datetimes are
    interpreted as UTC — the single definition every evaluator and the
    store share, so liveness never diverges between paths."""
    if t is None:
        return 0
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return int(t.timestamp() * 1_000_000)


def format_rfc3339_nano(t: _dt.datetime) -> str:
    """Format a datetime like Go's ``time.RFC3339Nano``: fractional seconds
    with trailing zeros (and a bare dot) trimmed, ``Z`` for UTC
    (rel/relationship.go:13,84-88)."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    base = t.strftime("%Y-%m-%dT%H:%M:%S")
    frac = f"{t.microsecond:06d}".rstrip("0")
    if frac:
        base += "." + frac
    off = t.utcoffset() or _dt.timedelta(0)
    if off == _dt.timedelta(0):
        return base + "Z"
    total = int(off.total_seconds())
    sign = "+" if total >= 0 else "-"
    total = abs(total)
    return f"{base}{sign}{total // 3600:02d}:{(total % 3600) // 60:02d}"


@dataclass(frozen=True, eq=False)
class Relationship:
    """A relationship tuple ``resource#relation@subject`` with optional
    caveat and expiration (rel/relationship.go:28-38).

    Any object exposing a ``relationship() -> Relationship`` method is
    accepted wherever a relationship is expected — the structural analogue of
    the reference's ``rel.Interface`` (rel/relationship.go:26,40).
    """

    resource_type: str = ""
    resource_id: str = ""
    resource_relation: str = ""
    subject_type: str = ""
    subject_id: str = ""
    subject_relation: str = ""
    caveat_name: str = ""
    caveat_context: Mapping[str, Any] = field(default_factory=dict)
    expiration: Optional[_dt.datetime] = None

    def __post_init__(self) -> None:
        # Defensive copy: the value is frozen and hashable, so it must not
        # alias a caller-owned dict that could mutate under it.
        object.__setattr__(self, "caveat_context", dict(self.caveat_context))

    # -- rel.Interface ----------------------------------------------------
    def relationship(self) -> "Relationship":
        return self

    # -- accessors (rel/relationship.go:41-49) ----------------------------
    @property
    def permission(self) -> str:
        return self.resource_relation

    def has_caveat(self) -> bool:
        return self.caveat_name != ""

    def has_expiration(self) -> bool:
        # nil and the zero time both mean "no expiration"
        # (rel/relationship.go:43-45; zero-time case tested in
        # rel/relationship_test.go:69-74).
        return self.expiration is not None and self.expiration != _dt.datetime(
            1, 1, 1, tzinfo=self.expiration.tzinfo
        )

    def caveat(self) -> tuple[str, Mapping[str, Any], bool]:
        return self.caveat_name, self.caveat_context, self.has_caveat()

    # -- canonical tuple format (rel/relationship.go:51-90) ----------------
    def __str__(self) -> str:
        parts = [
            self.resource_type,
            ":",
            self.resource_id,
            "#",
            self.resource_relation,
            "@",
            self.subject_type,
            ":",
            self.subject_id,
        ]
        if self.subject_relation != "":
            parts += ["#", self.subject_relation]
        if self.has_caveat():
            parts += ["[", self.caveat_name]
            if self.caveat_context:
                parts += [":", _canonical_caveat_json(self.caveat_context)]
            parts.append("]")
        if self.has_expiration():
            parts += ["[expiration:", format_rfc3339_nano(self.expiration), "]"]
        return "".join(parts)

    # -- copy-with builders (rel/relationship.go:93-120) -------------------
    def with_caveat(self, name: str, context: Mapping[str, Any]) -> "Relationship":
        return replace(self, caveat_name=name, caveat_context=dict(context))

    def with_expiration(self, expiration: _dt.datetime) -> "Relationship":
        return replace(self, expiration=expiration)

    # -- filter conversion (rel/relationship.go:122-126) -------------------
    def filter(self) -> "Filter":
        from .filter import new_filter

        f = new_filter(self.resource_type, self.resource_id, self.resource_relation)
        f.with_subject_filter(self.subject_type, self.subject_id, self.subject_relation)
        return f

    # -- equality/hashing: caveat context is a dict, so both use the same
    # canonical JSON form (keeps the hash/eq contract exact even for values
    # Python considers equal but JSON distinguishes, like 1 vs True) --------
    def _identity(self) -> tuple:
        return (
            self.resource_type, self.resource_id, self.resource_relation,
            self.subject_type, self.subject_id, self.subject_relation,
            self.caveat_name,
            _canonical_caveat_json(self.caveat_context) if self.caveat_context else "",
            self.expiration,
        )

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Relationship):
            return NotImplemented
        return self._identity() == other._identity()

    def __hash__(self) -> int:
        return hash(self._identity())

    def key(self) -> tuple[str, str, str, str, str, str]:
        """The identity key of a relationship: everything except caveat and
        expiration.  Two writes to the same key TOUCH/replace one another,
        matching SpiceDB tuple-uniqueness semantics."""
        return (
            self.resource_type, self.resource_id, self.resource_relation,
            self.subject_type, self.subject_id, self.subject_relation,
        )


#: Anything usable as a relationship: a Relationship or an object with a
#: ``relationship()`` method (rel.Interface, rel/relationship.go:26).
RelationshipLike = Any


def decoded_relationship(
    resource_type: str,
    resource_id: str,
    resource_relation: str,
    subject_type: str,
    subject_id: str,
    subject_relation: str,
    caveat_name: str,
    caveat_context: Mapping[str, Any],
    expiration: Optional[_dt.datetime],
) -> Relationship:
    """Bulk-decode fast constructor: bypasses the frozen-dataclass
    ``__init__`` (nine ``object.__setattr__`` calls, the measured ~220k
    objects/s ceiling of the export path) by populating ``__dict__``
    directly.  Semantics match ``Relationship(...)`` exactly, including
    the defensive caveat-context copy — fields arrive pre-validated from
    the snapshot's interned columns, so no parsing re-runs."""
    r = _obj_new(Relationship)
    _obj_setattr(r, "__dict__", {
        "resource_type": resource_type,
        "resource_id": resource_id,
        "resource_relation": resource_relation,
        "subject_type": subject_type,
        "subject_id": subject_id,
        "subject_relation": subject_relation,
        "caveat_name": caveat_name,
        "caveat_context": dict(caveat_context) if caveat_context else {},
        "expiration": expiration,
    })
    return r


#: bound once: the per-row constructor above runs millions of times per
#: export, and global lookups of object.__new__/__setattr__ are ~8% of it
_obj_new = object.__new__
_obj_setattr = object.__setattr__


def as_relationship(r: RelationshipLike) -> Relationship:
    if isinstance(r, Relationship):
        return r
    meth = getattr(r, "relationship", None)
    if callable(meth):
        got = meth()
        if isinstance(got, Relationship):
            return got
    raise TypeError(f"not a relationship or rel.Interface: {r!r}")


@dataclass(frozen=True)
class Object:
    """A typed object reference, optionally with a relation
    (rel/relationship.go:198-206)."""

    typ: str = ""
    id: str = ""
    relation: str = ""

    def object(self) -> "Object":
        return self


def _as_object(o: Any) -> Object:
    if isinstance(o, Object):
        return o
    meth = getattr(o, "object", None)
    if callable(meth):
        got = meth()
        if isinstance(got, Object):
            return got
    raise TypeError(f"not an Object or rel.Objecter: {o!r}")


def from_objects(resource: Any, subject: Any) -> Relationship:
    """Build a relationship from two object references
    (rel/relationship.go:208-218)."""
    r, s = _as_object(resource), _as_object(subject)
    return Relationship(
        resource_type=r.typ, resource_id=r.id, resource_relation=r.relation,
        subject_type=s.typ, subject_id=s.id, subject_relation=s.relation,
    )


def from_triple(resource: str, relation: str, subject: str) -> Relationship:
    """Parse ``("document:example", "viewer", "user:jzelinskie")``
    (rel/relationship.go:228-230)."""
    return from_tuple(resource + "#" + relation, subject)


def must_from_triple(resource: str, relation: str, subject: str) -> Relationship:
    return from_triple(resource, relation, subject)


def from_tuple(resource: str, subject: str) -> Relationship:
    """Parse ``("document:example#viewer", "user:jzelinskie[#rel]")`` with the
    reference's exact error taxonomy (rel/relationship.go:240-265): missing
    ``#relation`` → InvalidRelationError; missing resource ``type:id`` →
    InvalidResourceError; missing subject ``type:id`` → InvalidSubjectError.
    The subject relation is optional."""
    resource, sep, resource_relation = resource.partition("#")
    if sep == "" or resource_relation == "":
        raise InvalidRelationError("invalid relation")
    resource_type, sep, resource_id = resource.partition(":")
    if sep == "":
        raise InvalidResourceError("invalid resource")

    subject, _, subject_relation = subject.partition("#")
    subject_type, sep, subject_id = subject.partition(":")
    if sep == "":
        raise InvalidSubjectError("invalid subject")

    return Relationship(
        resource_type=resource_type,
        resource_id=resource_id,
        resource_relation=resource_relation,
        subject_type=subject_type,
        subject_id=subject_id,
        subject_relation=subject_relation,
    )


def must_from_tuple(resource: str, subject: str) -> Relationship:
    return from_tuple(resource, subject)


def as_relationships(rs: Iterable[RelationshipLike]) -> list[Relationship]:
    return [as_relationship(r) for r in rs]

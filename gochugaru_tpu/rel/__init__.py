"""The relationship data model (reference: ``rel/`` package).

Everything the client surface round-trips through: ``Relationship`` and its
constructors/parsers, ``Filter``/``PreconditionedFilter``, the ``Txn``
write-transaction builder, watch ``Update`` types, and the object-set /
typed-relation string parsers.
"""

from .relationship import (
    ELLIPSIS,
    WILDCARD_ID,
    InvalidRelationError,
    InvalidResourceError,
    InvalidSubjectError,
    Object,
    Relationship,
    from_objects,
    from_triple,
    from_tuple,
    must_from_triple,
    must_from_tuple,
)
from .filter import Filter, PreconditionedFilter, new_filter, new_preconditioned_filter
from .txn import Txn
from .update import (
    Update,
    UpdateFilter,
    UpdateType,
)
from .strings import (
    InvalidObjectStringError,
    InvalidTypedRelationStringError,
    parse_object_set,
    parse_typed_relation,
)

# Go-parity aliases (reference rel/relationship.go, rel/strings.go) so a
# gochugaru user finds the names they know.
FromTriple = from_triple
FromTuple = from_tuple
FromObjects = from_objects
MustFromTriple = must_from_triple
MustFromTuple = must_from_tuple
NewFilter = new_filter
NewPreconditionedFilter = new_preconditioned_filter
ParseObjectSet = parse_object_set
ParseTypedRelation = parse_typed_relation

ErrInvalidResource = InvalidResourceError
ErrInvalidRelation = InvalidRelationError
ErrInvalidSubject = InvalidSubjectError
ErrInvalidObjectString = InvalidObjectStringError
ErrInvalidTypedRelationString = InvalidTypedRelationStringError

__all__ = [
    "ELLIPSIS",
    "WILDCARD_ID",
    "Relationship",
    "Object",
    "Filter",
    "PreconditionedFilter",
    "Txn",
    "Update",
    "UpdateFilter",
    "UpdateType",
    "from_triple",
    "from_tuple",
    "from_objects",
    "must_from_triple",
    "must_from_tuple",
    "new_filter",
    "new_preconditioned_filter",
    "parse_object_set",
    "parse_typed_relation",
    "InvalidResourceError",
    "InvalidRelationError",
    "InvalidSubjectError",
    "InvalidObjectStringError",
    "InvalidTypedRelationStringError",
]

"""Write-transaction builder (reference: ``rel/txn.go``).

A ``Txn`` accumulates updates (CREATE / TOUCH / DELETE) and preconditions;
the zero value is usable, exactly like the reference's plain struct
(rel/txn.go:8-11).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from .filter import Filter, Precondition
from .relationship import Relationship, RelationshipLike, as_relationship
from .update import Update, UpdateType


@dataclass
class Txn:
    """An atomic modification with optional preconditions (rel/txn.go:7-11)."""

    updates: List[Update] = field(default_factory=list)
    preconditions: List[Precondition] = field(default_factory=list)

    def must_match(self, f: Filter) -> "Txn":
        """Only apply if the filter matches something (rel/txn.go:15-20)."""
        self.preconditions.append(Precondition(must_match=True, filter=f))
        return self

    def must_not_match(self, f: Filter) -> "Txn":
        """Only apply if the filter matches nothing (rel/txn.go:24-29)."""
        self.preconditions.append(Precondition(must_match=False, filter=f))
        return self

    def touch(self, r: RelationshipLike) -> "Txn":
        """Idempotently create or update a relationship (rel/txn.go:34-39)."""
        self.updates.append(Update(UpdateType.TOUCH, as_relationship(r)))
        return self

    def create(self, r: RelationshipLike) -> "Txn":
        """Insert a new relationship; the write fails if it already exists
        (rel/txn.go:43-48)."""
        self.updates.append(Update(UpdateType.CREATE, as_relationship(r)))
        return self

    def delete(self, r: RelationshipLike) -> "Txn":
        """Remove a relationship (rel/txn.go:51-56)."""
        self.updates.append(Update(UpdateType.DELETE, as_relationship(r)))
        return self

"""Watch-event types (reference: ``rel/relationship.go:267-306``)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List

from .filter import Filter
from .relationship import Relationship


class UpdateType(enum.IntEnum):
    """Mirrors the reference enum (rel/relationship.go:267-274)."""

    UNKNOWN = 0
    CREATE = 1
    DELETE = 2
    TOUCH = 3


@dataclass(frozen=True)
class Update:
    """A single watch event: an operation applied to a relationship
    (rel/relationship.go:291-294)."""

    update_type: UpdateType
    relationship: Relationship


@dataclass
class UpdateFilter:
    """Filters a watch stream by object types and/or relationship filters
    (rel/relationship.go:303-306)."""

    object_types: List[str] = field(default_factory=list)
    relationship_filters: List[Filter] = field(default_factory=list)

    def admits(self, u: Update) -> bool:
        # SpiceDB's WatchRequest treats these fields as mutually exclusive;
        # specifying both is rejected at subscribe time (see Client.updates),
        # so here whichever is set decides.
        if self.object_types:
            return u.relationship.resource_type in self.object_types
        if self.relationship_filters:
            return any(f.matches(u.relationship) for f in self.relationship_filters)
        return True

"""Object-set and typed-relation string parsers (reference: ``rel/strings.go``)."""

from __future__ import annotations


class InvalidObjectStringError(ValueError):
    """rel/strings.go:9"""

    def __init__(self) -> None:
        super().__init__(
            "invalid object string: must be in form `objectType:objectID#optionalRelation`"
        )


class InvalidTypedRelationStringError(ValueError):
    """rel/strings.go:10"""

    def __init__(self) -> None:
        super().__init__(
            "invalid typed permission string: must be in form `objectType#relation`"
        )


def parse_object_set(obj: str) -> tuple[str, str, str]:
    """``"document:README#reader"`` → ``("document", "README", "reader")``;
    the relation is optional (rel/strings.go:19-28)."""
    object_type, sep, object_id = obj.partition(":")
    if sep == "":
        raise InvalidObjectStringError()
    object_id, _, relation = object_id.partition("#")
    return object_type, object_id, relation


def parse_typed_relation(perm: str) -> tuple[str, str]:
    """``"document#reader"`` → ``("document", "reader")``
    (rel/strings.go:31-38)."""
    object_type, sep, relation = perm.partition("#")
    if sep == "":
        raise InvalidTypedRelationStringError()
    return object_type, relation

"""Schema AST → numeric IR.

The compiler assigns every distinct relation/permission *name* a global
integer slot (shared across types — programs are keyed by (type, slot), so
name collisions across types are fine and tuples can store just the slot id
for their relation column).  It validates cross-references, classifies
tupleset (arrow-LHS) relations, and bounds evaluation depth — the host-side
cycle analysis SURVEY.md §7 calls out as a hard part (hop caps must be
provably sufficient for non-recursive schemas).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..rel.relationship import Relationship, WILDCARD_ID
from .ast import (
    Arrow,
    Definition,
    Exclusion,
    Expr,
    Intersection,
    Nil,
    Permission,
    Relation,
    RelationRef,
    Schema,
    Union,
)


class SchemaValidationError(ValueError):
    pass


@dataclass(frozen=True)
class CompiledAllowed:
    """Numeric form of an AllowedSubject."""

    type_id: int
    relation_slot: int  # -1 = direct object subject
    wildcard: bool
    caveat_id: int  # 0 = none
    expiration: bool


@dataclass
class CompiledRelation:
    slot: int
    allowed: List[CompiledAllowed]


@dataclass
class CompiledPermission:
    slot: int
    expr: Expr  # AST expr; names resolved/validated, slots via slot_of_name


@dataclass
class CompiledType:
    type_id: int
    name: str
    relations: Dict[int, CompiledRelation] = field(default_factory=dict)  # slot →
    permissions: Dict[int, CompiledPermission] = field(default_factory=dict)  # slot →
    #: slots of relations on THIS type used as arrow LHS somewhere on this type
    tupleset_slots: FrozenSet[int] = frozenset()


@dataclass
class CompiledSchema:
    schema: Schema
    type_ids: Dict[str, int]
    slot_of_name: Dict[str, int]
    caveat_ids: Dict[str, int]  # 1-based; 0 = no caveat
    types: Dict[int, CompiledType]
    num_slots: int
    #: all (type_id, slot) pairs where slot is an arrow-LHS relation —
    #: the edges the Phase-B subgraph BFS must traverse
    tupleset_pairs: FrozenSet[Tuple[int, int]]
    #: union of tupleset relation slots across types (device-side filter)
    tupleset_slots: FrozenSet[int]
    #: longest acyclic dependency chain through the rewrite system
    depth: int
    #: True if the dependency graph has a cycle (nested recursive groups,
    #: recursive folder hierarchies, ...) — evaluation needs iteration caps
    is_recursive: bool
    #: True if any relation admits a userset subject whose relation is a
    #: permission — the device closure phase cannot expand those; the client
    #: routes affected checks to the host oracle
    has_permission_usersets: bool = False
    #: acyclic dependency depth per (type_name, item_name) — cycle members
    #: get their acyclic-part depth; used to topologically order permission
    #: updates in the device fixpoint so each iteration propagates a full
    #: dependency level
    item_depths: Dict[Tuple[str, str], int] = field(default_factory=dict)

    # -- name helpers ------------------------------------------------------
    @property
    def name_of_slot(self) -> Dict[int, str]:
        """slot → name inverse of ``slot_of_name`` (well-defined: slots
        are per-name), cached — the single shared inversion for decode
        paths and the fold."""
        cache = getattr(self, "_name_of_slot", None)
        if cache is None:
            cache = {v: k for k, v in self.slot_of_name.items()}
            self._name_of_slot = cache
        return cache

    def slot(self, name: str) -> int:
        s = self.slot_of_name.get(name)
        if s is None:
            raise SchemaValidationError(f"unknown relation/permission {name!r}")
        return s

    def type_id(self, name: str) -> int:
        t = self.type_ids.get(name)
        if t is None:
            raise SchemaValidationError(f"unknown object type {name!r}")
        return t

    def item_kind(self, type_name: str, item_name: str) -> str:
        """'relation' | 'permission' | 'absent' for a (type, name) pair."""
        d = self.schema.definitions.get(type_name)
        if d is None:
            return "absent"
        if item_name in d.relations:
            return "relation"
        if item_name in d.permissions:
            return "permission"
        return "absent"

    # -- write-path validation --------------------------------------------
    def validate_relationship(self, r: Relationship) -> None:
        """Validate a relationship against the schema the way SpiceDB
        validates writes: the resource type must be defined, the resource
        relation must be a plain relation (not a permission), and the
        subject must match one of the relation's allowed subject types
        (including wildcard/userset/caveat forms)."""
        d = self.schema.definitions.get(r.resource_type)
        if d is None:
            raise SchemaValidationError(f"object definition `{r.resource_type}` not found")
        if r.resource_relation in d.permissions:
            raise SchemaValidationError(
                f"cannot write to permission `{r.resource_type}#{r.resource_relation}`;"
                " writes must target relations"
            )
        relation = d.relations.get(r.resource_relation)
        if relation is None:
            raise SchemaValidationError(
                f"relation `{r.resource_relation}` not found on `{r.resource_type}`"
            )
        if r.subject_type not in self.schema.definitions:
            raise SchemaValidationError(f"object definition `{r.subject_type}` not found")
        wildcard = r.subject_id == WILDCARD_ID
        matches = relation.allows_all(r.subject_type, r.subject_relation, wildcard)
        if not matches:
            raise SchemaValidationError(
                f"subject `{r.subject_type}"
                + (":*" if wildcard else (f"#{r.subject_relation}" if r.subject_relation else ""))
                + f"` is not allowed on relation `{r.resource_type}#{r.resource_relation}`"
            )
        if r.subject_relation and self.item_kind(r.subject_type, r.subject_relation) == "absent":
            raise SchemaValidationError(
                f"relation `{r.subject_relation}` not found on `{r.subject_type}`"
            )
        if r.caveat_name and r.caveat_name not in self.schema.caveats:
            raise SchemaValidationError(f"caveat `{r.caveat_name}` not found")
        # Multiple alternatives may differ only in caveat/expiration traits
        # (``user | user with office_hours``); the relationship must satisfy
        # at least one alternative exactly.
        if not any(
            a.caveat == r.caveat_name and (not a.expiration or r.has_expiration())
            for a in matches
        ):
            if r.caveat_name:
                raise SchemaValidationError(
                    f"caveat `{r.caveat_name}` is not allowed for this subject on"
                    f" relation `{r.resource_type}#{r.resource_relation}`"
                )
            wants_caveats = sorted({a.caveat for a in matches if a.caveat})
            if wants_caveats:
                raise SchemaValidationError(
                    f"relation `{r.resource_type}#{r.resource_relation}` requires"
                    f" caveat `{wants_caveats[0]}` for this subject"
                )
            raise SchemaValidationError(
                f"relation `{r.resource_type}#{r.resource_relation}` requires an"
                " expiration for this subject"
            )


def _expr_refs(e: Expr) -> List[Expr]:
    if isinstance(e, (RelationRef, Arrow, Nil)):
        return [e]
    if isinstance(e, (Union, Intersection)):
        out: List[Expr] = []
        for c in e.children:
            out.extend(_expr_refs(c))
        return out
    if isinstance(e, Exclusion):
        return _expr_refs(e.base) + _expr_refs(e.subtracted)
    raise SchemaValidationError(f"unknown expression node {e!r}")


def compile_schema(schema: Schema) -> CompiledSchema:
    # Stable, deterministic numbering: sorted names.
    type_names = sorted(schema.definitions)
    type_ids = {n: i for i, n in enumerate(type_names)}

    names: Set[str] = set()
    for d in schema.definitions.values():
        names.update(d.relations)
        names.update(d.permissions)
    slot_of_name = {n: i for i, n in enumerate(sorted(names))}
    caveat_ids = {n: i + 1 for i, n in enumerate(sorted(schema.caveats))}

    has_permission_usersets = False

    # -- validate + lower each type ---------------------------------------
    types: Dict[int, CompiledType] = {}
    tupleset_pairs: Set[Tuple[int, int]] = set()
    for tname, d in schema.definitions.items():
        tid = type_ids[tname]
        ct = CompiledType(type_id=tid, name=tname)

        for rname, relation in d.relations.items():
            compiled_allowed = []
            for a in relation.allowed:
                if a.type not in schema.definitions:
                    raise SchemaValidationError(
                        f"relation `{tname}#{rname}`: unknown subject type `{a.type}`"
                    )
                rel_slot = -1
                if a.relation:
                    kind = None
                    sub_def = schema.definitions[a.type]
                    if a.relation in sub_def.relations:
                        kind = "relation"
                    elif a.relation in sub_def.permissions:
                        kind = "permission"
                        has_permission_usersets = True
                    if kind is None:
                        raise SchemaValidationError(
                            f"relation `{tname}#{rname}`: subject `{a.type}#{a.relation}`"
                            " references an unknown relation"
                        )
                    rel_slot = slot_of_name[a.relation]
                if a.caveat and a.caveat not in schema.caveats:
                    raise SchemaValidationError(
                        f"relation `{tname}#{rname}`: unknown caveat `{a.caveat}`"
                    )
                compiled_allowed.append(
                    CompiledAllowed(
                        type_id=type_ids[a.type],
                        relation_slot=rel_slot,
                        wildcard=a.wildcard,
                        caveat_id=caveat_ids.get(a.caveat, 0),
                        expiration=a.expiration,
                    )
                )
            ct.relations[slot_of_name[rname]] = CompiledRelation(
                slot=slot_of_name[rname], allowed=compiled_allowed
            )

        for pname, perm in d.permissions.items():
            for ref in _expr_refs(perm.expr):
                if isinstance(ref, RelationRef):
                    if d.item(ref.name) is None:
                        raise SchemaValidationError(
                            f"permission `{tname}#{pname}` references unknown item"
                            f" `{ref.name}`"
                        )
                elif isinstance(ref, Arrow):
                    lhs = d.relations.get(ref.left)
                    if lhs is None:
                        if ref.left in d.permissions:
                            raise SchemaValidationError(
                                f"permission `{tname}#{pname}`: arrow LHS `{ref.left}`"
                                " must be a relation, not a permission"
                            )
                        raise SchemaValidationError(
                            f"permission `{tname}#{pname}`: arrow LHS `{ref.left}`"
                            " is not a relation on this type"
                        )
                    # RHS must exist on at least one possible target type;
                    # types where it's absent simply contribute nothing.
                    target_types = {a.type for a in lhs.allowed if not a.wildcard}
                    if not any(
                        schema.definitions[t2].item(ref.right) is not None
                        for t2 in target_types
                    ):
                        raise SchemaValidationError(
                            f"permission `{tname}#{pname}`: arrow target `{ref.right}`"
                            f" not found on any subject type of `{ref.left}`"
                        )
                    tupleset_pairs.add((tid, slot_of_name[ref.left]))
            ct.permissions[slot_of_name[pname]] = CompiledPermission(
                slot=slot_of_name[pname], expr=perm.expr
            )

        types[tid] = ct

    for tid, ct in types.items():
        ct.tupleset_slots = frozenset(s for (t, s) in tupleset_pairs if t == tid)

    # -- dependency-depth analysis ----------------------------------------
    # Node = (type_name, item_name).  Edges follow evaluation: permissions
    # depend on referenced items; arrows depend on (target_type, rhs) and on
    # their LHS relation; relations depend on the userset items of their
    # allowed subjects.
    depth_memo: Dict[Tuple[str, str], int] = {}
    in_stack: Set[Tuple[str, str]] = set()
    recursive = False

    def deps(node: Tuple[str, str]) -> List[Tuple[str, str]]:
        tname, iname = node
        d = schema.definitions[tname]
        out: List[Tuple[str, str]] = []
        if iname in d.permissions:
            for ref in _expr_refs(d.permissions[iname].expr):
                if isinstance(ref, RelationRef):
                    out.append((tname, ref.name))
                elif isinstance(ref, Arrow):
                    out.append((tname, ref.left))
                    for a in d.relations[ref.left].allowed:
                        if not a.wildcard and schema.definitions[a.type].item(ref.right):
                            out.append((a.type, ref.right))
        elif iname in d.relations:
            for a in d.relations[iname].allowed:
                if a.relation:
                    out.append((a.type, a.relation))
        return out

    def depth_of(node: Tuple[str, str]) -> int:
        nonlocal recursive
        if node in depth_memo:
            return depth_memo[node]
        if node in in_stack:
            recursive = True
            return 0
        in_stack.add(node)
        d = 0
        for dep in deps(node):
            d = max(d, 1 + depth_of(dep))
        in_stack.discard(node)
        depth_memo[node] = d
        return d

    max_depth = 0
    for tname, d in schema.definitions.items():
        for iname in list(d.relations) + list(d.permissions):
            max_depth = max(max_depth, depth_of((tname, iname)))

    return CompiledSchema(
        schema=schema,
        type_ids=type_ids,
        slot_of_name=slot_of_name,
        caveat_ids=caveat_ids,
        types=types,
        num_slots=len(slot_of_name),
        tupleset_pairs=frozenset(tupleset_pairs),
        tupleset_slots=frozenset(s for (_, s) in tupleset_pairs),
        depth=max_depth,
        is_recursive=recursive,
        has_permission_usersets=has_permission_usersets,
        item_depths=dict(depth_memo),
    )

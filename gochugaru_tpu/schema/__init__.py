"""SpiceDB schema-language front-end: parser, AST, and IR compiler.

The reference delegates schema handling to the server (WriteSchema /
ReadSchema round-trip raw text, client/client.go:416-434); the schema
language itself is the evaluator spec implied by the client's API surface
(SURVEY.md §2.6).  This package parses that language and compiles it into
the numeric IR the evaluation engines execute.
"""

from .ast import (
    AllowedSubject,
    Arrow,
    CaveatDecl,
    Definition,
    Exclusion,
    Expr,
    Intersection,
    Nil,
    Permission,
    Relation,
    RelationRef,
    Schema,
    Union,
)
from .parser import SchemaParseError, parse_schema
from .compiler import CompiledSchema, SchemaValidationError, compile_schema

__all__ = [
    "parse_schema",
    "compile_schema",
    "Schema",
    "Definition",
    "Relation",
    "Permission",
    "CaveatDecl",
    "AllowedSubject",
    "Expr",
    "RelationRef",
    "Arrow",
    "Union",
    "Intersection",
    "Exclusion",
    "Nil",
    "SchemaParseError",
    "SchemaValidationError",
    "CompiledSchema",
]

"""Recursive-descent parser for the SpiceDB schema language subset.

Grammar (whitespace/comments insignificant; ``//`` and ``/* */`` comments):

    schema      := (use | caveat | definition)*
    use         := 'use' identifier
    caveat      := 'caveat' qname '(' [param (',' param)*] ')' '{' cel '}'
    param       := identifier type_name
    definition  := 'definition' qname '{' (relation | permission)* '}'
    relation    := 'relation' identifier ':' allowed ('|' allowed)*
    allowed     := qname (':*' | '#' identifier)? ('with' trait ('and' trait)*)?
    trait       := 'expiration' | qname           -- caveat name
    permission  := 'permission' identifier '=' expr
    expr        := term (op term)*                -- op ∈ {+, -, &}, left-assoc,
                                                     equal precedence
    term        := '(' expr ')' | 'nil' | operand
    operand     := identifier ('->' identifier)?  -- single arrow, LHS a relation

Chained arrows (``a->b->c``) are rejected, as SpiceDB requires an
intermediate permission.  ``use`` statements (e.g. ``use expiration``) are
accepted and ignored.  Caveat bodies are raw CEL text captured between
balanced braces and compiled separately by ``gochugaru_tpu.caveats``.
"""

from __future__ import annotations

import re
from typing import List, NamedTuple

from .ast import (
    AllowedSubject,
    Arrow,
    CaveatDecl,
    Definition,
    Exclusion,
    Expr,
    Intersection,
    Nil,
    Permission,
    Relation,
    RelationRef,
    Schema,
    Union,
)


class SchemaParseError(ValueError):
    def __init__(self, message: str, line: int = 0) -> None:
        super().__init__(f"schema parse error at line {line}: {message}" if line else message)
        self.line = line


class _Tok(NamedTuple):
    kind: str  # ident, punct, other, eof
    text: str
    line: int
    offset: int


_TOKEN_RE = re.compile(
    r"""
      (?P<comment>//[^\n]*|/\*.*?\*/)
    | (?P<ws>\s+)
    | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*(?:/[A-Za-z_][A-Za-z0-9_]*)*)
    | (?P<punct>->|:\*|[{}():#|+\-&=,])
    | (?P<other>.)
    """,
    re.VERBOSE | re.DOTALL,
)


def _tokenize(text: str) -> List[_Tok]:
    """Tokenize schema source.  Characters outside the schema grammar (CEL
    numbers, comparison operators, strings…) become ``other`` tokens — legal
    only inside caveat bodies, which are re-scanned raw by offset."""
    toks: List[_Tok] = []
    pos = 0
    line = 1
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        assert m is not None  # the 'other' branch matches any character
        tok_line = line
        line += text[pos : m.end()].count("\n")
        kind = m.lastgroup
        if kind in ("ident", "punct"):
            toks.append(_Tok(kind, m.group(), tok_line, pos))
        elif kind in ("string", "other"):
            toks.append(_Tok("other", m.group(), tok_line, pos))
        pos = m.end()
    return toks


class _Parser:
    def __init__(self, text: str) -> None:
        self.text = text
        self.toks = _tokenize(text)
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self) -> _Tok:
        if self.i < len(self.toks):
            return self.toks[self.i]
        return _Tok("eof", "", self.toks[-1].line if self.toks else 0, len(self.text))

    def next(self) -> _Tok:
        t = self.peek()
        self.i += 1
        return t

    def expect(self, text: str) -> _Tok:
        t = self.next()
        if t.text != text:
            raise SchemaParseError(f"expected {text!r}, got {t.text!r}", t.line)
        return t

    def expect_ident(self, what: str = "identifier") -> _Tok:
        t = self.next()
        if t.kind != "ident":
            raise SchemaParseError(f"expected {what}, got {t.text!r}", t.line)
        return t

    # -- grammar -----------------------------------------------------------
    def parse(self) -> Schema:
        schema = Schema(text=self.text)
        while self.peek().kind != "eof":
            t = self.peek()
            if t.text == "definition":
                d = self.parse_definition()
                if d.name in schema.definitions:
                    raise SchemaParseError(f"duplicate definition {d.name!r}", t.line)
                schema.definitions[d.name] = d
            elif t.text == "caveat":
                c = self.parse_caveat()
                if c.name in schema.caveats:
                    raise SchemaParseError(f"duplicate caveat {c.name!r}", t.line)
                schema.caveats[c.name] = c
            elif t.text == "use":
                self.next()
                self.expect_ident("feature name")
            else:
                raise SchemaParseError(
                    f"expected 'definition', 'caveat', or 'use', got {t.text!r}", t.line
                )
        return schema

    def parse_definition(self) -> Definition:
        self.expect("definition")
        name = self.expect_ident("definition name").text
        d = Definition(name=name)
        self.expect("{")
        while self.peek().text != "}":
            t = self.peek()
            if t.text == "relation":
                r = self.parse_relation()
                if d.item(r.name) is not None:
                    raise SchemaParseError(f"duplicate item {r.name!r} in {name}", t.line)
                d.relations[r.name] = r
            elif t.text == "permission":
                p = self.parse_permission()
                if d.item(p.name) is not None:
                    raise SchemaParseError(f"duplicate item {p.name!r} in {name}", t.line)
                d.permissions[p.name] = p
            else:
                raise SchemaParseError(
                    f"expected 'relation' or 'permission', got {t.text!r}", t.line
                )
        self.expect("}")
        return d

    def parse_relation(self) -> Relation:
        self.expect("relation")
        name = self.expect_ident("relation name").text
        self.expect(":")
        allowed = [self.parse_allowed()]
        while self.peek().text == "|":
            self.next()
            allowed.append(self.parse_allowed())
        return Relation(name=name, allowed=allowed)

    def parse_allowed(self) -> AllowedSubject:
        typ = self.expect_ident("subject type").text
        relation = ""
        wildcard = False
        if self.peek().text == ":*":
            self.next()
            wildcard = True
        elif self.peek().text == "#":
            self.next()
            relation = self.expect_ident("subject relation").text
        caveat = ""
        expiration = False
        if self.peek().text == "with":
            self.next()
            while True:
                trait = self.expect_ident("caveat name or 'expiration'").text
                if trait == "expiration":
                    expiration = True
                else:
                    if caveat:
                        raise SchemaParseError(
                            f"multiple caveats on one allowed subject: {caveat!r}, {trait!r}",
                            self.peek().line,
                        )
                    caveat = trait
                if self.peek().text == "and":
                    self.next()
                    continue
                break
        return AllowedSubject(
            type=typ, relation=relation, wildcard=wildcard, caveat=caveat, expiration=expiration
        )

    def parse_permission(self) -> Permission:
        self.expect("permission")
        name = self.expect_ident("permission name").text
        self.expect("=")
        return Permission(name=name, expr=self.parse_expr())

    def parse_expr(self) -> Expr:
        left = self.parse_term()
        while True:
            op = self.peek().text
            if op == "+":
                self.next()
                right = self.parse_term()
                if isinstance(left, Union):
                    left = Union(left.children + (right,))
                else:
                    left = Union((left, right))
            elif op == "&":
                self.next()
                right = self.parse_term()
                if isinstance(left, Intersection):
                    left = Intersection(left.children + (right,))
                else:
                    left = Intersection((left, right))
            elif op == "-":
                self.next()
                left = Exclusion(base=left, subtracted=self.parse_term())
            else:
                return left

    def parse_term(self) -> Expr:
        t = self.peek()
        if t.text == "(":
            self.next()
            e = self.parse_expr()
            self.expect(")")
            return e
        if t.text == "nil":
            self.next()
            return Nil()
        ident = self.expect_ident("relation or permission name").text
        if self.peek().text == "->":
            self.next()
            right = self.expect_ident("arrow target").text
            if self.peek().text == "->":
                raise SchemaParseError(
                    "chained arrows are not supported; introduce an intermediate permission",
                    self.peek().line,
                )
            return Arrow(left=ident, right=right)
        return RelationRef(name=ident)

    # -- caveats -----------------------------------------------------------
    def parse_caveat(self) -> CaveatDecl:
        self.expect("caveat")
        name = self.expect_ident("caveat name").text
        self.expect("(")
        params = {}
        while self.peek().text != ")":
            pname = self.expect_ident("parameter name").text
            ptype = self.expect_ident("parameter type").text
            # generic types: list<int>, map<string>, nested generics
            if self.peek().text == "<":
                depth = 0
                while True:
                    t = self.next()
                    ptype += t.text
                    if t.text == "<":
                        depth += 1
                    elif t.text == ">":
                        depth -= 1
                        if depth == 0:
                            break
                    if t.kind == "eof":
                        raise SchemaParseError(
                            f"unterminated generic type for parameter {pname!r}",
                            t.line,
                        )
            if pname in params:
                raise SchemaParseError(f"duplicate caveat parameter {pname!r}", self.peek().line)
            params[pname] = ptype
            if self.peek().text == ",":
                self.next()
        self.expect(")")
        body = self._raw_braced_body()
        return CaveatDecl(name=name, params=params, expression=body.strip())

    def _raw_braced_body(self) -> str:
        """Capture the raw source between balanced braces starting at the
        next token (which must be '{'), and advance the token index past the
        closing '}'.  Used for caveat bodies, whose CEL content is outside
        the schema token set."""
        open_tok = self.expect("{")
        start = open_tok.offset
        depth = 0
        j = start
        n = len(self.text)
        while j < n:
            ch = self.text[j]
            if ch in "\"'":
                # skip string literals — braces inside them don't count
                quote = ch
                j += 1
                while j < n and self.text[j] != quote:
                    j += 2 if self.text[j] == "\\" else 1
                if j >= n:
                    raise SchemaParseError("unterminated string in caveat body", open_tok.line)
            elif ch == "/" and j + 1 < n and self.text[j + 1] == "/":
                while j < n and self.text[j] != "\n":
                    j += 1
                continue
            elif ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    body = self.text[start + 1 : j]
                    while self.i < len(self.toks) and self.toks[self.i].offset <= j:
                        self.i += 1
                    return body
            j += 1
        raise SchemaParseError("unterminated caveat body", open_tok.line)


def parse_schema(text: str) -> Schema:
    """Parse schema source text into an AST.

    Raises SchemaParseError on malformed input — the local analogue of the
    server rejecting WriteSchema (client/client.go:424-434).
    """
    return _Parser(text).parse()

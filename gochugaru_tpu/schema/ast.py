"""AST for the SpiceDB schema language subset this framework evaluates.

Spec sources: the example schema in the reference's integration tests
(client/client_test.go:23-32) plus the public SpiceDB schema language —
``definition`` types holding typed ``relation`` edges and ``permission``
userset-rewrite expressions over ``+`` (union), ``&`` (intersection),
``-`` (exclusion), ``->`` (arrow / tupleset traversal), ``nil``, wildcard
subjects (``user:*``), userset subjects (``group#member``), and ``caveat``
declarations with CEL-subset bodies.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


# --------------------------------------------------------------------------
# Permission expressions
# --------------------------------------------------------------------------


class Expr:
    """Base class for permission userset-rewrite expressions."""

    __slots__ = ()


@dataclass(frozen=True)
class RelationRef(Expr):
    """A bare reference to a relation or permission on the same type,
    e.g. ``edit`` in ``permission view = reader + edit``."""

    name: str

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Arrow(Expr):
    """Tupleset traversal ``left->right``: walk tuples of relation ``left``
    on the resource, then evaluate ``right`` on each subject reached.
    The left side must name a plain relation on the same type (SpiceDB
    rejects arrows over permissions and chained arrows)."""

    left: str
    right: str

    def __str__(self) -> str:
        return f"{self.left}->{self.right}"


@dataclass(frozen=True)
class Union(Expr):
    children: tuple

    def __str__(self) -> str:
        return "(" + " + ".join(str(c) for c in self.children) + ")"


@dataclass(frozen=True)
class Intersection(Expr):
    children: tuple

    def __str__(self) -> str:
        return "(" + " & ".join(str(c) for c in self.children) + ")"


@dataclass(frozen=True)
class Exclusion(Expr):
    """``base - subtracted`` — grants base minus subtracted."""

    base: Expr
    subtracted: Expr

    def __str__(self) -> str:
        return f"({self.base} - {self.subtracted})"


@dataclass(frozen=True)
class Nil(Expr):
    """``permission p = nil`` — grants nobody."""

    def __str__(self) -> str:
        return "nil"


# --------------------------------------------------------------------------
# Declarations
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class AllowedSubject:
    """One alternative in a relation's type annotation:
    ``user`` (direct), ``user:*`` (wildcard), ``group#member`` (userset),
    optionally ``with caveat_name`` and/or ``with expiration``."""

    type: str
    relation: str = ""  # userset subject relation; "" = direct object
    wildcard: bool = False
    caveat: str = ""  # required caveat name, "" = none
    expiration: bool = False  # subject must carry an expiration trait

    def __str__(self) -> str:
        s = self.type
        if self.wildcard:
            s += ":*"
        elif self.relation:
            s += f"#{self.relation}"
        traits = ([self.caveat] if self.caveat else []) + (
            ["expiration"] if self.expiration else []
        )
        if traits:
            s += " with " + " and ".join(traits)
        return s


@dataclass
class Relation:
    """``relation name: allowed | allowed | ...`` — a typed edge label."""

    name: str
    allowed: List[AllowedSubject] = field(default_factory=list)

    def allows_all(self, subject_type: str, subject_relation: str, wildcard: bool) -> List[AllowedSubject]:
        """All alternatives matching (type, relation, wildcard) — there can
        be several differing only in caveat/expiration traits
        (``user | user with office_hours``)."""
        out = []
        for a in self.allowed:
            if a.type != subject_type:
                continue
            if wildcard != a.wildcard:
                continue
            if not wildcard and a.relation != subject_relation:
                continue
            out.append(a)
        return out

    def allows(self, subject_type: str, subject_relation: str, wildcard: bool) -> Optional[AllowedSubject]:
        matches = self.allows_all(subject_type, subject_relation, wildcard)
        return matches[0] if matches else None


@dataclass
class Permission:
    """``permission name = expr`` — a userset-rewrite expression."""

    name: str
    expr: Expr


@dataclass
class Definition:
    """``definition name { ... }`` — an object type."""

    name: str
    relations: Dict[str, Relation] = field(default_factory=dict)
    permissions: Dict[str, Permission] = field(default_factory=dict)

    def item(self, name: str):
        return self.relations.get(name) or self.permissions.get(name)


@dataclass
class CaveatDecl:
    """``caveat name(param type, ...) { cel_expression }``."""

    name: str
    params: Dict[str, str] = field(default_factory=dict)  # name -> CEL type
    expression: str = ""  # raw CEL text; compiled by gochugaru_tpu.caveats


@dataclass
class Schema:
    """A parsed schema document."""

    definitions: Dict[str, Definition] = field(default_factory=dict)
    caveats: Dict[str, CaveatDecl] = field(default_factory=dict)
    text: str = ""  # original source, round-tripped by ReadSchema

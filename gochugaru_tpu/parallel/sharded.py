"""The sharded bulk-check engine: shard_map over a (data × model) mesh.

Queries are partitioned along ``data`` (each device row evaluates its own
slice of the batch), the sorted edge columns along ``model`` (each device
column holds a contiguous, still-sorted block of every view).  The engine
body is exactly the single-chip two-phase evaluation with collectives at
the merge points (``engine.device`` with ``axis=MODEL_AXIS``):

- closure seed/propagation gathers all-gather shard-local candidates;
- leaf tests OR-reduce shard-local hits (all-reduce over ICI);
- the arrow BFS all-gathers shard-local children, then assigns node slots
  deterministically so every shard holds the identical subgraph.

This is the SPMD replacement for what a multi-node SpiceDB does with its
dispatch cluster (SURVEY.md §2.5): one XLA program, collectives riding
ICI, no RPC fan-out.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax ≥ 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

import inspect

#: the replication-check kwarg was renamed check_rep → check_vma across
#: jax versions; feature-detect so both signatures disable it
_SHARD_MAP_NO_CHECK = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(shard_map).parameters
    else {"check_rep": False}
)

from ..engine.device import (
    DeviceEngine,
    DeviceSnapshot,
    _ceil_pow2,
    _make_check_fn,
    _pad_payload,
)
from ..engine.flat import build_qm
from ..engine.plan import EngineConfig
from ..rel.relationship import Relationship
from ..schema.compiler import CompiledSchema
from ..store.snapshot import Snapshot
from ..utils import faults
from ..utils import trace as _trace
from .mesh import DATA_AXIS, MODEL_AXIS


class ShardedEngine(DeviceEngine):
    """A DeviceEngine whose batched check runs shard_mapped over a mesh."""

    def __init__(
        self,
        compiled: CompiledSchema,
        mesh: Mesh,
        config: Optional[EngineConfig] = None,
    ) -> None:
        super().__init__(compiled, config)
        self.mesh = mesh
        self.data_size = mesh.shape[DATA_AXIS]
        self.model_size = mesh.shape[MODEL_AXIS]
        raw = _make_check_fn(
            self.plan, self.config, axis=MODEL_AXIS, jit=False,
            caveat_plan=self.caveat_plan,
        )

        def arr_spec_of(key: str):
            # lookup tables (node type map, caveat context tables, the
            # static possibly-userset pair set — probed whole by every
            # leaf test) are replicated; sorted edge columns shard along
            # the model axis
            if key == "node_type" or key.startswith(("ectx_", "pus_")):
                return P()
            return P(MODEL_AXIS)

        self._arr_spec_of = arr_spec_of
        arr_spec = {k: arr_spec_of(k) for k in self._array_keys()}
        qctx_spec = {k: P() for k in ("vi", "vf", "pr", "host")}
        in_specs = (
            arr_spec, P(), P(),  # arrays, tid_map, now
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # u_*
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # q_res, q_perm, q_subj
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # srel, wc, row, self
            P(DATA_AXIS),  # q_ctx
            qctx_spec,
        )
        out_specs = (P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS))
        self._fn = jax.jit(
            shard_map(
                raw, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **_SHARD_MAP_NO_CHECK,
            )
        )
        #: shard_mapped flat kernels per (slots, FlatMeta, array keys)
        self._flat_sharded_fns: Dict = {}

    def _array_keys(self):
        # single source of truth for the column set lives in DeviceEngine
        # (ARRAY_COLUMN_KEYS), so a new column added to _host_arrays can't
        # silently diverge from the shard_map specs
        keys = list(DeviceEngine.ARRAY_COLUMN_KEYS)
        if self.caveat_plan is not None:
            keys += ["ectx_vi", "ectx_vf", "ectx_pr", "ectx_host"]
        return keys

    # -- flat (bucket-sharded) path ---------------------------------------
    @staticmethod
    def _flat_spec_of(key: str):
        """Sharded flat tables split on the leading (stacked) axis; node
        types, stored-context tables, and the delta-sized ``dl_*``
        overlays are replicated."""
        if key == "node_type" or key.startswith(("ectx_", "dl_")):
            return P()
        return P(MODEL_AXIS)

    def _flat_sharded_fn(self, slots: Tuple[int, ...], meta, arr_keys):
        """Cache of shard_mapped flat kernels per (slots, meta, keys)."""
        key = (slots, meta, arr_keys)
        fn = self._flat_sharded_fns.get(key)
        if fn is not None:
            return fn
        from ..engine.flat import make_flat_fn

        raw = make_flat_fn(
            self.compiled, self.plan, self.config, meta, slots,
            caveat_plan=self.caveat_plan, jit=False,
            axis=MODEL_AXIS, model_size=self.model_size,
        )
        arr_spec = {k: self._flat_spec_of(k) for k in arr_keys}
        qctx_spec = {k: P() for k in ("vi", "vf", "pr", "host")}
        in_specs = (
            arr_spec, P(), P(),  # arrays, tid_map, now
            P(None, DATA_AXIS),  # packed query matrix (flat.QM_LAYOUT)
            qctx_spec,
        )
        fn = jax.jit(
            shard_map(
                raw, mesh=self.mesh, in_specs=in_specs,
                out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
                **_SHARD_MAP_NO_CHECK,
            )
        )
        while len(self._flat_sharded_fns) >= self.FLAT_FN_CACHE_MAX:
            self._flat_sharded_fns.pop(next(iter(self._flat_sharded_fns)))
        self._flat_sharded_fns[key] = fn
        return fn

    # -- snapshot preparation: pad every view to a multiple of model_size --
    def prepare(
        self, snap: Snapshot, prev: Optional[DeviceSnapshot] = None
    ) -> DeviceSnapshot:
        """With ``prev`` (the previous revision's sharded DeviceSnapshot),
        try the incremental path first: the bucket-sharded base tables
        stay resident on their shards, and only the small REPLICATED
        ``dl_*`` overlay ships per revision — the multi-host Watch-driven
        re-index costs O(delta), not O(E/M)·M, per revision."""
        if prev is not None:
            out = self._prepare_delta(snap, prev)
            if out is not None:
                return out
        if (
            self.config.use_flat
            and self.config.flat_blockslice
            and self.model_size & (self.model_size - 1) == 0
        ):
            from ..engine.flat import build_flat_arrays_sharded

            built = build_flat_arrays_sharded(
                snap, self.config, self.model_size, plan=self.plan
            )
            if built is not None:
                flat_arrays, flat_meta, fold_state, _cstate = built
                host = dict(flat_arrays)
                host["node_type"] = _pad_payload(
                    snap.node_type, _ceil_pow2(2 * snap.num_nodes), -1
                )
                ectx, strings = self._ectx_tables(snap)
                host.update(ectx)
                arrays = {
                    k: jax.device_put(
                        v, NamedSharding(self.mesh, self._flat_spec_of(k))
                    )
                    for k, v in host.items()
                }
                tid_map = np.full(
                    max(self.plan.num_schema_types, 1), -1, dtype=np.int32
                )
                for tname, tid in self.compiled.type_ids.items():
                    tid_map[tid] = snap.interner.type_lookup(tname)
                return DeviceSnapshot(
                    revision=snap.revision,
                    arrays=arrays,
                    tid_map=jnp.asarray(tid_map),
                    snapshot=snap,
                    strings=strings,
                    flat_meta=flat_meta,
                    fold_state=fold_state,
                )
        return self._prepare_legacy(snap)

    def prepare_partitioned(self, part) -> DeviceSnapshot:
        """DeviceSnapshot from a bucket-partitioned feed
        (engine/partition.py partition_feed): the O(E) stacked tables
        exist host-side ONLY for this process's owned shards
        (ShardSlices); ``jax.make_array_from_callback`` asks for exactly
        the addressable blocks, so assembling the global arrays never
        materializes the full table on any host.  Replicated tables
        (node types, contexts, dl_* — and the closure-derived stacks,
        which every process builds whole from the replicated membership
        subgraph) ship via the ordinary replicated device_put."""
        from ..engine.partition import ShardSlices

        snap = part.snapshot
        host = dict(part.arrays)
        host["node_type"] = _pad_payload(
            snap.node_type, _ceil_pow2(2 * snap.num_nodes), -1
        )
        ectx, strings = self._ectx_tables(snap)
        host.update(ectx)
        arrays = {}
        for k, v in host.items():
            sh = NamedSharding(self.mesh, self._flat_spec_of(k))
            if isinstance(v, ShardSlices):
                cb = v.block_for
            else:
                # replicated / full tables place via the same callback
                # API: device_put of a replicated array onto a process-
                # spanning mesh runs a consistency-assert COLLECTIVE
                # (multihost_utils.assert_equal), which some CPU jaxlib
                # builds cannot execute — the callback path places local
                # buffers directly and is collective-free by design
                cb = (lambda v: lambda index: v[index])(v)
            arrays[k] = jax.make_array_from_callback(v.shape, sh, cb)
        tid_map = np.full(
            max(self.plan.num_schema_types, 1), -1, dtype=np.int32
        )
        for tname, tid in self.compiled.type_ids.items():
            tid_map[tid] = snap.interner.type_lookup(tname)
        return DeviceSnapshot(
            revision=snap.revision,
            arrays=arrays,
            tid_map=jnp.asarray(tid_map),
            snapshot=snap,
            strings=strings,
            flat_meta=part.meta,
        )

    def _delta_prev_ok(self, prev: DeviceSnapshot) -> bool:
        # the sharded incremental prepare rides bucket-sharded base tables
        return prev.flat_meta is not None and prev.flat_meta.sharded

    def _place_replicated(self, v: np.ndarray):
        # overlays are delta-sized: replication beats bucket-sharding and
        # lets the kernel probe them without ownership collectives
        return jax.device_put(v, NamedSharding(self.mesh, P()))

    def _prepare_legacy(self, snap: Snapshot) -> DeviceSnapshot:
        host = self._host_arrays(snap)
        # Model-sharded columns must split evenly across model_size (power
        # of two); the base padding is already pow2, so only meshes wider
        # than the smallest bucket need more.  Sorted key columns keep the
        # I32_MAX sentinel so the padded tail sorts last; payload pads are
        # never read through a matching key.
        sorted_keys = {
            "e_rel", "e_res", "e_subj", "e_srel1", "us_rel", "us_res",
            "ms_subj", "mp_subj", "mp_srel", "ar_rel", "ar_res",
        }
        m = max(8, _ceil_pow2(self.model_size, 1))
        for k, v in list(host.items()):
            if self._arr_spec_of(k) == P(MODEL_AXIS) and v.shape[0] % self.model_size:
                size = _ceil_pow2(v.shape[0], m)
                fill = (2**31 - 1) if k in sorted_keys else -1
                out = np.full(size, fill, v.dtype)
                out[: v.shape[0]] = v
                host[k] = out
        ectx, strings = self._ectx_tables(snap)
        host.update(ectx)
        arrays = {}
        for k, v in host.items():
            arrays[k] = jax.device_put(
                v, NamedSharding(self.mesh, self._arr_spec_of(k))
            )
        tid_map = np.full(max(self.plan.num_schema_types, 1), -1, dtype=np.int32)
        for tname, tid in self.compiled.type_ids.items():
            tid_map[tid] = snap.interner.type_lookup(tname)
        return DeviceSnapshot(
            revision=snap.revision,
            arrays=arrays,
            tid_map=jnp.asarray(tid_map),
            snapshot=snap,
            strings=strings,
        )

    # -- batched check: queries partitioned per data-shard ----------------
    def _dispatch_flat(
        self,
        dsnap: DeviceSnapshot,
        queries: Dict[str, np.ndarray],
        qctx: Dict[str, np.ndarray],
        now_us: Optional[int],
        fetch: bool = True,
        bucket_min: int = 0,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Dispatch over the bucket-sharded flat tables: queries partition
        along the data axis; the kernel's probe sites OR-reduce over the
        model axis internally (engine/flat.py make_flat_fn with axis)."""
        faults.fire("sharded.collective")
        snap = dsnap.snapshot
        D = self.data_size
        B = queries["q_res"].shape[0]
        per = _ceil_pow2(
            -(-B // D), max(bucket_min, self.config.batch_bucket_min)
        )
        BP = per * D

        all_slots = sorted(
            {int(s) for s in np.unique(queries["q_perm"]) if s >= 0}
        )
        now = jnp.int32(snap.now_rel32(now_us))
        # packed query matrix (flat.QM_LAYOUT): batch rides axis 1, which
        # partitions over the data axis — ONE sharded transfer; the rare
        # multi-chunk path (more distinct permissions than
        # flat_max_slots) ships only the small perm row per chunk and
        # splices it on device
        dsh = NamedSharding(self.mesh, P(None, DATA_AXIS))
        rep = NamedSharding(self.mesh, P())
        qm_dev = jax.device_put(build_qm(queries, BP, dsnap.flat_meta), dsh)
        qctx_dev = {k: jax.device_put(v, rep) for k, v in qctx.items()}
        arr_keys = tuple(sorted(dsnap.arrays.keys()))
        # batches with more distinct permissions than flat_max_slots are
        # evaluated in slot chunks (each query's slot lives in exactly one
        # chunk; masked-out queries read -1 → all-false) — the compile
        # cost stays bounded instead of unrolling one program per slot
        cap = max(self.config.flat_max_slots, 1)
        q_perm = queries["q_perm"]
        multi = len(all_slots) > cap
        if multi:
            row_sh = NamedSharding(self.mesh, P(DATA_AXIS))
            # one jitted splice per engine: a fresh jax.jit here would
            # retrace on every multi-chunk dispatch.  BOTH slot-bearing
            # rows splice — leaving row 7 (dense q_perm_k1) unmasked
            # would let masked-out queries drive the dynamic leaf in
            # every chunk and OR in spurious overflow flags
            set_perm = self.__dict__.get("_set_perm_fn")
            if set_perm is None:
                set_perm = jax.jit(
                    lambda q, pc, pk: q.at[1].set(pc).at[7].set(pk),
                    out_shardings=dsh,
                )
                self._set_perm_fn = set_perm
            from ..engine.flat import _dense_np

            k1d = _dense_np(dsnap.flat_meta.k1_dense)
        d = p = ovf = None
        for at in range(0, max(len(all_slots), 1), cap):
            chunk = tuple(all_slots[at : at + cap])
            if multi:
                pc = np.full(BP, -1, np.int32)
                pc[:B] = np.where(
                    np.isin(q_perm, np.asarray(chunk, np.int32)), q_perm, -1
                )
                pk = np.where(
                    pc >= 0, k1d[np.clip(pc, 0, k1d.shape[0] - 1)], -1
                ).astype(np.int32)
                qmc = set_perm(
                    qm_dev,
                    jax.device_put(pc, row_sh),
                    jax.device_put(pk, row_sh),
                )
            else:
                qmc = qm_dev
            fn = self._flat_sharded_fn(chunk, dsnap.flat_meta, arr_keys)
            cd, cp, covf = fn(
                dsnap.arrays, dsnap.tid_map, now, qmc, qctx_dev,
            )
            d = cd if d is None else d | cd
            p = cp if p is None else p | cp
            ovf = covf if ovf is None else ovf | covf
        if not fetch:
            return d, p, ovf
        d, p, ovf = jax.device_get((d, p, ovf))
        return d[:B], p[:B], ovf[:B]

    def _dispatch_columns(
        self,
        dsnap: DeviceSnapshot,
        queries: Dict[str, np.ndarray],
        qctx: Dict[str, np.ndarray],
        now_us: Optional[int],
        fetch: bool = True,
        bucket_min: int = 0,
        span=_trace.NOOP,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Partition query columns across the data axis, compute per-shard
        unique (subject, context) closure rows, and dispatch the
        shard_mapped check.  ``queries`` holds length-B columns (q_res,
        q_perm, q_subj, q_srel, q_wc, q_ctx, q_self); q_row is derived
        here per shard.  With ``fetch=False`` the raw padded sharded
        device outputs (length BP ≥ B) are returned for pipelined
        dispatch, mirroring DeviceEngine.check_columns.  A sampled
        ``span`` records a ``sharded.dispatch`` child (partition /
        collective / fetch stage events)."""
        faults.fire("sharded.dispatch")
        ssp = span.child(
            "sharded.dispatch",
            batch=int(queries["q_res"].shape[0]),
            data=self.data_size, model=self.model_size,
        )
        try:
            if dsnap.flat_meta is not None:
                with _trace.annotate_dispatch(span):
                    return self._dispatch_flat(
                        dsnap, queries, qctx, now_us, fetch,
                        bucket_min=bucket_min,
                    )
            snap = dsnap.snapshot
            D = self.data_size
            B = queries["q_res"].shape[0]
            per = _ceil_pow2(-(-B // D), self.config.batch_bucket_min)
            BP = per * D

            q = {
                k: np.full(BP, -1 if v.dtype != bool else 0, v.dtype)
                for k, v in queries.items()
                if k != "q_row"
            }
            for k in q:
                q[k][:B] = queries[k]
            # per-data-shard unique subjects (each shard computes closures only
            # for its own slice of the batch)
            subj_key = np.stack(
                [q["q_subj"], q["q_srel"], q["q_wc"], q["q_ctx"]], axis=1
            )
            ulists = []
            rows = np.zeros(BP, np.int32)
            for s in range(D):
                blk = slice(s * per, (s + 1) * per)
                uniq, inv = np.unique(subj_key[blk], axis=0, return_inverse=True)
                ulists.append(uniq)
                rows[blk] = inv.astype(np.int32)
            UP = _ceil_pow2(max(u.shape[0] for u in ulists), self.config.batch_bucket_min)
            u_subj = np.full(D * UP, -1, np.int32)
            u_srel = np.full(D * UP, -1, np.int32)
            u_wc = np.full(D * UP, -1, np.int32)
            u_qctx = np.full(D * UP, -1, np.int32)
            for s, uniq in enumerate(ulists):
                n = uniq.shape[0]
                u_subj[s * UP : s * UP + n] = uniq[:, 0]
                u_srel[s * UP : s * UP + n] = uniq[:, 1]
                u_wc[s * UP : s * UP + n] = uniq[:, 2]
                u_qctx[s * UP : s * UP + n] = uniq[:, 3]
            q["q_row"] = rows
            ssp.event("stage.partition")

            faults.fire("sharded.collective")
            now = jnp.int32(snap.now_rel32(now_us))
            dsh = NamedSharding(self.mesh, P(DATA_AXIS))
            rep = NamedSharding(self.mesh, P())

            def put(a):
                return jax.device_put(a, dsh)

            with _trace.annotate_dispatch(span):
                d, p, ovf = self._fn(
                    dsnap.arrays, dsnap.tid_map, now,
                    put(u_subj), put(u_srel), put(u_wc), put(u_qctx),
                    put(q["q_res"]), put(q["q_perm"]), put(q["q_subj"]),
                    put(q["q_srel"]), put(q["q_wc"]), put(q["q_row"]), put(q["q_self"]),
                    put(q["q_ctx"]),
                    {k: jax.device_put(v, rep) for k, v in qctx.items()},
                )
            ssp.event("stage.collective")
            if not fetch:
                return d, p, ovf
            d, p, ovf = jax.device_get((d, p, ovf))
            ssp.event("stage.fetch")
            return d[:B], p[:B], ovf[:B]
        finally:
            ssp.end()

    def check_batch(
        self,
        dsnap: DeviceSnapshot,
        rels: Sequence[Relationship],
        *,
        now_us: Optional[int] = None,
        latency: bool = False,  # accepted for Client parity; the latency
        # path is single-chip (engine/latency.py), so it's ignored here
        span=_trace.NOOP,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not rels:
            z = np.zeros(0, bool)
            return z, z, z
        queries, _, qctx = self._lower_queries(dsnap.snapshot, rels, dsnap.strings)
        return self._dispatch_columns(dsnap, queries, qctx, now_us, span=span)

    def check_columns(
        self,
        dsnap: DeviceSnapshot,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        *,
        q_srel: Optional[np.ndarray] = None,
        q_wc: Optional[np.ndarray] = None,
        q_ctx: Optional[np.ndarray] = None,
        qctx_rows=None,
        now_us: Optional[int] = None,
        fetch: bool = True,
        bucket_min: int = 0,
    ):
        """Columnar bulk check with the sharded layout (the base-class fast
        path assumes an unsharded q_row/uniq table, which would be wrong
        under shard_map — see _dispatch_columns).  ``bucket_min`` raises
        the per-data-shard padding floor, matching DeviceEngine."""
        queries, qctx = self._columns_preamble(
            dsnap, q_res, q_perm, q_subj, q_srel, q_wc, q_ctx, qctx_rows
        )
        return self._dispatch_columns(
            dsnap, queries, qctx, now_us, fetch=fetch, bucket_min=bucket_min
        )

"""The sharded bulk-check engine: shard_map over a (data × model) mesh.

Queries are partitioned along ``data`` (each device row evaluates its own
slice of the batch), the sorted edge columns along ``model`` (each device
column holds a contiguous, still-sorted block of every view).  The engine
body is exactly the single-chip two-phase evaluation with collectives at
the merge points (``engine.device`` with ``axis=MODEL_AXIS``):

- closure seed/propagation gathers all-gather shard-local candidates;
- leaf tests OR-reduce shard-local hits (all-reduce over ICI);
- the arrow BFS all-gathers shard-local children, then assigns node slots
  deterministically so every shard holds the identical subgraph.

This is the SPMD replacement for what a multi-node SpiceDB does with its
dispatch cluster (SURVEY.md §2.5): one XLA program, collectives riding
ICI, no RPC fan-out.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax ≥ 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from ..engine.device import (
    DeviceEngine,
    DeviceSnapshot,
    _ceil_pow2,
    _make_check_fn,
    _pad_payload,
    _pad_sorted,
)
from ..engine.plan import EngineConfig
from ..rel.relationship import Relationship
from ..schema.compiler import CompiledSchema
from ..store.snapshot import Snapshot
from .mesh import DATA_AXIS, MODEL_AXIS


class ShardedEngine(DeviceEngine):
    """A DeviceEngine whose batched check runs shard_mapped over a mesh."""

    def __init__(
        self,
        compiled: CompiledSchema,
        mesh: Mesh,
        config: Optional[EngineConfig] = None,
    ) -> None:
        super().__init__(compiled, config)
        self.mesh = mesh
        self.data_size = mesh.shape[DATA_AXIS]
        self.model_size = mesh.shape[MODEL_AXIS]
        raw = _make_check_fn(self.plan, self.config, axis=MODEL_AXIS, jit=False)

        arr_spec = {k: P(MODEL_AXIS) for k in self._ARRAY_KEYS}
        # node_type and tid_map are lookup tables, replicated everywhere
        arr_spec["node_type"] = P()
        in_specs = (
            arr_spec, P(), P(),  # arrays, tid_map, now
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # u_subj, u_srel, u_wc
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # q_res, q_perm, q_subj
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # srel, wc, row, self
        )
        out_specs = (P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS))
        self._fn = jax.jit(
            shard_map(
                raw, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )
        )

    _ARRAY_KEYS = (
        "e_rel", "e_res", "e_subj", "e_srel1", "e_caveat", "e_exp",
        "us_rel", "us_res", "us_subj", "us_srel", "us_caveat", "us_exp",
        "ms_subj", "ms_res", "ms_rel", "ms_caveat", "ms_exp",
        "mp_subj", "mp_srel", "mp_res", "mp_rel", "mp_caveat", "mp_exp",
        "ar_rel", "ar_res", "ar_child", "ar_caveat", "ar_exp",
        "node_type",
    )

    # -- snapshot preparation: pad every view to a multiple of model_size --
    def prepare(self, snap: Snapshot) -> DeviceSnapshot:
        def bucket(n: int) -> int:
            return _ceil_pow2(max(n, 1), max(8, self.model_size))

        E = bucket(snap.e_rel.shape[0])
        US = bucket(snap.us_rel.shape[0])
        MS = bucket(snap.ms_subj.shape[0])
        MP = bucket(snap.mp_subj.shape[0])
        AR = bucket(snap.ar_rel.shape[0])
        NN = _ceil_pow2(snap.num_nodes)
        host = {
            "e_rel": _pad_sorted(snap.e_rel, E),
            "e_res": _pad_sorted(snap.e_res, E),
            "e_subj": _pad_sorted(snap.e_subj, E),
            "e_srel1": _pad_sorted(snap.e_srel1, E),
            "e_caveat": _pad_payload(snap.e_caveat, E),
            "e_exp": _pad_payload(snap.e_exp, E),
            "us_rel": _pad_sorted(snap.us_rel, US),
            "us_res": _pad_sorted(snap.us_res, US),
            "us_subj": _pad_payload(snap.us_subj, US, -1),
            "us_srel": _pad_payload(snap.us_srel, US, -1),
            "us_caveat": _pad_payload(snap.us_caveat, US),
            "us_exp": _pad_payload(snap.us_exp, US),
            "ms_subj": _pad_sorted(snap.ms_subj, MS),
            "ms_res": _pad_payload(snap.ms_res, MS, -1),
            "ms_rel": _pad_payload(snap.ms_rel, MS, -1),
            "ms_caveat": _pad_payload(snap.ms_caveat, MS),
            "ms_exp": _pad_payload(snap.ms_exp, MS),
            "mp_subj": _pad_sorted(snap.mp_subj, MP),
            "mp_srel": _pad_sorted(snap.mp_srel, MP),
            "mp_res": _pad_payload(snap.mp_res, MP, -1),
            "mp_rel": _pad_payload(snap.mp_rel, MP, -1),
            "mp_caveat": _pad_payload(snap.mp_caveat, MP),
            "mp_exp": _pad_payload(snap.mp_exp, MP),
            "ar_rel": _pad_sorted(snap.ar_rel, AR),
            "ar_res": _pad_sorted(snap.ar_res, AR),
            "ar_child": _pad_payload(snap.ar_child, AR, -1),
            "ar_caveat": _pad_payload(snap.ar_caveat, AR),
            "ar_exp": _pad_payload(snap.ar_exp, AR),
            "node_type": _pad_payload(snap.node_type, NN, -1),
        }
        arrays = {}
        for k, v in host.items():
            spec = P() if k == "node_type" else P(MODEL_AXIS)
            arrays[k] = jax.device_put(v, NamedSharding(self.mesh, spec))
        tid_map = np.full(max(self.plan.num_schema_types, 1), -1, dtype=np.int32)
        for tname, tid in self.compiled.type_ids.items():
            tid_map[tid] = snap.interner.type_lookup(tname)
        return DeviceSnapshot(
            revision=snap.revision,
            arrays=arrays,
            tid_map=jnp.asarray(tid_map),
            snapshot=snap,
        )

    # -- batched check: queries partitioned per data-shard ----------------
    def check_batch(
        self,
        dsnap: DeviceSnapshot,
        rels: Sequence[Relationship],
        *,
        now_us: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not rels:
            z = np.zeros(0, bool)
            return z, z, z
        snap = dsnap.snapshot
        D = self.data_size
        B = len(rels)
        per = _ceil_pow2(-(-B // D), self.config.batch_bucket_min)
        BP = per * D

        queries, _ = self._lower_queries(snap, rels)
        # per-data-shard unique subjects (each shard computes closures only
        # for its own slice of the batch)
        q = {k: np.full(BP, -1 if v.dtype != bool else 0, v.dtype) for k, v in queries.items()}
        for k, v in queries.items():
            q[k][:B] = v
        subj_key = np.stack([q["q_subj"], q["q_srel"], q["q_wc"]], axis=1)
        ulists = []
        rows = np.zeros(BP, np.int32)
        for s in range(D):
            blk = slice(s * per, (s + 1) * per)
            uniq, inv = np.unique(subj_key[blk], axis=0, return_inverse=True)
            ulists.append(uniq)
            rows[blk] = inv.astype(np.int32)
        UP = _ceil_pow2(max(u.shape[0] for u in ulists), self.config.batch_bucket_min)
        u_subj = np.full(D * UP, -1, np.int32)
        u_srel = np.full(D * UP, -1, np.int32)
        u_wc = np.full(D * UP, -1, np.int32)
        for s, uniq in enumerate(ulists):
            n = uniq.shape[0]
            u_subj[s * UP : s * UP + n] = uniq[:, 0]
            u_srel[s * UP : s * UP + n] = uniq[:, 1]
            u_wc[s * UP : s * UP + n] = uniq[:, 2]
        q["q_row"] = rows

        now = jnp.int32(snap.now_rel32(now_us))
        dsh = NamedSharding(self.mesh, P(DATA_AXIS))

        def put(a):
            return jax.device_put(a, dsh)

        d, p, ovf = self._fn(
            dsnap.arrays, dsnap.tid_map, now,
            put(u_subj), put(u_srel), put(u_wc),
            put(q["q_res"]), put(q["q_perm"]), put(q["q_subj"]),
            put(q["q_srel"]), put(q["q_wc"]), put(q["q_row"]), put(q["q_self"]),
        )
        return (np.asarray(d)[:B], np.asarray(p)[:B], np.asarray(ovf)[:B])

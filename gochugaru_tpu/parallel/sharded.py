"""The sharded bulk-check engine: shard_map over a (data × model) mesh.

Queries are partitioned along ``data`` (each device row evaluates its own
slice of the batch), the sorted edge columns along ``model`` (each device
column holds a contiguous, still-sorted block of every view).  The engine
body is exactly the single-chip two-phase evaluation with collectives at
the merge points (``engine.device`` with ``axis=MODEL_AXIS``):

- closure seed/propagation gathers all-gather shard-local candidates;
- leaf tests OR-reduce shard-local hits (all-reduce over ICI);
- the arrow BFS all-gathers shard-local children, then assigns node slots
  deterministically so every shard holds the identical subgraph.

This is the SPMD replacement for what a multi-node SpiceDB does with its
dispatch cluster (SURVEY.md §2.5): one XLA program, collectives riding
ICI, no RPC fan-out.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax ≥ 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

import inspect

#: the replication-check kwarg was renamed check_rep → check_vma across
#: jax versions; feature-detect so both signatures disable it
_SHARD_MAP_NO_CHECK = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(shard_map).parameters
    else {"check_rep": False}
)

from ..engine.device import (
    DeviceEngine,
    DeviceSnapshot,
    _ceil_pow2,
    _make_check_fn,
    _pad_payload,
)
from ..engine.flat import build_qm
from ..engine.plan import EngineConfig
from ..rel.relationship import Relationship
from ..schema.compiler import CompiledSchema
from ..store.snapshot import Snapshot
from ..utils import faults
from ..utils import trace as _trace
from .mesh import DATA_AXIS, MODEL_AXIS


class ShardedEngine(DeviceEngine):
    """A DeviceEngine whose batched check runs shard_mapped over a mesh."""

    def __init__(
        self,
        compiled: CompiledSchema,
        mesh: Mesh,
        config: Optional[EngineConfig] = None,
    ) -> None:
        super().__init__(compiled, config)
        self.mesh = mesh
        self.data_size = mesh.shape[DATA_AXIS]
        self.model_size = mesh.shape[MODEL_AXIS]
        raw = _make_check_fn(
            self.plan, self.config, axis=MODEL_AXIS, jit=False,
            caveat_plan=self.caveat_plan,
        )

        def arr_spec_of(key: str):
            # lookup tables (node type map, caveat context tables, the
            # static possibly-userset pair set — probed whole by every
            # leaf test) are replicated; sorted edge columns shard along
            # the model axis
            if key == "node_type" or key.startswith(("ectx_", "pus_")):
                return P()
            return P(MODEL_AXIS)

        self._arr_spec_of = arr_spec_of
        arr_spec = {k: arr_spec_of(k) for k in self._array_keys()}
        qctx_spec = {k: P() for k in ("vi", "vf", "pr", "host")}
        in_specs = (
            arr_spec, P(), P(),  # arrays, tid_map, now
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # u_*
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # q_res, q_perm, q_subj
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # srel, wc, row, self
            P(DATA_AXIS),  # q_ctx
            qctx_spec,
        )
        out_specs = (P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS))
        self._fn = jax.jit(
            shard_map(
                raw, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **_SHARD_MAP_NO_CHECK,
            )
        )
        #: shard_mapped flat kernels per (slots, FlatMeta, array keys)
        self._flat_sharded_fns: Dict = {}

    def _array_keys(self):
        # single source of truth for the column set lives in DeviceEngine
        # (ARRAY_COLUMN_KEYS), so a new column added to _host_arrays can't
        # silently diverge from the shard_map specs
        keys = list(DeviceEngine.ARRAY_COLUMN_KEYS)
        if self.caveat_plan is not None:
            keys += ["ectx_vi", "ectx_vf", "ectx_pr", "ectx_host"]
        return keys

    # -- flat (bucket-sharded) path ---------------------------------------
    @staticmethod
    def _flat_spec_of(key: str):
        """Sharded flat tables split on the leading (stacked) axis; node
        types, stored-context tables, and the delta-sized ``dl_*``
        overlays are replicated."""
        if key == "node_type" or key.startswith(("ectx_", "dl_")):
            return P()
        return P(MODEL_AXIS)

    @staticmethod
    def _part_spec_of(key: str):
        """Partitioned-serve placement (FlatMeta.part_serve): the
        O(E)-scale point tables (primary, fold, T join) split along the
        model axis; every other stacked table is membership-/group-
        structure-sized and resident whole per device (the kernel
        resolves their bucket owners arithmetically — no collective at
        those sites)."""
        from ..engine.flat import PART_SHARDED_KEYS

        return P(MODEL_AXIS) if key in PART_SHARDED_KEYS else P()

    def _spec_fn_for(self, meta):
        return self._part_spec_of if (
            meta is not None and meta.part_serve
        ) else self._flat_spec_of

    def _flat_sharded_fn(
        self, slots: Tuple[int, ...], meta, arr_keys, routed: bool = False
    ):
        """Cache of shard_mapped flat kernels per (slots, meta, keys,
        routed).  A ROUTED kernel takes the query matrix split along the
        model axis (each shard holds exactly the queries whose root
        bucket it owns) and compiles with no collectives; the plain
        kernel replicates the batch along model and psums the e/pf
        sites (part_serve) or every site (classic stacked layout)."""
        key = (slots, meta, arr_keys, routed)
        fn = self._flat_sharded_fns.get(key)
        if fn is not None:
            return fn
        from ..engine.flat import make_flat_fn

        raw = make_flat_fn(
            self.compiled, self.plan, self.config, meta, slots,
            caveat_plan=self.caveat_plan, jit=False,
            axis=MODEL_AXIS, model_size=self.model_size,
            routed=routed,
        )
        spec_of = self._spec_fn_for(meta)
        arr_spec = {k: spec_of(k) for k in arr_keys}
        qctx_spec = {k: P() for k in ("vi", "vf", "pr", "host")}
        batch_axis = MODEL_AXIS if routed else DATA_AXIS
        in_specs = (
            arr_spec, P(), P(),  # arrays, tid_map, now
            P(None, batch_axis),  # packed query matrix (flat.QM_LAYOUT)
            qctx_spec,
        )
        fn = jax.jit(
            shard_map(
                raw, mesh=self.mesh, in_specs=in_specs,
                out_specs=(P(batch_axis),) * 3,
                **_SHARD_MAP_NO_CHECK,
            )
        )
        while len(self._flat_sharded_fns) >= self.FLAT_FN_CACHE_MAX:
            self._flat_sharded_fns.pop(next(iter(self._flat_sharded_fns)))
        self._flat_sharded_fns[key] = fn
        return fn

    def _routable(self, meta, slots) -> bool:
        """A batch owner-routes iff every root probe a query can make is
        local on its owner shard: all slots are either fully folded
        permissions (pf probe pair) or bare relation leaves (dynamic
        e/KU sites keyed by the query's own (k1, k2)); wildcard edges
        probe a SECOND e/pf bucket whose owner differs, so worlds with
        them keep the psum path.  T-probing slots (meta.t_slots) are
        unroutable too: the T join is model-split under part-serve and
        its bucket geometry differs from the routing geometry, so only
        the psum path's ownership-mask probe is exact there (the KU
        walk those slots compile alongside probes whole-resident
        membership tables and stays local)."""
        if meta.has_wc_edges or meta.pf_haswc:
            return False
        if meta.has_tindex and any(s in meta.t_slots for s in slots):
            return False
        dm = meta.delta
        fold_on = bool(meta.fold_pairs) and not (
            dm is not None and dm.pf_off
        )
        folded = frozenset(meta.fold_pairs) if fold_on else frozenset()
        unfolded = {
            s for (tname, _tid, s, _e) in self.plan.topo_programs
            if (tname, s) not in folded
        }
        return all(s not in unfolded for s in slots)

    # -- snapshot preparation: pad every view to a multiple of model_size --
    def prepare(
        self, snap: Snapshot, prev: Optional[DeviceSnapshot] = None
    ) -> DeviceSnapshot:
        """With ``prev`` (the previous revision's sharded DeviceSnapshot),
        try the incremental path first: the bucket-sharded base tables
        stay resident on their shards, and only the small REPLICATED
        ``dl_*`` overlay ships per revision — the multi-host Watch-driven
        re-index costs O(delta), not O(E/M)·M, per revision."""
        if prev is not None:
            out = self._prepare_delta(snap, prev)
            if out is not None:
                return out
        if (
            self.config.use_flat
            and self.config.flat_blockslice
            and self.model_size & (self.model_size - 1) == 0
        ):
            from ..engine.flat import build_flat_arrays_sharded

            built = build_flat_arrays_sharded(
                snap, self.config, self.model_size, plan=self.plan
            )
            if built is not None:
                flat_arrays, flat_meta, fold_state, _cstate = built
                host = dict(flat_arrays)
                host["node_type"] = _pad_payload(
                    snap.node_type, _ceil_pow2(2 * snap.num_nodes), -1
                )
                ectx, strings = self._ectx_tables(snap)
                host.update(ectx)
                arrays = {
                    k: jax.device_put(
                        v, NamedSharding(self.mesh, self._flat_spec_of(k))
                    )
                    for k, v in host.items()
                }
                self.record_device_bytes(arrays)
                tid_map = np.full(
                    max(self.plan.num_schema_types, 1), -1, dtype=np.int32
                )
                for tname, tid in self.compiled.type_ids.items():
                    tid_map[tid] = snap.interner.type_lookup(tname)
                return DeviceSnapshot(
                    revision=snap.revision,
                    arrays=arrays,
                    tid_map=jnp.asarray(tid_map),
                    snapshot=snap,
                    strings=strings,
                    flat_meta=flat_meta,
                    fold_state=fold_state,
                )
        return self._prepare_legacy(snap)

    def prepare_partitioned(self, part) -> DeviceSnapshot:
        """DeviceSnapshot from a bucket-partitioned feed
        (engine/partition.py partition_feed): the O(E) stacked tables
        exist host-side ONLY for this process's owned shards
        (ShardSlices); ``jax.make_array_from_callback`` asks for exactly
        the addressable blocks, so assembling the global arrays never
        materializes the full table on any host.  Replicated tables
        (node types, contexts, dl_* — and the closure-derived stacks,
        which every process builds whole from the replicated membership
        subgraph) ship via the ordinary replicated device_put.

        A ``serve="routed"`` feed (FlatMeta.part_serve) places the
        O(E)-scale point tables (primary, fold, T join) model-split —
        genuinely disjoint per-device slices, O(E/M) HBM each — and
        everything else whole per device, so owner-routed batches
        dispatch with no collectives (``_dispatch_flat_routed``)."""
        from ..engine.partition import ShardSlices

        snap = part.snapshot
        spec_of = self._spec_fn_for(part.meta)
        host = dict(part.arrays)
        host["node_type"] = _pad_payload(
            snap.node_type, _ceil_pow2(2 * snap.num_nodes), -1
        )
        ectx, strings = self._ectx_tables(snap)
        host.update(ectx)
        arrays = {}
        for k, v in host.items():
            sh = NamedSharding(self.mesh, spec_of(k))
            if isinstance(v, ShardSlices):
                cb = v.block_for
            else:
                # replicated / full tables place via the same callback
                # API: device_put of a replicated array onto a process-
                # spanning mesh runs a consistency-assert COLLECTIVE
                # (multihost_utils.assert_equal), which some CPU jaxlib
                # builds cannot execute — the callback path places local
                # buffers directly and is collective-free by design
                cb = (lambda v: lambda index: v[index])(v)
            arrays[k] = jax.make_array_from_callback(v.shape, sh, cb)
        self.record_device_bytes(arrays)
        tid_map = np.full(
            max(self.plan.num_schema_types, 1), -1, dtype=np.int32
        )
        for tname, tid in self.compiled.type_ids.items():
            tid_map[tid] = snap.interner.type_lookup(tname)
        return DeviceSnapshot(
            revision=snap.revision,
            arrays=arrays,
            tid_map=jnp.asarray(tid_map),
            snapshot=snap,
            strings=strings,
            flat_meta=part.meta,
            fold_state=part.fold_state,
        )

    def prepare_snapshot_partitioned(
        self, snap: Snapshot, prev: Optional[DeviceSnapshot] = None
    ) -> DeviceSnapshot:
        """Partitioned (owner-routed) serve from a resident Snapshot —
        the client's ``with_mesh(partitioned=True)`` path: feed the
        snapshot's raw columns through ``partition_feed(serve="routed")``
        and place with ``prepare_partitioned``.  The incremental path
        rides the partitioned base tables like any sharded snapshot;
        worlds the feed declines (keys past the int32 pack) fall back to
        the ordinary sharded prepare."""
        if prev is not None:
            out = self._prepare_delta(snap, prev)
            if out is not None:
                out.source_snapshot = snap
                return out
        from ..engine.partition import partition_feed, snapshot_raw_columns

        raw = snapshot_raw_columns(snap)
        part = partition_feed(
            snap.revision, snap.compiled, snap.interner, raw,
            self.config, self.model_size,
            contexts=snap.contexts, epoch_us=snap.epoch_us,
            plan=self.plan, serve="routed",
        )
        if part is None:
            return self.prepare(snap)
        out = self.prepare_partitioned(part)
        out.source_snapshot = snap
        return out

    def _delta_prev_ok(self, prev: DeviceSnapshot) -> bool:
        # the sharded incremental prepare rides bucket-sharded base tables
        return prev.flat_meta is not None and prev.flat_meta.sharded

    def _place_replicated(self, v: np.ndarray):
        # overlays are delta-sized: replication beats bucket-sharding and
        # lets the kernel probe them without ownership collectives
        return jax.device_put(v, NamedSharding(self.mesh, P()))

    def _prepare_legacy(self, snap: Snapshot) -> DeviceSnapshot:
        host = self._host_arrays(snap)
        # Model-sharded columns must split evenly across model_size (power
        # of two); the base padding is already pow2, so only meshes wider
        # than the smallest bucket need more.  Sorted key columns keep the
        # I32_MAX sentinel so the padded tail sorts last; payload pads are
        # never read through a matching key.
        sorted_keys = {
            "e_rel", "e_res", "e_subj", "e_srel1", "us_rel", "us_res",
            "ms_subj", "mp_subj", "mp_srel", "ar_rel", "ar_res",
        }
        m = max(8, _ceil_pow2(self.model_size, 1))
        for k, v in list(host.items()):
            if self._arr_spec_of(k) == P(MODEL_AXIS) and v.shape[0] % self.model_size:
                size = _ceil_pow2(v.shape[0], m)
                fill = (2**31 - 1) if k in sorted_keys else -1
                out = np.full(size, fill, v.dtype)
                out[: v.shape[0]] = v
                host[k] = out
        ectx, strings = self._ectx_tables(snap)
        host.update(ectx)
        arrays = {}
        for k, v in host.items():
            arrays[k] = jax.device_put(
                v, NamedSharding(self.mesh, self._arr_spec_of(k))
            )
        tid_map = np.full(max(self.plan.num_schema_types, 1), -1, dtype=np.int32)
        for tname, tid in self.compiled.type_ids.items():
            tid_map[tid] = snap.interner.type_lookup(tname)
        return DeviceSnapshot(
            revision=snap.revision,
            arrays=arrays,
            tid_map=jnp.asarray(tid_map),
            snapshot=snap,
            strings=strings,
        )

    # -- batched check: queries partitioned per data-shard ----------------
    def _dispatch_flat(
        self,
        dsnap: DeviceSnapshot,
        queries: Dict[str, np.ndarray],
        qctx: Dict[str, np.ndarray],
        now_us: Optional[int],
        fetch: bool = True,
        bucket_min: int = 0,
        span=_trace.NOOP,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Dispatch over the bucket-sharded flat tables: queries partition
        along the data axis; the kernel's probe sites OR-reduce over the
        model axis internally (engine/flat.py make_flat_fn with axis).
        On a partitioned-serve snapshot (FlatMeta.part_serve), batches
        whose slot set is routable are owner-routed instead — each model
        shard evaluates only the queries whose root bucket it owns, with
        no collective in the compiled program."""
        faults.fire("sharded.collective")
        snap = dsnap.snapshot
        D = self.data_size
        B = queries["q_res"].shape[0]

        all_slots = sorted(
            {int(s) for s in np.unique(queries["q_perm"]) if s >= 0}
        )
        meta = dsnap.flat_meta
        if (
            meta.part_serve and D == 1 and fetch
            and self._routable(meta, all_slots)
        ):
            return self._dispatch_flat_routed(
                dsnap, queries, qctx, now_us, all_slots,
                bucket_min=bucket_min, span=span,
            )
        per = _ceil_pow2(
            -(-B // D), max(bucket_min, self.config.batch_bucket_min)
        )
        BP = per * D
        now = jnp.int32(snap.now_rel32(now_us))
        # packed query matrix (flat.QM_LAYOUT): batch rides axis 1, which
        # partitions over the data axis — ONE sharded transfer; the rare
        # multi-chunk path (more distinct permissions than
        # flat_max_slots) ships only the small perm row per chunk and
        # splices it on device
        dsh = NamedSharding(self.mesh, P(None, DATA_AXIS))
        rep = NamedSharding(self.mesh, P())
        qm_dev = jax.device_put(build_qm(queries, BP, dsnap.flat_meta), dsh)
        qctx_dev = {k: jax.device_put(v, rep) for k, v in qctx.items()}
        arr_keys = tuple(sorted(dsnap.arrays.keys()))
        # batches with more distinct permissions than flat_max_slots are
        # evaluated in slot chunks (each query's slot lives in exactly one
        # chunk; masked-out queries read -1 → all-false) — the compile
        # cost stays bounded instead of unrolling one program per slot
        cap = max(self.config.flat_max_slots, 1)
        q_perm = queries["q_perm"]
        multi = len(all_slots) > cap
        if multi:
            row_sh = NamedSharding(self.mesh, P(DATA_AXIS))
            # one jitted splice per engine: a fresh jax.jit here would
            # retrace on every multi-chunk dispatch.  BOTH slot-bearing
            # rows splice — leaving row 7 (dense q_perm_k1) unmasked
            # would let masked-out queries drive the dynamic leaf in
            # every chunk and OR in spurious overflow flags
            set_perm = self.__dict__.get("_set_perm_fn")
            if set_perm is None:
                set_perm = jax.jit(
                    lambda q, pc, pk: q.at[1].set(pc).at[7].set(pk),
                    out_shardings=dsh,
                )
                self._set_perm_fn = set_perm
            from ..engine.flat import _dense_np

            k1d = _dense_np(dsnap.flat_meta.k1_dense)
        d = p = ovf = None
        for at in range(0, max(len(all_slots), 1), cap):
            chunk = tuple(all_slots[at : at + cap])
            if multi:
                pc = np.full(BP, -1, np.int32)
                pc[:B] = np.where(
                    np.isin(q_perm, np.asarray(chunk, np.int32)), q_perm, -1
                )
                pk = np.where(
                    pc >= 0, k1d[np.clip(pc, 0, k1d.shape[0] - 1)], -1
                ).astype(np.int32)
                qmc = set_perm(
                    qm_dev,
                    jax.device_put(pc, row_sh),
                    jax.device_put(pk, row_sh),
                )
            else:
                qmc = qm_dev
            fn = self._flat_sharded_fn(chunk, dsnap.flat_meta, arr_keys)
            cd, cp, covf = fn(
                dsnap.arrays, dsnap.tid_map, now, qmc, qctx_dev,
            )
            d = cd if d is None else d | cd
            p = cp if p is None else p | cp
            ovf = covf if ovf is None else ovf | covf
        if not fetch:
            return d, p, ovf
        d, p, ovf = jax.device_get((d, p, ovf))
        return d[:B], p[:B], ovf[:B]

    def _dispatch_flat_routed(
        self,
        dsnap: DeviceSnapshot,
        queries: Dict[str, np.ndarray],
        qctx: Dict[str, np.ndarray],
        now_us: Optional[int],
        all_slots,
        bucket_min: int = 0,
        span=_trace.NOOP,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Owner-routed dispatch over a partitioned-serve snapshot: each
        query is hashed by its root (k1, k2) bucket on the HOST and
        grouped to its owner shard before H2D, so each device dispatches
        only against its owned primary/fold slices — O(E/M) HBM per
        device — and the compiled program contains no collective (the
        membership/group tables are whole per device; engine/flat.py
        make_flat_fn routed=True).  Folded-slot queries route by the pf
        geometry, everything else by the primary geometry — same mix32,
        different modulus.  The model-split T join is never probed here:
        _routable keeps T-probing slots on the psum path."""
        import time as _time

        from ..engine.flat import QM_ROWS, _dense_np
        from ..engine.hash import mix32
        from ..engine.partition import shard_owner
        from ..utils import metrics as _metrics

        meta = dsnap.flat_meta
        M = self.model_size
        B = queries["q_res"].shape[0]
        _t0 = _time.perf_counter()
        qmh = build_qm(queries, B, meta)  # [8, B] dense-mapped host matrix
        k1 = (qmh[7].astype(np.int64) * meta.N + qmh[0]).astype(np.int32)
        k2 = (qmh[2].astype(np.int64) * meta.S1 + qmh[3]).astype(np.int32)
        h = mix32([k1, k2], np)
        e_size = (
            int(dsnap.arrays["eh_off"].shape[0]) // M - 1
        ) * M
        owner = shard_owner(h, e_size, M).astype(np.int64)
        pf_slots = sorted({s for _, s in meta.fold_pairs})
        if pf_slots and "pfh_off" in dsnap.arrays:
            pf_size = (
                int(dsnap.arrays["pfh_off"].shape[0]) // M - 1
            ) * M
            pf_owner = shard_owner(h, pf_size, M).astype(np.int64)
            is_pf = np.isin(qmh[1], np.asarray(pf_slots, np.int32))
            owner = np.where(is_pf, pf_owner, owner)
        # invalid / self queries probe nothing that needs locality
        owner = np.where((qmh[0] < 0) | (qmh[1] < 0), 0, owner)
        counts = np.bincount(owner, minlength=M)
        per = _ceil_pow2(
            int(counts.max()), max(bucket_min, self.config.batch_bucket_min)
        )
        order = np.argsort(owner, kind="stable")
        starts = np.cumsum(counts) - counts
        pos = np.arange(B, dtype=np.int64) - np.repeat(starts, counts)
        dst = np.empty(B, np.int64)
        dst[order] = owner[order] * per + pos
        qm_r = np.full((QM_ROWS, M * per), -1, np.int32)
        qm_r[3] = qm_r[6] = 0
        qm_r[:, dst] = qmh
        route_s = _time.perf_counter() - _t0
        _metrics.default.observe("dispatch.route_s", route_s)
        span.event(
            "route",
            shard_batches=[int(c) for c in counts],
            pad_per_shard=int(per),
            exchange_bytes=int(qm_r.nbytes),
        )

        # NOTE: no faults.fire here — _dispatch_flat already fired
        # "sharded.collective" for this dispatch before routing; firing
        # again would double-count injections on the routed path
        now = jnp.int32(dsnap.snapshot.now_rel32(now_us))
        dsh = NamedSharding(self.mesh, P(None, MODEL_AXIS))
        rep = NamedSharding(self.mesh, P())
        qctx_dev = {k: jax.device_put(v, rep) for k, v in qctx.items()}
        arr_keys = tuple(sorted(dsnap.arrays.keys()))
        cap = max(self.config.flat_max_slots, 1)
        k1d = _dense_np(meta.k1_dense)
        d = p = ovf = None
        for at in range(0, max(len(all_slots), 1), cap):
            chunk = tuple(all_slots[at : at + cap])
            if len(all_slots) > cap:
                # multi-chunk: splice the slot rows on the ROUTED layout
                # host-side (rare path — distinct permissions > cap)
                qmc_h = qm_r.copy()
                pc = qm_r[1]
                keep = np.isin(pc, np.asarray(chunk, np.int32))
                qmc_h[1] = np.where(keep, pc, -1)
                qmc_h[7] = np.where(
                    keep & (pc >= 0),
                    k1d[np.clip(pc, 0, k1d.shape[0] - 1)], -1,
                ).astype(np.int32)
                qm_dev = jax.device_put(qmc_h, dsh)
            else:
                qm_dev = jax.device_put(qm_r, dsh)
            fn = self._flat_sharded_fn(chunk, meta, arr_keys, routed=True)
            cd, cp, covf = fn(
                dsnap.arrays, dsnap.tid_map, now, qm_dev, qctx_dev,
            )
            d = cd if d is None else d | cd
            p = cp if p is None else p | cp
            ovf = covf if ovf is None else ovf | covf
        d, p, ovf = jax.device_get((d, p, ovf))
        span.event("unroute")
        return (
            np.asarray(d)[dst], np.asarray(p)[dst], np.asarray(ovf)[dst]
        )

    def _dispatch_columns(
        self,
        dsnap: DeviceSnapshot,
        queries: Dict[str, np.ndarray],
        qctx: Dict[str, np.ndarray],
        now_us: Optional[int],
        fetch: bool = True,
        bucket_min: int = 0,
        span=_trace.NOOP,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Partition query columns across the data axis, compute per-shard
        unique (subject, context) closure rows, and dispatch the
        shard_mapped check.  ``queries`` holds length-B columns (q_res,
        q_perm, q_subj, q_srel, q_wc, q_ctx, q_self); q_row is derived
        here per shard.  With ``fetch=False`` the raw padded sharded
        device outputs (length BP ≥ B) are returned for pipelined
        dispatch, mirroring DeviceEngine.check_columns.  A sampled
        ``span`` records a ``sharded.dispatch`` child (partition /
        collective / fetch stage events)."""
        faults.fire("sharded.dispatch")
        ssp = span.child(
            "sharded.dispatch",
            batch=int(queries["q_res"].shape[0]),
            data=self.data_size, model=self.model_size,
        )
        try:
            if dsnap.flat_meta is not None:
                with _trace.annotate_dispatch(span):
                    return self._dispatch_flat(
                        dsnap, queries, qctx, now_us, fetch,
                        bucket_min=bucket_min, span=ssp,
                    )
            snap = dsnap.snapshot
            D = self.data_size
            B = queries["q_res"].shape[0]
            per = _ceil_pow2(-(-B // D), self.config.batch_bucket_min)
            BP = per * D

            q = {
                k: np.full(BP, -1 if v.dtype != bool else 0, v.dtype)
                for k, v in queries.items()
                if k != "q_row"
            }
            for k in q:
                q[k][:B] = queries[k]
            # per-data-shard unique subjects (each shard computes closures only
            # for its own slice of the batch)
            subj_key = np.stack(
                [q["q_subj"], q["q_srel"], q["q_wc"], q["q_ctx"]], axis=1
            )
            ulists = []
            rows = np.zeros(BP, np.int32)
            for s in range(D):
                blk = slice(s * per, (s + 1) * per)
                uniq, inv = np.unique(subj_key[blk], axis=0, return_inverse=True)
                ulists.append(uniq)
                rows[blk] = inv.astype(np.int32)
            UP = _ceil_pow2(max(u.shape[0] for u in ulists), self.config.batch_bucket_min)
            u_subj = np.full(D * UP, -1, np.int32)
            u_srel = np.full(D * UP, -1, np.int32)
            u_wc = np.full(D * UP, -1, np.int32)
            u_qctx = np.full(D * UP, -1, np.int32)
            for s, uniq in enumerate(ulists):
                n = uniq.shape[0]
                u_subj[s * UP : s * UP + n] = uniq[:, 0]
                u_srel[s * UP : s * UP + n] = uniq[:, 1]
                u_wc[s * UP : s * UP + n] = uniq[:, 2]
                u_qctx[s * UP : s * UP + n] = uniq[:, 3]
            q["q_row"] = rows
            ssp.event("stage.partition")

            faults.fire("sharded.collective")
            now = jnp.int32(snap.now_rel32(now_us))
            dsh = NamedSharding(self.mesh, P(DATA_AXIS))
            rep = NamedSharding(self.mesh, P())

            def put(a):
                return jax.device_put(a, dsh)

            with _trace.annotate_dispatch(span):
                d, p, ovf = self._fn(
                    dsnap.arrays, dsnap.tid_map, now,
                    put(u_subj), put(u_srel), put(u_wc), put(u_qctx),
                    put(q["q_res"]), put(q["q_perm"]), put(q["q_subj"]),
                    put(q["q_srel"]), put(q["q_wc"]), put(q["q_row"]), put(q["q_self"]),
                    put(q["q_ctx"]),
                    {k: jax.device_put(v, rep) for k, v in qctx.items()},
                )
            ssp.event("stage.collective")
            if not fetch:
                return d, p, ovf
            d, p, ovf = jax.device_get((d, p, ovf))
            ssp.event("stage.fetch")
            return d[:B], p[:B], ovf[:B]
        finally:
            ssp.end()

    def check_batch(
        self,
        dsnap: DeviceSnapshot,
        rels: Sequence[Relationship],
        *,
        now_us: Optional[int] = None,
        latency: bool = False,  # accepted for Client parity; the latency
        # path is single-chip (engine/latency.py), so it's ignored here
        span=_trace.NOOP,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not rels:
            z = np.zeros(0, bool)
            return z, z, z
        queries, _, qctx = self._lower_queries(dsnap.snapshot, rels, dsnap.strings)
        return self._dispatch_columns(dsnap, queries, qctx, now_us, span=span)

    # -- owner-routed lookup hops (engine/spmv.py frontier SpMV) ----------
    def lookup_hops_for(self, dsnap: DeviceSnapshot, kern):
        """The sharded hop backend of the lookup frontier engine: each
        hop's frontier keys are grouped to their OWNER shard host-side
        (high bits of the reverse-index bucket — only owner-crossing
        IDs move), and the single-shard probe/emit bodies run
        shard_mapped over the model axis with no collective (inside a
        shard the stacked off/table blocks have exactly the
        single-chip shapes, so the bodies are shared verbatim)."""
        return _ShardedLookupHops(self, dsnap, kern)

    def check_columns(
        self,
        dsnap: DeviceSnapshot,
        q_res: np.ndarray,
        q_perm: np.ndarray,
        q_subj: np.ndarray,
        *,
        q_srel: Optional[np.ndarray] = None,
        q_wc: Optional[np.ndarray] = None,
        q_ctx: Optional[np.ndarray] = None,
        qctx_rows=None,
        now_us: Optional[int] = None,
        fetch: bool = True,
        bucket_min: int = 0,
    ):
        """Columnar bulk check with the sharded layout (the base-class fast
        path assumes an unsharded q_row/uniq table, which would be wrong
        under shard_map — see _dispatch_columns).  ``bucket_min`` raises
        the per-data-shard padding floor, matching DeviceEngine."""
        queries, qctx = self._columns_preamble(
            dsnap, q_res, q_perm, q_subj, q_srel, q_wc, q_ctx, qctx_rows
        )
        return self._dispatch_columns(
            dsnap, queries, qctx, now_us, fetch=fetch, bucket_min=bucket_min
        )


# ---------------------------------------------------------------------------
# owner-routed lookup hops (engine/spmv.py frontier SpMV over the
# bucket-sharded reverse-CSR tables)
# ---------------------------------------------------------------------------


class _ShardedLookupHops:
    """One DeviceSnapshot's routed hop executor.  A hop:

    1. HOST: owner of each frontier key = high bits of its reverse-index
       bucket (the partition discipline of engine/partition.py) — keys
       group into per-owner blocks, so the only bytes that cross shards
       are the owner-crossing frontier IDs themselves;
    2. DEVICE: the shard_mapped probe body finds each key's contiguous
       run in ITS shard's block (local bucket = low bits — the stacked
       layout guarantees a key's rows live wholly on its owner), then
       budgeted emission kernels stream the matches per shard, each
       shard walking its own chunk cursor;
    3. HOST: merged live rows feed the frontier engine exactly like the
       single-chip path (engine/spmv.py FrontierState).

    The compiled programs contain NO collective — routing made every
    probe local by construction, mirroring _dispatch_flat_routed."""

    #: probe-argument table per hop kind: (off key, rows-table key)
    _TABS = {
        "rv": ("rv_off", "rvx"),
        "ra": ("ra_off", "rax"),
        "fw": ("fw_off", "fwx"),
        "arg": ("arr_off", "argx"),
    }

    def __init__(self, engine: ShardedEngine, dsnap: DeviceSnapshot, kern):
        self.engine = engine
        self.dsnap = dsnap
        self.kern = kern
        self.M = engine.model_size
        self.mesh = engine.mesh
        self._fns: Dict = engine.__dict__.setdefault("_lookup_hop_fns", {})
        self._dummy = jnp.zeros(1, jnp.int32)

    def _fn_pair(self, kind: str):
        """(runs_fn, emit_fn) shard_mapped over the model axis, cached
        per (meta, kind) on the engine."""
        key = (self.dsnap.flat_meta, kind)
        got = self._fns.get(key)
        if got is not None:
            return got
        MP = P(MODEL_AXIS)
        runs = jax.jit(shard_map(
            self.kern.raw_runs[kind], mesh=self.mesh,
            in_specs=(MP, P(), MP, MP), out_specs=(MP, MP),
            **_SHARD_MAP_NO_CHECK,
        ))
        body = self.kern.raw_emits[kind]
        CH = self.kern.CH  # fixed chunk per shard (static under jit)
        emit = jax.jit(shard_map(
            lambda t, l, n, c0, nw: body(t, l, n, c0, nw, CH),
            mesh=self.mesh,
            in_specs=(MP, MP, MP, MP, P()), out_specs=(MP, MP),
            **_SHARD_MAP_NO_CHECK,
        ))
        got = (runs, emit)
        while len(self._fns) >= 16:
            self._fns.pop(next(iter(self._fns)))
        self._fns[key] = got
        return got

    def expand(self, kind: str, keys: np.ndarray, now):
        """Generator of live row blocks for ``keys`` over one view —
        the sharded mirror of FrontierKernels.expand."""
        from ..engine.hash import mix32 as _mix
        from ..engine.spmv import _mt
        from ..utils import faults as _faults

        if keys.shape[0] == 0:
            return
        _faults.fire("lookup.dispatch")
        arrs = self.dsnap.arrays
        off_key, tbl_key = self._TABS[kind]
        off, tbl = arrs[off_key], arrs[tbl_key]
        # emission gathers rows from the arx view for arrow hops (the
        # group table only resolves ranges)
        emit_tbl = arrs["arx"] if kind == "arg" else tbl
        M = self.M
        bpd = off.shape[0] // M - 1
        size = bpd * M
        kk = np.ascontiguousarray(keys, np.int32)
        h = _mix([kk], np)
        owner = ((h & np.uint32(size - 1)) >> np.uint32(
            bpd.bit_length() - 1
        )).astype(np.int64)
        counts = np.bincount(owner, minlength=M)
        per = 1 << max(int(counts.max()) - 1, 0).bit_length()
        per = max(per, self.kern.F_min)
        routed = np.full(M * per, -1, np.int32)
        order = np.argsort(owner, kind="stable")
        starts = np.cumsum(counts) - counts
        # rank within the owner group, aligned with the sorted order
        rank = np.arange(kk.shape[0], dtype=np.int64) - np.repeat(
            starts, counts
        )
        routed[owner[order] * per + rank] = kk[order]
        runs_fn, emit_fn = self._fn_pair(kind)
        lo, ln = runs_fn(off, self._dummy, tbl, jnp.asarray(routed))
        _mt.inc("lookup.hops")
        totals = np.asarray(ln).reshape(M, per).sum(axis=1)
        CH = self.kern.CH
        at = np.zeros(M, np.int64)
        nowj = jnp.asarray(now)
        while bool((at < totals).any()):
            rows, live = emit_fn(
                emit_tbl, lo, ln, jnp.asarray(at.astype(np.int32)), nowj
            )
            rows, live = jax.device_get((rows, live))
            got = rows[live]
            if got.shape[0]:
                yield got
            at = np.minimum(at + CH, totals)

"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    data: int = 1,
    model: int = 1,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data × model) device mesh.  ``data`` shards the query
    batch; ``model`` shards the edge columns."""
    devices = list(devices if devices is not None else jax.devices())
    need = data * model
    if len(devices) < need:
        raise ValueError(f"mesh {data}x{model} needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def default_mesh(model: int = 1) -> Mesh:
    """All available devices, with ``model`` of them dedicated to edge
    sharding and the rest to data parallelism."""
    n = len(jax.devices())
    if n % model != 0:
        raise ValueError(f"{n} devices not divisible by model={model}")
    return make_mesh(n // model, model)

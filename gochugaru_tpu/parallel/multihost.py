"""Multi-host (multi-process) deployment: jax.distributed startup, a
global mesh spanning processes, and the 2-process CPU dryrun that proves
the bucket-sharded tables + replicated overlays work across process
boundaries.

This is the distributed-communication backend SURVEY.md §5 maps from the
reference's gRPC process boundary (/root/reference/client/client.go:31-61):
collectives ride ICI *within* a slice and DCN *across* slices, selected
by XLA from the mesh layout — the code is identical either way.

Deployment shape for BASELINE config 5's v5e-16 (two v5e-8 slices):

- one process per host; each calls :func:`initialize` (coordinator =
  host 0), then builds the SAME snapshot tables from its replicated
  store feed — the standard multihost pattern: identical host inputs +
  ``jax.device_put(x, NamedSharding(global_mesh, spec))`` yield one
  consistent global array.
- mesh ``(data, model)`` from :func:`global_mesh`: the model (edge-
  bucket) axis should stay WITHIN a slice so the per-probe psum-OR /
  single-owner broadcasts ride ICI; the data (query-batch) axis crosses
  slices over DCN, where the only traffic is the per-dispatch query
  matrix and the result planes (no per-probe collectives cross DCN).
  ``global_mesh`` lays devices out process-major, which produces exactly
  that split when ``data`` is a multiple of the process count.
- Watch deltas: the ``dl_*`` overlays are replicated (engine/flat.py),
  so each host ships the same small overlay per revision — the
  cross-host delta path costs O(delta) per host, never O(E/M)·M.

The dryrun (driver hook: ``__graft_entry__.dryrun_multichip``'s
multi-process mode) runs this file as a module in N spawned processes on
the CPU backend (the moral equivalent of serve-testing, SURVEY.md §4)
and verifies every process's local result shards against the host
oracle.  Both 2-process (4 devices each) and 4-process (2 devices each)
splits are exercised by tests/test_multihost.py.

Measured per-dispatch collective accounting (StableHLO lowering of the
shard_mapped flat kernel on the virtual 8-device mesh, feature schema
with walked userset/arrow/exclusion sites — r05):

- every collective is an ``all_reduce`` whose replica groups span ONLY
  the model axis (e.g. ``[[0,1],[2,3],[4,5],[6,7]]`` on a 4x2 mesh):
  the per-probe psum-OR / single-owner broadcasts stay within a data
  row, i.e. on ICI when the model axis is laid out within a slice;
- count: 17 reduces/dispatch on the feature schema (one per walked
  probe site); a fully folded schema (config-2 shape) drops to 6;
- payload: int32[B/data] per reduce -> 17 B per query per dispatch
  crossing ICI, independent of batch size (measured identical at
  B=8192 and B=131072);
- NOTHING crosses the data axis inside the kernel: the DCN-analogue
  boundary carries only the packed query matrix in (32 B/query) and
  the three result planes out (3 B/query) per dispatch.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` with env-var defaults
    (GOCHUGARU_COORDINATOR / GOCHUGARU_NUM_PROCESSES /
    GOCHUGARU_PROCESS_ID) — call once per process, before any jax
    computation.  On a single process (no env, no args) this is a no-op
    so the same entrypoint serves both deployments."""
    coordinator_address = coordinator_address or os.environ.get(
        "GOCHUGARU_COORDINATOR"
    )
    if num_processes is None:
        num_processes = int(os.environ.get("GOCHUGARU_NUM_PROCESSES") or "1")
    if process_id is None:
        process_id = int(os.environ.get("GOCHUGARU_PROCESS_ID") or "0")
    if num_processes <= 1:
        return
    if not coordinator_address:
        # fail FAST: silently running each host as its own single-process
        # JAX would surface only as a confusing mesh-size error later
        raise ValueError(
            "multi-process init requires a coordinator address "
            "(GOCHUGARU_COORDINATOR) when GOCHUGARU_NUM_PROCESSES > 1"
        )
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(data: int, model: int):
    """A (data × model) mesh over every device of every process,
    process-major: with ``data`` a multiple of the process count, each
    data row's ``model`` group stays within one process/slice (probe
    collectives on ICI; only the batch axis crosses DCN)."""
    from .mesh import make_mesh

    return make_mesh(data, model)


# ---------------------------------------------------------------------------
# 2-process CPU dryrun
# ---------------------------------------------------------------------------


def _worker_main() -> None:
    """One dryrun process: init distributed CPU JAX, build the shared
    world, run the sharded check step over the GLOBAL mesh, verify the
    locally-addressable result rows against the host oracle."""
    from gochugaru_tpu.utils.platform import force_cpu_platform

    n_local = int(os.environ["GOCHUGARU_DRYRUN_LOCAL_DEVICES"])
    force_cpu_platform(n_local)
    initialize()
    import numpy as np

    import jax

    import __graft_entry__ as ge
    from gochugaru_tpu.engine.oracle import T
    from gochugaru_tpu.parallel import ShardedEngine

    pid = jax.process_index()
    n_dev = len(jax.devices())
    model = 2 if n_local % 2 == 0 else 1
    data = n_dev // model
    mesh = global_mesh(data, model)

    cs, snap, oracle, checks = ge._world(n_checks=32)
    engine = ShardedEngine(cs, mesh)
    dsnap = engine.prepare(snap)
    queries, _, qctx = engine._lower_queries(snap, checks, dsnap.strings)
    d, p, ovf = engine._dispatch_columns(
        dsnap, queries, qctx, ge.NOW_US, fetch=False
    )
    # every process verifies ITS addressable shard rows (deduped: the
    # model axis replicates each data shard); row index = the global
    # position on the data-partitioned axis 0
    seen = set()
    checked = 0
    for shard, oshard in zip(d.addressable_shards, ovf.addressable_shards):
        lo = shard.index[0].start or 0
        if lo in seen:
            continue
        seen.add(lo)
        vals = np.asarray(shard.data)
        ovals = np.asarray(oshard.data)
        for j, got in enumerate(vals):
            gi = lo + j
            if gi >= len(checks):
                continue
            assert not ovals[j], (
                f"proc {pid}: unexpected overflow at {checks[gi]} (row {gi})"
            )
            want = oracle.check_relationship(checks[gi]) == T
            assert bool(got) == want, (
                f"proc {pid}: mismatch at {checks[gi]} (row {gi})"
            )
            checked += 1
    print(f"DRYRUN-OK proc={pid} devices={n_dev} mesh={data}x{model} "
          f"verified={checked}/{len(checks)}", flush=True)


def dryrun_multihost(
    n_processes: int = 2, n_devices: int = 8, timeout_s: int = 600
) -> None:
    """Spawn ``n_processes`` CPU processes (each with
    ``n_devices // n_processes`` virtual devices), run the full sharded
    check step over the process-spanning global mesh, and require every
    process to verify its result shards.  The multi-process analogue of
    ``__graft_entry__.dryrun_multichip``."""
    assert n_devices % n_processes == 0
    local = n_devices // n_processes
    # a fresh coordinator port per run: a stale worker from a timed-out
    # previous run holding the hardcoded port would otherwise absorb the
    # new run's joins into a zombie coordinator
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    procs = []
    for pid in range(n_processes):
        env = dict(
            os.environ,
            GOCHUGARU_COORDINATOR=coordinator,
            GOCHUGARU_NUM_PROCESSES=str(n_processes),
            GOCHUGARU_PROCESS_ID=str(pid),
            GOCHUGARU_DRYRUN_LOCAL_DEVICES=str(local),
            JAX_PLATFORMS="cpu",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "gochugaru_tpu.parallel.multihost"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )),
        ))
    outs = []
    ok = True
    for pid, pr in enumerate(procs):
        try:
            out, _ = pr.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            pr.kill()
            out, _ = pr.communicate()
            ok = False
        outs.append(out)
        if pr.returncode != 0 or "DRYRUN-OK" not in (out or ""):
            ok = False
    if not ok:
        for pid, out in enumerate(outs):
            tail = "\n".join((out or "").splitlines()[-12:])
            print(f"--- proc {pid} tail ---\n{tail}", file=sys.stderr)
        raise RuntimeError("multi-host dryrun failed")
    total = 0
    want = None
    for out in outs:
        for line in (out or "").splitlines():
            if line.startswith("DRYRUN-OK"):
                print(line)
                frac = line.rsplit("verified=", 1)[1]
                k, n = frac.split("/")
                total += int(k)
                want = int(n)
    if want is not None and total < want:
        raise RuntimeError(
            f"dryrun shards covered only {total}/{want} checks across"
            " processes — data-axis partitioning is dropping rows"
        )


if __name__ == "__main__":
    _worker_main()

"""Multi-host (multi-process) deployment: jax.distributed startup, a
global mesh spanning processes, and the 2-process CPU dryrun that proves
the bucket-sharded tables + replicated overlays work across process
boundaries.

This is the distributed-communication backend SURVEY.md §5 maps from the
reference's gRPC process boundary (/root/reference/client/client.go:31-61):
collectives ride ICI *within* a slice and DCN *across* slices, selected
by XLA from the mesh layout — the code is identical either way.

Deployment shape for BASELINE config 5's v5e-16 (two v5e-8 slices):

- one process per host; each calls :func:`initialize` (coordinator =
  host 0), then builds the SAME snapshot tables from its replicated
  store feed — the standard multihost pattern: identical host inputs +
  ``jax.device_put(x, NamedSharding(global_mesh, spec))`` yield one
  consistent global array.
- mesh ``(data, model)`` from :func:`global_mesh`: the model (edge-
  bucket) axis should stay WITHIN a slice so the per-probe psum-OR /
  single-owner broadcasts ride ICI; the data (query-batch) axis crosses
  slices over DCN, where the only traffic is the per-dispatch query
  matrix and the result planes (no per-probe collectives cross DCN).
  ``global_mesh`` lays devices out process-major, which produces exactly
  that split when ``data`` is a multiple of the process count.
- Watch deltas: the ``dl_*`` overlays are replicated (engine/flat.py),
  so each host ships the same small overlay per revision — the
  cross-host delta path costs O(delta) per host, never O(E/M)·M.

The dryrun (driver hook: ``__graft_entry__.dryrun_multichip``'s
multi-process mode) runs this file as a module in N spawned processes on
the CPU backend (the moral equivalent of serve-testing, SURVEY.md §4)
and verifies every process's local result shards against the host
oracle.  Both 2-process (4 devices each) and 4-process (2 devices each)
splits are exercised by tests/test_multihost.py.

Measured per-dispatch collective accounting (StableHLO lowering of the
shard_mapped flat kernel on the virtual 8-device mesh, feature schema
with walked userset/arrow/exclusion sites — r05):

- every collective is an ``all_reduce`` whose replica groups span ONLY
  the model axis (e.g. ``[[0,1],[2,3],[4,5],[6,7]]`` on a 4x2 mesh):
  the per-probe psum-OR / single-owner broadcasts stay within a data
  row, i.e. on ICI when the model axis is laid out within a slice;
- count: 17 reduces/dispatch on the feature schema (one per walked
  probe site); a fully folded schema (config-2 shape) drops to 6;
- payload: int32[B/data] per reduce -> 17 B per query per dispatch
  crossing ICI, independent of batch size (measured identical at
  B=8192 and B=131072);
- NOTHING crosses the data axis inside the kernel: the DCN-analogue
  boundary carries only the packed query matrix in (32 B/query) and
  the three result planes out (3 B/query) per dispatch.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` with env-var defaults
    (GOCHUGARU_COORDINATOR / GOCHUGARU_NUM_PROCESSES /
    GOCHUGARU_PROCESS_ID) — call once per process, before any jax
    computation.  On a single process (no env, no args) this is a no-op
    so the same entrypoint serves both deployments."""
    coordinator_address = coordinator_address or os.environ.get(
        "GOCHUGARU_COORDINATOR"
    )
    if num_processes is None:
        num_processes = int(os.environ.get("GOCHUGARU_NUM_PROCESSES") or "1")
    if process_id is None:
        process_id = int(os.environ.get("GOCHUGARU_PROCESS_ID") or "0")
    if num_processes <= 1:
        return
    if not coordinator_address:
        # fail FAST: silently running each host as its own single-process
        # JAX would surface only as a confusing mesh-size error later
        raise ValueError(
            "multi-process init requires a coordinator address "
            "(GOCHUGARU_COORDINATOR) when GOCHUGARU_NUM_PROCESSES > 1"
        )
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(data: int, model: int):
    """A (data × model) mesh over every device of every process,
    process-major: with ``data`` a multiple of the process count, each
    data row's ``model`` group stays within one process/slice (probe
    collectives on ICI; only the batch axis crosses DCN)."""
    from .mesh import make_mesh

    return make_mesh(data, model)


def owned_model_shards(mesh):
    """Model-shard indices whose mesh column contains at least one of
    THIS process's devices — the ownership set the feed partition
    materializes rows for (engine/partition.py partition_feed).  On a
    mesh whose model axis spans processes (e.g. ``global_mesh(1, n)``)
    the sets are disjoint and per-process host RSS is O(E·|owned|/M);
    on the within-slice layout every process owns all M shards and the
    win is the O(E/M) build scratch alone."""
    import numpy as np

    import jax

    pid = jax.process_index()
    devs = np.asarray(mesh.devices)
    if devs.ndim == 1:
        devs = devs[None, :]
    return tuple(
        m for m in range(devs.shape[1])
        if any(d.process_index == pid for d in devs[:, m].flat)
    )


# ---------------------------------------------------------------------------
# 2-process CPU dryrun
# ---------------------------------------------------------------------------


def _worker_main() -> None:
    """One dryrun process: init distributed CPU JAX, build the shared
    world, run the sharded check step over the GLOBAL mesh, verify the
    locally-addressable result rows against the host oracle."""
    from gochugaru_tpu.utils.platform import force_cpu_platform

    n_local = int(os.environ["GOCHUGARU_DRYRUN_LOCAL_DEVICES"])
    force_cpu_platform(n_local)
    initialize()
    import numpy as np

    import jax

    import __graft_entry__ as ge
    from gochugaru_tpu.engine.oracle import T
    from gochugaru_tpu.parallel import ShardedEngine

    pid = jax.process_index()
    n_dev = len(jax.devices())
    model = 2 if n_local % 2 == 0 else 1
    data = n_dev // model
    mesh = global_mesh(data, model)

    cs, snap, oracle, checks = ge._world(n_checks=32)
    engine = ShardedEngine(cs, mesh)
    dsnap = engine.prepare(snap)
    queries, _, qctx = engine._lower_queries(snap, checks, dsnap.strings)
    d, p, ovf = engine._dispatch_columns(
        dsnap, queries, qctx, ge.NOW_US, fetch=False
    )

    def verify(d_out, ovf_out) -> int:
        # every process verifies ITS addressable shard rows (deduped: the
        # model axis replicates each data shard); row index = the global
        # position on the data-partitioned axis 0
        seen = set()
        checked = 0
        for shard, oshard in zip(
            d_out.addressable_shards, ovf_out.addressable_shards
        ):
            lo = shard.index[0].start or 0
            if lo in seen:
                continue
            seen.add(lo)
            vals = np.asarray(shard.data)
            ovals = np.asarray(oshard.data)
            for j, got in enumerate(vals):
                gi = lo + j
                if gi >= len(checks):
                    continue
                assert not ovals[j], (
                    f"proc {pid}: unexpected overflow at {checks[gi]} (row {gi})"
                )
                want = oracle.check_relationship(checks[gi]) == T
                assert bool(got) == want, (
                    f"proc {pid}: mismatch at {checks[gi]} (row {gi})"
                )
                checked += 1
        return checked

    checked = verify(d, ovf)

    # partitioned-feed prepare over the SAME world: each process
    # materializes only its owned bucket shards from the feed columns
    # (engine/partition.py), and the dispatch must verify identically
    part_checked = -1
    if os.environ.get("GOCHUGARU_DRYRUN_PARTITION", "1") == "1":
        from gochugaru_tpu.engine.partition import (
            partition_feed,
            snapshot_raw_columns,
        )

        cols = snapshot_raw_columns(snap)
        part = partition_feed(
            snap.revision, cs, snap.interner, cols, engine.config,
            engine.model_size, owned=owned_model_shards(mesh),
            contexts=snap.contexts, epoch_us=ge.NOW_US,
        )
        assert part is not None
        dsnap2 = engine.prepare_partitioned(part)
        d2, _p2, ovf2 = engine._dispatch_columns(
            dsnap2, queries, qctx, ge.NOW_US, fetch=False
        )
        part_checked = verify(d2, ovf2)
        assert part_checked == checked
    print(f"DRYRUN-OK proc={pid} devices={n_dev} mesh={data}x{model} "
          f"verified={checked}/{len(checks)} partitioned={part_checked}",
          flush=True)


def dryrun_multihost(
    n_processes: int = 2, n_devices: int = 8, timeout_s: int = 600
) -> None:
    """Spawn ``n_processes`` CPU processes (each with
    ``n_devices // n_processes`` virtual devices), run the full sharded
    check step over the process-spanning global mesh, and require every
    process to verify its result shards.  The multi-process analogue of
    ``__graft_entry__.dryrun_multichip``."""
    assert n_devices % n_processes == 0
    local = n_devices // n_processes
    # a fresh coordinator port per run: a stale worker from a timed-out
    # previous run holding the hardcoded port would otherwise absorb the
    # new run's joins into a zombie coordinator
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    procs = []
    for pid in range(n_processes):
        env = dict(
            os.environ,
            GOCHUGARU_COORDINATOR=coordinator,
            GOCHUGARU_NUM_PROCESSES=str(n_processes),
            GOCHUGARU_PROCESS_ID=str(pid),
            GOCHUGARU_DRYRUN_LOCAL_DEVICES=str(local),
            JAX_PLATFORMS="cpu",
            # children inherit the parent's probe verdict (or the pin
            # above): a spawned dryrun must never re-pay the bounded
            # 75 s degraded TPU probe per process (benchmarks/run_all.py
            # exports GOCHUGARU_BACKEND_PROBED after ITS probe)
            GOCHUGARU_BACKEND_PROBED=os.environ.get(
                "GOCHUGARU_BACKEND_PROBED", "cpu"
            ),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "gochugaru_tpu.parallel.multihost"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )),
        ))
    outs = []
    ok = True
    for pid, pr in enumerate(procs):
        try:
            out, _ = pr.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            pr.kill()
            out, _ = pr.communicate()
            ok = False
        outs.append(out)
        if pr.returncode != 0 or "DRYRUN-OK" not in (out or ""):
            ok = False
    if not ok:
        for pid, out in enumerate(outs):
            tail = "\n".join((out or "").splitlines()[-12:])
            print(f"--- proc {pid} tail ---\n{tail}", file=sys.stderr)
        raise RuntimeError("multi-host dryrun failed")
    total = 0
    want = None
    for out in outs:
        for line in (out or "").splitlines():
            if line.startswith("DRYRUN-OK"):
                print(line)
                frac = line.rsplit("verified=", 1)[1]
                k, n = frac.split("/")
                total += int(k)
                want = int(n)
    if want is not None and total < want:
        raise RuntimeError(
            f"dryrun shards covered only {total}/{want} checks across"
            " processes — data-axis partitioning is dropping rows"
        )


# ---------------------------------------------------------------------------
# RSS dryrun: the measured host-sharded-build memory claim
# ---------------------------------------------------------------------------

_RSS_EPOCH = 1_700_000_000_000_000


def _raw_rbac_world(edges: int):
    """The GitHub-RBAC world (bench.py build_world's shape) as UNSORTED
    raw feed columns — what a store feed hands partition_feed, generated
    with deterministic arithmetic (no duplicate rows) so every process
    of an RSS dryrun builds the identical feed."""
    import numpy as np

    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner

    schema = """
    definition user {}
    definition team { relation member: user }
    definition org {
        relation admin: user
        relation member: user | team#member
    }
    definition repo {
        relation org: org
        relation maintainer: user | team#member
        relation reader: user
        permission admin = org->admin + maintainer
        permission read = reader + admin + org->member
    }
    """
    cs = compile_schema(parse_schema(schema))
    itn = Interner()
    n_repos = max(edges // 5, 40)
    n_users = max(n_repos // 10, 70)
    n_teams = max(n_users // 10, 8)
    n_orgs = max(n_teams // 10, 2)
    users = np.asarray(
        [itn.node("user", f"u{i}") for i in range(n_users)], np.int32
    )
    teams = np.asarray(
        [itn.node("team", f"t{i}") for i in range(n_teams)], np.int32
    )
    orgs = np.asarray(
        [itn.node("org", f"o{i}") for i in range(n_orgs)], np.int32
    )
    repos = np.asarray(
        [itn.node("repo", f"r{i}") for i in range(n_repos)], np.int32
    )
    slot = cs.slot_of_name
    member, admin = slot["member"], slot["admin"]
    org_rel, maint, reader = slot["org"], slot["maintainer"], slot["reader"]

    res_p, rel_p, subj_p, srel_p = [], [], [], []

    def add(r, rl, s, sr):
        res_p.append(r.astype(np.int32))
        rel_p.append(np.full(r.shape[0], rl, np.int32))
        subj_p.append(s.astype(np.int32))
        srel_p.append(np.full(r.shape[0], sr, np.int32))

    # team edges budgeted to ~edges/5 (repos carry 4/5); capped under
    # n_users/7 so the 7-stride below stays duplicate-free per team
    per_team = max(2, min((edges // 5) // n_teams, n_users // 7))
    t_idx = np.repeat(np.arange(n_teams), per_team)
    k_idx = np.tile(np.arange(per_team), n_teams)
    add(teams[t_idx], member, users[(t_idx * 13 + 7 * k_idx) % n_users], -1)
    o_idx = np.arange(n_orgs)
    add(orgs, admin, users[o_idx % n_users], -1)
    for j in range(2):  # org member usersets: 2 teams each
        add(orgs, member, teams[(o_idx * 3 + j) % n_teams], member)
    for j in range(5):  # org direct members
        add(orgs, member, users[(o_idx * 11 + j) % n_users], -1)
    r_idx = np.arange(n_repos)
    add(repos, org_rel, orgs[r_idx % n_orgs], -1)
    add(repos, maint, teams[r_idx % n_teams], member)
    for j in range(2):
        add(repos, reader, users[(r_idx * 17 + j * 5 + 1) % n_users], -1)

    cols = dict(
        res=np.concatenate(res_p), rel=np.concatenate(rel_p),
        subj=np.concatenate(subj_p), srel=np.concatenate(srel_p),
    )
    return cs, itn, cols, dict(users=users, repos=repos, slot=slot)


def _rss_env_int(name: str, default: int) -> int:
    return int(os.environ.get(name) or str(default))


def _rss_baseline_main() -> None:
    """Single-process reference: full snapshot + the pre-PR
    build-full-then-stack prepare over the same (1 × n_dev) mesh —
    the denominator of the RSS comparison."""
    import json

    from gochugaru_tpu.utils.platform import force_cpu_platform

    n_dev = _rss_env_int("GOCHUGARU_DRYRUN_DEVICES", 8)
    force_cpu_platform(n_dev)
    import jax

    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.parallel import ShardedEngine
    from gochugaru_tpu.store.snapshot import build_snapshot_from_columns
    from gochugaru_tpu.utils.metrics import peak_rss_mb

    edges = _rss_env_int("GOCHUGARU_DRYRUN_EDGES", 1_000_000)
    cs, itn, cols, _info = _raw_rbac_world(edges)
    E = int(cols["res"].shape[0])
    jax.devices()
    base = peak_rss_mb()
    snap = build_snapshot_from_columns(
        1, cs, itn, epoch_us=_RSS_EPOCH, **cols
    )
    del cols
    engine = ShardedEngine(
        cs, global_mesh(1, n_dev),
        EngineConfig.for_schema(cs, flat_partition_build=False),
    )
    dsnap = engine.prepare(snap)
    assert dsnap.flat_meta is not None and dsnap.flat_meta.sharded
    peak = peak_rss_mb()
    print("RSS-BASELINE " + json.dumps(dict(
        edges=E, base_mb=base, peak_mb=peak,
        build_delta_mb=round(peak - base, 1),
    )), flush=True)


def _rss_worker_main() -> None:
    """One multi-process RSS worker: feed-partitioned prepare over a
    mesh whose MODEL axis spans the processes, so ownership is disjoint
    and each process materializes only its share of the feed."""
    import json

    from gochugaru_tpu.utils.platform import force_cpu_platform

    n_local = _rss_env_int("GOCHUGARU_DRYRUN_LOCAL_DEVICES", 4)
    force_cpu_platform(n_local)
    initialize()
    import numpy as np

    import jax

    from gochugaru_tpu.engine.partition import partition_feed
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.parallel import ShardedEngine
    from gochugaru_tpu.utils.metrics import peak_rss_mb

    edges = _rss_env_int("GOCHUGARU_DRYRUN_EDGES", 1_000_000)
    n_dev = len(jax.devices())
    mesh = global_mesh(1, n_dev)
    cs, itn, cols, info = _raw_rbac_world(edges)
    E = int(cols["res"].shape[0])
    base = peak_rss_mb()
    engine = ShardedEngine(cs, mesh, EngineConfig.for_schema(cs))
    owned = owned_model_shards(mesh)
    part = partition_feed(
        1, cs, itn, cols, engine.config, engine.model_size,
        owned=owned, epoch_us=_RSS_EPOCH,
    )
    assert part is not None
    dsnap = engine.prepare_partitioned(part)
    peak = peak_rss_mb()
    print("RSS-OK " + json.dumps(dict(
        proc=int(jax.process_index()), owned=list(owned), edges=E,
        local_rows=int(part.snapshot.e_rel.shape[0]),
        base_mb=base, peak_mb=peak,
        build_delta_mb=round(peak - base, 1),
    )), flush=True)
    # dispatch smoke: some CPU jaxlib builds cannot run multiprocess
    # collectives at all — the BUILD is this mode's claim; correctness
    # of the tables is pinned by the parity child + the partitioned
    # single-process dispatch suites (tests/test_feed_partition.py)
    try:
        rng = np.random.default_rng(3)
        B = 1024
        d, _p, ovf = engine.check_columns(
            dsnap,
            rng.choice(info["repos"], B).astype(np.int32),
            np.full(B, info["slot"]["read"], np.int32),
            rng.choice(info["users"], B).astype(np.int32),
            now_us=_RSS_EPOCH,
        )
        assert not ovf.any()
        print(f"RSS-DISPATCH-OK granted={int(d.sum())}/{B}", flush=True)
    except Exception as e:  # noqa: BLE001 — reported, not fatal
        print(
            f"RSS-DISPATCH-SKIP {type(e).__name__}: {str(e)[:140]}",
            flush=True,
        )


def _rss_parity_main() -> None:
    """Single-process bitwise check at the harness's world shape: the
    feed-partitioned tables == the pre-PR builder's, array for array."""
    import numpy as np

    from gochugaru_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(_rss_env_int("GOCHUGARU_DRYRUN_DEVICES", 8))
    from gochugaru_tpu.engine.flat import build_flat_arrays_sharded
    from gochugaru_tpu.engine.partition import ShardSlices, partition_feed
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.store.snapshot import build_snapshot_from_columns

    edges = min(_rss_env_int("GOCHUGARU_DRYRUN_EDGES", 1_000_000), 300_000)
    M = _rss_env_int("GOCHUGARU_DRYRUN_DEVICES", 8)
    cs, itn, cols, _info = _raw_rbac_world(edges)
    snap = build_snapshot_from_columns(
        1, cs, itn, epoch_us=_RSS_EPOCH,
        **{k: v.copy() for k, v in cols.items()},
    )
    cfg = EngineConfig.for_schema(cs)
    # the reference MUST be the pre-PR build-full-then-stack path — with
    # the partition-first default both sides would share the new
    # machinery and a shared bug would cancel out of the comparison
    legacy = EngineConfig.for_schema(cs, flat_partition_build=False)
    built = build_flat_arrays_sharded(snap, legacy, M, plan=None)
    assert built is not None
    ref, ref_meta, _f, _c = built
    part = partition_feed(1, cs, itn, cols, cfg, M, epoch_us=_RSS_EPOCH)
    assert part is not None and part.meta == ref_meta
    assert set(part.arrays) == set(ref)
    for k in sorted(ref):
        v = part.arrays[k]
        got = v.to_full() if isinstance(v, ShardSlices) else v
        assert np.array_equal(got, ref[k]), f"table {k} differs"
    print(f"PARITY-OK tables={len(ref)} edges={snap.num_edges}", flush=True)


def _spawn_rss(mode: str, extra_env: dict, timeout_s: int):
    env = dict(
        os.environ,
        GOCHUGARU_DRYRUN_MODE=mode,
        JAX_PLATFORMS="cpu",
        GOCHUGARU_BACKEND_PROBED=os.environ.get(
            "GOCHUGARU_BACKEND_PROBED", "cpu"
        ),
        **extra_env,
    )
    return subprocess.Popen(
        [sys.executable, "-m", "gochugaru_tpu.parallel.multihost"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )),
    )


def _communicate(pr, timeout_s: int):
    try:
        out, _ = pr.communicate(timeout=timeout_s)
        return out or "", pr.returncode
    except subprocess.TimeoutExpired:
        pr.kill()
        out, _ = pr.communicate()
        return out or "", -1


def rss_dryrun(
    edges: int = 1_000_000,
    n_processes: int = 2,
    n_devices: int = 8,
    timeout_s: int = 900,
    max_ratio: float = 0.6,
) -> dict:
    """The measured host-sharded-build memory claim, end to end:

    1. single-process baseline — full snapshot + pre-PR
       build-full-then-stack prepare (``flat_partition_build=False``);
    2. bitwise parity child — feed-partitioned tables == the pre-PR
       builder's at the same world (bounded world size: it must hold
       BOTH builds);
    3. ``n_processes`` jax.distributed workers over a (1 × n_devices)
       mesh (model axis spanning processes → disjoint shard ownership),
       each building ONLY its owned partitions via partition_feed.

    Passes when every worker's build-phase RSS delta (peak − post-
    worldgen base: both paths generate the identical feed, so the delta
    isolates feed→tables memory) is ≤ ``max_ratio`` × the baseline's.
    Returns the summary dict; raises on any failure."""
    import json
    import socket

    env_c = dict(
        GOCHUGARU_DRYRUN_EDGES=str(edges),
        GOCHUGARU_DRYRUN_DEVICES=str(n_devices),
    )
    out, rc = _communicate(
        _spawn_rss("rss-baseline", env_c, timeout_s), timeout_s
    )
    base_line = [l for l in out.splitlines() if l.startswith("RSS-BASELINE ")]
    if rc != 0 or not base_line:
        raise RuntimeError(f"rss baseline failed:\n{out[-2000:]}")
    baseline = json.loads(base_line[0].split(" ", 1)[1])
    print(base_line[0], flush=True)

    out, rc = _communicate(
        _spawn_rss("rss-parity", env_c, timeout_s), timeout_s
    )
    if rc != 0 or "PARITY-OK" not in out:
        raise RuntimeError(f"rss parity failed:\n{out[-2000:]}")
    print([l for l in out.splitlines() if "PARITY-OK" in l][0], flush=True)

    assert n_devices % n_processes == 0
    local = n_devices // n_processes
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    procs = [
        _spawn_rss("rss", dict(
            env_c,
            GOCHUGARU_COORDINATOR=coordinator,
            GOCHUGARU_NUM_PROCESSES=str(n_processes),
            GOCHUGARU_PROCESS_ID=str(pid),
            GOCHUGARU_DRYRUN_LOCAL_DEVICES=str(local),
        ), timeout_s)
        for pid in range(n_processes)
    ]
    workers = []
    dispatch_ok = 0
    for pid, pr in enumerate(procs):
        out, rc = _communicate(pr, timeout_s)
        lines = [l for l in out.splitlines() if l.startswith("RSS-OK ")]
        if rc != 0 or not lines:
            tail = "\n".join(out.splitlines()[-12:])
            raise RuntimeError(f"rss worker {pid} failed:\n{tail}")
        workers.append(json.loads(lines[0].split(" ", 1)[1]))
        print(lines[0], flush=True)
        if "RSS-DISPATCH-OK" in out:
            dispatch_ok += 1
        else:
            skip = [l for l in out.splitlines() if "RSS-DISPATCH-SKIP" in l]
            if skip:
                print(f"# worker {pid}: {skip[0]}", flush=True)
    worst = max(w["build_delta_mb"] for w in workers)
    ratio = worst / max(baseline["build_delta_mb"], 1e-9)
    summary = dict(
        edges=baseline["edges"],
        n_processes=n_processes,
        baseline_build_delta_mb=baseline["build_delta_mb"],
        baseline_peak_mb=baseline["peak_mb"],
        worker_build_delta_mb=[w["build_delta_mb"] for w in workers],
        worker_peak_mb=[w["peak_mb"] for w in workers],
        ratio=round(ratio, 3),
        max_ratio=max_ratio,
        dispatch_verified_workers=dispatch_ok,
    )
    print("RSS-SUMMARY " + json.dumps(summary), flush=True)
    if ratio > max_ratio:
        raise RuntimeError(
            f"per-process build RSS {worst} MB is {ratio:.2f}x the "
            f"single-process {baseline['build_delta_mb']} MB "
            f"(bar: {max_ratio})"
        )
    return summary


def _main() -> None:
    if "--rss" in sys.argv[1:]:
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("--rss", action="store_true")
        ap.add_argument("--edges", type=int, default=1_000_000)
        ap.add_argument("--processes", type=int, default=2)
        ap.add_argument("--devices", type=int, default=8)
        ap.add_argument("--max-ratio", type=float, default=0.6)
        args = ap.parse_args()
        rss_dryrun(
            edges=args.edges, n_processes=args.processes,
            n_devices=args.devices, max_ratio=args.max_ratio,
        )
        return
    mode = os.environ.get("GOCHUGARU_DRYRUN_MODE", "")
    if mode == "rss":
        _rss_worker_main()
    elif mode == "rss-baseline":
        _rss_baseline_main()
    elif mode == "rss-parity":
        _rss_parity_main()
    else:
        _worker_main()


if __name__ == "__main__":
    _main()

"""Multi-chip scaling: mesh construction and the sharded bulk-check engine.

The reference's only distribution machinery is a gRPC channel plus
client-side batching (SURVEY.md §2.5); here the same roles are played by a
``jax.sharding.Mesh`` with two axes:

- ``data``  — queries partitioned across devices (throughput scaling; the
  batch axis of ``CheckBulkPermissions`` spread over chips);
- ``model`` — the sorted edge columns partitioned across devices
  (capacity scaling for graphs beyond one chip's HBM), with per-hop
  all-gather/all-reduce(OR) collectives riding ICI.
"""

from .mesh import default_mesh, make_mesh
from .sharded import ShardedEngine

__all__ = ["make_mesh", "default_mesh", "ShardedEngine"]

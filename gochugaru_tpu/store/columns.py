"""Columnar base segments: the scalable half of the Store.

The reference's BulkImport streams to a server engineered for bulk load
(client/client.go:438-465).  Here the equivalent is this layer: bulk
imports land as immutable int32 column blocks (one per import call) with
a sorted key sidecar, instead of per-edge Python ``Relationship`` objects
in the live dict — the dict stays for small interactive writes.  100M+
edges then cost numpy/native work (batch interning, vectorized
validation by *shape*, sorted-key dedup), not 100M Python objects.

Key packing: an edge key (res, rel, subj, srel1) packs into two int64s
h=(rel<<32)|res, l=(subj<<32)|srel1 (all components non-negative), and a
numpy structured array of (h, l) compares lexicographically — giving
O(log N) existence probes via ``searchsorted`` with no Python sets.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..native.sort import lexsort4
from ..rel.filter import Filter
from ..rel.relationship import Relationship, expiration_micros
from ..schema.compiler import CompiledSchema
from ..utils.errors import SchemaError

KEY_DT = np.dtype([("h", np.int64), ("l", np.int64)])


def pack_keys(
    res: np.ndarray, rel: np.ndarray, subj: np.ndarray, srel1: np.ndarray
) -> np.ndarray:
    out = np.empty(res.shape[0], KEY_DT)
    out["h"] = (rel.astype(np.int64) << 32) | res.astype(np.int64)
    out["l"] = (subj.astype(np.int64) << 32) | srel1.astype(np.int64)
    return out


def filter_columns(
    cols: Mapping[str, np.ndarray], rows: np.ndarray
) -> Dict[str, np.ndarray]:
    """Bucket-filtered column view: one vectorized (native-parallel) take
    per column, shared by the feed-partition path (engine/partition.py)
    — a multihost process keeps only the store-feed rows whose bucket
    shard it owns, as a gather over the feed columns, never a row-wise
    copy of the world.  int64 columns (exact expiry micros, packed keys)
    keep their width; everything else is int32 by construction."""
    from ..native.sort import take32, take64

    idx = np.ascontiguousarray(rows, np.int64)
    return {
        k: take64(v, idx) if v.dtype == np.int64 else take32(v, idx)
        for k, v in cols.items()
    }


class ColumnSegment:
    """One immutable bulk-imported block of edges with a mutable liveness
    mask (TOUCH/DELETE of an imported edge marks its row dead; the
    replacement lives in a newer segment or the live dict)."""

    __slots__ = (
        "res", "rel", "subj", "srel1", "caveat", "ctx", "exp_us",
        "live", "sorder", "_skey_h", "_skey_l",
    )

    def __init__(self, res, rel, subj, srel1, caveat, ctx, exp_us,
                 presorted=None) -> None:
        self.res = res
        self.rel = rel
        self.subj = subj
        self.srel1 = srel1
        self.caveat = caveat
        self.ctx = ctx
        self.exp_us = exp_us
        self.live = np.ones(res.shape[0], bool)
        if presorted is not None:
            # the commit path already key-sorted the batch: reuse its
            # (sorder, h-keys, l-keys) instead of re-sorting 10M rows
            self.sorder, self._skey_h, self._skey_l = presorted
        else:
            # native stable radix lexsort: np.argsort on the structured
            # key dtype is ~10s at 10M rows on this host, lexsort4 ~1.5s
            # (all key components are non-negative, so signed order ==
            # key order).  Only the two contiguous int64 halves are kept
            # — a structured copy would double per-segment key memory
            self.sorder = lexsort4(rel, res, subj, srel1)
            self._skey_h = (
                (rel.astype(np.int64) << 32) | res.astype(np.int64)
            )[self.sorder]
            self._skey_l = (
                (subj.astype(np.int64) << 32) | srel1.astype(np.int64)
            )[self.sorder]

    def __len__(self) -> int:
        return int(self.res.shape[0])

    @property
    def live_count(self) -> int:
        return int(np.count_nonzero(self.live))

    # -- key probes ------------------------------------------------------
    def rows_of_sorted_halves(
        self, qh: np.ndarray, ql: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(hit_mask, row_index) per query for queries ALREADY lexsorted
        by (h, l): one native linear merge against the segment's sorted
        keys (native/sort.py join_sorted2) — the bulk-import dup-probe
        path, O(E + B) with no per-key bisection."""
        from ..native.sort import join_sorted2

        n = int(self._skey_h.shape[0])
        hit = np.zeros(qh.shape[0], bool)
        rows = np.zeros(qh.shape[0], np.int64)
        if n:
            pos = join_sorted2(self._skey_h, self._skey_l, qh, ql)
            found = pos >= 0
            rows = self.sorder[np.clip(pos, 0, n - 1)]
            hit = found & self.live[rows]
        return hit, rows

    def rows_of_keys(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(hit_mask, row_index) per query key; only LIVE rows hit.  Keys
        are unique within a segment, so at most one row matches.

        The probe is a two-level int64 search over the (h, l) halves —
        np.searchsorted on the structured KEY_DT dtype falls off numpy's
        fast path (~4us per lookup, 37s for a 10M-row batch); the split
        search is plain int64 bisection (~100x faster)."""
        from .delta import find_in_view

        n = int(self._skey_h.shape[0])
        hit = np.zeros(keys.shape[0], bool)
        rows = np.zeros(keys.shape[0], np.int64)
        if n:
            pos = find_in_view(
                self._skey_h, self._skey_l,
                np.ascontiguousarray(keys["h"]),
                np.ascontiguousarray(keys["l"]),
            )
            found = pos >= 0
            rows = self.sorder[np.clip(pos, 0, n - 1)]
            hit = found & self.live[rows]
        return hit, rows

    def row_of_key(self, key: np.ndarray) -> int:
        """Live row index for one packed key, or -1."""
        hit, rows = self.rows_of_keys(key.reshape(1))
        return int(rows[0]) if hit[0] else -1

    # -- decoding --------------------------------------------------------
    def decode(
        self,
        row: int,
        interner,
        slot_names: Mapping[int, str],
        caveat_names: Mapping[int, str],
        contexts: Sequence[Mapping[str, Any]],
    ) -> Relationship:
        rtype, rid = interner.key_of(int(self.res[row]))
        stype, sid = interner.key_of(int(self.subj[row]))
        srel1 = int(self.srel1[row])
        cav = int(self.caveat[row])
        ctx_i = int(self.ctx[row])
        exp_us = int(self.exp_us[row])
        expiration = None
        if exp_us:
            expiration = _dt.datetime.fromtimestamp(
                exp_us / 1_000_000, tz=_dt.timezone.utc
            )
        return Relationship(
            resource_type=rtype,
            resource_id=rid,
            resource_relation=slot_names[int(self.rel[row])],
            subject_type=stype,
            subject_id=sid,
            subject_relation=slot_names[srel1 - 1] if srel1 > 0 else "",
            caveat_name=caveat_names[cav] if cav else "",
            caveat_context=contexts[ctx_i] if ctx_i >= 0 else {},
            expiration=expiration,
        )

    # -- vectorized filter matching -------------------------------------
    def filter_mask(
        self,
        f: Optional[Filter],
        compiled: CompiledSchema,
        interner,
        node_type: np.ndarray,
        now_us: Optional[int],
    ) -> np.ndarray:
        """Boolean mask of LIVE, unexpired rows matching the filter —
        the columnar mirror of Filter.matches/Snapshot.iter_relationships."""
        mask = self.live.copy()
        if now_us is not None:
            mask &= (self.exp_us == 0) | (self.exp_us > now_us)
        if f is None:
            return mask
        none = np.zeros(len(self), bool)
        if f.resource_type != "":
            tid = interner.type_lookup(f.resource_type)
            if tid < 0:
                return none
            mask &= node_type[self.res] == tid
        if f.optional_resource_id != "":
            n = interner.lookup(f.resource_type, f.optional_resource_id)
            if n < 0:
                return none
            mask &= self.res == n
        if f.optional_relation != "":
            s = compiled.slot_of_name.get(f.optional_relation)
            if s is None:
                return none
            mask &= self.rel == s
        sf = f.optional_subject_filter
        if sf is not None:
            if sf.subject_type != "":
                tid = interner.type_lookup(sf.subject_type)
                if tid < 0:
                    return none
                mask &= node_type[self.subj] == tid
            if sf.optional_subject_id != "":
                n = interner.lookup(sf.subject_type, sf.optional_subject_id)
                if n < 0:
                    return none
                mask &= self.subj == n
            if sf.optional_relation is not None:
                if sf.optional_relation == "":
                    mask &= self.srel1 == 0
                else:
                    s = compiled.slot_of_name.get(sf.optional_relation)
                    if s is None:
                        return none
                    mask &= self.srel1 == s + 1
        return mask

    # -- schema migration ------------------------------------------------
    def remap_slots(
        self, slot_map: np.ndarray, caveat_map: np.ndarray
    ) -> None:
        """Renumber relation/caveat ids after a schema write (slot
        numbering is schema-derived; segments outlive schemas).  Maps are
        old-id → new-id arrays; -1 entries never occur for ids referenced
        by validated live rows."""
        self.rel = slot_map[self.rel]
        srel = self.srel1.astype(np.int64) - 1
        remapped = np.where(srel >= 0, slot_map[np.clip(srel, 0, None)], -1)
        self.srel1 = (remapped + 1).astype(np.int32)
        self.caveat = caveat_map[self.caveat]
        self.sorder = lexsort4(self.rel, self.res, self.subj, self.srel1)
        self._skey_h = (
            (self.rel.astype(np.int64) << 32) | self.res.astype(np.int64)
        )[self.sorder]
        self._skey_l = (
            (self.subj.astype(np.int64) << 32) | self.srel1.astype(np.int64)
        )[self.sorder]


def relationships_to_columns(
    batch: Sequence[Relationship],
    compiled: CompiledSchema,
    interner,
    contexts: List[Mapping[str, Any]],
    ctx_index: Dict[str, int],
) -> Dict[str, np.ndarray]:
    """Convert a batch of Relationship objects to int columns with batch
    interning and *shape-level* validation: write-validity depends only on
    (resource_type, relation, subject_type, subject_relation, wildcard,
    caveat, has_expiration) — one validate per distinct shape, not per
    edge.  Appends novel caveat contexts to ``contexts`` (deduplicated by
    canonical repr through ``ctx_index``)."""
    B = len(batch)
    slot_of = compiled.slot_of_name
    caveat_ids = compiled.caveat_ids

    rtypes: List[str] = [""] * B
    rids: List[str] = [""] * B
    stypes: List[str] = [""] * B
    sids: List[str] = [""] * B
    rrels: List[str] = [""] * B
    srels: List[str] = [""] * B
    cavs: List[str] = [""] * B
    caveat = np.zeros(B, np.int32)
    ctx = np.full(B, -1, np.int32)
    exp_us = np.zeros(B, np.int64)

    # single pass over the Python objects: attribute copies only; the
    # conditional work (caveat context dedup, expiry lowering) runs per
    # row ONLY where the fields are set — bulk restores are dominated by
    # plain rows, and every avoidable per-row op costs ~0.2s per million
    shape_rep: Dict[tuple, int] = {}
    for i, r in enumerate(batch):
        rtypes[i] = r.resource_type
        rids[i] = r.resource_id
        stypes[i] = r.subject_type
        sids[i] = r.subject_id
        rrels[i] = r.resource_relation
        srels[i] = r.subject_relation
        if r.caveat_name:
            cavs[i] = r.caveat_name
            cid = caveat_ids.get(r.caveat_name)
            if cid is None:
                # unknown caveat: validation (which runs after this
                # loop) owns the error type — raise ITS error, not a
                # bare KeyError
                compiled.validate_relationship(r)
                raise SchemaError(f"caveat `{r.caveat_name}` not found")
            caveat[i] = cid
            if r.caveat_context:
                ck = repr(sorted(r.caveat_context.items(), key=lambda kv: kv[0]))
                at = ctx_index.get(ck)
                if at is None:
                    at = len(contexts)
                    ctx_index[ck] = at
                    contexts.append(r.caveat_context)
                ctx[i] = at
        if r.expiration is not None and r.has_expiration():
            exp_us[i] = expiration_micros(r.expiration)

    # shape-level validation OUTSIDE the row loop: zip+set runs at C
    # speed, one validate per distinct shape
    for shape, i in {
        (rt, rr, st, sr, sid == "*", cv, bool(e)): i
        for i, (rt, rr, st, sr, sid, cv, e) in enumerate(
            zip(rtypes, rrels, stypes, srels, sids, cavs, exp_us)
        )
    }.items():
        compiled.validate_relationship(batch[i])

    rel = np.fromiter((slot_of[x] for x in rrels), np.int32, B)
    srel1 = np.fromiter(
        (slot_of[x] + 1 if x else 0 for x in srels), np.int32, B
    )

    if hasattr(interner, "node_batch_typed"):
        tid_of: Dict[str, int] = {}

        def tids(names: List[str]) -> np.ndarray:
            # distinct type names are few: resolve them once, then map
            # the column through the dict at C speed
            for n in set(names) - tid_of.keys():
                tid_of[n] = interner.type_id(n)
            return np.fromiter((tid_of[n] for n in names), np.int32, len(names))

        res = interner.node_batch_typed(tids(rtypes), rids)
        subj = interner.node_batch_typed(tids(stypes), sids)
    else:
        res = np.fromiter(
            (interner.node(t, i) for t, i in zip(rtypes, rids)), np.int32, B
        )
        subj = np.fromiter(
            (interner.node(t, i) for t, i in zip(stypes, sids)), np.int32, B
        )
    return {
        "res": res, "rel": rel, "subj": subj, "srel1": srel1,
        "caveat": caveat, "ctx": ctx, "exp_us": exp_us,
    }


def iter_segment_rows(seg: ColumnSegment, rows: Iterator[int]):
    """Helper for lazy Update views (see store._ColumnUpdates)."""
    return rows

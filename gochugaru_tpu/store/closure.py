"""Precomputed membership closure: the Leopard-style flattened index.

SpiceDB's dispatch cluster re-walks group nesting on every check; Zanzibar's
Leopard index instead flattens the member→group transitive closure offline so
a check becomes one set-membership probe (BASELINE.md config 5 names it).
That is the TPU-shaped move: closure computation happens ONCE per snapshot
revision on the host (vectorized numpy sort-merge joins over the snapshot's
membership columns, native parallel sorts), and the per-check device work
collapses to O(1) hash probes into the flattened table — no per-query
frontier walk, no device-side sort/dedup (the round-2 hot-path bottleneck,
engine/device.py Phase A).

Two planes, one max-min expiry semiring each (SURVEY.md §2.6 expiration +
three-valued permissionship):

- ``definite``: paths made only of caveat-free edges.  The stored value is
  ``max over paths of (min over path edges of expiry)`` — an edge with no
  expiration contributes +inf (stored ``NO_EXP``).  At query time the pair
  grants definitely iff ``value > now``.
- ``possible``: paths through any edge (caveated edges admitted — the host
  oracle resolves the caveat per query with real context).  Same semiring,
  so expiry alone never sends a check to the host: the max-min value
  answers "is some path fully live at ``now``" exactly.

A source whose closure exceeds ``per_source_cap`` — or that is still
unconverged when ``max_hops`` runs out — is dropped from the table and
recorded in the overflow set; queries whose subject hits the overflow set
are re-checked on the host oracle (caps bound memory, never correctness —
the same contract as engine/plan.py's EngineConfig).

Replaces (the membership half of) the reference's server-side graph walk
behind CheckBulkPermissions (client/client.go:238-266).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional, Tuple

import numpy as np

from ..utils import metrics

if TYPE_CHECKING:  # pragma: no cover
    from .snapshot import Snapshot

#: semiring +inf: "no expiration along the best path"
NO_EXP = np.int32(2**31 - 1)
#: semiring -inf: "no admissible path on this plane"
NEVER = np.int32(-(2**31))


@dataclass
class ClosureIndex:
    """Flattened membership closure at one revision.

    Rows are sorted lexicographically by (src, srel1, g, grel) where
    ``src``/``srel1`` identify the member (``srel1 == 0`` → a direct object
    subject, e.g. a user node; ``srel1 == r+1`` → the userset ``src#r``)
    and (``g``, ``grel``) is a userset the member transitively belongs to.
    Reflexive pairs (``X#r ∈ X#r``) are NOT stored — probes test identity
    directly.  ``d_until``/``p_until`` are the per-plane semiring values.
    """

    revision: int
    c_src: np.ndarray  # int32[P]
    c_srel1: np.ndarray  # int32[P]
    c_g: np.ndarray  # int32[P]
    c_grel: np.ndarray  # int32[P]
    c_d_until: np.ndarray  # int32[P]  NEVER = not definite via any path
    c_p_until: np.ndarray  # int32[P]
    # sources whose closure overflowed per_source_cap, sorted lex
    ovf_src: np.ndarray  # int32[O]
    ovf_srel1: np.ndarray  # int32[O]

    @property
    def num_pairs(self) -> int:
        return int(self.c_src.shape[0])


def _in_sorted(sorted_arr: np.ndarray, x: np.ndarray) -> np.ndarray:
    """Membership of x in a sorted unique array, via binary search."""
    if sorted_arr.size == 0 or x.size == 0:
        return np.zeros(x.shape[0], bool)
    pos = np.clip(np.searchsorted(sorted_arr, x), 0, sorted_arr.shape[0] - 1)
    return sorted_arr[pos] == x


class _Builder:
    """Mutable state of one build_closure run."""

    def __init__(self, S1: np.int64, per_source_cap: int) -> None:
        self.S1 = S1
        self.cap = per_source_cap
        self.ovf = np.zeros(0, np.int64)  # sorted unique overflowed src keys

    def add_overflow(self, keys: np.ndarray) -> None:
        if keys.size:
            self.ovf = np.union1d(self.ovf, keys)

    def group_max(self, src, dst, d, p):
        """Combine duplicate (src, dst) rows, per-plane max; lexsorted out.
        Sorts via the native parallel radix directly on the packed
        non-negative int64 keys (order-equivalent to the unpacked column
        lexsort — the packing is monotone), applied with parallel
        gathers; numpy lexsort is tens of seconds at 100M rows."""
        if src.size == 0:
            return src, dst, d, p
        from ..native.sort import sortperm_words, take32, take64

        order = sortperm_words([src, dst], (dst, src))
        src, dst = take64(src, order), take64(dst, order)
        d, p = take32(d, order), take32(p, order)
        first = np.ones(src.shape[0], bool)
        first[1:] = (src[1:] != src[:-1]) | (dst[1:] != dst[:-1])
        starts = np.nonzero(first)[0]
        return (
            src[first],
            dst[first],
            np.maximum.reduceat(d, starts),
            np.maximum.reduceat(p, starts),
        )

    def drop_oversized(self, src, dst, d, p):
        """Enforce per_source_cap; src must be sorted (post group_max)."""
        if src.size == 0:
            return src, dst, d, p
        uniq, counts = np.unique(src, return_counts=True)
        self.add_overflow(uniq[counts > self.cap])
        return self.drop_overflowed(src, dst, d, p)

    def drop_overflowed(self, src, dst, d, p):
        if self.ovf.size == 0 or src.size == 0:
            return src, dst, d, p
        keep = ~_in_sorted(self.ovf, src)
        return src[keep], dst[keep], d[keep], p[keep]


def _pair_ids(
    src_a: np.ndarray, dst_a: np.ndarray, src_b: np.ndarray, dst_b: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Dense int64 ids for (src, dst) pairs, consistent across both inputs
    and monotone w.r.t. (src, dst) lexicographic order (so a lexsorted
    table yields sorted ids, and np.searchsorted applies)."""
    ns, nb = src_a.shape[0], src_b.shape[0]
    _, inv_s = np.unique(np.concatenate([src_a, src_b]), return_inverse=True)
    ud, inv_d = np.unique(np.concatenate([dst_a, dst_b]), return_inverse=True)
    ids = inv_s.astype(np.int64) * np.int64(max(ud.shape[0], 1)) + inv_d
    return ids[:ns], ids[ns : ns + nb]


def _edge_values(cav: np.ndarray, exp: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-edge semiring weights: expiry 0 → +inf; caveated edges are
    NEVER on the definite plane (resolving them needs per-query context).
    Pure int32 (both sentinels fit): no int64 round trip."""
    w = np.where(exp == 0, NO_EXP, exp).astype(np.int32)
    return np.where(cav == 0, w, NEVER), w


def _expand_join(
    keys_sorted: np.ndarray, probe: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """All-pairs sort-merge join: for each probe[i], the row indices of
    every match in keys_sorted.  Returns (probe_row, match_row) flattened."""
    lo = np.searchsorted(keys_sorted, probe, "left")
    hi = np.searchsorted(keys_sorted, probe, "right")
    counts = (hi - lo).astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    reps = np.repeat(np.arange(probe.shape[0], dtype=np.int64), counts)
    ends = np.cumsum(counts)
    ii = np.repeat(lo.astype(np.int64), counts) + (
        np.arange(total, dtype=np.int64) - np.repeat(ends - counts, counts)
    )
    return reps, ii


def build_closure(
    snap: "Snapshot",
    *,
    per_source_cap: int = 4096,
    global_cap: int = 200_000_000,
    max_hops: int = 10_000,
) -> ClosureIndex:
    """Flatten the snapshot's membership graph (ms_/mp_ views) into a
    ClosureIndex via a semi-naive fixpoint of vectorized joins."""
    metrics.default.inc("closure.rebuilds")
    from ..utils import trace as _trace

    _trace.event_if_active("closure.rebuild", revision=int(snap.revision))
    S1 = np.int64(snap.num_slots + 1)  # srel1 radix
    b = _Builder(S1, per_source_cap)

    def src_key(node: np.ndarray, srel1) -> np.ndarray:
        return node.astype(np.int64) * S1 + srel1

    # -- pair-level closure over userset-propagation edges ----------------
    # direct pair edges: (mp_subj # mp_srel)  →  (mp_res # mp_rel)
    e_src = src_key(snap.mp_subj, snap.mp_srel.astype(np.int64) + 1)
    e_dst = src_key(snap.mp_res, snap.mp_rel.astype(np.int64) + 1)
    e_d, e_p = _edge_values(snap.mp_caveat, snap.mp_exp)
    # self-loop edges (a#m @ a#m) add nothing to any path: drop them so the
    # no-reflexive-rows invariant holds from the initial table on
    loop = e_src == e_dst
    if loop.any():
        e_src, e_dst, e_d, e_p = e_src[~loop], e_dst[~loop], e_d[~loop], e_p[~loop]
    e_order = np.argsort(e_src, kind="stable")
    e_src, e_dst = e_src[e_order], e_dst[e_order]
    e_d, e_p = e_d[e_order], e_p[e_order]

    c_src, c_dst, c_d, c_p = b.group_max(e_src, e_dst, e_d, e_p)
    c_src, c_dst, c_d, c_p = b.drop_oversized(c_src, c_dst, c_d, c_p)
    n_src, n_dst, n_d, n_p = c_src, c_dst, c_d, c_p  # frontier

    for _ in range(max_hops):
        if n_src.size == 0:
            break
        reps, ii = _expand_join(e_src, n_dst)
        if reps.size == 0:
            n_src = n_src[:0]
            break
        j_src = n_src[reps]
        j_dst = e_dst[ii]
        j_d = np.minimum(n_d[reps], e_d[ii])
        j_p = np.minimum(n_p[reps], e_p[ii])
        keep = j_src != j_dst  # reflexivity is the probe's job
        j_src, j_dst, j_d, j_p = j_src[keep], j_dst[keep], j_d[keep], j_p[keep]
        j_src, j_dst, j_d, j_p = b.group_max(j_src, j_dst, j_d, j_p)
        # an overflowed source stays overflowed: no partial creep-back
        j_src, j_dst, j_d, j_p = b.drop_overflowed(j_src, j_dst, j_d, j_p)
        if j_src.size == 0:
            n_src = j_src
            break

        # improvement test against the current table
        c_ids, j_ids = _pair_ids(c_src, c_dst, j_src, j_dst)
        pos = np.searchsorted(c_ids, j_ids)
        posc = np.clip(pos, 0, max(c_ids.shape[0] - 1, 0))
        found = (c_ids.shape[0] > 0) & (c_ids[posc] == j_ids)
        old_d = np.where(found, c_d[posc], NEVER)
        old_p = np.where(found, c_p[posc], NEVER)
        improved = (j_d > old_d) | (j_p > old_p)
        j_src, j_dst = j_src[improved], j_dst[improved]
        j_d, j_p = j_d[improved], j_p[improved]
        if j_src.size == 0:
            n_src = j_src
            break

        c_src, c_dst, c_d, c_p = b.group_max(
            np.concatenate([c_src, j_src]),
            np.concatenate([c_dst, j_dst]),
            np.concatenate([c_d, j_d]),
            np.concatenate([c_p, j_p]),
        )
        c_src, c_dst, c_d, c_p = b.drop_oversized(c_src, c_dst, c_d, c_p)
        if c_src.size > global_cap:
            raise MemoryError(
                f"membership closure exceeded global cap ({c_src.size} pairs)"
            )
        n_src, n_dst, n_d, n_p = b.drop_overflowed(j_src, j_dst, j_d, j_p)
    if n_src.size:
        # hop budget exhausted before convergence: the unconverged sources'
        # rows may be incomplete — overflow them so queries fall back to the
        # host oracle instead of silently missing memberships
        b.add_overflow(np.unique(n_src))

    # -- user-level closure: direct seeds ∪ (seeds ⋈ pair closure) --------
    s_src = src_key(snap.ms_subj, 0)  # direct-object members, srel1 = 0
    s_dst = src_key(snap.ms_res, snap.ms_rel.astype(np.int64) + 1)
    s_d, s_p = _edge_values(snap.ms_caveat, snap.ms_exp)

    reps, ii = _expand_join(c_src, s_dst)
    if reps.size:
        u_src = np.concatenate([s_src, s_src[reps]])
        u_dst = np.concatenate([s_dst, c_dst[ii]])
        u_d = np.concatenate([s_d, np.minimum(s_d[reps], c_d[ii])])
        u_p = np.concatenate([s_p, np.minimum(s_p[reps], c_p[ii])])
    else:
        u_src, u_dst, u_d, u_p = s_src, s_dst, s_d, s_p
    # a user whose seed points at an overflowed pair overflows too: the
    # pair's (dropped) closure would have been part of the user's closure
    if b.ovf.size:
        b.add_overflow(np.unique(s_src[_in_sorted(b.ovf, s_dst)]))
    u_src, u_dst, u_d, u_p = b.group_max(u_src, u_dst, u_d, u_p)
    u_src, u_dst, u_d, u_p = b.drop_oversized(u_src, u_dst, u_d, u_p)

    # -- assemble (final sweep drops any row of an overflowed source) -----
    a_src = np.concatenate([u_src, c_src])
    a_dst = np.concatenate([u_dst, c_dst])
    a_d = np.concatenate([u_d, c_d]).astype(np.int32)
    a_p = np.concatenate([u_p, c_p]).astype(np.int32)
    a_src, a_dst, a_d, a_p = b.drop_overflowed(a_src, a_dst, a_d, a_p)
    from ..native.sort import sortperm_words, take32, take64

    order = sortperm_words([a_src, a_dst], (a_dst, a_src))
    a_src, a_dst = take64(a_src, order), take64(a_dst, order)
    a_d, a_p = take32(a_d, order), take32(a_p, order)

    return ClosureIndex(
        revision=snap.revision,
        c_src=(a_src // S1).astype(np.int32),
        c_srel1=(a_src % S1).astype(np.int32),
        c_g=(a_dst // S1).astype(np.int32),
        c_grel=(a_dst % S1 - 1).astype(np.int32),
        c_d_until=a_d,
        c_p_until=a_p,
        ovf_src=(b.ovf // S1).astype(np.int32),
        ovf_srel1=(b.ovf % S1).astype(np.int32),
    )


# ---------------------------------------------------------------------------
# incremental maintenance: O(Δ·depth) closure advance along a Watch chain
# ---------------------------------------------------------------------------
#
# A membership-edge delta (rows of the ms/mp subgraph) used to force a full
# rebuild of the flattened closure — the top bail class of the device's
# incremental prepare (ROADMAP "Incremental closure maintenance").  The
# machinery below advances the index instead:
#
# 1. **Affected-set discovery** (reverse reachability): a source's closure
#    can only change if it reaches the tail of a touched edge, so walk the
#    membership graph BACKWARDS from the touched edge sources over the
#    union of old and new edges — O(Δ·depth) frontier work, capped.
# 2. **Subset recompute**: rerun build_closure's exact fixpoint restricted
#    to the affected sources over the full new edge set — the same
#    group_max/cap/overflow machinery, so the recomputed rows are the rows
#    a full rebuild would produce (deletions need no derivation counting:
#    affected sources are recomputed wholesale).
# 3. **Merge**: drop the affected sources' old rows, interleave the
#    recomputed rows into the lex-sorted arrays (O(P + Δ') searchsorted
#    merge, no global re-sort) — bitwise-identical to a from-scratch
#    build_closure by construction (the final table is a pure function of
#    the deduped pair→value map and the overflow set, both reproduced
#    exactly; tests/test_closure.py asserts array equality).
#
# Any condition the subset recompute cannot keep sound or cheap —
# affected set past the cap, unconverged fixpoint, global-cap overflow —
# returns None and the caller falls back to build_closure (counted by the
# ``closure.rebuilds`` / ``closure.delta_applies`` metrics pair).


@dataclass
class ClosureState:
    """Host-side state for advancing a ClosureIndex by membership deltas.

    Everything is packed int64 keys (``node·S1 + srel1`` sources,
    ``node·S1 + rel + 1`` targets, S1 = num_slots + 1 — the same radix
    build_closure uses internally).  Edge identities are unique (they
    mirror primary-row identities), so removal is exact.  Instances are
    immutable in practice: ``advance_closure`` returns a new state and
    never mutates its input, which makes a retried advance (fault
    injection, utils/faults.py ``closure.delta``) idempotent."""

    S1: np.int64
    per_source_cap: int
    revision: int
    cl: ClosureIndex
    a_src: np.ndarray  # int64[P] packed src per closure row (lex order)
    a_dst: np.ndarray  # int64[P] packed dst per closure row
    ovf: np.ndarray  # int64[O] sorted packed overflowed sources
    # membership edge sets at this revision, sorted by (src, dst):
    e_src: np.ndarray  # pair (mp) edges; self-loops dropped
    e_dst: np.ndarray
    e_d: np.ndarray  # int32 per-plane edge weights (_edge_values)
    e_p: np.ndarray
    s_src: np.ndarray  # seed (ms) edges
    s_dst: np.ndarray
    s_d: np.ndarray
    s_p: np.ndarray
    # reverse views sorted by (dst, src): affected-set discovery
    er_dst: np.ndarray
    er_src: np.ndarray
    sr_dst: np.ndarray
    sr_src: np.ndarray


@dataclass
class AdvanceResult:
    """Outcome of one successful advance_closure call."""

    state: ClosureState
    #: sorted unique packed dst keys whose member set (or a member's
    #: admissibility value) changed — exactly the groups whose baked
    #: T-index rows are stale (engine/flat.py turns these into dirty keys)
    changed_dsts: np.ndarray
    #: the affected source sets (diagnostics + wildcard checks upstream)
    affected_pairs: np.ndarray
    affected_users: np.ndarray


def _sort_pairs(S1: np.int64, k1, k2, *vals):
    if k1.shape[0] == 0:
        return (k1, k2) + tuple(vals)
    from ..native.sort import sortperm_words, take64

    order = sortperm_words([k1, k2], (k2, k1))
    return (take64(k1, order), take64(k2, order)) + tuple(
        v[order] for v in vals
    )


def build_closure_state(snap: "Snapshot", cl: ClosureIndex,
                        *, per_source_cap: int = 4096) -> ClosureState:
    """The advance-ready form of a freshly built closure (full prepare)."""
    S1 = np.int64(snap.num_slots + 1)
    e_src = snap.mp_subj.astype(np.int64) * S1 + snap.mp_srel.astype(np.int64) + 1
    e_dst = snap.mp_res.astype(np.int64) * S1 + snap.mp_rel.astype(np.int64) + 1
    e_d, e_p = _edge_values(snap.mp_caveat, snap.mp_exp)
    keep = e_src != e_dst  # build_closure drops self-loop pair edges
    e_src, e_dst, e_d, e_p = e_src[keep], e_dst[keep], e_d[keep], e_p[keep]
    e_src, e_dst, e_d, e_p = _sort_pairs(S1, e_src, e_dst, e_d, e_p)
    er_dst, er_src = _sort_pairs(S1, e_dst, e_src)

    s_src = snap.ms_subj.astype(np.int64) * S1
    s_dst = snap.ms_res.astype(np.int64) * S1 + snap.ms_rel.astype(np.int64) + 1
    s_d, s_p = _edge_values(snap.ms_caveat, snap.ms_exp)
    s_src, s_dst, s_d, s_p = _sort_pairs(S1, s_src, s_dst, s_d, s_p)
    sr_dst, sr_src = _sort_pairs(S1, s_dst, s_src)

    return ClosureState(
        S1=S1, per_source_cap=per_source_cap, revision=snap.revision, cl=cl,
        a_src=cl.c_src.astype(np.int64) * S1 + cl.c_srel1,
        a_dst=cl.c_g.astype(np.int64) * S1 + cl.c_grel + 1,
        ovf=cl.ovf_src.astype(np.int64) * S1 + cl.ovf_srel1,
        e_src=e_src, e_dst=e_dst, e_d=e_d, e_p=e_p,
        s_src=s_src, s_dst=s_dst, s_d=s_d, s_p=s_p,
        er_dst=er_dst, er_src=er_src, sr_dst=sr_dst, sr_src=sr_src,
    )


def _apply_edge_delta(S1, k1, k2, vals, del1, del2, add1, add2, addvals):
    """Remove identities (del1, del2) from a (k1, k2)-lexsorted edge set
    and merge the (sorted) additions; returns the new sorted columns.
    Fully vectorized: pair-id membership for the removal (identities are
    unique) and ONE native lexsort for the merge — the per-run binary
    search loops of the generic store merge cost more than this whole
    advance at typical delta sizes."""
    if del1.shape[0]:
        e_ids, d_ids = _pair_ids(k1, k2, del1, del2)
        keep = ~_in_sorted(np.sort(d_ids), e_ids)
        k1k, k2k = k1[keep], k2[keep]
        valsk = [v[keep] for v in vals]
    else:
        k1k, k2k = k1, k2
        valsk = list(vals)
    if add1.shape[0] == 0:
        return (k1k, k2k) + tuple(valsk)
    return _sort_pairs(
        S1,
        np.concatenate([k1k, add1]),
        np.concatenate([k2k, add2]),
        *(
            np.concatenate([o, a.astype(o.dtype)])
            for o, a in zip(valsk, addvals)
        ),
    )


def advance_closure(
    st: ClosureState,
    revision: int,
    *,
    pair_add=None,  # (src, dst, cav, exp) int64/int32 columns
    pair_del=None,  # (src, dst)
    seed_add=None,
    seed_del=None,
    affected_cap: int = 65_536,
    global_cap: int = 200_000_000,
    max_hops: int = 10_000,
) -> Optional[AdvanceResult]:
    """Advance the closure by one revision's membership-edge delta, or
    None when the subset recompute cannot stay sound/cheap (the caller
    then rebuilds).  Pure: ``st`` is never mutated."""
    from ..utils import faults

    faults.fire("closure.delta")
    S1 = st.S1
    z64 = np.zeros(0, np.int64)

    def unpack4(t):
        if t is None:
            return z64, z64, np.zeros(0, np.int32), np.zeros(0, np.int32)
        src, dst, cav, exp = (np.asarray(x) for x in t)
        d, p = _edge_values(np.asarray(cav, np.int32), np.asarray(exp, np.int32))
        return src.astype(np.int64), dst.astype(np.int64), d, p

    def unpack2(t):
        if t is None:
            return z64, z64
        return np.asarray(t[0], np.int64), np.asarray(t[1], np.int64)

    pa_src, pa_dst, pa_d, pa_p = unpack4(pair_add)
    pd_src, pd_dst = unpack2(pair_del)
    sa_src, sa_dst, sa_d, sa_p = unpack4(seed_add)
    sd_src, sd_dst = unpack2(seed_del)
    # self-loop pair edges never enter the edge set: drop from both sides
    if pa_src.shape[0]:
        keep = pa_src != pa_dst
        pa_src, pa_dst, pa_d, pa_p = (
            pa_src[keep], pa_dst[keep], pa_d[keep], pa_p[keep]
        )
    if pd_src.shape[0]:
        keep = pd_src != pd_dst
        pd_src, pd_dst = pd_src[keep], pd_dst[keep]

    if not (pa_src.shape[0] or pd_src.shape[0] or sa_src.shape[0]
            or sd_src.shape[0]):
        return AdvanceResult(st, z64, z64, z64)

    # -- 1. affected sources: reverse reachability over old ∪ new edges --
    touched = np.unique(np.concatenate([pa_src, pd_src]))
    add_rd, add_rs = _sort_pairs(S1, pa_dst, pa_src)  # adds by dst
    R = touched
    frontier = touched
    hops = 0
    while frontier.shape[0]:
        preds = []
        _, ii = _expand_join(st.er_dst, frontier)
        if ii.shape[0]:
            preds.append(st.er_src[ii])
        _, jj = _expand_join(add_rd, frontier)
        if jj.shape[0]:
            preds.append(add_rs[jj])
        if not preds:
            break
        cand = np.unique(np.concatenate(preds))
        frontier = cand[~_in_sorted(R, cand)]
        if frontier.shape[0]:
            R = np.union1d(R, frontier)
        if R.shape[0] > affected_cap:
            return None
        hops += 1
        if hops > max_hops:
            return None
    A_p = R  # sorted unique pair-source keys (srel1 > 0 by construction)

    # affected users: touched seeds, plus seeds (old ∪ added) whose target
    # reaches a touched pair source
    u_parts = [np.unique(np.concatenate([sa_src, sd_src]))]
    if A_p.shape[0]:
        _, ii = _expand_join(st.sr_dst, A_p)
        if ii.shape[0]:
            u_parts.append(st.sr_src[ii])
        if sa_src.shape[0]:
            hit = _in_sorted(A_p, sa_dst)
            if hit.any():
                u_parts.append(sa_src[hit])
    A_u = np.unique(np.concatenate(u_parts))
    if A_p.shape[0] + A_u.shape[0] > affected_cap:
        return None
    A_all = np.union1d(A_p, A_u)  # srel1 planes are disjoint

    # -- 2. edge-set update ------------------------------------------------
    pa_s, pa_ds, pa_dv, pa_pv = _sort_pairs(S1, pa_src, pa_dst, pa_d, pa_p)
    ne_src, ne_dst, ne_d, ne_p = _apply_edge_delta(
        S1, st.e_src, st.e_dst, (st.e_d, st.e_p),
        pd_src, pd_dst, pa_s, pa_ds, (pa_dv, pa_pv),
    )
    ner_dst, ner_src = _apply_edge_delta(
        S1, st.er_dst, st.er_src, (), pd_dst, pd_src, add_rd, add_rs, ()
    )
    sa_s, sa_ds, sa_dv, sa_pv = _sort_pairs(S1, sa_src, sa_dst, sa_d, sa_p)
    ns_src, ns_dst, ns_d, ns_p = _apply_edge_delta(
        S1, st.s_src, st.s_dst, (st.s_d, st.s_p),
        sd_src, sd_dst, sa_s, sa_ds, (sa_dv, sa_pv),
    )
    sr_a_d, sr_a_s = _sort_pairs(S1, sa_dst, sa_src)
    nsr_dst, nsr_src = _apply_edge_delta(
        S1, st.sr_dst, st.sr_src, (), sd_dst, sd_src, sr_a_d, sr_a_s, ()
    )

    # -- 3. subset recompute over the new edge set -------------------------
    b = _Builder(S1, st.per_source_cap)

    # pair phase: the fixpoint of build_closure restricted to A_p (the
    # expansion never changes a row's source, so restriction is exact)
    if A_p.shape[0]:
        _, ii = _expand_join(ne_src, A_p)
        c_src, c_dst = ne_src[ii], ne_dst[ii]
        c_d, c_p = ne_d[ii], ne_p[ii]
    else:
        c_src = c_dst = z64
        c_d = c_p = np.zeros(0, np.int32)
    c_src, c_dst, c_d, c_p = b.group_max(c_src, c_dst, c_d, c_p)
    c_src, c_dst, c_d, c_p = b.drop_oversized(c_src, c_dst, c_d, c_p)
    n_src, n_dst, n_d, n_p = c_src, c_dst, c_d, c_p
    for _ in range(max_hops):
        if n_src.size == 0:
            break
        reps, ii = _expand_join(ne_src, n_dst)
        if reps.size == 0:
            n_src = n_src[:0]
            break
        j_src = n_src[reps]
        j_dst = ne_dst[ii]
        j_d = np.minimum(n_d[reps], ne_d[ii])
        j_p = np.minimum(n_p[reps], ne_p[ii])
        keep = j_src != j_dst
        j_src, j_dst, j_d, j_p = j_src[keep], j_dst[keep], j_d[keep], j_p[keep]
        j_src, j_dst, j_d, j_p = b.group_max(j_src, j_dst, j_d, j_p)
        j_src, j_dst, j_d, j_p = b.drop_overflowed(j_src, j_dst, j_d, j_p)
        if j_src.size == 0:
            n_src = j_src
            break
        c_ids, j_ids = _pair_ids(c_src, c_dst, j_src, j_dst)
        pos = np.searchsorted(c_ids, j_ids)
        posc = np.clip(pos, 0, max(c_ids.shape[0] - 1, 0))
        found = (c_ids.shape[0] > 0) & (c_ids[posc] == j_ids)
        old_d = np.where(found, c_d[posc], NEVER)
        old_p = np.where(found, c_p[posc], NEVER)
        improved = (j_d > old_d) | (j_p > old_p)
        j_src, j_dst = j_src[improved], j_dst[improved]
        j_d, j_p = j_d[improved], j_p[improved]
        if j_src.size == 0:
            n_src = j_src
            break
        c_src, c_dst, c_d, c_p = b.group_max(
            np.concatenate([c_src, j_src]),
            np.concatenate([c_dst, j_dst]),
            np.concatenate([c_d, j_d]),
            np.concatenate([c_p, j_p]),
        )
        c_src, c_dst, c_d, c_p = b.drop_oversized(c_src, c_dst, c_d, c_p)
        n_src, n_dst, n_d, n_p = b.drop_overflowed(j_src, j_dst, j_d, j_p)
    if n_src.size:
        return None  # unconverged within the hop budget: rebuild

    # user phase: A_u's seeds ∪ (those seeds ⋈ pair closure), where the
    # pair closure is the recomputed subset at affected targets and the
    # untouched stored rows elsewhere
    if A_u.shape[0]:
        _, ii = _expand_join(ns_src, A_u)
        su_src, su_dst = ns_src[ii], ns_dst[ii]
        su_d, su_p = ns_d[ii], ns_p[ii]
    else:
        su_src = su_dst = z64
        su_d = su_p = np.zeros(0, np.int32)
    u_cols = [(su_src, su_dst, su_d, su_p)]
    if su_src.shape[0]:
        in_a = _in_sorted(A_p, su_dst) if A_p.shape[0] else np.zeros(
            su_dst.shape[0], bool
        )
        # recomputed pair rows for affected targets
        if in_a.any():
            reps, jj = _expand_join(c_src, su_dst[in_a])
            if reps.shape[0]:
                base_idx = np.nonzero(in_a)[0][reps]
                u_cols.append((
                    su_src[base_idx], c_dst[jj],
                    np.minimum(su_d[base_idx], c_d[jj]),
                    np.minimum(su_p[base_idx], c_p[jj]),
                ))
        # stored pair rows for untouched targets (src ∉ A by definition)
        if (~in_a).any():
            pair_rows = (st.a_src % S1) > 0
            op_src, op_dst = st.a_src[pair_rows], st.a_dst[pair_rows]
            op_d = st.cl.c_d_until[pair_rows]
            op_p = st.cl.c_p_until[pair_rows]
            reps, jj = _expand_join(op_src, su_dst[~in_a])
            if reps.shape[0]:
                base_idx = np.nonzero(~in_a)[0][reps]
                u_cols.append((
                    su_src[base_idx], op_dst[jj],
                    np.minimum(su_d[base_idx], op_d[jj]),
                    np.minimum(su_p[base_idx], op_p[jj]),
                ))
    u_src = np.concatenate([t[0] for t in u_cols])
    u_dst = np.concatenate([t[1] for t in u_cols])
    u_d = np.concatenate([t[2] for t in u_cols]).astype(np.int32)
    u_p = np.concatenate([t[3] for t in u_cols]).astype(np.int32)

    # overflow propagation: a user whose seed points at an overflowed pair
    # overflows too (checked against the GLOBAL new overflow set — kept
    # old entries plus the subset recompute's; user-plane keys in it can
    # never match a seed target, so the mix is harmless)
    ovf_kept = st.ovf[~_in_sorted(A_all, st.ovf)] if st.ovf.shape[0] else z64
    ovf_glob = np.union1d(ovf_kept, b.ovf)
    if ovf_glob.shape[0] and su_src.shape[0]:
        over = np.unique(su_src[_in_sorted(ovf_glob, su_dst)])
        b.add_overflow(over)
    u_src, u_dst, u_d, u_p = b.group_max(u_src, u_dst, u_d, u_p)
    u_src, u_dst, u_d, u_p = b.drop_oversized(u_src, u_dst, u_d, u_p)

    # -- 4. merge into the stored arrays ----------------------------------
    new_src = np.concatenate([u_src, c_src])
    new_dst = np.concatenate([u_dst, c_dst])
    new_d = np.concatenate([u_d, c_d]).astype(np.int32)
    new_p = np.concatenate([u_p, c_p]).astype(np.int32)
    full_ovf = np.union1d(ovf_kept, b.ovf)
    if full_ovf.shape[0] and new_src.shape[0]:
        keep = ~_in_sorted(full_ovf, new_src)
        new_src, new_dst = new_src[keep], new_dst[keep]
        new_d, new_p = new_d[keep], new_p[keep]
    new_src, new_dst, new_d, new_p = _sort_pairs(
        S1, new_src, new_dst, new_d, new_p
    )

    keep_old = (
        ~_in_sorted(A_all, st.a_src)
        if A_all.shape[0] and st.a_src.shape[0]
        else np.ones(st.a_src.shape[0], bool)
    )
    rm_src, rm_dst = st.a_src[~keep_old], st.a_dst[~keep_old]
    rm_d = st.cl.c_d_until[~keep_old]
    rm_p = st.cl.c_p_until[~keep_old]

    from .delta import find_in_view

    o_src, o_dst = st.a_src[keep_old], st.a_dst[keep_old]
    o_d = st.cl.c_d_until[keep_old]
    o_p = st.cl.c_p_until[keep_old]
    P = o_src.shape[0] + new_src.shape[0]
    if P > global_cap:
        return None
    # one native lexsort interleaves kept + recomputed rows (keys are
    # unique across the two sets: recomputed sources were removed above)
    m_src, m_dst, m_d, m_p = _sort_pairs(
        S1,
        np.concatenate([o_src, new_src]),
        np.concatenate([o_dst, new_dst]),
        np.concatenate([o_d, new_d]),
        np.concatenate([o_p, new_p]),
    )

    # -- 5. exact changed-row diff (old affected rows vs recomputed) ------
    at = find_in_view(new_src, new_dst, rm_src, rm_dst)
    gone_or_changed = (at < 0)
    found = at >= 0
    if found.any():
        fi = at[found]
        gone_or_changed[found] = (
            (new_d[fi] != rm_d[found]) | (new_p[fi] != rm_p[found])
        )
    back = find_in_view(rm_src, rm_dst, new_src, new_dst)
    fresh = back < 0  # value-changed rows are already covered above
    changed_dsts = np.unique(np.concatenate([
        rm_dst[gone_or_changed], new_dst[fresh],
    ]))

    cl = ClosureIndex(
        revision=revision,
        c_src=(m_src // S1).astype(np.int32),
        c_srel1=(m_src % S1).astype(np.int32),
        c_g=(m_dst // S1).astype(np.int32),
        c_grel=(m_dst % S1 - 1).astype(np.int32),
        c_d_until=m_d,
        c_p_until=m_p,
        ovf_src=(full_ovf // S1).astype(np.int32),
        ovf_srel1=(full_ovf % S1).astype(np.int32),
    )
    metrics.default.inc("closure.delta_applies")
    if int(revision) - int(st.revision) > 1:
        # one advance covering a multi-revision span — the whole point
        # of group commit: k writes, one closure delta
        metrics.default.inc("closure.batch_applies")
    # write-path observability: a sampled request whose delta-prepare
    # reached this advance records it on the request's active span
    # (utils/trace.py thread-local; one branch when tracing is off)
    from ..utils import trace as _trace

    _trace.event_if_active(
        "closure.advance",
        revision=int(revision),
        affected_pairs=int(A_p.shape[0]),
        affected_users=int(A_u.shape[0]),
        changed_dsts=int(changed_dsts.shape[0]),
    )
    return AdvanceResult(
        state=ClosureState(
            S1=S1, per_source_cap=st.per_source_cap, revision=revision,
            cl=cl, a_src=m_src, a_dst=m_dst, ovf=full_ovf,
            e_src=ne_src, e_dst=ne_dst, e_d=ne_d, e_p=ne_p,
            s_src=ns_src, s_dst=ns_dst, s_d=ns_d, s_p=ns_p,
            er_dst=ner_dst, er_src=ner_src, sr_dst=nsr_dst, sr_src=nsr_src,
        ),
        changed_dsts=changed_dsts,
        affected_pairs=A_p,
        affected_users=A_u,
    )

"""Precomputed membership closure: the Leopard-style flattened index.

SpiceDB's dispatch cluster re-walks group nesting on every check; Zanzibar's
Leopard index instead flattens the member→group transitive closure offline so
a check becomes one set-membership probe (BASELINE.md config 5 names it).
That is the TPU-shaped move: closure computation happens ONCE per snapshot
revision on the host (vectorized numpy sort-merge joins over the snapshot's
membership columns, native parallel sorts), and the per-check device work
collapses to O(1) hash probes into the flattened table — no per-query
frontier walk, no device-side sort/dedup (the round-2 hot-path bottleneck,
engine/device.py Phase A).

Two planes, one max-min expiry semiring each (SURVEY.md §2.6 expiration +
three-valued permissionship):

- ``definite``: paths made only of caveat-free edges.  The stored value is
  ``max over paths of (min over path edges of expiry)`` — an edge with no
  expiration contributes +inf (stored ``NO_EXP``).  At query time the pair
  grants definitely iff ``value > now``.
- ``possible``: paths through any edge (caveated edges admitted — the host
  oracle resolves the caveat per query with real context).  Same semiring,
  so expiry alone never sends a check to the host: the max-min value
  answers "is some path fully live at ``now``" exactly.

A source whose closure exceeds ``per_source_cap`` — or that is still
unconverged when ``max_hops`` runs out — is dropped from the table and
recorded in the overflow set; queries whose subject hits the overflow set
are re-checked on the host oracle (caps bound memory, never correctness —
the same contract as engine/plan.py's EngineConfig).

Replaces (the membership half of) the reference's server-side graph walk
behind CheckBulkPermissions (client/client.go:238-266).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Tuple

import numpy as np

from ..native.sort import lexsort4

if TYPE_CHECKING:  # pragma: no cover
    from .snapshot import Snapshot

#: semiring +inf: "no expiration along the best path"
NO_EXP = np.int32(2**31 - 1)
#: semiring -inf: "no admissible path on this plane"
NEVER = np.int32(-(2**31))


@dataclass
class ClosureIndex:
    """Flattened membership closure at one revision.

    Rows are sorted lexicographically by (src, srel1, g, grel) where
    ``src``/``srel1`` identify the member (``srel1 == 0`` → a direct object
    subject, e.g. a user node; ``srel1 == r+1`` → the userset ``src#r``)
    and (``g``, ``grel``) is a userset the member transitively belongs to.
    Reflexive pairs (``X#r ∈ X#r``) are NOT stored — probes test identity
    directly.  ``d_until``/``p_until`` are the per-plane semiring values.
    """

    revision: int
    c_src: np.ndarray  # int32[P]
    c_srel1: np.ndarray  # int32[P]
    c_g: np.ndarray  # int32[P]
    c_grel: np.ndarray  # int32[P]
    c_d_until: np.ndarray  # int32[P]  NEVER = not definite via any path
    c_p_until: np.ndarray  # int32[P]
    # sources whose closure overflowed per_source_cap, sorted lex
    ovf_src: np.ndarray  # int32[O]
    ovf_srel1: np.ndarray  # int32[O]

    @property
    def num_pairs(self) -> int:
        return int(self.c_src.shape[0])


def _in_sorted(sorted_arr: np.ndarray, x: np.ndarray) -> np.ndarray:
    """Membership of x in a sorted unique array, via binary search."""
    if sorted_arr.size == 0 or x.size == 0:
        return np.zeros(x.shape[0], bool)
    pos = np.clip(np.searchsorted(sorted_arr, x), 0, sorted_arr.shape[0] - 1)
    return sorted_arr[pos] == x


class _Builder:
    """Mutable state of one build_closure run."""

    def __init__(self, S1: np.int64, per_source_cap: int) -> None:
        self.S1 = S1
        self.cap = per_source_cap
        self.ovf = np.zeros(0, np.int64)  # sorted unique overflowed src keys

    def add_overflow(self, keys: np.ndarray) -> None:
        if keys.size:
            self.ovf = np.union1d(self.ovf, keys)

    def group_max(self, src, dst, d, p):
        """Combine duplicate (src, dst) rows, per-plane max; lexsorted out.
        Sorts via the native parallel lexsort on the unpacked int32 columns
        (native/sort.py — numpy lexsort is tens of seconds at 100M rows)."""
        if src.size == 0:
            return src, dst, d, p
        order = lexsort4(src // self.S1, src % self.S1, dst // self.S1, dst % self.S1)
        src, dst, d, p = src[order], dst[order], d[order], p[order]
        first = np.ones(src.shape[0], bool)
        first[1:] = (src[1:] != src[:-1]) | (dst[1:] != dst[:-1])
        starts = np.nonzero(first)[0]
        return (
            src[first],
            dst[first],
            np.maximum.reduceat(d, starts),
            np.maximum.reduceat(p, starts),
        )

    def drop_oversized(self, src, dst, d, p):
        """Enforce per_source_cap; src must be sorted (post group_max)."""
        if src.size == 0:
            return src, dst, d, p
        uniq, counts = np.unique(src, return_counts=True)
        self.add_overflow(uniq[counts > self.cap])
        return self.drop_overflowed(src, dst, d, p)

    def drop_overflowed(self, src, dst, d, p):
        if self.ovf.size == 0 or src.size == 0:
            return src, dst, d, p
        keep = ~_in_sorted(self.ovf, src)
        return src[keep], dst[keep], d[keep], p[keep]


def _pair_ids(
    src_a: np.ndarray, dst_a: np.ndarray, src_b: np.ndarray, dst_b: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Dense int64 ids for (src, dst) pairs, consistent across both inputs
    and monotone w.r.t. (src, dst) lexicographic order (so a lexsorted
    table yields sorted ids, and np.searchsorted applies)."""
    ns, nb = src_a.shape[0], src_b.shape[0]
    _, inv_s = np.unique(np.concatenate([src_a, src_b]), return_inverse=True)
    ud, inv_d = np.unique(np.concatenate([dst_a, dst_b]), return_inverse=True)
    ids = inv_s.astype(np.int64) * np.int64(max(ud.shape[0], 1)) + inv_d
    return ids[:ns], ids[ns : ns + nb]


def _edge_values(cav: np.ndarray, exp: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-edge semiring weights: expiry 0 → +inf; caveated edges are
    NEVER on the definite plane (resolving them needs per-query context)."""
    w = np.where(exp == 0, np.int64(NO_EXP), exp.astype(np.int64)).astype(np.int32)
    return np.where(cav == 0, w, NEVER), w


def _expand_join(
    keys_sorted: np.ndarray, probe: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """All-pairs sort-merge join: for each probe[i], the row indices of
    every match in keys_sorted.  Returns (probe_row, match_row) flattened."""
    lo = np.searchsorted(keys_sorted, probe, "left")
    hi = np.searchsorted(keys_sorted, probe, "right")
    counts = (hi - lo).astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    reps = np.repeat(np.arange(probe.shape[0], dtype=np.int64), counts)
    ends = np.cumsum(counts)
    ii = np.repeat(lo.astype(np.int64), counts) + (
        np.arange(total, dtype=np.int64) - np.repeat(ends - counts, counts)
    )
    return reps, ii


def build_closure(
    snap: "Snapshot",
    *,
    per_source_cap: int = 4096,
    global_cap: int = 200_000_000,
    max_hops: int = 10_000,
) -> ClosureIndex:
    """Flatten the snapshot's membership graph (ms_/mp_ views) into a
    ClosureIndex via a semi-naive fixpoint of vectorized joins."""
    S1 = np.int64(snap.num_slots + 1)  # srel1 radix
    b = _Builder(S1, per_source_cap)

    def src_key(node: np.ndarray, srel1) -> np.ndarray:
        return node.astype(np.int64) * S1 + srel1

    # -- pair-level closure over userset-propagation edges ----------------
    # direct pair edges: (mp_subj # mp_srel)  →  (mp_res # mp_rel)
    e_src = src_key(snap.mp_subj, snap.mp_srel.astype(np.int64) + 1)
    e_dst = src_key(snap.mp_res, snap.mp_rel.astype(np.int64) + 1)
    e_d, e_p = _edge_values(snap.mp_caveat, snap.mp_exp)
    # self-loop edges (a#m @ a#m) add nothing to any path: drop them so the
    # no-reflexive-rows invariant holds from the initial table on
    loop = e_src == e_dst
    if loop.any():
        e_src, e_dst, e_d, e_p = e_src[~loop], e_dst[~loop], e_d[~loop], e_p[~loop]
    e_order = np.argsort(e_src, kind="stable")
    e_src, e_dst = e_src[e_order], e_dst[e_order]
    e_d, e_p = e_d[e_order], e_p[e_order]

    c_src, c_dst, c_d, c_p = b.group_max(e_src, e_dst, e_d, e_p)
    c_src, c_dst, c_d, c_p = b.drop_oversized(c_src, c_dst, c_d, c_p)
    n_src, n_dst, n_d, n_p = c_src, c_dst, c_d, c_p  # frontier

    for _ in range(max_hops):
        if n_src.size == 0:
            break
        reps, ii = _expand_join(e_src, n_dst)
        if reps.size == 0:
            n_src = n_src[:0]
            break
        j_src = n_src[reps]
        j_dst = e_dst[ii]
        j_d = np.minimum(n_d[reps], e_d[ii])
        j_p = np.minimum(n_p[reps], e_p[ii])
        keep = j_src != j_dst  # reflexivity is the probe's job
        j_src, j_dst, j_d, j_p = j_src[keep], j_dst[keep], j_d[keep], j_p[keep]
        j_src, j_dst, j_d, j_p = b.group_max(j_src, j_dst, j_d, j_p)
        # an overflowed source stays overflowed: no partial creep-back
        j_src, j_dst, j_d, j_p = b.drop_overflowed(j_src, j_dst, j_d, j_p)
        if j_src.size == 0:
            n_src = j_src
            break

        # improvement test against the current table
        c_ids, j_ids = _pair_ids(c_src, c_dst, j_src, j_dst)
        pos = np.searchsorted(c_ids, j_ids)
        posc = np.clip(pos, 0, max(c_ids.shape[0] - 1, 0))
        found = (c_ids.shape[0] > 0) & (c_ids[posc] == j_ids)
        old_d = np.where(found, c_d[posc], NEVER)
        old_p = np.where(found, c_p[posc], NEVER)
        improved = (j_d > old_d) | (j_p > old_p)
        j_src, j_dst = j_src[improved], j_dst[improved]
        j_d, j_p = j_d[improved], j_p[improved]
        if j_src.size == 0:
            n_src = j_src
            break

        c_src, c_dst, c_d, c_p = b.group_max(
            np.concatenate([c_src, j_src]),
            np.concatenate([c_dst, j_dst]),
            np.concatenate([c_d, j_d]),
            np.concatenate([c_p, j_p]),
        )
        c_src, c_dst, c_d, c_p = b.drop_oversized(c_src, c_dst, c_d, c_p)
        if c_src.size > global_cap:
            raise MemoryError(
                f"membership closure exceeded global cap ({c_src.size} pairs)"
            )
        n_src, n_dst, n_d, n_p = b.drop_overflowed(j_src, j_dst, j_d, j_p)
    if n_src.size:
        # hop budget exhausted before convergence: the unconverged sources'
        # rows may be incomplete — overflow them so queries fall back to the
        # host oracle instead of silently missing memberships
        b.add_overflow(np.unique(n_src))

    # -- user-level closure: direct seeds ∪ (seeds ⋈ pair closure) --------
    s_src = src_key(snap.ms_subj, 0)  # direct-object members, srel1 = 0
    s_dst = src_key(snap.ms_res, snap.ms_rel.astype(np.int64) + 1)
    s_d, s_p = _edge_values(snap.ms_caveat, snap.ms_exp)

    reps, ii = _expand_join(c_src, s_dst)
    if reps.size:
        u_src = np.concatenate([s_src, s_src[reps]])
        u_dst = np.concatenate([s_dst, c_dst[ii]])
        u_d = np.concatenate([s_d, np.minimum(s_d[reps], c_d[ii])])
        u_p = np.concatenate([s_p, np.minimum(s_p[reps], c_p[ii])])
    else:
        u_src, u_dst, u_d, u_p = s_src, s_dst, s_d, s_p
    # a user whose seed points at an overflowed pair overflows too: the
    # pair's (dropped) closure would have been part of the user's closure
    if b.ovf.size:
        b.add_overflow(np.unique(s_src[_in_sorted(b.ovf, s_dst)]))
    u_src, u_dst, u_d, u_p = b.group_max(u_src, u_dst, u_d, u_p)
    u_src, u_dst, u_d, u_p = b.drop_oversized(u_src, u_dst, u_d, u_p)

    # -- assemble (final sweep drops any row of an overflowed source) -----
    a_src = np.concatenate([u_src, c_src])
    a_dst = np.concatenate([u_dst, c_dst])
    a_d = np.concatenate([u_d, c_d]).astype(np.int32)
    a_p = np.concatenate([u_p, c_p]).astype(np.int32)
    a_src, a_dst, a_d, a_p = b.drop_overflowed(a_src, a_dst, a_d, a_p)
    order = lexsort4(a_src // S1, a_src % S1, a_dst // S1, a_dst % S1)
    a_src, a_dst, a_d, a_p = a_src[order], a_dst[order], a_d[order], a_p[order]

    return ClosureIndex(
        revision=snap.revision,
        c_src=(a_src // S1).astype(np.int32),
        c_srel1=(a_src % S1).astype(np.int32),
        c_g=(a_dst // S1).astype(np.int32),
        c_grel=(a_dst % S1 - 1).astype(np.int32),
        c_d_until=a_d,
        c_p_until=a_p,
        ovf_src=(b.ovf // S1).astype(np.int32),
        ovf_srel1=(b.ovf % S1).astype(np.int32),
    )

"""Host-side tuple storage: interners, the MVCC tuple log, and columnar
snapshot materialization.

This subsystem plays the role SpiceDB's datastore plays behind the
reference client: writes are validated against the schema and applied
atomically with preconditions (rel/txn.go semantics), every write mints a
revision token (ZedToken analogue, client/client.go:125), and reads/checks
evaluate against a materialized snapshot generation selected by a
consistency Strategy (SURVEY.md §5 "Checkpoint / resume").

The S2-compression lesson from the reference ("compress the boundary",
README.md:22) becomes: intern strings host-side once, ship only int32/int64
columns across the host↔device boundary.
"""

from .interner import Interner
from .store import RevisionToken, Store, parse_revision
from .snapshot import Snapshot

__all__ = ["Interner", "Store", "Snapshot", "RevisionToken", "parse_revision"]

"""Group-commit write pipeline: the write-side mirror of the serving
micro-batcher (serve/batcher.py).

Every revision costs fixed machinery regardless of how many tuples it
carries — a closure advance, a device table reship, a snapshot finish,
a replication frame.  One-transaction-at-a-time writes pay that
machinery per transaction; production write streams (PAPER.md §3.2:
~10k writes/s sustained while serving reads) amortize it across a
GROUP.  This module forms the groups:

- ``GroupCommitter`` coalesces concurrent ``submit(txn)`` calls and
  commits each group through ``Store.write_group`` — ONE collapsed
  last-writer-wins delta, ONE log entry, per-transaction zookies minted
  inside the group (base+1..base+k) so client-visible revision
  semantics are unchanged.  Two daemon threads, so group FORMATION
  overlaps the in-flight group APPLICATION (the serve-side former/
  dispatcher overlap, transplanted): the former drains the submission
  queue into the next group while the applier holds the store lock for
  the previous one.  The deadline-aware hold-back reuses the admission
  ``CostModel`` (utils/admission.py) — a DEDICATED instance fed by
  group-apply walls, so write-apply EWMAs never pollute the read-path
  deadline shed's estimate.

- ``ChainCompactor`` is the background half of the LSM story: today a
  long delta chain materializes only when the static
  ``max(lsm_compact_min, E/8)`` trip fires INSIDE apply_delta — a
  synchronous O(E) merge landing on whichever writer crosses the bound.
  The compactor polls the newest resident generation off the request
  path and materializes the chain early (at a soft fraction of the hard
  trip), so week-long write streams keep probe depth bounded without
  any writer ever paying the merge.  ``LsmSnapshot._materialize`` is
  idempotent under its own lock, so compacting OUTSIDE the store lock
  races safely with a reader touching a lazy column.

Telemetry: ``write.group_size`` (store-side histogram, writes per
group), ``write.group_form_wall`` (formation wall histogram),
``write.flush_{full,deadline,maxhold,drain}`` counters,
``store.lsm_overlay_rows`` / ``store.lsm_chain_len`` gauges,
``store.bg_compactions`` counter — and a ``write_path`` /perf section
(utils/perf.py register_report_section) next to the read-side buckets.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import List, Optional

from ..utils import metrics as _metrics
from ..utils import perf as _perf
from ..utils.admission import CostModel
from ..utils.errors import DeadlineExceededError, ShedError, UnavailableError
from .delta import LSM_COMPACT_MIN


@dataclass(frozen=True)
class GroupCommitConfig:
    """Tuning for the group-commit former and the chain compactor."""

    #: transactions per group before the former flushes on "full"
    max_group: int = 256
    #: max seconds a queued transaction may wait before a partial group
    #: flushes anyway (the hold-back ceiling)
    hold_max_s: float = 0.002
    #: safety slack subtracted from deadline budgets in the hold-back
    #: decision (clock granularity + wakeup jitter)
    deadline_margin_s: float = 0.0005
    #: pending transactions before submit() sheds with ``ShedError``
    queue_max: int = 8_192
    #: seconds close() waits for the drain before rejecting leftovers
    drain_timeout_s: float = 10.0
    #: chain-compactor poll interval (seconds); 0 disables the worker
    compact_poll_s: float = 0.05
    #: soft trip as a fraction of the hard max(lsm_compact_min, E/8)
    #: bound: the compactor materializes early so apply_delta never has
    #: to do it synchronously on a writer
    compact_fraction: float = 0.5


#: formation-wall histogram uppers (seconds, first-submission→formed)
GROUP_FORM_WALL_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
)

#: flush reasons → counter names (write.flush_*)
_FLUSH_FULL = "full"
_FLUSH_DEADLINE = "deadline"
_FLUSH_MAXHOLD = "maxhold"
_FLUSH_DRAIN = "drain"

#: guards lazy waiter-event creation on WriteFuture (module-global, same
#: rationale as the serve batcher's: the submit path must not pay ~8µs
#: of Event construction for a wait that usually never happens)
_FUT_EV_LOCK = threading.Lock()


class WriteFuture:
    """The zookie handle one submitted transaction awaits.  Resolves
    exactly once: with the minted revision token, or with the exception
    that ejected the transaction (precondition, CREATE conflict,
    validation) or failed its whole group (injected fault, store
    error)."""

    __slots__ = ("_done", "_ev", "_value", "_error", "t_submit", "t_done")

    def __init__(self, t_submit: float) -> None:
        self._done = False
        self._ev: Optional[threading.Event] = None
        self._value: Optional[str] = None
        self._error: Optional[BaseException] = None
        self.t_submit = t_submit
        self.t_done: Optional[float] = None

    def done(self) -> bool:
        return self._done

    def _settle(self) -> None:
        self._done = True
        ev = self._ev
        if ev is None:
            with _FUT_EV_LOCK:
                ev = self._ev
        if ev is not None:
            ev.set()

    def _resolve(self, value: str, t_done: float) -> None:
        assert not self._done, "write future resolved twice"
        self._value = value
        self.t_done = t_done
        self._settle()

    def _reject(self, err: BaseException, t_done: float) -> None:
        assert not self._done, "write future resolved twice"
        self._error = err
        self.t_done = t_done
        self._settle()

    def result(self, ctx=None, timeout: Optional[float] = None) -> str:
        """Block until the zookie (or the ejection error) arrives.
        ``ctx`` cancellation/deadline interrupts the wait."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if not self._done and self._ev is None:
            with _FUT_EV_LOCK:
                if self._ev is None:
                    self._ev = threading.Event()
        while not self._done:
            if ctx is not None:
                err = ctx.err()
                if err is not None:
                    raise err
            step = 0.05
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise DeadlineExceededError(
                        "timed out waiting for group commit"
                    )
                step = min(step, remaining)
            self._ev.wait(step)
        if self._error is not None:
            raise self._error
        return self._value


class _WriteSub:
    __slots__ = ("txn", "deadline", "future", "t_queued")

    def __init__(self, txn, deadline, future, t_queued):
        self.txn = txn
        self.deadline = deadline  # absolute monotonic, or None
        self.future = future
        self.t_queued = t_queued


class _FormedGroup:
    __slots__ = ("subs", "reason", "t_formed")

    def __init__(self, subs, reason, t_formed):
        self.subs = subs
        self.reason = reason
        self.t_formed = t_formed


class GroupCommitter:
    """Coalesce concurrent write transactions into atomic store groups."""

    def __init__(
        self,
        store,
        config: Optional[GroupCommitConfig] = None,
        *,
        registry: Optional[_metrics.Metrics] = None,
    ) -> None:
        self._store = store
        self._cfg = config if config is not None else GroupCommitConfig()
        self._m = registry if registry is not None else _metrics.default
        # dedicated estimator, shared CLASS with the admission gate: the
        # hold-back asks "would holding this txn past its deadline,
        # given what a group apply costs" with the same EWMA machinery
        # the read shed uses — but write-apply samples must not inflate
        # the read path's expected dispatch cost, so no shared instance
        self._cost = CostModel()
        self._cond = threading.Condition()
        self._pending: deque = deque()
        self._closing = False
        self._apply_q: _queue.Queue = _queue.Queue(maxsize=1)
        _perf.register_report_section("write_path", self._report_section)
        self._former = threading.Thread(
            target=self._former_loop, name="group-commit-former", daemon=True
        )
        self._applier = threading.Thread(
            target=self._applier_loop, name="group-commit-applier", daemon=True
        )
        self._former.start()
        self._applier.start()

    # -- submit ----------------------------------------------------------
    def submit(self, txn, *, deadline: Optional[float] = None) -> WriteFuture:
        """Queue one transaction for the next group; returns the future
        its zookie (or ejection error) arrives on.  Sheds with
        ``ShedError`` past ``queue_max`` pending transactions — bounded
        queues, same contract as the serving front-end."""
        now = time.monotonic()
        fut = WriteFuture(now)
        with self._cond:
            if self._closing:
                raise UnavailableError("group committer is closed")
            if len(self._pending) >= self._cfg.queue_max:
                raise ShedError(
                    f"write queue at capacity ({self._cfg.queue_max})"
                )
            self._pending.append(_WriteSub(txn, deadline, fut, now))
            self._cond.notify_all()
        return fut

    def write(self, txn, ctx=None, *, timeout: Optional[float] = None) -> str:
        """Submit and wait — the drop-in replacement for ``store.write``
        the client routes through when group commit is on."""
        deadline = None
        if ctx is not None:
            dl = getattr(ctx, "deadline", None)
            if callable(dl):
                dl = dl()
            if dl is not None:
                deadline = float(dl)
        return self.submit(txn, deadline=deadline).result(ctx, timeout)

    # -- formation -------------------------------------------------------
    def _flush_decision_locked(self, now: float):
        """(flush?, reason, wait_s) for the current queue state."""
        if not self._pending:
            return False, None, None
        if len(self._pending) >= self._cfg.max_group:
            return True, _FLUSH_FULL, None
        oldest = self._pending[0]
        held = now - oldest.t_queued
        if held >= self._cfg.hold_max_s:
            return True, _FLUSH_MAXHOLD, None
        wait = self._cfg.hold_max_s - held
        earliest = min(
            (s.deadline for s in self._pending if s.deadline is not None),
            default=None,
        )
        if earliest is not None:
            # deadline-aware hold-back: flush once waiting longer would
            # push the earliest deadline past the expected apply cost
            slack = (
                (earliest - now)
                - self._cost.expected_s()
                - self._cfg.deadline_margin_s
            )
            if slack <= 0:
                return True, _FLUSH_DEADLINE, None
            wait = min(wait, slack)
        return False, None, max(wait, 0.0)

    def _form_group(self) -> Optional[_FormedGroup]:
        """Block until a group is due, then drain it from the queue.
        Returns None when closing with nothing left to drain."""
        with self._cond:
            while True:
                now = time.monotonic()
                if self._closing:
                    if not self._pending:
                        return None
                    flush, reason = True, _FLUSH_DRAIN
                else:
                    flush, reason, wait = self._flush_decision_locked(now)
                if flush:
                    subs = []
                    while self._pending and len(subs) < self._cfg.max_group:
                        subs.append(self._pending.popleft())
                    t_formed = time.monotonic()
                    self._m.inc(f"write.flush_{reason}")
                    self._m.observe("write.form_s", t_formed - subs[0].t_queued)
                    self._m.observe_hist(
                        "write.group_form_wall",
                        t_formed - subs[0].t_queued,
                        GROUP_FORM_WALL_BUCKETS,
                    )
                    return _FormedGroup(subs, reason, t_formed)
                self._cond.wait(
                    self._cfg.hold_max_s if wait is None else wait
                )

    def _former_loop(self) -> None:
        while True:
            try:
                group = self._form_group()
            except Exception:  # emergency stop: never kill the thread
                time.sleep(0.002)
                continue
            if group is None:
                self._apply_q.put(None)  # drain sentinel for the applier
                return
            self._apply_q.put(group)

    # -- application -----------------------------------------------------
    def _apply_group(self, group: _FormedGroup) -> None:
        t0 = time.monotonic()
        try:
            outcomes = self._store.write_group([s.txn for s in group.subs])
        except BaseException as e:
            # whole-group failure (injected fault, store error): every
            # member rejects with the same error — the group was atomic,
            # nothing applied, a retry resubmits cleanly
            now = time.monotonic()
            for s in group.subs:
                if not s.future.done():
                    s.future._reject(e, now)
            return
        t1 = time.monotonic()
        self._cost.observe(t1 - t0)
        self._m.inc("write.groups")
        self._m.inc("write.txns", len(group.subs))
        self._m.observe("write.apply_s", t1 - t0)
        for s, out in zip(group.subs, outcomes):
            if isinstance(out, BaseException):
                s.future._reject(out, t1)
            else:
                s.future._resolve(out, t1)

    def _applier_loop(self) -> None:
        while True:
            group = self._apply_q.get()
            if group is None:
                return
            try:
                self._apply_group(group)
            except Exception:
                # _apply_group settles futures itself; a failure past
                # that point must not take the applier down
                now = time.monotonic()
                for s in group.subs:
                    if not s.future.done():
                        s.future._reject(
                            UnavailableError("group apply failed"), now
                        )

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        """Drain pending groups and stop both threads.  Submissions the
        drain window cannot flush reject with ``UnavailableError``."""
        with self._cond:
            if self._closing:
                return
            self._closing = True
            self._cond.notify_all()
        self._former.join(timeout=self._cfg.drain_timeout_s)
        self._applier.join(timeout=self._cfg.drain_timeout_s)
        now = time.monotonic()
        with self._cond:
            leftovers = list(self._pending)
            self._pending.clear()
        for s in leftovers:
            if not s.future.done():
                s.future._reject(
                    UnavailableError("group committer closed"), now
                )

    # -- observability ---------------------------------------------------
    def _report_section(self) -> dict:
        """The ``write_path`` /perf section: group formation and apply
        next to the read-side wall buckets."""
        hists = self._m.hist_snapshot()

        def _hist(name):
            h = hists.get(name)
            if h is None:
                return None
            uppers, counts, total, s, _ = h
            return {
                "uppers": list(uppers), "counts": counts,
                "total": total, "sum": s,
            }

        return {
            "groups": self._m.counter("write.groups"),
            "txns": self._m.counter("write.txns"),
            "flush": {
                r: self._m.counter(f"write.flush_{r}")
                for r in (_FLUSH_FULL, _FLUSH_DEADLINE, _FLUSH_MAXHOLD,
                          _FLUSH_DRAIN)
            },
            "group_size": _hist("write.group_size"),
            "group_form_wall_s": _hist("write.group_form_wall"),
            "apply_cost": self._cost.state(),
            "chain": {
                "overlay_rows": self._m.gauge("store.lsm_overlay_rows"),
                "chain_len": self._m.gauge("store.lsm_chain_len"),
                "bg_compactions": self._m.counter("store.bg_compactions"),
                "batch_applies": self._m.counter("closure.batch_applies"),
            },
        }


class ChainCompactor:
    """Low-priority worker that materializes long delta chains off the
    request path.

    Polls ``Store.peek_chain()`` and, when the accumulated overlay
    crosses ``compact_fraction`` of the hard ``max(lsm_compact_min,
    E/8)`` trip, merges the chain OUTSIDE the store lock
    (``LsmSnapshot._materialize`` is idempotent under the snapshot's own
    lock, so it races safely with readers touching lazy columns and
    with the trip firing inside apply_delta).  The next apply_delta
    then starts a fresh chain from the merged base — probe depth stays
    bounded instead of ratcheting toward a synchronous O(E) merge on a
    writer."""

    def __init__(
        self,
        store,
        config: Optional[GroupCommitConfig] = None,
        *,
        registry: Optional[_metrics.Metrics] = None,
    ) -> None:
        self._store = store
        self._cfg = config if config is not None else GroupCommitConfig()
        self._m = registry if registry is not None else _metrics.default
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="chain-compactor", daemon=True
        )
        if self._cfg.compact_poll_s > 0:
            self._thread.start()

    def poll_once(self) -> bool:
        """One poll: publish chain gauges, compact if due.  Returns True
        when a compaction ran (exposed for tests and benchmarks that
        drive the compactor deterministically)."""
        got = self._store.peek_chain()
        if got is None:
            self._m.set_gauge("store.lsm_overlay_rows", 0.0)
            self._m.set_gauge("store.lsm_chain_len", 0.0)
            return False
        snap, rows, chain_len = got
        self._m.set_gauge("store.lsm_overlay_rows", float(rows))
        self._m.set_gauge("store.lsm_chain_len", float(chain_len))
        if rows <= 0:
            return False
        cm = getattr(self._store, "lsm_compact_min", None)
        if cm is None:
            cm = LSM_COMPACT_MIN
        trip = max(int(cm), int(snap.num_edges) // 8)
        if rows <= trip * self._cfg.compact_fraction:
            return False
        mat = getattr(snap, "_materialize", None)
        if mat is None:
            return False
        # NEVER compact_ctx here: the device may still hold this
        # revision's delta_info, and renumbering contexts post-handoff
        # would invalidate ids it already consumed
        mat(compact_ctx=False)
        self._m.inc("store.bg_compactions")
        self._m.set_gauge("store.lsm_overlay_rows", 0.0)
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self._cfg.compact_poll_s):
            try:
                self.poll_once()
            except Exception:
                # best-effort worker: a transient race (snapshot evicted
                # mid-poll) must not kill the thread
                continue

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)

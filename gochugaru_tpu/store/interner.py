"""String interning: (object_type, object_id) pairs → dense int32 node ids.

Node ids are append-only and stable across revisions, which is what lets
watch-driven incremental re-indexing (BASELINE config 5) patch device
buffers instead of rebuilding them.  Wildcard subjects (``user:*``) are
interned as ordinary nodes with id ``*`` so a wildcard grant is an exact
device-side key lookup.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np


class Interner:
    """Bidirectional (type, id) ↔ node-int mapping, thread-safe, append-only."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._node_of: Dict[Tuple[str, str], int] = {}
        self._types: Dict[str, int] = {}
        self._type_names: List[str] = []
        self._keys: List[Tuple[str, str]] = []
        self._node_type: List[int] = []

    # -- types -------------------------------------------------------------
    def type_id(self, type_name: str) -> int:
        with self._lock:
            return self._type_id_locked(type_name)

    def _type_id_locked(self, type_name: str) -> int:
        tid = self._types.get(type_name)
        if tid is None:
            tid = len(self._type_names)
            self._types[type_name] = tid
            self._type_names.append(type_name)
        return tid

    def type_name(self, tid: int) -> str:
        return self._type_names[tid]

    def type_lookup(self, type_name: str) -> int:
        """Interner type id or -1, without interning.  NOTE: interner type
        ids are assigned in first-seen order and are NOT the schema
        compiler's type ids — always translate names through the right
        table."""
        with self._lock:
            return self._types.get(type_name, -1)

    # -- nodes -------------------------------------------------------------
    def node(self, type_name: str, object_id: str) -> int:
        """Intern (create if needed) and return the node id."""
        key = (type_name, object_id)
        with self._lock:
            n = self._node_of.get(key)
            if n is None:
                n = len(self._keys)
                self._node_of[key] = n
                self._keys.append(key)
                self._node_type.append(self._type_id_locked(type_name))
            return n

    def lookup(self, type_name: str, object_id: str) -> int:
        """Return the node id or -1 without interning (query path: an
        unknown object can never have permissions, so -1 flows through the
        engine as a guaranteed miss — checks on nonexistent resources return
        False, not an error, client/client_test.go:209-215)."""
        return self._node_of.get((type_name, object_id), -1)

    def key_of(self, node: int) -> Tuple[str, str]:
        return self._keys[node]

    def keys_batch(self, nodes) -> List[Tuple[str, str]]:
        """(type, id) pairs for an int array of nodes — the batched
        decode path (snapshot exports).  Reads race-safely without the
        lock: the list is append-only and CPython appends are atomic."""
        k = self._keys
        return [k[n] for n in np.asarray(nodes).tolist()]

    def __len__(self) -> int:
        return len(self._keys)

    @property
    def num_types(self) -> int:
        return len(self._type_names)

    def node_type_array(self) -> np.ndarray:
        """int32[num_nodes] type id per node (snapshot-time copy)."""
        with self._lock:
            return np.asarray(self._node_type, dtype=np.int32)

    def node_type_tail(self, start: int) -> np.ndarray:
        """Type ids of nodes interned at or after ``start`` — lets the
        O(delta) snapshot path extend a base node_type array without
        copying the full list (store/delta.py LsmSnapshot)."""
        with self._lock:
            return np.asarray(self._node_type[start:], dtype=np.int32)

"""Columnar snapshot materialization.

A Snapshot is the device-facing form of the tuple graph at one revision:
sorted int64-keyed columnar arrays built once on the host, then shipped to
TPU.  Four views cover every access pattern the evaluator needs, each a
sorted array family binary-searchable on device:

- **primary** (``e_*``): every live edge sorted by (forward key, subject
  key) — O(log E) exact-match direct/wildcard leaf tests.
- **usersets** (``us_*``): edges with userset subjects sorted by forward
  key — leaf tests gather the userset grants under (relation, resource).
- **membership** (``ms_*``/``mp_*``): the group-nesting subgraph — direct
  seeds by subject node, userset propagation edges by subject userset key —
  the Phase-A subject-closure BFS frontier arrays.  Restricted to usersets
  that actually appear as tuple subjects, which keeps the closure the size
  of the *group* structure rather than the whole grant set.
- **arrows** (``ar_*``): edges of tupleset (arrow-LHS) relations by forward
  key — the Phase-B resource-subgraph BFS.

Key packing: ``fwd = rel_slot * num_nodes + res_node`` and
``userset = node * num_slots + rel_slot`` (both < 2^40 for int64 safety at
2^31 nodes × 2^8 slots).
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..rel.filter import Filter
from ..rel.relationship import Relationship, WILDCARD_ID
from ..schema.compiler import CompiledSchema
from .interner import Interner


from ..rel.relationship import expiration_micros as _to_micros


def _from_micros(us: int) -> Optional[_dt.datetime]:
    if us == 0:
        return None
    return _dt.datetime.fromtimestamp(us / 1_000_000, tz=_dt.timezone.utc)


@dataclass
class Snapshot:
    """Immutable columnar view of the graph at one revision."""

    revision: int
    compiled: CompiledSchema
    interner: Interner
    num_nodes: int
    num_slots: int
    node_type: np.ndarray  # int32[num_nodes]
    wildcard_node_of_type: np.ndarray  # int32[num_types]; -1 = none

    # primary: all edges sorted by (e_k1, e_k2)
    e_k1: np.ndarray  # int64[E]  rel_slot * num_nodes + res_node
    e_k2: np.ndarray  # int64[E]  subj_node * (num_slots+1) + subj_rel_slot + 1
    e_caveat: np.ndarray  # int32[E]  0 = none
    e_ctx: np.ndarray  # int32[E]  index into contexts, -1 = none
    e_exp: np.ndarray  # int64[E]  expiry micros, 0 = none

    # userset edges sorted by us_k1
    us_k1: np.ndarray
    us_key: np.ndarray  # int64  subj_node * num_slots + subj_rel_slot
    us_caveat: np.ndarray
    us_ctx: np.ndarray
    us_exp: np.ndarray

    # membership seeds (direct edges into used usersets) sorted by ms_subj
    ms_subj: np.ndarray  # int32
    ms_key: np.ndarray  # int64  res_node * num_slots + rel_slot
    ms_caveat: np.ndarray
    ms_ctx: np.ndarray
    ms_exp: np.ndarray

    # membership propagation (userset edges into used usersets) by mp_skey
    mp_skey: np.ndarray  # int64  subj_node * num_slots + subj_rel_slot
    mp_key: np.ndarray  # int64  res_node * num_slots + rel_slot
    mp_caveat: np.ndarray
    mp_ctx: np.ndarray
    mp_exp: np.ndarray

    # arrow (tupleset) edges sorted by ar_k1
    ar_k1: np.ndarray
    ar_child: np.ndarray  # int32 subject node
    ar_caveat: np.ndarray
    ar_ctx: np.ndarray
    ar_exp: np.ndarray

    contexts: List[Mapping[str, Any]] = field(default_factory=list)

    # ------------------------------------------------------------------
    @property
    def num_edges(self) -> int:
        return int(self.e_k1.shape[0])

    def fwd_key(self, rel_slot: int, res_node: int) -> int:
        return rel_slot * self.num_nodes + res_node

    def userset_key(self, node: int, rel_slot: int) -> int:
        return node * self.num_slots + rel_slot

    # -- host-side reads ------------------------------------------------
    def decode_edge(self, i: int) -> Relationship:
        k1 = int(self.e_k1[i])
        k2 = int(self.e_k2[i])
        rel_slot, res_node = divmod(k1, self.num_nodes)
        subj_node, srel1 = divmod(k2, self.num_slots + 1)
        rtype, rid = self.interner.key_of(res_node)
        stype, sid = self.interner.key_of(subj_node)
        slot_names = self._slot_names()
        caveat_id = int(self.e_caveat[i])
        caveat_name = ""
        caveat_ctx: Mapping[str, Any] = {}
        if caveat_id:
            caveat_name = self._caveat_names()[caveat_id]
            ctx_i = int(self.e_ctx[i])
            if ctx_i >= 0:
                caveat_ctx = self.contexts[ctx_i]
        return Relationship(
            resource_type=rtype,
            resource_id=rid,
            resource_relation=slot_names[rel_slot],
            subject_type=stype,
            subject_id=sid,
            subject_relation=slot_names[srel1 - 1] if srel1 > 0 else "",
            caveat_name=caveat_name,
            caveat_context=caveat_ctx,
            expiration=_from_micros(int(self.e_exp[i])),
        )

    def _slot_names(self) -> Dict[int, str]:
        if not hasattr(self, "_slot_name_cache"):
            self._slot_name_cache = {v: k for k, v in self.compiled.slot_of_name.items()}
        return self._slot_name_cache

    def _caveat_names(self) -> Dict[int, str]:
        if not hasattr(self, "_caveat_name_cache"):
            self._caveat_name_cache = {v: k for k, v in self.compiled.caveat_ids.items()}
        return self._caveat_name_cache

    def iter_relationships(
        self, f: Optional[Filter] = None, now_us: Optional[int] = None
    ) -> Iterator[Relationship]:
        """Filtered scan, vectorized on the interned columns; expired edges
        are excluded (they no longer grant, rel/relationship.go:43-45)."""
        mask = np.ones(self.num_edges, dtype=bool)
        if now_us is not None:
            mask &= (self.e_exp == 0) | (self.e_exp > now_us)
        if f is not None and self.num_edges:
            rel_slot = self.e_k1 // self.num_nodes
            res_node = self.e_k1 % self.num_nodes
            subj_node = self.e_k2 // (self.num_slots + 1)
            srel1 = self.e_k2 % (self.num_slots + 1)
            if f.resource_type != "":
                # node_type holds INTERNER type ids, not schema type ids
                tid = self.interner.type_lookup(f.resource_type)
                if tid < 0:
                    return
                mask &= self.node_type[res_node] == tid
            if f.optional_resource_id != "":
                if f.resource_type == "":
                    return  # resource type is required by construction
                n = self.interner.lookup(f.resource_type, f.optional_resource_id)
                if n < 0:
                    return
                mask &= res_node == n
            if f.optional_relation != "":
                s = self.compiled.slot_of_name.get(f.optional_relation)
                if s is None:
                    return
                mask &= rel_slot == s
            sf = f.optional_subject_filter
            if sf is not None:
                if sf.subject_type != "":
                    tid = self.interner.type_lookup(sf.subject_type)
                    if tid < 0:
                        return
                    mask &= self.node_type[subj_node] == tid
                if sf.optional_subject_id != "":
                    if sf.subject_type == "":
                        return
                    n = self.interner.lookup(sf.subject_type, sf.optional_subject_id)
                    if n < 0:
                        return
                    mask &= subj_node == n
                if sf.optional_relation is not None:
                    if sf.optional_relation == "":
                        mask &= srel1 == 0
                    else:
                        s = self.compiled.slot_of_name.get(sf.optional_relation)
                        if s is None:
                            return
                        mask &= srel1 == s + 1
        for i in np.nonzero(mask)[0]:
            yield self.decode_edge(int(i))


def build_snapshot(
    revision: int,
    compiled: CompiledSchema,
    interner: Interner,
    relationships: Sequence[Relationship],
) -> Snapshot:
    """Materialize sorted columnar arrays from live relationships."""
    num_nodes = max(len(interner), 1)
    num_slots = max(compiled.num_slots, 1)
    E = len(relationships)

    res = np.empty(E, dtype=np.int64)
    rel_s = np.empty(E, dtype=np.int64)
    subj = np.empty(E, dtype=np.int64)
    srel = np.empty(E, dtype=np.int64)  # -1 = direct
    cav = np.zeros(E, dtype=np.int32)
    ctx = np.full(E, -1, dtype=np.int32)
    exp = np.zeros(E, dtype=np.int64)
    contexts: List[Mapping[str, Any]] = []

    slot_of = compiled.slot_of_name
    caveat_ids = compiled.caveat_ids
    for i, r in enumerate(relationships):
        res[i] = interner.node(r.resource_type, r.resource_id)
        rel_s[i] = slot_of[r.resource_relation]
        subj[i] = interner.node(r.subject_type, r.subject_id)
        srel[i] = slot_of[r.subject_relation] if r.subject_relation else -1
        if r.caveat_name:
            cav[i] = caveat_ids[r.caveat_name]
            if r.caveat_context:
                ctx[i] = len(contexts)
                contexts.append(r.caveat_context)
        exp[i] = _to_micros(r.expiration)

    node_type = interner.node_type_array()
    num_nodes = max(len(interner), 1)  # interning above may have grown it

    wc = np.full(interner.num_types, -1, dtype=np.int32)
    for tname, tid_schema in compiled.type_ids.items():
        n = interner.lookup(tname, WILDCARD_ID)
        if n >= 0:
            itid = interner.type_id(tname)
            if itid < wc.shape[0]:
                wc[itid] = n

    k1 = rel_s * num_nodes + res
    k2 = subj * (num_slots + 1) + (srel + 1)

    order = np.lexsort((k2, k1))
    e_k1, e_k2 = k1[order], k2[order]
    e_cav, e_ctx, e_exp = cav[order], ctx[order], exp[order]

    res_o, rel_o, subj_o, srel_o = res[order], rel_s[order], subj[order], srel[order]

    # userset view
    is_us = srel_o >= 0
    us_sort = np.argsort(e_k1[is_us], kind="stable")
    us_k1 = e_k1[is_us][us_sort]
    us_key = (subj_o[is_us] * num_slots + srel_o[is_us])[us_sort]
    us_cav = e_cav[is_us][us_sort]
    us_ctx = e_ctx[is_us][us_sort]
    us_exp = e_exp[is_us][us_sort]

    # usersets used as subjects anywhere
    used = np.unique(us_key)

    edge_key = res_o * num_slots + rel_o  # the userset each edge grants

    feeds = np.isin(edge_key, used)
    # seeds: direct edges into used usersets, by subject node
    seed_mask = feeds & (srel_o < 0)
    seed_sort = np.argsort(subj_o[seed_mask], kind="stable")
    ms_subj = subj_o[seed_mask][seed_sort].astype(np.int32)
    ms_key = edge_key[seed_mask][seed_sort]
    ms_cav = e_cav[seed_mask][seed_sort]
    ms_ctx = e_ctx[seed_mask][seed_sort]
    ms_exp = e_exp[seed_mask][seed_sort]

    # propagation: userset edges into used usersets, by subject userset key
    prop_mask = feeds & (srel_o >= 0)
    prop_skey = subj_o[prop_mask] * num_slots + srel_o[prop_mask]
    prop_sort = np.argsort(prop_skey, kind="stable")
    mp_skey = prop_skey[prop_sort]
    mp_key = edge_key[prop_mask][prop_sort]
    mp_cav = e_cav[prop_mask][prop_sort]
    mp_ctx = e_ctx[prop_mask][prop_sort]
    mp_exp = e_exp[prop_mask][prop_sort]

    # arrow view: tupleset relations, direct subjects only (SpiceDB arrows
    # traverse ellipsis subjects)
    ts_slots = np.asarray(sorted(compiled.tupleset_slots), dtype=np.int64)
    ar_mask = np.isin(rel_o, ts_slots) & (srel_o < 0)
    ar_sort = np.argsort(e_k1[ar_mask], kind="stable")
    ar_k1 = e_k1[ar_mask][ar_sort]
    ar_child = subj_o[ar_mask][ar_sort].astype(np.int32)
    ar_cav = e_cav[ar_mask][ar_sort]
    ar_ctx = e_ctx[ar_mask][ar_sort]
    ar_exp = e_exp[ar_mask][ar_sort]

    return Snapshot(
        revision=revision,
        compiled=compiled,
        interner=interner,
        num_nodes=num_nodes,
        num_slots=num_slots,
        node_type=node_type,
        wildcard_node_of_type=wc,
        e_k1=e_k1, e_k2=e_k2, e_caveat=e_cav, e_ctx=e_ctx, e_exp=e_exp,
        us_k1=us_k1, us_key=us_key, us_caveat=us_cav, us_ctx=us_ctx, us_exp=us_exp,
        ms_subj=ms_subj, ms_key=ms_key, ms_caveat=ms_cav, ms_ctx=ms_ctx, ms_exp=ms_exp,
        mp_skey=mp_skey, mp_key=mp_key, mp_caveat=mp_cav, mp_ctx=mp_ctx, mp_exp=mp_exp,
        ar_k1=ar_k1, ar_child=ar_child, ar_caveat=ar_cav, ar_ctx=ar_ctx, ar_exp=ar_exp,
        contexts=contexts,
    )

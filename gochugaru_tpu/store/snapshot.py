"""Columnar snapshot materialization.

A Snapshot is the device-facing form of the tuple graph at one revision:
lexicographically sorted int32 columns built once on the host, then shipped
to TPU.  Everything is int32 on purpose — TPU has no native int64, so keys
are kept as column tuples compared lexicographically (custom binary search /
multi-operand ``lax.sort``) instead of packed 64-bit scalars.  Expirations
are epoch-relative seconds clipped into int32 around a per-snapshot epoch.

Four views cover every access pattern the evaluator needs:

- **primary** (``e_*``): every live edge sorted by (rel, res, subj, srel) —
  O(log E) exact-match direct/wildcard leaf tests.
- **usersets** (``us_*``): edges with userset subjects sorted by (rel, res)
  — leaf tests gather the userset grants under (relation, resource).
- **membership** (``ms_*``/``mp_*``): the group-nesting subgraph — direct
  seeds by subject node, userset propagation edges by (subject, srel) — the
  Phase-A subject-closure BFS arrays.  Restricted to usersets that actually
  appear as tuple subjects, which keeps the closure the size of the *group*
  structure rather than the whole grant set.
- **arrows** (``ar_*``): edges of tupleset (arrow-LHS) relations by
  (rel, res) — the Phase-B resource-subgraph BFS.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from ..native.sort import argsort1, lexsort2, lexsort4
from ..rel.filter import Filter
from ..rel.relationship import Relationship, WILDCARD_ID, expiration_micros
from ..schema.compiler import CompiledSchema
from .interner import Interner

#: int32 sentinel used to pad sorted key columns past the end.
I32_MAX = np.int32(2**31 - 1)


def _exp_to_rel32(exp_us: np.ndarray, epoch_us: int) -> np.ndarray:
    """Expiry micros → epoch-relative seconds in int32 (ceiling, so an
    expiry never rounds earlier).  0 stays 0 ("no expiration"); an expiry
    that would land exactly on 0 (i.e. at/before the snapshot epoch) maps
    to -1 so it can't collide with the no-expiration sentinel; out-of-range
    futures clip to I32_MAX-1 (still in the future for any plausible query
    time)."""
    if not exp_us.any():
        # bulk imports rarely carry expirations: skip the int64 clip
        # chain for the all-zero column (identical output — zero maps
        # to the no-expiration sentinel 0 either way)
        return np.zeros(exp_us.shape[0], np.int32)
    rel = np.clip(
        -(-(exp_us - epoch_us) // 1_000_000),  # ceil division
        -(2**31) + 2,
        2**31 - 2,
    )
    rel = np.where(rel == 0, np.int64(-1), rel)
    return np.where(exp_us == 0, np.int64(0), rel).astype(np.int32)


@dataclass
class Snapshot:
    """Immutable columnar view of the graph at one revision."""

    revision: int
    compiled: CompiledSchema
    interner: Interner
    num_nodes: int
    num_slots: int
    epoch_us: int  # expiration reference epoch (snapshot build time)
    node_type: np.ndarray  # int32[num_nodes] INTERNER type ids
    wildcard_node_of_type: np.ndarray  # int32[interner num_types]; -1 = none

    # primary: all edges sorted lex by (rel, res, subj, srel1)
    e_rel: np.ndarray  # int32[E]
    e_res: np.ndarray  # int32[E]
    e_subj: np.ndarray  # int32[E]
    e_srel1: np.ndarray  # int32[E]  subject relation slot + 1; 0 = direct
    e_caveat: np.ndarray  # int32[E]  0 = none
    e_ctx: np.ndarray  # int32[E]  index into contexts, -1 = none
    e_exp: np.ndarray  # int32[E]  epoch-relative expiry seconds, 0 = none
    e_exp_us: np.ndarray  # int64[E] exact expiry micros (host-only; 0 = none)

    # userset edges sorted lex by (rel, res)
    us_rel: np.ndarray
    us_res: np.ndarray
    us_subj: np.ndarray
    us_srel: np.ndarray  # subject relation slot (>= 0)
    us_caveat: np.ndarray
    us_ctx: np.ndarray
    us_exp: np.ndarray
    #: 1 where the userset's relation is a *permission* on the subject's
    #: type (rel/relationship.go:35-37 makes these first-class): the device
    #: can't decide membership (it's the permission fixpoint), so such leaf
    #: grants hit only the possible plane → per-query host resolution
    us_perm: np.ndarray

    #: static possibly-userset pairs, sorted lex (node, rel): relation
    #: usersets whose membership may be extended through a permission-valued
    #: userset chain (transitive mp-closure of permission-srel edge targets);
    #: leaf probes treat containment as possible for every subject
    pus_n: np.ndarray
    pus_r: np.ndarray

    # membership seeds (direct edges into used usersets) sorted by ms_subj
    ms_subj: np.ndarray
    ms_res: np.ndarray
    ms_rel: np.ndarray
    ms_caveat: np.ndarray
    ms_ctx: np.ndarray
    ms_exp: np.ndarray

    # membership propagation (userset edges into used usersets) sorted lex
    # by (mp_subj, mp_srel)
    mp_subj: np.ndarray
    mp_srel: np.ndarray
    mp_res: np.ndarray
    mp_rel: np.ndarray
    mp_caveat: np.ndarray
    mp_ctx: np.ndarray
    mp_exp: np.ndarray

    # arrow (tupleset) edges sorted lex by (rel, res)
    ar_rel: np.ndarray
    ar_res: np.ndarray
    ar_child: np.ndarray  # int32 subject node
    ar_caveat: np.ndarray
    ar_ctx: np.ndarray
    ar_exp: np.ndarray

    contexts: List[Mapping[str, Any]] = field(default_factory=list)

    # ------------------------------------------------------------------
    @property
    def num_edges(self) -> int:
        return int(self.e_rel.shape[0])

    def now_rel32(self, now_us: Optional[int] = None) -> int:
        """Query time in the snapshot's epoch-relative seconds."""
        import time as _time

        if now_us is None:
            now_us = int(_time.time() * 1_000_000)
        return int(
            np.clip((now_us - self.epoch_us) // 1_000_000, -(2**31) + 2, 2**31 - 2)
        )

    # -- host-side reads ------------------------------------------------
    def decode_edge(self, i: int) -> Relationship:
        # one definition of field decoding: the batched path is it
        return next(self._decode_rows(np.asarray([i], np.int64)))

    def _slot_names(self) -> Dict[int, str]:
        return self.compiled.name_of_slot

    def _caveat_names(self) -> Dict[int, str]:
        if not hasattr(self, "_caveat_name_cache"):
            self._caveat_name_cache = {v: k for k, v in self.compiled.caveat_ids.items()}
        return self._caveat_name_cache

    def iter_relationships(
        self, f: Optional[Filter] = None, now_us: Optional[int] = None
    ) -> Iterator[Relationship]:
        """Filtered scan, vectorized on the interned columns; expired edges
        are excluded (they no longer grant, rel/relationship.go:43-45)."""
        if self.num_edges == 0:
            return
        mask = np.ones(self.num_edges, dtype=bool)
        if now_us is not None:
            mask &= (self.e_exp_us == 0) | (self.e_exp_us > now_us)
        if f is not None:
            if f.resource_type != "":
                # node_type holds INTERNER type ids, not schema type ids
                tid = self.interner.type_lookup(f.resource_type)
                if tid < 0:
                    return
                mask &= self.node_type[self.e_res] == tid
            if f.optional_resource_id != "":
                if f.resource_type == "":
                    return  # resource type is required by construction
                n = self.interner.lookup(f.resource_type, f.optional_resource_id)
                if n < 0:
                    return
                mask &= self.e_res == n
            if f.optional_relation != "":
                s = self.compiled.slot_of_name.get(f.optional_relation)
                if s is None:
                    return
                mask &= self.e_rel == s
            sf = f.optional_subject_filter
            if sf is not None:
                if sf.subject_type != "":
                    tid = self.interner.type_lookup(sf.subject_type)
                    if tid < 0:
                        return
                    mask &= self.node_type[self.e_subj] == tid
                if sf.optional_subject_id != "":
                    if sf.subject_type == "":
                        return
                    n = self.interner.lookup(sf.subject_type, sf.optional_subject_id)
                    if n < 0:
                        return
                    mask &= self.e_subj == n
                if sf.optional_relation is not None:
                    if sf.optional_relation == "":
                        mask &= self.e_srel1 == 0
                    else:
                        s = self.compiled.slot_of_name.get(sf.optional_relation)
                        if s is None:
                            return
                        mask &= self.e_srel1 == s + 1
        yield from self._decode_rows(np.nonzero(mask)[0])

    def decode_columns(
        self, rows: np.ndarray, chunk: int = 1 << 16
    ) -> Iterator[Dict[str, list]]:
        """Columnar row decoding: yields chunks of parallel string/value
        lists instead of Relationship objects — the native export path
        (the backup mirror of Store.import_columns).  Each chunk dict
        holds resource_types/resource_ids/resource_relations/
        subject_types/subject_ids/subject_relations (lists of str) plus
        caveat_names, caveat_contexts, expirations_us for rows that
        carry them.  ~4× faster than object decoding: no dataclass
        construction, one batched interner fetch per chunk."""
        slot_names = self._slot_names()
        caveat_names = self._caveat_names()
        contexts = self.contexts
        cols_of = getattr(self.interner, "keys_columns", None)
        at = 0
        while at < rows.shape[0]:
            blk = rows[at : at + chunk]
            at += chunk
            if cols_of is not None:
                rtypes, rids = cols_of(self.e_res[blk])
                stypes, sids = cols_of(self.e_subj[blk])
            else:
                rkeys = self.interner.keys_batch(self.e_res[blk])
                skeys = self.interner.keys_batch(self.e_subj[blk])
                rtypes, rids = map(list, zip(*rkeys)) if rkeys else ([], [])
                stypes, sids = map(list, zip(*skeys)) if skeys else ([], [])
            srel1 = self.e_srel1[blk].tolist()
            cav = self.e_caveat[blk].tolist()
            ctx_i = self.e_ctx[blk].tolist()
            yield {
                "resource_types": rtypes,
                "resource_ids": rids,
                "resource_relations": [
                    slot_names[s] for s in self.e_rel[blk].tolist()
                ],
                "subject_types": stypes,
                "subject_ids": sids,
                "subject_relations": [
                    slot_names[s - 1] if s > 0 else "" for s in srel1
                ],
                "caveat_names": [
                    caveat_names[c] if c else "" for c in cav
                ],
                "caveat_contexts": [
                    contexts[i] if c and i >= 0 else {}
                    for c, i in zip(cav, ctx_i)
                ],
                "expirations_us": self.e_exp_us[blk].tolist(),
            }

    def _decode_rows(self, rows: np.ndarray) -> Iterator[Relationship]:
        """Batched row decoding to Relationship objects, built ON TOP of
        decode_columns so there is ONE definition of field decoding (the
        columnar path).  Progressive chunks: an early-exiting consumer
        (first-match reads) pays a 256-row decode; full exports amortize
        at 64k.  Rows materialize through the bulk-decode fast
        constructor (rel/relationship.py decoded_relationship) with a
        C-speed zip over the column lists — the frozen-dataclass
        ``__init__`` was the export path's throughput ceiling."""
        from ..rel.relationship import decoded_relationship

        ch, at = 256, 0
        while at < rows.shape[0]:
            blk = rows[at : at + ch]
            at += ch
            ch = min(ch * 4, 1 << 16)
            for cols in self.decode_columns(blk, chunk=int(blk.shape[0])):
                # C-level map over the column lists: no per-row Python
                # loop frame (~1.3× over the explicit zip loop; the
                # remaining cost IS the object construction itself)
                exps = [
                    _dt.datetime.fromtimestamp(
                        e / 1_000_000, tz=_dt.timezone.utc
                    ) if e else None
                    for e in cols["expirations_us"]
                ]
                yield from map(
                    decoded_relationship,
                    cols["resource_types"], cols["resource_ids"],
                    cols["resource_relations"], cols["subject_types"],
                    cols["subject_ids"], cols["subject_relations"],
                    cols["caveat_names"], cols["caveat_contexts"], exps,
                )


def relationships_to_raw_columns(
    compiled: CompiledSchema,
    interner: Interner,
    relationships: Sequence[Relationship],
):
    """Intern live relationships into UNSORTED raw columns + contexts —
    the store-feed form ``build_snapshot`` sorts into a Snapshot and the
    feed-partition path (engine/partition.py partition_feed) buckets by
    shard ownership instead.  Row order is the input order, which is
    what makes both paths' stable sorts break ties identically."""
    E = len(relationships)
    res = np.empty(E, dtype=np.int64)
    rel_s = np.empty(E, dtype=np.int64)
    subj = np.empty(E, dtype=np.int64)
    srel = np.empty(E, dtype=np.int64)  # -1 = direct
    cav = np.zeros(E, dtype=np.int32)
    ctx = np.full(E, -1, dtype=np.int32)
    exp_us = np.zeros(E, dtype=np.int64)
    contexts: List[Mapping[str, Any]] = []

    slot_of = compiled.slot_of_name
    caveat_ids = compiled.caveat_ids
    for i, r in enumerate(relationships):
        res[i] = interner.node(r.resource_type, r.resource_id)
        rel_s[i] = slot_of[r.resource_relation]
        subj[i] = interner.node(r.subject_type, r.subject_id)
        srel[i] = slot_of[r.subject_relation] if r.subject_relation else -1
        if r.caveat_name:
            cav[i] = caveat_ids[r.caveat_name]
            if r.caveat_context:
                ctx[i] = len(contexts)
                contexts.append(r.caveat_context)
        exp_us[i] = expiration_micros(r.expiration) if r.has_expiration() else 0

    return (
        dict(res=res, rel=rel_s, subj=subj, srel=srel, caveat=cav,
             ctx=ctx, exp_us=exp_us),
        contexts,
    )


def build_snapshot(
    revision: int,
    compiled: CompiledSchema,
    interner: Interner,
    relationships: Sequence[Relationship],
    *,
    epoch_us: Optional[int] = None,
) -> Snapshot:
    """Materialize sorted columnar arrays from live relationships."""
    import time as _time

    if epoch_us is None:
        epoch_us = int(_time.time() * 1_000_000)
    raw, contexts = relationships_to_raw_columns(
        compiled, interner, relationships
    )
    return build_snapshot_from_columns(
        revision, compiled, interner,
        contexts=contexts, epoch_us=epoch_us, **raw,
    )


def build_snapshot_from_columns(
    revision: int,
    compiled: CompiledSchema,
    interner: Interner,
    *,
    res: np.ndarray,
    rel: np.ndarray,
    subj: np.ndarray,
    srel: np.ndarray,
    caveat: Optional[np.ndarray] = None,
    ctx: Optional[np.ndarray] = None,
    exp_us: Optional[np.ndarray] = None,
    contexts: Optional[List[Mapping[str, Any]]] = None,
    epoch_us: Optional[int] = None,
) -> Snapshot:
    """Materialize directly from pre-interned integer columns — the fast
    bulk path synthetic benchmarks use so 100M+-edge graphs never pass
    through per-tuple Python objects (SURVEY.md §7 "interning throughput
    at 1B edges is the real bottleneck")."""
    import time as _time

    if epoch_us is None:
        epoch_us = int(_time.time() * 1_000_000)
    E = res.shape[0]
    if caveat is None:
        caveat = np.zeros(E, dtype=np.int32)
    if ctx is None:
        ctx = np.full(E, -1, dtype=np.int32)
    if exp_us is None:
        exp_us = np.zeros(E, dtype=np.int64)
    contexts = contexts or []

    # node ids and slots are int32 by construction (interner/compiler):
    # keep every key column int32 end-to-end — the int64 round trips this
    # path used to make cost ~8 full passes over a 30M-edge import
    res = np.ascontiguousarray(res, np.int32)
    rel = np.ascontiguousarray(rel, np.int32)
    subj = np.ascontiguousarray(subj, np.int32)
    exp_us = np.ascontiguousarray(exp_us, np.int64)
    exp32 = _exp_to_rel32(exp_us, epoch_us)

    num_slots = max(compiled.num_slots, 1)
    if num_slots > 2**15:
        raise ValueError("schemas with >32768 relation/permission names unsupported")

    srel1 = np.ascontiguousarray(srel, np.int32) + 1

    # primary order (rel, res, subj, srel1) — native parallel sort when the
    # C++ ingest layer is available (the 100M-edge rebuild bottleneck);
    # permutation applies through the parallel native gathers
    from ..native.sort import take32, take64

    order = lexsort4(rel, res, subj, srel1)
    return finish_snapshot(
        revision, compiled, interner,
        e_rel=take32(rel, order),
        e_res=take32(res, order),
        e_subj=take32(subj, order),
        e_srel1=take32(srel1, order),
        e_caveat=take32(caveat, order),
        e_ctx=take32(ctx, order),
        e_exp=take32(exp32, order),
        e_exp_us=take64(exp_us, order),
        contexts=contexts,
        epoch_us=epoch_us,
    )


def finish_snapshot(
    revision: int,
    compiled: CompiledSchema,
    interner: Interner,
    *,
    e_rel: np.ndarray,
    e_res: np.ndarray,
    e_subj: np.ndarray,
    e_srel1: np.ndarray,
    e_caveat: np.ndarray,
    e_ctx: np.ndarray,
    e_exp: np.ndarray,
    e_exp_us: np.ndarray,
    contexts: List[Mapping[str, Any]],
    epoch_us: int,
) -> Snapshot:
    """Derive every secondary view from primary columns already sorted lex
    by (rel, res, subj, srel1).  Shared by the full build above and the
    incremental delta path (store/delta.py), so both produce identical
    snapshots by construction."""
    import time as _time

    from ..utils import faults, metrics

    # injection site: both the full build and the delta path funnel
    # through here, so one armed site covers every snapshot construction
    faults.fire("snapshot.finish")
    _t0 = _time.perf_counter()
    node_type = interner.node_type_array()
    num_nodes = max(len(interner), 1)
    num_slots = max(compiled.num_slots, 1)

    wc = np.full(max(interner.num_types, 1), -1, dtype=np.int32)
    for tname in compiled.type_ids:
        n = interner.lookup(tname, WILDCARD_ID)
        if n >= 0:
            wc[interner.type_lookup(tname)] = n

    e_cav = e_caveat
    rel_o = e_rel.astype(np.int64)
    res_o = e_res.astype(np.int64)
    subj_o = e_subj.astype(np.int64)
    srel_o = e_srel1.astype(np.int64) - 1

    # userset view (sorted by rel, res — inherited from the primary order)
    is_us = srel_o >= 0
    us_rel = e_rel[is_us]
    us_res = e_res[is_us]
    us_subj = e_subj[is_us]
    us_srel = srel_o[is_us].astype(np.int32)
    us_cav = e_cav[is_us]
    us_ctx = e_ctx[is_us]
    us_exp = e_exp[is_us]

    # usersets used as subjects anywhere (packed int64 keys, host-only)
    us_subj_key = subj_o[is_us] * num_slots + srel_o[is_us]
    used = np.unique(us_subj_key)
    edge_key = res_o * num_slots + rel_o  # the userset each edge grants
    # membership of edge_key in the sorted-unique ``used`` via binary
    # search: np.isin sorts the 30M-row edge_key column, this is
    # O(E log U) with no big sort (identical boolean output)
    if used.shape[0]:
        pos = np.clip(
            np.searchsorted(used, edge_key), 0, used.shape[0] - 1
        )
        feeds = used[pos] == edge_key
    else:
        feeds = np.zeros(edge_key.shape[0], bool)
    used_keys = used  # persisted below: the delta-prepare bail test

    from ..native.sort import take32

    # seeds: direct edges into used usersets, by subject node
    seed_mask = feeds & (srel_o < 0)
    seed_sort = argsort1(e_subj[seed_mask])
    ms_subj = take32(e_subj[seed_mask], seed_sort)
    ms_res = take32(e_res[seed_mask], seed_sort)
    ms_rel = take32(e_rel[seed_mask], seed_sort)
    ms_cav = take32(e_cav[seed_mask], seed_sort)
    ms_ctx = take32(e_ctx[seed_mask], seed_sort)
    ms_exp = take32(e_exp[seed_mask], seed_sort)

    # propagation: userset edges into used usersets, by (subj, srel)
    prop_mask = feeds & (srel_o >= 0)
    prop_srel = e_srel1[prop_mask] - 1
    prop_sort = lexsort2(e_subj[prop_mask], prop_srel)
    mp_subj = take32(e_subj[prop_mask], prop_sort)
    mp_srel = take32(prop_srel, prop_sort)
    mp_res = take32(e_res[prop_mask], prop_sort)
    mp_rel = take32(e_rel[prop_mask], prop_sort)
    mp_cav = take32(e_cav[prop_mask], prop_sort)
    mp_ctx = take32(e_ctx[prop_mask], prop_sort)
    mp_exp = take32(e_exp[prop_mask], prop_sort)

    # permission-valued userset machinery: per-(interner type, slot) "is a
    # permission" table → us_perm leaf flags + the transitive possibly-
    # userset pair set (see Snapshot.us_perm / pus_n docs)
    perm_table = np.zeros((max(interner.num_types, 1), num_slots), bool)
    for tname2, d2 in compiled.schema.definitions.items():
        itid = interner.type_lookup(tname2)
        if itid < 0:
            continue
        for pname2 in d2.permissions:
            perm_table[itid, compiled.slot_of_name[pname2]] = True
    if us_subj.shape[0]:
        us_perm = perm_table[
            node_type[us_subj], np.clip(us_srel, 0, num_slots - 1)
        ].astype(np.int32)
    else:
        us_perm = np.zeros(0, np.int32)

    pus_n = np.zeros(0, np.int32)
    pus_r = np.zeros(0, np.int32)
    if mp_subj.shape[0] and compiled.has_permission_usersets:
        mp_is_perm = perm_table[
            node_type[mp_subj], np.clip(mp_srel, 0, num_slots - 1)
        ]
        seeds = np.unique(
            mp_res[mp_is_perm].astype(np.int64) * num_slots + mp_rel[mp_is_perm]
        )
        mp_key = mp_subj.astype(np.int64) * num_slots + mp_srel.astype(np.int64)
        visited = seeds
        frontier = seeds
        while frontier.size:
            lo = np.searchsorted(mp_key, frontier, "left")
            hi = np.searchsorted(mp_key, frontier, "right")
            counts = (hi - lo).astype(np.int64)
            total = int(counts.sum())
            if total == 0:
                break
            starts = np.repeat(lo.astype(np.int64), counts)
            ends = np.cumsum(counts)
            ii = starts + (np.arange(total) - np.repeat(ends - counts, counts))
            nxt = np.unique(
                mp_res[ii].astype(np.int64) * num_slots + mp_rel[ii]
            )
            frontier = nxt[~np.isin(nxt, visited)]
            visited = np.union1d(visited, frontier)
        if visited.size:
            pus_n = (visited // num_slots).astype(np.int32)
            pus_r = (visited % num_slots).astype(np.int32)

    # arrow view: tupleset relations, direct subjects only (SpiceDB arrows
    # traverse ellipsis subjects)
    ts_slots = np.asarray(sorted(compiled.tupleset_slots), dtype=np.int64)
    ar_mask = np.isin(rel_o, ts_slots) & (srel_o < 0)
    ar_rel = e_rel[ar_mask]
    ar_res = e_res[ar_mask]
    ar_child = e_subj[ar_mask]
    ar_cav = e_cav[ar_mask]
    ar_ctx = e_ctx[ar_mask]
    ar_exp = e_exp[ar_mask]

    snap = Snapshot(
        revision=revision,
        compiled=compiled,
        interner=interner,
        num_nodes=num_nodes,
        num_slots=num_slots,
        epoch_us=epoch_us,
        node_type=node_type,
        wildcard_node_of_type=wc,
        e_rel=e_rel, e_res=e_res, e_subj=e_subj, e_srel1=e_srel1,
        e_caveat=e_cav, e_ctx=e_ctx, e_exp=e_exp, e_exp_us=e_exp_us,
        us_rel=us_rel, us_res=us_res, us_subj=us_subj, us_srel=us_srel,
        us_caveat=us_cav, us_ctx=us_ctx, us_exp=us_exp, us_perm=us_perm,
        pus_n=pus_n, pus_r=pus_r,
        ms_subj=ms_subj, ms_res=ms_res, ms_rel=ms_rel,
        ms_caveat=ms_cav, ms_ctx=ms_ctx, ms_exp=ms_exp,
        mp_subj=mp_subj, mp_srel=mp_srel, mp_res=mp_res, mp_rel=mp_rel,
        mp_caveat=mp_cav, mp_ctx=mp_ctx, mp_exp=mp_exp,
        ar_rel=ar_rel, ar_res=ar_res, ar_child=ar_child,
        ar_caveat=ar_cav, ar_ctx=ar_ctx, ar_exp=ar_exp,
        contexts=contexts,
    )
    # packed (subj · num_slots + srel) int64 keys of usersets that appear
    # as tuple subjects: the device delta-prepare (engine/flat.py
    # build_delta_arrays) bails to a full rebuild when a delta row touches
    # the membership subgraph, which it detects against this set
    snap.us_used_keys = used_keys
    metrics.default.observe(
        "prepare.snapshot_s", _time.perf_counter() - _t0
    )
    return snap


def partitioned_snapshot(
    mem_snap: Snapshot,
    *,
    e_cols: Mapping[str, np.ndarray],
    us_rows: np.ndarray,
    ar_cols: Mapping[str, np.ndarray],
    owned,
) -> Snapshot:
    """Bucket-filtered Snapshot: the process-local view of one feed
    partition (engine/partition.py partition_feed).

    The big per-edge views hold ONLY shard-owned rows — primary rows by
    their (k1, k2) bucket, userset/arrow rows by their (rel, res) group
    bucket — each in global sort order restricted to the owned set
    (equal keys co-locate per shard, so local stable sorts reproduce the
    global tie-breaks).  The membership subgraph (``ms_*``/``mp_*``),
    the used-userset key set, ``pus_*``, node types, and contexts come
    whole from ``mem_snap`` (the replicated membership snapshot): the
    flattened closure must be derivable on every process.  NOT a full
    snapshot: host-oracle fallbacks and exports over it see only the
    local partition — the sharded dispatch path never consults those
    for in-cap queries."""
    from .columns import filter_columns

    us = filter_columns(
        {
            "rel": mem_snap.us_rel, "res": mem_snap.us_res,
            "subj": mem_snap.us_subj, "srel": mem_snap.us_srel,
            "caveat": mem_snap.us_caveat, "ctx": mem_snap.us_ctx,
            "exp": mem_snap.us_exp, "perm": mem_snap.us_perm,
        },
        us_rows,
    )
    snap = Snapshot(
        revision=mem_snap.revision,
        compiled=mem_snap.compiled,
        interner=mem_snap.interner,
        num_nodes=mem_snap.num_nodes,
        num_slots=mem_snap.num_slots,
        epoch_us=mem_snap.epoch_us,
        node_type=mem_snap.node_type,
        wildcard_node_of_type=mem_snap.wildcard_node_of_type,
        e_rel=e_cols["rel"], e_res=e_cols["res"], e_subj=e_cols["subj"],
        e_srel1=e_cols["srel1"], e_caveat=e_cols["caveat"],
        e_ctx=e_cols["ctx"], e_exp=e_cols["exp"],
        e_exp_us=e_cols["exp_us"],
        us_rel=us["rel"], us_res=us["res"], us_subj=us["subj"],
        us_srel=us["srel"], us_caveat=us["caveat"], us_ctx=us["ctx"],
        us_exp=us["exp"], us_perm=us["perm"],
        pus_n=mem_snap.pus_n, pus_r=mem_snap.pus_r,
        ms_subj=mem_snap.ms_subj, ms_res=mem_snap.ms_res,
        ms_rel=mem_snap.ms_rel, ms_caveat=mem_snap.ms_caveat,
        ms_ctx=mem_snap.ms_ctx, ms_exp=mem_snap.ms_exp,
        mp_subj=mem_snap.mp_subj, mp_srel=mem_snap.mp_srel,
        mp_res=mem_snap.mp_res, mp_rel=mem_snap.mp_rel,
        mp_caveat=mem_snap.mp_caveat, mp_ctx=mem_snap.mp_ctx,
        mp_exp=mem_snap.mp_exp,
        ar_rel=ar_cols["rel"], ar_res=ar_cols["res"],
        ar_child=ar_cols["child"], ar_caveat=ar_cols["caveat"],
        ar_ctx=ar_cols["ctx"], ar_exp=ar_cols["exp"],
        contexts=mem_snap.contexts,
    )
    snap.us_used_keys = mem_snap.us_used_keys
    snap.partition_owned = tuple(owned)  # marker: bucket-filtered view
    return snap

"""The MVCC tuple store: schema + tuple log + snapshot generations.

Single-writer append-only design (SURVEY.md §5 "Race detection": the
engine stays functionally pure; the only mutable state is here, guarded by
one lock with RCU-style snapshot swaps).  Semantics enforced:

- **Write** (rel/txn.go): CREATE fails on existing key, TOUCH upserts,
  DELETE removes; MustMatch/MustNotMatch preconditions checked atomically
  with the append; every write mints a revision token.
- **Delete by filter** with preconditions and per-call limits
  (client/client.go:319-358).
- **Schema write** validates that no live relationship becomes
  unreferenced (client/client.go:426-427).
- **Watch**: ordered, resumable, filtered replay of the update log
  (client/client.go:364-413).
- **Revisions**: ZedToken-analogue strings naming snapshot generations;
  consistency strategies pick the generation (SURVEY.md §5).
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from ..caveats import CelProgram, compile_cel
from ..consistency import Requirement, Strategy
from ..rel.filter import Filter, Precondition, PreconditionedFilter
from ..rel.relationship import Relationship
from ..rel.txn import Txn
from ..rel.update import Update, UpdateType
from ..schema import CompiledSchema, compile_schema, parse_schema
from ..native.sort import lexsort2, lexsort4
from ..schema.compiler import SchemaValidationError
from ..utils import faults
from ..utils import metrics as _metrics
from ..utils import trace as _trace
from ..utils.errors import (
    AlreadyExistsError,
    PreconditionFailedError,
    RevisionUnavailableError,
)
from .columns import KEY_DT, ColumnSegment, pack_keys, relationships_to_columns
from .interner import Interner
from .snapshot import Snapshot, build_snapshot, build_snapshot_from_columns

_TOKEN_PREFIX = "gtz1."

#: batches at least this large land as columnar segments; smaller imports
#: go through the live dict (interactive-write path) so segment count
#: stays bounded by the number of genuine bulk loads
COLUMNAR_IMPORT_MIN = 10_000


def RevisionToken(rev: int) -> str:
    """Mint the opaque revision string for a generation (the ZedToken
    analogue returned by every write, client/client.go:125)."""
    return f"{_TOKEN_PREFIX}{rev}"


def parse_revision(token: str) -> int:
    if not token.startswith(_TOKEN_PREFIX):
        raise RevisionUnavailableError(f"malformed revision token {token!r}")
    try:
        return int(token[len(_TOKEN_PREFIX):])
    except ValueError as e:
        raise RevisionUnavailableError(f"malformed revision token {token!r}") from e


_Key = Tuple[str, str, str, str, str, str]


@dataclass
class _LogEntry:
    revision: int
    updates: Sequence[Update]


class _ColumnUpdates(Sequence):
    """Lazy Update view over a column segment's rows: Watch replay and
    delta materialization decode on demand instead of materializing one
    Update object per imported edge (100M-edge imports stay columnar
    end to end).  Names resolve against the store's *current* schema so
    views survive slot renumbering (remap_slots keeps columns aligned)."""

    def __init__(self, store: "Store", seg: ColumnSegment, rows: np.ndarray,
                 update_type: UpdateType) -> None:
        self._store = store
        self._seg = seg
        self._rows = rows
        self._type = update_type

    def __len__(self) -> int:
        return int(self._rows.shape[0])

    def _decode(self, row: int) -> Update:
        compiled = self._store._compiled
        return Update(
            self._type,
            self._seg.decode(
                row,
                self._store.interner,
                {v: k for k, v in compiled.slot_of_name.items()},
                {v: k for k, v in compiled.caveat_ids.items()},
                self._store._base_contexts,
            ),
        )

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._decode(int(r)) for r in self._rows[i]]
        return self._decode(int(self._rows[i]))

    def __iter__(self) -> Iterator[Update]:
        compiled = self._store._compiled
        slot_names = compiled.name_of_slot
        caveat_names = {v: k for k, v in compiled.caveat_ids.items()}
        for r in self._rows:
            yield Update(
                self._type,
                self._seg.decode(
                    int(r), self._store.interner, slot_names, caveat_names,
                    self._store._base_contexts,
                ),
            )


class _ChainedUpdates(Sequence):
    """Concatenation of eager and lazy Update sequences (one log entry
    may span the live dict and several column segments)."""

    def __init__(self, parts: List[Sequence[Update]]) -> None:
        self._parts = parts
        self._len = sum(len(p) for p in parts)

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, i):
        if isinstance(i, slice):
            return list(iter(self))[i]
        if i < 0:
            i += self._len
        for p in self._parts:
            if i < len(p):
                return p[i]
            i -= len(p)
        raise IndexError(i)

    def __iter__(self) -> Iterator[Update]:
        for p in self._parts:
            yield from p


#: pow2 buckets for the writes-per-group histogram (write.group_size)
_GROUP_SIZE_BUCKETS = (
    1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0,
)


class Store:
    """In-process authorization datastore with MVCC snapshot generations."""

    def __init__(self, *, keep_generations: int = 4) -> None:
        self._lock = threading.RLock()
        self._new_data = threading.Condition(self._lock)
        self._live: Dict[_Key, Relationship] = {}
        self._log: List[_LogEntry] = []
        self._head_rev = 0
        self._schema_text = ""
        self._compiled: Optional[CompiledSchema] = None
        self._caveat_programs: Dict[str, CelProgram] = {}
        # native C++ interner when the ingest library loads; pure-Python
        # fallback with identical semantics (native/interner.py)
        from ..native.interner import make_interner

        self.interner = make_interner()
        self._snapshots: Dict[int, Snapshot] = {}
        self._keep_generations = keep_generations
        # columnar base: immutable bulk-import segments + shared context
        # pool (append-only, so snapshot/log ctx indexes stay stable)
        self._segments: List[ColumnSegment] = []
        self._base_contexts: List[Mapping[str, Any]] = []
        self._base_ctx_index: Dict[str, int] = {}
        self._node_type_cache: Optional[np.ndarray] = None
        # host LSM materialization floor override: None falls back to
        # store/delta.py's LSM_COMPACT_MIN; the client threads
        # EngineConfig.lsm_compact_min here so the tuner can move it
        self.lsm_compact_min: Optional[int] = None

    # -- schema ----------------------------------------------------------
    def write_schema(self, text: str) -> str:
        """Parse, compile, and install a schema.  Any live relationship the
        new schema leaves unreferenced/invalid aborts the write
        (client/client.go:426-427)."""
        schema = parse_schema(text)
        compiled = compile_schema(schema)
        programs = {
            name: compile_cel(name, decl.params, decl.expression)
            for name, decl in schema.caveats.items()
        }
        with self._lock:
            for r in self._live.values():
                try:
                    compiled.validate_relationship(r)
                except SchemaValidationError as e:
                    raise SchemaValidationError(
                        f"schema change would leave relationship `{r}` invalid: {e}"
                    ) from e
            # base segments: validate one representative per distinct row
            # shape (type/relation/subject-type/srel/caveat/expiration),
            # not per edge — then renumber slots/caveats in place
            old = self._compiled
            if self._segments and old is not None:
                nt = self._node_type()
                for seg in self._segments:
                    live = seg.live
                    if not live.any():
                        continue
                    shape = np.stack(
                        [
                            nt[seg.res[live]], seg.rel[live],
                            nt[seg.subj[live]], seg.srel1[live],
                            seg.caveat[live], (seg.exp_us[live] != 0).astype(np.int32),
                        ],
                        axis=1,
                    )
                    _, reps = np.unique(shape, axis=0, return_index=True)
                    rows = np.nonzero(live)[0][reps]
                    for row in rows:
                        r = self._decode_base(seg, int(row))
                        try:
                            compiled.validate_relationship(r)
                        except SchemaValidationError as e:
                            raise SchemaValidationError(
                                f"schema change would leave relationship `{r}`"
                                f" invalid: {e}"
                            ) from e
                slot_map = np.full(max(old.num_slots, 1), -1, np.int32)
                for name, s in old.slot_of_name.items():
                    slot_map[s] = compiled.slot_of_name.get(name, -1)
                caveat_map = np.zeros(len(old.caveat_ids) + 1, np.int32)
                for name, c in old.caveat_ids.items():
                    caveat_map[c] = compiled.caveat_ids.get(name, 0)
                for seg in self._segments:
                    seg.remap_slots(slot_map, caveat_map)
            self._schema_text = text
            self._compiled = compiled
            self._caveat_programs = programs
            self._snapshots.clear()  # slot numbering may have changed
            self._head_rev += 1
            self._new_data.notify_all()
            return RevisionToken(self._head_rev)

    def read_schema(self) -> Tuple[str, str]:
        with self._lock:
            return self._schema_text, RevisionToken(self._head_rev)

    @property
    def compiled_schema(self) -> Optional[CompiledSchema]:
        with self._lock:
            return self._compiled

    def caveat_program(self, name: str) -> Optional[CelProgram]:
        return self._caveat_programs.get(name)

    # -- helpers ----------------------------------------------------------
    def _require_schema(self) -> CompiledSchema:
        if self._compiled is None:
            raise SchemaValidationError("no schema has been written")
        return self._compiled

    def _now_us(self) -> int:
        return int(time.time() * 1_000_000)

    def _is_live(self, r: Relationship, now_us: int) -> bool:
        from ..rel.relationship import expiration_micros

        return not r.has_expiration() or expiration_micros(r.expiration) > now_us

    def _filter_matches_any(self, f: Filter, now_us: int) -> bool:
        if any(
            f.matches(r) and self._is_live(r, now_us) for r in self._live.values()
        ):
            return True
        if self._segments and self._compiled is not None:
            nt = self._node_type()
            for seg in self._segments:
                if seg.filter_mask(f, self._compiled, self.interner, nt, now_us).any():
                    return True
        return False

    def _check_preconditions(self, pcs: List[Precondition], now_us: int) -> None:
        for pc in pcs:
            matched = self._filter_matches_any(pc.filter, now_us)
            if pc.must_match and not matched:
                raise PreconditionFailedError(
                    f"precondition MUST_MATCH failed for filter on "
                    f"`{pc.filter.resource_type}`"
                )
            if not pc.must_match and matched:
                raise PreconditionFailedError(
                    f"precondition MUST_NOT_MATCH failed for filter on "
                    f"`{pc.filter.resource_type}`"
                )

    def _intern(self, r: Relationship) -> None:
        self.interner.node(r.resource_type, r.resource_id)
        self.interner.node(r.subject_type, r.subject_id)

    # -- columnar base helpers --------------------------------------------
    def _node_type(self) -> np.ndarray:
        n = len(self.interner)
        if self._node_type_cache is None or self._node_type_cache.shape[0] != n:
            self._node_type_cache = self.interner.node_type_array()
        return self._node_type_cache

    def _packed_key(self, r: Relationship) -> Optional[np.ndarray]:
        """Packed (h, l) key of a relationship, or None if any component
        is not interned (then it cannot exist in the base)."""
        res = self.interner.lookup(r.resource_type, r.resource_id)
        subj = self.interner.lookup(r.subject_type, r.subject_id)
        rel = self._compiled.slot_of_name.get(r.resource_relation, -1) \
            if self._compiled else -1
        if r.subject_relation:
            srel = self._compiled.slot_of_name.get(r.subject_relation, -2) \
                if self._compiled else -2
            srel1 = srel + 1
        else:
            srel1 = 0
        if res < 0 or subj < 0 or rel < 0 or srel1 < 0:
            return None
        return pack_keys(
            np.array([res], np.int32), np.array([rel], np.int32),
            np.array([subj], np.int32), np.array([srel1], np.int32),
        )

    def _base_find(self, r: Relationship) -> Optional[Tuple[ColumnSegment, int]]:
        """Newest live base row for the relationship's key, if any."""
        if not self._segments:
            return None
        key = self._packed_key(r)
        if key is None:
            return None
        for seg in reversed(self._segments):
            row = seg.row_of_key(key[0])
            if row >= 0:
                return seg, row
        return None

    def _base_row_live(self, seg: ColumnSegment, row: int, now_us: int) -> bool:
        exp = int(seg.exp_us[row])
        return exp == 0 or exp > now_us

    def _decode_base(self, seg: ColumnSegment, row: int) -> Relationship:
        compiled = self._require_schema()
        return seg.decode(
            row, self.interner,
            {v: k for k, v in compiled.slot_of_name.items()},
            {v: k for k, v in compiled.caveat_ids.items()},
            self._base_contexts,
        )

    def _base_live_count(self) -> int:
        return sum(seg.live_count for seg in self._segments)

    # -- writes ------------------------------------------------------------
    def write(self, txn: Txn) -> str:
        """Atomically apply a transaction (rel/txn.go semantics); returns
        the new revision token (client/client.go:117-126).  A sampled
        write leaves a root trace (utils/trace.py) whose events include
        any incremental-closure advance this revision later triggers on
        the prepare path."""
        wsp = _trace.root_span("write", updates=len(txn.updates))
        with wsp, self._lock:
            compiled = self._require_schema()
            now_us = self._now_us()
            for u in txn.updates:
                compiled.validate_relationship(u.relationship)
                self._validate_caveat_context(u.relationship)
            self._check_preconditions(txn.preconditions, now_us)

            # Pre-validate the whole transaction against a shadow overlay so
            # a CREATE conflict aborts with nothing applied (atomicity,
            # rel/txn.go semantics).  The overlay also sequences in-txn ops:
            # DELETE x then CREATE x in one txn is legal.  Existence spans
            # the live dict AND the columnar base segments.
            shadow: Dict[_Key, Optional[Relationship]] = {}
            for u in txn.updates:
                key = u.relationship.key()
                if u.update_type == UpdateType.CREATE:
                    if key in shadow:
                        exists = shadow[key] is not None and self._is_live(
                            shadow[key], now_us
                        )
                    else:
                        existing = self._live.get(key)
                        exists = existing is not None and self._is_live(
                            existing, now_us
                        )
                        if not exists:
                            hit = self._base_find(u.relationship)
                            exists = hit is not None and self._base_row_live(
                                hit[0], hit[1], now_us
                            )
                    if exists:
                        raise AlreadyExistsError(
                            f"relationship already exists: {u.relationship}"
                        )
                    shadow[key] = u.relationship
                elif u.update_type == UpdateType.TOUCH:
                    shadow[key] = u.relationship
                elif u.update_type == UpdateType.DELETE:
                    shadow[key] = None
                else:
                    raise ValueError(f"unknown update type {u.update_type}")

            applied: List[Update] = []
            for u in txn.updates:
                key = u.relationship.key()
                if u.update_type in (UpdateType.CREATE, UpdateType.TOUCH):
                    hit = self._base_find(u.relationship)
                    if hit is not None:
                        hit[0].live[hit[1]] = False  # superseded base row
                    self._live[key] = u.relationship
                    self._intern(u.relationship)
                    applied.append(u)
                else:  # DELETE
                    if key in self._live:
                        del self._live[key]
                        applied.append(u)
                    else:
                        hit = self._base_find(u.relationship)
                        if hit is not None:
                            hit[0].live[hit[1]] = False
                            applied.append(u)

            self._head_rev += 1
            self._log.append(_LogEntry(self._head_rev, applied))
            self._new_data.notify_all()
            wsp.set_attr("revision", self._head_rev)
            wsp.set_attr("applied", len(applied))
            return RevisionToken(self._head_rev)

    def write_group(self, txns: Sequence[Txn]) -> List[object]:
        """Atomically commit a GROUP of transactions as ONE log entry —
        the commit half of the group-commit write pipeline
        (store/group.py forms the groups, this applies them).

        Semantics:

        * preconditions and CREATE-conflict checks evaluate once against
          the group's BASE revision, plus earlier surviving members of
          the same group in arrival order (a CREATE colliding with an
          earlier member's CREATE is a conflict, same as two sequential
          writes would see);
        * a transaction that fails validation, a precondition, or a
          CREATE conflict is EJECTED before collapse — its slot gets the
          exception instance, the rest of the group proceeds;
        * survivors mint consecutive zookies base+1..base+k so
          client-visible revision semantics match k sequential writes,
          but the log carries ONE entry at base+k holding the
          last-writer-wins collapse of every surviving update — closure
          advance, device reship, and replication all pay one delta per
          group.  Mid-group tokens resolve under FULL / AT_LEAST /
          MIN_LATENCY (head >= token); pinning a SNAPSHOT read to one
          raises RevisionUnavailableError, exactly like any other
          unmaterialized generation.

        Returns one outcome per input transaction, in order: a revision
        token (str) for survivors, the exception for ejected ones.  A
        fault fired at the ``closure.delta`` site (modelling the group's
        single delta application failing after formation) aborts the
        WHOLE group before the commit point: head stays at the base
        revision, no zookie is minted, and a retry is idempotent."""
        wsp = _trace.root_span("write_group", txns=len(txns))
        with wsp, self._lock:
            compiled = self._require_schema()
            now_us = self._now_us()
            base = self._head_rev
            outcomes: List[object] = [None] * len(txns)
            # group-wide shadow overlay: merged from each survivor in
            # arrival order so later members see earlier ones; an
            # ejected member's staged entries never land in it
            shadow: Dict[_Key, Optional[Relationship]] = {}
            survivors: List[int] = []
            for i, txn in enumerate(txns):
                try:
                    for u in txn.updates:
                        compiled.validate_relationship(u.relationship)
                        self._validate_caveat_context(u.relationship)
                    self._check_preconditions(txn.preconditions, now_us)
                    local: Dict[_Key, Optional[Relationship]] = {}
                    for u in txn.updates:
                        key = u.relationship.key()
                        if u.update_type == UpdateType.CREATE:
                            if key in local or key in shadow:
                                prior = local.get(key, shadow.get(key))
                                exists = prior is not None and self._is_live(
                                    prior, now_us
                                )
                            else:
                                existing = self._live.get(key)
                                exists = existing is not None and self._is_live(
                                    existing, now_us
                                )
                                if not exists:
                                    hit = self._base_find(u.relationship)
                                    exists = hit is not None and self._base_row_live(
                                        hit[0], hit[1], now_us
                                    )
                            if exists:
                                raise AlreadyExistsError(
                                    f"relationship already exists: {u.relationship}"
                                )
                            local[key] = u.relationship
                        elif u.update_type == UpdateType.TOUCH:
                            local[key] = u.relationship
                        elif u.update_type == UpdateType.DELETE:
                            local[key] = None
                        else:
                            raise ValueError(
                                f"unknown update type {u.update_type}"
                            )
                except Exception as e:  # per-slot ejection, group proceeds
                    outcomes[i] = e
                    continue
                shadow.update(local)
                survivors.append(i)

            if not survivors:
                wsp.set_attr("revision", base)
                wsp.set_attr("survivors", 0)
                return outcomes

            # last-writer-wins collapse across survivors in arrival
            # order: the final update per tuple key determines the end
            # state, so the single log entry replays identically to the
            # k sequential transactions it stands for
            collapsed: Dict[_Key, Update] = {}
            for i in survivors:
                for u in txns[i].updates:
                    collapsed[u.relationship.key()] = u

            # injection site shared with the closure advance: fired after
            # formation but BEFORE the commit point, so an armed fault
            # leaves the store at the group's base revision with no
            # zookies minted (the atomicity contract the fault-injection
            # tests pin down)
            faults.fire("closure.delta")

            # -- commit point: nothing above mutated state -------------
            applied: List[Update] = []
            for u in collapsed.values():
                key = u.relationship.key()
                if u.update_type in (UpdateType.CREATE, UpdateType.TOUCH):
                    hit = self._base_find(u.relationship)
                    if hit is not None:
                        hit[0].live[hit[1]] = False  # superseded base row
                    self._live[key] = u.relationship
                    self._intern(u.relationship)
                    applied.append(u)
                else:  # DELETE
                    if key in self._live:
                        del self._live[key]
                        applied.append(u)
                    else:
                        hit = self._base_find(u.relationship)
                        if hit is not None:
                            hit[0].live[hit[1]] = False
                            applied.append(u)

            k = len(survivors)
            for j, i in enumerate(survivors, start=1):
                outcomes[i] = RevisionToken(base + j)
            self._head_rev = base + k
            self._log.append(_LogEntry(self._head_rev, applied))
            self._new_data.notify_all()
            _metrics.default.observe_hist(
                "write.group_size", float(k), _GROUP_SIZE_BUCKETS
            )
            wsp.set_attr("revision", self._head_rev)
            wsp.set_attr("survivors", k)
            wsp.set_attr("collapsed", len(applied))
            return outcomes

    def apply_replicated(self, revision: int, updates: Sequence[Update]) -> str:
        """Apply an already-committed upstream log entry at EXACTLY the
        given revision — the replica tail path (fleet/replica.py).

        The upstream store validated, sequenced, and precondition-checked
        the transaction when it committed; a replica replays the *applied*
        updates verbatim, so no validation or shadow-overlay pass re-runs
        here.  CREATE and TOUCH both land as upserts (the upstream already
        rejected conflicting CREATEs).  Entries at or below the local head
        are skipped and the current head token returned — the idempotence
        that makes watch-stream redelivery after a resume exactly-once:
        the tail re-subscribes from its local head and any replayed prefix
        is a no-op."""
        with self._lock:
            if revision <= self._head_rev:
                return RevisionToken(self._head_rev)
            self._require_schema()
            applied: List[Update] = []
            for u in updates:
                key = u.relationship.key()
                if u.update_type in (UpdateType.CREATE, UpdateType.TOUCH):
                    hit = self._base_find(u.relationship)
                    if hit is not None:
                        hit[0].live[hit[1]] = False
                    self._live[key] = u.relationship
                    self._intern(u.relationship)
                    applied.append(u)
                else:  # DELETE
                    if key in self._live:
                        del self._live[key]
                        applied.append(u)
                    else:
                        hit = self._base_find(u.relationship)
                        if hit is not None:
                            hit[0].live[hit[1]] = False
                            applied.append(u)
            # land at the UPSTREAM revision, not head+1: replicas share the
            # authority's revision numbering so zookies minted on write
            # resolve to the same world on every replica
            self._head_rev = int(revision)
            self._log.append(_LogEntry(self._head_rev, applied))
            self._new_data.notify_all()
            return RevisionToken(self._head_rev)

    def align_replica_head(self, revision: int) -> None:
        """Fast-forward the head revision counter to the upstream revision
        a bootstrap export materialized at (fleet/replica.py).  The
        schema write and bulk import minted small local revisions; after
        alignment, streamed entries land at upstream numbers and zookies
        minted upstream resolve locally.  Rewinding is refused — a replica
        never travels back below state it already holds."""
        with self._lock:
            if revision < self._head_rev:
                raise ValueError(
                    f"cannot rewind head from {self._head_rev} to {revision}"
                )
            self._head_rev = int(revision)

    def resident_revisions(self) -> List[int]:
        """Sorted materialized snapshot generations — the store half of a
        replica's residency report (the verdict cache's revision shards
        are the other half)."""
        with self._lock:
            return sorted(self._snapshots)

    def peek_chain(self) -> Optional[Tuple[Snapshot, int, int]]:
        """(snapshot, overlay_rows, chain_len_revisions) for the newest
        resident generation — the background chain compactor's poll
        (store/group.py).  Deliberately does not touch the snapshot LRU
        order; returns None when nothing is materialized yet.  The
        returned snapshot reference is safe to materialize outside the
        store lock (LsmSnapshot._materialize is idempotent under its own
        lock)."""
        with self._lock:
            if not self._snapshots:
                return None
            rev = max(self._snapshots)
            snap = self._snapshots[rev]
        rows = int(getattr(snap, "overlay_rows", 0))
        base_rev = int(getattr(snap, "chain_base_revision", rev))
        return snap, rows, int(rev) - base_rev

    def _validate_caveat_context(self, r: Relationship) -> None:
        if not r.caveat_name or not r.caveat_context:
            return
        prog = self._caveat_programs.get(r.caveat_name)
        if prog is None:
            return
        unknown = set(r.caveat_context) - set(prog.params)
        if unknown:
            raise SchemaValidationError(
                f"caveat `{r.caveat_name}` context has undeclared parameters: "
                f"{sorted(unknown)}"
            )

    def delete_by_filter(
        self,
        pf: PreconditionedFilter,
        *,
        limit: int = 0,
        allow_partial: bool = False,
    ) -> Tuple[str, bool]:
        """Delete relationships matching the filter.  Returns (revision,
        complete).  With a limit, at most ``limit`` are removed and
        ``complete`` reports whether the filter is now empty — the engine
        behind both DeleteAtomic (no limit; one transaction,
        client/client.go:319-336) and batched Delete
        (client/client.go:340-358)."""
        with self._lock:
            compiled = self._require_schema()
            now_us = self._now_us()
            self._check_preconditions(pf.preconditions, now_us)
            keys = [k for k, r in self._live.items() if pf.filter.matches(r)]
            # base matches: vectorized per-segment masks (no filter=None
            # shortcut — delete-all must still mark rows dead)
            seg_rows: List[Tuple[ColumnSegment, np.ndarray]] = []
            total_base = 0
            nt = self._node_type() if self._segments else None
            for seg in self._segments:
                mask = seg.filter_mask(
                    pf.filter, compiled, self.interner, nt, None
                )
                rows = np.nonzero(mask)[0]
                if rows.size:
                    seg_rows.append((seg, rows))
                    total_base += rows.size
            total = len(keys) + total_base
            budget = total if limit <= 0 else limit

            applied_objs: List[Update] = []
            take_dict = min(len(keys), budget)
            for k in keys[:take_dict]:
                applied_objs.append(Update(UpdateType.DELETE, self._live.pop(k)))
            budget -= take_dict
            lazy_parts: List[Sequence[Update]] = []
            if applied_objs:
                lazy_parts.append(applied_objs)
            for seg, rows in seg_rows:
                if budget <= 0:
                    break
                victims = rows[:budget]
                seg.live[victims] = False
                lazy_parts.append(
                    _ColumnUpdates(self, seg, victims, UpdateType.DELETE)
                )
                budget -= victims.size
            applied: Sequence[Update] = (
                lazy_parts[0] if len(lazy_parts) == 1 else _ChainedUpdates(lazy_parts)
            ) if lazy_parts else []
            complete = limit <= 0 or total <= limit
            self._head_rev += 1
            self._log.append(_LogEntry(self._head_rev, applied))
            self._new_data.notify_all()
            return RevisionToken(self._head_rev), complete

    def import_relationships(
        self, rs: Iterable[Relationship], *, touch: bool = False
    ) -> str:
        """Bulk-create a batch; raises AlreadyExistsError (with nothing
        applied) if any key exists or repeats within the batch — the
        BulkImport contract the client's TOUCH fallback depends on
        (client/client.go:449-459).  With ``touch=True`` duplicates
        upsert instead (the columnar form of the reference's TOUCH-txn
        recovery).  Returns the minted revision token.

        Batches of ≥ COLUMNAR_IMPORT_MIN land as immutable column
        segments: batch interning, one schema validation per distinct
        relationship *shape*, sorted-key dedup — no per-edge Python in
        the store, which is what lets the Client API carry 100M+ edges
        (round-1 Weak: configs 4-5 bypassed the product)."""
        batch = list(rs)
        with self._lock:
            compiled = self._require_schema()
            now_us = self._now_us()
            if len(batch) >= COLUMNAR_IMPORT_MIN:
                return self._import_columnar_locked(batch, compiled, now_us, touch)
            seen: set = set()
            base_hits: List[Tuple[ColumnSegment, int]] = []
            for r in batch:
                compiled.validate_relationship(r)
                key = r.key()
                existing = self._live.get(key)
                exists = key in seen or (
                    existing is not None and self._is_live(existing, now_us)
                )
                if not exists:
                    hit = self._base_find(r)
                    if hit is not None and self._base_row_live(
                        hit[0], hit[1], now_us
                    ):
                        exists = True
                        if touch:
                            base_hits.append(hit)
                if exists and not touch:
                    raise AlreadyExistsError(f"relationship already exists: {r}")
                seen.add(key)
            for seg, row in base_hits:
                seg.live[row] = False
            applied = []
            utype = UpdateType.TOUCH if touch else UpdateType.CREATE
            for r in batch:
                self._live[r.key()] = r
                self._intern(r)
                applied.append(Update(utype, r))
            self._head_rev += 1
            self._log.append(_LogEntry(self._head_rev, applied))
            self._new_data.notify_all()
            return RevisionToken(self._head_rev)

    def import_columns(
        self,
        *,
        resource_type: str,
        resource_ids: Sequence[str],
        resource_relation: str,
        subject_type: str,
        subject_ids: Sequence[str],
        subject_relation: str = "",
        touch: bool = False,
    ) -> str:
        """Columnar bulk import: one (resource type, relation, subject
        type[, subject relation]) SHAPE per call, ids as parallel string
        columns.  This is the restore path the S2-compression lesson
        points at (SURVEY.md §2.1 — "compress the boundary": intern
        strings host-side, ship int32 columns): no per-edge Relationship
        objects, one validation for the whole call, batch interning.
        Caveated/expiring rows use the object path
        (``import_relationships``).  Returns the minted revision; raises
        AlreadyExistsError (nothing applied) on any live duplicate
        unless ``touch``."""
        B = len(resource_ids)
        if len(subject_ids) != B:
            raise ValueError("resource_ids and subject_ids lengths differ")
        with self._lock:
            compiled = self._require_schema()
            now_us = self._now_us()
            # shape validation: wildcardness is part of the validation
            # shape, so a mixed batch validates BOTH representatives
            concrete = next((s for s in subject_ids if s != "*"), None)
            reps = ([concrete] if concrete is not None else []) + (
                ["*"] if "*" in subject_ids else []
            )
            for rep in reps or (["x"] if B == 0 else []):
                compiled.validate_relationship(Relationship(
                    resource_type=resource_type,
                    resource_id=resource_ids[0] if B else "x",
                    resource_relation=resource_relation,
                    subject_type=subject_type,
                    subject_id=rep,
                    subject_relation=subject_relation,
                ))
            if B == 0:
                return RevisionToken(self._head_rev)
            itn = self.interner
            if hasattr(itn, "node_batch"):
                res = itn.node_batch(resource_type, resource_ids)
                subj = itn.node_batch(subject_type, subject_ids)
            else:
                res = np.fromiter(
                    (itn.node(resource_type, i) for i in resource_ids),
                    np.int32, B,
                )
                subj = np.fromiter(
                    (itn.node(subject_type, i) for i in subject_ids),
                    np.int32, B,
                )
            slot_of = compiled.slot_of_name
            cols = {
                "res": res,
                "rel": np.full(B, slot_of[resource_relation], np.int32),
                "subj": subj,
                "srel1": np.full(
                    B,
                    slot_of[subject_relation] + 1 if subject_relation else 0,
                    np.int32,
                ),
                "caveat": np.zeros(B, np.int32),
                "ctx": np.full(B, -1, np.int32),
                "exp_us": np.zeros(B, np.int64),
            }

            def describe(i: int) -> str:
                srel = f"#{subject_relation}" if subject_relation else ""
                return (
                    f"{resource_type}:{resource_ids[i]}#{resource_relation}"
                    f"@{subject_type}:{subject_ids[i]}{srel}"
                )

            return self._commit_columns_locked(
                cols, now_us, touch, describe=describe
            )

    def import_interned_columns(
        self,
        *,
        resource_ids,
        resource_relation: str,
        subject_ids,
        subject_relation: str = "",
        touch: bool = False,
    ) -> str:
        """Pre-interned columnar bulk import: node-id columns from THIS
        store's interner (``export_interned_columns_at`` output, or
        ``Interner.node_batch`` results), skipping ALL string work — no
        hashing, no packing, no per-id Python.  Rows may mix resource
        and subject types freely; validation runs once per distinct
        (resource type, subject type, wildcardness) combination through
        the same validator as the object path.  This is the 1B-edge
        restore fast path (the reference's BulkImportRelationships
        surface, client/client.go:438-465, at ~5x the string-columnar
        rate).  Returns the minted revision; raises AlreadyExistsError
        (nothing applied) on any live duplicate unless ``touch``."""
        res = np.ascontiguousarray(resource_ids, dtype=np.int32)
        subj = np.ascontiguousarray(subject_ids, dtype=np.int32)
        B = int(res.shape[0])
        if int(subj.shape[0]) != B:
            raise ValueError("resource_ids and subject_ids lengths differ")
        with self._lock:
            compiled = self._require_schema()
            now_us = self._now_us()
            itn = self.interner
            NN = len(itn)
            if B:
                if (
                    int(res.min()) < 0 or int(res.max()) >= NN
                    or int(subj.min()) < 0 or int(subj.max()) >= NN
                ):
                    raise ValueError(
                        "node id out of range for this store's interner"
                    )
            slot_of = compiled.slot_of_name
            if resource_relation not in slot_of:
                raise SchemaValidationError(
                    f"relation `{resource_relation}` not found in schema"
                )
            if subject_relation and subject_relation not in slot_of:
                raise SchemaValidationError(
                    f"relation `{subject_relation}` not found in schema"
                )
            if B:
                nt = itn.node_type_array()
                rt = nt[res].astype(np.int64)
                st = nt[subj].astype(np.int64)
                # wildcard subjects change the validation shape: detect
                # them via the (few) interned wildcard node ids
                from ..rel.relationship import WILDCARD_ID

                wc_ids = np.asarray(
                    [
                        w for w in (
                            itn.lookup(t, WILDCARD_ID)
                            for t in compiled.type_ids
                        ) if w >= 0
                    ],
                    np.int32,
                )
                wc = (
                    np.isin(subj, wc_ids)
                    if wc_ids.size else np.zeros(B, bool)
                )
                combos = np.unique(
                    (rt << 21) | (st << 1) | wc, return_index=True
                )[1]
                for i in combos:
                    rtype, rid = itn.key_of(int(res[i]))
                    stype, sid = itn.key_of(int(subj[i]))
                    compiled.validate_relationship(Relationship(
                        resource_type=rtype, resource_id=rid,
                        resource_relation=resource_relation,
                        subject_type=stype, subject_id=sid,
                        subject_relation=subject_relation,
                    ))
            if B == 0:
                return RevisionToken(self._head_rev)
            cols = {
                "res": res,
                "rel": np.full(B, slot_of[resource_relation], np.int32),
                "subj": subj,
                "srel1": np.full(
                    B,
                    slot_of[subject_relation] + 1 if subject_relation else 0,
                    np.int32,
                ),
                "caveat": np.zeros(B, np.int32),
                "ctx": np.full(B, -1, np.int32),
                "exp_us": np.zeros(B, np.int64),
            }

            def describe(i: int) -> str:
                rtype, rid = itn.key_of(int(res[i]))
                stype, sid = itn.key_of(int(subj[i]))
                srel = f"#{subject_relation}" if subject_relation else ""
                return (
                    f"{rtype}:{rid}#{resource_relation}"
                    f"@{stype}:{sid}{srel}"
                )

            return self._commit_columns_locked(
                cols, now_us, touch, describe=describe
            )

    def export_interned_columns_at(self, revision: str):
        """Interned columnar export at an exact snapshot: yields chunk
        dicts with int32 ``res``/``subj`` node-id columns plus decoded
        ``resource_relation``/``subject_relation`` names — the zero-
        string mirror of ``import_interned_columns`` for restore
        pipelines that stay within this store's interner (the ids remain
        valid across revisions: the interner is append-only)."""
        snap = self.snapshot_for(Strategy(Requirement.SNAPSHOT, revision))
        now_us = self._now_us()
        live = (snap.e_exp_us == 0) | (snap.e_exp_us > now_us)
        rows = np.nonzero(live)[0]
        if rows.shape[0] == 0:
            return
        compiled = snap.compiled
        name_of_slot = {s: n for n, s in compiled.slot_of_name.items()}
        # one chunk per (relation, srel1) run keeps each chunk a single
        # import_interned_columns call
        rel_c = snap.e_rel[rows]
        srel_c = snap.e_srel1[rows]
        key = rel_c.astype(np.int64) * (snap.num_slots + 2) + srel_c
        order = lexsort2(rel_c.astype(np.int32), srel_c.astype(np.int32))
        rows = rows[order]
        key = key[order]
        starts = np.nonzero(
            np.concatenate([[True], key[1:] != key[:-1]])
        )[0]
        ends = np.concatenate([starts[1:], [rows.shape[0]]])
        for lo, hi in zip(starts, ends):
            r0 = rows[lo]
            yield {
                "res": snap.e_res[rows[lo:hi]].astype(np.int32),
                "subj": snap.e_subj[rows[lo:hi]].astype(np.int32),
                "resource_relation": name_of_slot[int(snap.e_rel[r0])],
                "subject_relation": (
                    name_of_slot[int(snap.e_srel1[r0]) - 1]
                    if int(snap.e_srel1[r0]) > 0 else ""
                ),
            }

    def _import_columnar_locked(
        self,
        batch: List[Relationship],
        compiled: CompiledSchema,
        now_us: int,
        touch: bool,
    ) -> str:
        cols = relationships_to_columns(
            batch, compiled, self.interner,
            self._base_contexts, self._base_ctx_index,
        )
        return self._commit_columns_locked(
            cols, now_us, touch, describe=lambda i: str(batch[i])
        )

    def _commit_columns_locked(
        self,
        cols: Dict[str, np.ndarray],
        now_us: int,
        touch: bool,
        *,
        describe,
    ) -> str:
        """Shared commit of lowered int columns: batch dedup, existence
        vs the live dict and base segments, one immutable ColumnSegment,
        one revision.  ``describe`` lazily renders a row for error
        messages — the columnar API derives it from the columns, the
        object path from the batch."""
        B = int(cols["res"].shape[0])
        # stable native lexsort == argsort of the packed keys (both sort
        # by (rel, res, subj, srel1); components are non-negative), ~10x
        # faster at 10M rows on one core.  All masks below live in the
        # SORTED domain (suffix _s) — batch-domain scatters at 10M rows
        # cost ~0.7s per segment and are needed only once, for `keep`
        order = lexsort4(
            cols["rel"], cols["res"], cols["subj"], cols["srel1"]
        )
        sh = (
            (cols["rel"].astype(np.int64) << 32)
            | cols["res"].astype(np.int64)
        )[order]
        sl = (
            (cols["subj"].astype(np.int64) << 32)
            | cols["srel1"].astype(np.int64)
        )[order]
        dup_s = np.zeros(B, bool)
        if B > 1:
            eq = (sh[1:] == sh[:-1]) & (sl[1:] == sl[:-1])
            if touch:
                # TOUCH upsert: the LAST occurrence of a key wins (the
                # sort is stable, so batch order == run order)
                dup_s[:-1] = eq
            elif eq.any():
                raise AlreadyExistsError(
                    "relationship already exists: "
                    f"{describe(int(order[1:][eq][0]))}"
                )
        dup = np.zeros(B, bool)
        dup[order] = dup_s
        # existence vs the live dict: probe in whichever direction is
        # cheaper at runtime — the dict against the sorted batch keys
        # (O(live · log B)) when the dict is the smaller side, else the
        # batch rows against the dict (O(B) un-intern + dict gets), so a
        # 2M-row import flush never pays O(live) Python per flush after
        # many object-path write()s
        dict_hits: List[_Key] = []
        if self._live and len(self._live) > B:
            name_of_slot = self._require_schema().name_of_slot
            cols_of = getattr(self.interner, "keys_columns", None)
            if cols_of is not None:
                rtypes, rids = cols_of(cols["res"])
                stypes, sids = cols_of(cols["subj"])
            else:
                rk = self.interner.keys_batch(cols["res"])
                sk = self.interner.keys_batch(cols["subj"])
                rtypes, rids = map(list, zip(*rk)) if rk else ([], [])
                stypes, sids = map(list, zip(*sk)) if sk else ([], [])
            rel_l = cols["rel"].tolist()
            srel1_l = cols["srel1"].tolist()
            live_get = self._live.get
            for i in range(B):
                if dup[i]:
                    continue  # a later occurrence carries the same key
                s1 = srel1_l[i]
                key = (
                    rtypes[i], rids[i], name_of_slot[rel_l[i]],
                    stypes[i], sids[i],
                    name_of_slot[s1 - 1] if s1 > 0 else "",
                )
                existing = live_get(key)
                if existing is None or not self._is_live(existing, now_us):
                    continue
                if not touch:
                    raise AlreadyExistsError(
                        f"relationship already exists: {describe(i)}"
                    )
                dict_hits.append(key)
        elif self._live:
            compiled = self._require_schema()
            slot_of = compiled.slot_of_name
            probe = np.empty(1, KEY_DT)
            for key, existing in self._live.items():
                if not self._is_live(existing, now_us):
                    continue
                res = self.interner.lookup(
                    existing.resource_type, existing.resource_id
                )
                subj = self.interner.lookup(
                    existing.subject_type, existing.subject_id
                )
                if res < 0 or subj < 0:
                    continue  # never interned → cannot collide
                rel_s = slot_of.get(existing.resource_relation)
                if existing.subject_relation:
                    ss = slot_of.get(existing.subject_relation)
                    if ss is None:
                        continue
                    srel1 = ss + 1
                else:
                    srel1 = 0
                if rel_s is None:
                    continue
                ph = (rel_s << 32) | res
                pl = (int(subj) << 32) | srel1
                pos = int(np.searchsorted(sh, ph, "left"))
                pos += int(np.searchsorted(sl[pos:np.searchsorted(sh, ph, "right")], pl, "left"))
                if pos < B and sh[pos] == ph and sl[pos] == pl:
                    if not touch:
                        raise AlreadyExistsError(
                            "relationship already exists: "
                            f"{describe(int(order[pos]))}"
                        )
                    dict_hits.append(key)
        seg_hits: List[Tuple[ColumnSegment, np.ndarray]] = []
        for seg in self._segments:
            # probe in SORTED batch order: one linear merge per segment,
            # no batch-domain scatter (hits stay sorted-side)
            hit_s, rows_s = seg.rows_of_sorted_halves(sh, sl)
            hit_s &= ~dup_s
            if hit_s.any():
                live_rows = rows_s[hit_s]
                exp = seg.exp_us[live_rows]
                alive = (exp == 0) | (exp > now_us)
                if alive.any():
                    if not touch:
                        first = int(
                            order[np.nonzero(hit_s)[0][int(np.argmax(alive))]]
                        )
                        raise AlreadyExistsError(
                            f"relationship already exists: {describe(first)}"
                        )
                    seg_hits.append((seg, live_rows[alive]))
                # an expired base row is superseded either way
                if (~alive).any():
                    seg_hits.append((seg, live_rows[~alive]))
        # -- commit point: nothing above mutated state -------------------
        for k in dict_hits:
            del self._live[k]
        for seg, rows in seg_hits:
            seg.live[rows] = False
        keep = ~dup
        # reuse the batch's sorted order for the segment sidecar: kept
        # rows keep their relative order, so filtering the sorted view
        # and remapping positions avoids a second 10M-row sort
        kept_sorted = ~dup_s
        remap = np.cumsum(keep) - 1
        seg = ColumnSegment(
            res=cols["res"][keep], rel=cols["rel"][keep],
            subj=cols["subj"][keep], srel1=cols["srel1"][keep],
            caveat=cols["caveat"][keep], ctx=cols["ctx"][keep],
            exp_us=cols["exp_us"][keep],
            presorted=(
                remap[order[kept_sorted]],
                sh[kept_sorted], sl[kept_sorted],
            ),
        )
        self._segments.append(seg)
        utype = UpdateType.TOUCH if touch else UpdateType.CREATE
        self._head_rev += 1
        self._log.append(
            _LogEntry(
                self._head_rev,
                _ColumnUpdates(self, seg, np.arange(len(seg)), utype),
            )
        )
        self._new_data.notify_all()
        return RevisionToken(self._head_rev)

    # -- snapshots / consistency ------------------------------------------
    @property
    def head_revision(self) -> int:
        with self._lock:
            return self._head_rev

    def _materialize_locked(self, rev: int) -> Snapshot:
        # injection site: a snapshot swap that fails mid-materialization
        # leaves prior generations untouched (RCU semantics) — callers see
        # a transient error and retry against the old generation or later
        faults.fire("store.materialize")
        snap = self._delta_materialize_locked(rev)
        if snap is None and self._segments:
            snap = self._materialize_columnar_locked(rev)
        if snap is None:
            snap = build_snapshot(
                rev, self._require_schema(), self.interner, list(self._live.values())
            )
        self._snapshots[rev] = snap
        # evict least-recently-USED, not lowest revision: a Snapshot-pinned
        # reader that keeps querying an old generation must not be thrashed
        # by concurrent head writes (round-2 Weak #5) — every access moves
        # its generation to the back via _snap_touch
        while len(self._snapshots) > self._keep_generations:
            # never evict the newest materialized generation: MIN_LATENCY
            # reads must not move backwards in revision
            newest = max(self._snapshots)
            victim = next(k for k in self._snapshots if k != newest)
            self._snapshots.pop(victim)
        return snap

    def _snap_touch(self, rev: int) -> Snapshot:
        """LRU access to a materialized generation (dicts keep order)."""
        s = self._snapshots.pop(rev)
        self._snapshots[rev] = s
        return s

    def _materialize_columnar_locked(self, rev: int) -> Snapshot:
        """Full materialization straight from the columnar base + the live
        dict overlay — no per-edge Python for the segment rows."""
        compiled = self._require_schema()
        contexts: List[Mapping[str, Any]] = list(self._base_contexts)
        parts: List[Dict[str, np.ndarray]] = []
        for seg in self._segments:
            live = seg.live
            if not live.any():
                continue
            if live.all():
                # fully-live segment (the bulk-import common case): use
                # the columns directly — no 7-column boolean gather
                parts.append(
                    {
                        "res": seg.res, "rel": seg.rel,
                        "subj": seg.subj, "srel1": seg.srel1,
                        "caveat": seg.caveat, "ctx": seg.ctx,
                        "exp_us": seg.exp_us,
                    }
                )
                continue
            parts.append(
                {
                    "res": seg.res[live], "rel": seg.rel[live],
                    "subj": seg.subj[live], "srel1": seg.srel1[live],
                    "caveat": seg.caveat[live], "ctx": seg.ctx[live],
                    "exp_us": seg.exp_us[live],
                }
            )
        if self._live:
            overlay = relationships_to_columns(
                list(self._live.values()), compiled, self.interner,
                contexts, dict(self._base_ctx_index),
            )
            parts.append(overlay)
        if not parts:
            parts.append(
                {
                    "res": np.zeros(0, np.int32), "rel": np.zeros(0, np.int32),
                    "subj": np.zeros(0, np.int32), "srel1": np.zeros(0, np.int32),
                    "caveat": np.zeros(0, np.int32),
                    "ctx": np.zeros(0, np.int32),
                    "exp_us": np.zeros(0, np.int64),
                }
            )
        cat = {
            k: np.concatenate([p[k] for p in parts]) for k in parts[0]
        }
        return build_snapshot_from_columns(
            rev, compiled, self.interner,
            res=cat["res"], rel=cat["rel"], subj=cat["subj"],
            srel=cat["srel1"] - 1,  # int32 end-to-end; builder normalizes
            caveat=cat["caveat"], ctx=cat["ctx"],
            exp_us=cat["exp_us"], contexts=contexts,
        )

    def _delta_materialize_locked(self, rev: int) -> Optional[Snapshot]:
        """Incremental path: advance the newest materialized snapshot to
        ``rev`` by replaying the update log through store/delta.py's sorted
        merge — the Watch-driven re-index of BASELINE config 5.  Returns
        None when a full rebuild is required (no usable base, schema
        changed since the base, or the delta rivals the graph in size)."""
        if not self._snapshots:
            return None
        base_rev = max(self._snapshots)
        base = self._snapshots[base_rev]
        if base_rev >= rev or base.compiled is not self._compiled:
            return None
        collapsed: Dict[_Key, Tuple[bool, Relationship]] = {}
        start = bisect.bisect_right(self._log, base_rev, key=lambda e: e.revision)
        for entry in self._log[start:]:
            if entry.revision > rev:
                break
            for u in entry.updates:
                key = u.relationship.key()
                is_add = u.update_type in (UpdateType.CREATE, UpdateType.TOUCH)
                collapsed[key] = (is_add, u.relationship)
        if len(collapsed) > max(1024, base.num_edges // 4):
            return None
        adds = [r for is_add, r in collapsed.values() if is_add]
        deletes = [r for is_add, r in collapsed.values() if not is_add]
        from .delta import apply_delta

        return apply_delta(
            base, rev, adds, deletes, interner=self.interner,
            compact_min=self.lsm_compact_min,
        )

    def snapshot_for(self, strategy: Strategy) -> Snapshot:
        """Select (materializing if needed) the snapshot generation a
        request evaluates at (consistency/consistency.go:29-77)."""
        faults.fire("store.snapshot_for")
        with self._lock:
            self._require_schema()
            req = strategy.requirement
            latest = max(self._snapshots) if self._snapshots else None
            if req == Requirement.FULL:
                if latest == self._head_rev:
                    return self._snap_touch(latest)
                return self._materialize_locked(self._head_rev)
            if req == Requirement.MIN_LATENCY:
                if latest is not None:
                    return self._snap_touch(latest)
                return self._materialize_locked(self._head_rev)
            if req == Requirement.AT_LEAST:
                want = parse_revision(strategy.revision or "")
                if want > self._head_rev:
                    raise RevisionUnavailableError(
                        f"revision {strategy.revision} is in the future"
                    )
                if latest is not None and latest >= want:
                    return self._snap_touch(latest)
                return self._materialize_locked(self._head_rev)
            if req == Requirement.SNAPSHOT:
                want = parse_revision(strategy.revision or "")
                if want in self._snapshots:
                    return self._snap_touch(want)
                if want == self._head_rev:
                    return self._materialize_locked(self._head_rev)
                raise RevisionUnavailableError(
                    f"revision {strategy.revision} is not materialized"
                    " (written snapshots are kept for a bounded number of"
                    " generations)"
                )
            raise ValueError(f"unknown consistency requirement {req}")

    # -- reads -------------------------------------------------------------
    def read(self, strategy: Strategy, f: Filter) -> Iterator[Relationship]:
        snap = self.snapshot_for(strategy)
        return snap.iter_relationships(f, now_us=self._now_us())

    def export_at(self, revision: str) -> Iterator[Relationship]:
        snap = self.snapshot_for(Strategy(Requirement.SNAPSHOT, revision))
        return snap.iter_relationships(None, now_us=self._now_us())

    def export_columns_at(self, revision: str):
        """Columnar export at an exact snapshot: yields chunk dicts of
        parallel lists (Snapshot.decode_columns) — the backup mirror of
        ``import_columns``, skipping per-edge Relationship objects."""
        snap = self.snapshot_for(Strategy(Requirement.SNAPSHOT, revision))
        now_us = self._now_us()
        live = (snap.e_exp_us == 0) | (snap.e_exp_us > now_us)
        return snap.decode_columns(np.nonzero(live)[0])

    # -- watch -------------------------------------------------------------
    def updates_since(
        self, since_rev: int, *, stop: Optional[threading.Event] = None,
        poll_interval: float = 0.1,
        cancelled: Optional[Callable[[], bool]] = None,
    ) -> Iterator[Tuple[int, Update]]:
        """Yield (revision, update) in log order, blocking for new writes.
        Resumable: pass the revision of the last seen entry
        (client/client.go:370-382).  Ends when ``stop`` is set or
        ``cancelled()`` returns True (polled between waits, so a blocked
        subscriber unblocks within ``poll_interval`` of cancellation)."""
        import bisect

        next_rev = since_rev
        while True:
            batch: List[_LogEntry] = []
            with self._lock:
                while True:
                    # _log is append-only and revision-ordered: bisect for
                    # the first entry newer than the cursor.
                    i = bisect.bisect_right(
                        self._log, next_rev, key=lambda e: e.revision
                    )
                    batch = self._log[i:]
                    if batch:
                        break
                    if stop is not None and stop.is_set():
                        return
                    if cancelled is not None and cancelled():
                        return
                    self._new_data.wait(timeout=poll_interval)
            for entry in batch:
                for u in entry.updates:
                    if stop is not None and stop.is_set():
                        return
                    yield entry.revision, u
                next_rev = entry.revision

    def entries_since(
        self, since_rev: int, *, stop: Optional[threading.Event] = None,
        poll_interval: float = 0.1,
        cancelled: Optional[Callable[[], bool]] = None,
        heartbeats: bool = False,
    ) -> Iterator[Tuple[int, Optional[List[Update]]]]:
        """Yield whole log entries ``(revision, updates)`` in order,
        blocking for new writes — the replication feed (fleet/router.py
        streams these to tailing replicas, which apply each entry
        atomically at its upstream revision via ``apply_replicated``).

        With ``heartbeats=True`` an idle poll yields ``(head_rev, None)``
        so a quiescent tail still learns the upstream head — that is what
        a replica's catchup-lag gauge and readiness gate are computed
        from.  Ends when ``stop`` is set or ``cancelled()`` returns
        True."""
        import bisect

        next_rev = since_rev
        while True:
            batch: List[_LogEntry] = []
            head = 0
            with self._lock:
                i = bisect.bisect_right(
                    self._log, next_rev, key=lambda e: e.revision
                )
                batch = self._log[i:]
                head = self._head_rev
                if not batch:
                    if (stop is None or not stop.is_set()) and (
                        cancelled is None or not cancelled()
                    ):
                        self._new_data.wait(timeout=poll_interval)
                        i = bisect.bisect_right(
                            self._log, next_rev, key=lambda e: e.revision
                        )
                        batch = self._log[i:]
                        head = self._head_rev
            if stop is not None and stop.is_set():
                return
            if cancelled is not None and cancelled():
                return
            if not batch:
                if heartbeats:
                    yield head, None
                continue
            for entry in batch:
                if stop is not None and stop.is_set():
                    return
                yield entry.revision, list(entry.updates)
                next_rev = entry.revision

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._live)

    def live_relationships(self) -> List[Relationship]:
        with self._lock:
            return list(self._live.values())

"""The MVCC tuple store: schema + tuple log + snapshot generations.

Single-writer append-only design (SURVEY.md §5 "Race detection": the
engine stays functionally pure; the only mutable state is here, guarded by
one lock with RCU-style snapshot swaps).  Semantics enforced:

- **Write** (rel/txn.go): CREATE fails on existing key, TOUCH upserts,
  DELETE removes; MustMatch/MustNotMatch preconditions checked atomically
  with the append; every write mints a revision token.
- **Delete by filter** with preconditions and per-call limits
  (client/client.go:319-358).
- **Schema write** validates that no live relationship becomes
  unreferenced (client/client.go:426-427).
- **Watch**: ordered, resumable, filtered replay of the update log
  (client/client.go:364-413).
- **Revisions**: ZedToken-analogue strings naming snapshot generations;
  consistency strategies pick the generation (SURVEY.md §5).
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

from ..caveats import CelProgram, compile_cel
from ..consistency import Requirement, Strategy
from ..rel.filter import Filter, Precondition, PreconditionedFilter
from ..rel.relationship import Relationship
from ..rel.txn import Txn
from ..rel.update import Update, UpdateType
from ..schema import CompiledSchema, compile_schema, parse_schema
from ..schema.compiler import SchemaValidationError
from ..utils.errors import (
    AlreadyExistsError,
    PreconditionFailedError,
    RevisionUnavailableError,
)
from .interner import Interner
from .snapshot import Snapshot, build_snapshot

_TOKEN_PREFIX = "gtz1."


def RevisionToken(rev: int) -> str:
    """Mint the opaque revision string for a generation (the ZedToken
    analogue returned by every write, client/client.go:125)."""
    return f"{_TOKEN_PREFIX}{rev}"


def parse_revision(token: str) -> int:
    if not token.startswith(_TOKEN_PREFIX):
        raise RevisionUnavailableError(f"malformed revision token {token!r}")
    try:
        return int(token[len(_TOKEN_PREFIX):])
    except ValueError as e:
        raise RevisionUnavailableError(f"malformed revision token {token!r}") from e


_Key = Tuple[str, str, str, str, str, str]


@dataclass
class _LogEntry:
    revision: int
    updates: List[Update]


class Store:
    """In-process authorization datastore with MVCC snapshot generations."""

    def __init__(self, *, keep_generations: int = 4) -> None:
        self._lock = threading.RLock()
        self._new_data = threading.Condition(self._lock)
        self._live: Dict[_Key, Relationship] = {}
        self._log: List[_LogEntry] = []
        self._head_rev = 0
        self._schema_text = ""
        self._compiled: Optional[CompiledSchema] = None
        self._caveat_programs: Dict[str, CelProgram] = {}
        # native C++ interner when the ingest library loads; pure-Python
        # fallback with identical semantics (native/interner.py)
        from ..native.interner import make_interner

        self.interner = make_interner()
        self._snapshots: Dict[int, Snapshot] = {}
        self._keep_generations = keep_generations

    # -- schema ----------------------------------------------------------
    def write_schema(self, text: str) -> str:
        """Parse, compile, and install a schema.  Any live relationship the
        new schema leaves unreferenced/invalid aborts the write
        (client/client.go:426-427)."""
        schema = parse_schema(text)
        compiled = compile_schema(schema)
        programs = {
            name: compile_cel(name, decl.params, decl.expression)
            for name, decl in schema.caveats.items()
        }
        with self._lock:
            for r in self._live.values():
                try:
                    compiled.validate_relationship(r)
                except SchemaValidationError as e:
                    raise SchemaValidationError(
                        f"schema change would leave relationship `{r}` invalid: {e}"
                    ) from e
            self._schema_text = text
            self._compiled = compiled
            self._caveat_programs = programs
            self._snapshots.clear()  # slot numbering may have changed
            self._head_rev += 1
            self._new_data.notify_all()
            return RevisionToken(self._head_rev)

    def read_schema(self) -> Tuple[str, str]:
        with self._lock:
            return self._schema_text, RevisionToken(self._head_rev)

    @property
    def compiled_schema(self) -> Optional[CompiledSchema]:
        with self._lock:
            return self._compiled

    def caveat_program(self, name: str) -> Optional[CelProgram]:
        return self._caveat_programs.get(name)

    # -- helpers ----------------------------------------------------------
    def _require_schema(self) -> CompiledSchema:
        if self._compiled is None:
            raise SchemaValidationError("no schema has been written")
        return self._compiled

    def _now_us(self) -> int:
        return int(time.time() * 1_000_000)

    def _is_live(self, r: Relationship, now_us: int) -> bool:
        from ..rel.relationship import expiration_micros

        return not r.has_expiration() or expiration_micros(r.expiration) > now_us

    def _filter_matches_any(self, f: Filter, now_us: int) -> bool:
        return any(
            f.matches(r) and self._is_live(r, now_us) for r in self._live.values()
        )

    def _check_preconditions(self, pcs: List[Precondition], now_us: int) -> None:
        for pc in pcs:
            matched = self._filter_matches_any(pc.filter, now_us)
            if pc.must_match and not matched:
                raise PreconditionFailedError(
                    f"precondition MUST_MATCH failed for filter on "
                    f"`{pc.filter.resource_type}`"
                )
            if not pc.must_match and matched:
                raise PreconditionFailedError(
                    f"precondition MUST_NOT_MATCH failed for filter on "
                    f"`{pc.filter.resource_type}`"
                )

    def _intern(self, r: Relationship) -> None:
        self.interner.node(r.resource_type, r.resource_id)
        self.interner.node(r.subject_type, r.subject_id)

    # -- writes ------------------------------------------------------------
    def write(self, txn: Txn) -> str:
        """Atomically apply a transaction (rel/txn.go semantics); returns
        the new revision token (client/client.go:117-126)."""
        with self._lock:
            compiled = self._require_schema()
            now_us = self._now_us()
            for u in txn.updates:
                compiled.validate_relationship(u.relationship)
                self._validate_caveat_context(u.relationship)
            self._check_preconditions(txn.preconditions, now_us)

            # Pre-validate the whole transaction against a shadow overlay so
            # a CREATE conflict aborts with nothing applied (atomicity,
            # rel/txn.go semantics).  The overlay also sequences in-txn ops:
            # DELETE x then CREATE x in one txn is legal.
            shadow: Dict[_Key, Optional[Relationship]] = {}
            for u in txn.updates:
                key = u.relationship.key()
                if u.update_type == UpdateType.CREATE:
                    existing = (
                        shadow[key] if key in shadow else self._live.get(key)
                    )
                    if existing is not None and self._is_live(existing, now_us):
                        raise AlreadyExistsError(
                            f"relationship already exists: {u.relationship}"
                        )
                    shadow[key] = u.relationship
                elif u.update_type == UpdateType.TOUCH:
                    shadow[key] = u.relationship
                elif u.update_type == UpdateType.DELETE:
                    shadow[key] = None
                else:
                    raise ValueError(f"unknown update type {u.update_type}")

            applied: List[Update] = []
            for u in txn.updates:
                key = u.relationship.key()
                if u.update_type in (UpdateType.CREATE, UpdateType.TOUCH):
                    self._live[key] = u.relationship
                    self._intern(u.relationship)
                    applied.append(u)
                else:  # DELETE
                    if key in self._live:
                        del self._live[key]
                        applied.append(u)

            self._head_rev += 1
            self._log.append(_LogEntry(self._head_rev, applied))
            self._new_data.notify_all()
            return RevisionToken(self._head_rev)

    def _validate_caveat_context(self, r: Relationship) -> None:
        if not r.caveat_name or not r.caveat_context:
            return
        prog = self._caveat_programs.get(r.caveat_name)
        if prog is None:
            return
        unknown = set(r.caveat_context) - set(prog.params)
        if unknown:
            raise SchemaValidationError(
                f"caveat `{r.caveat_name}` context has undeclared parameters: "
                f"{sorted(unknown)}"
            )

    def delete_by_filter(
        self,
        pf: PreconditionedFilter,
        *,
        limit: int = 0,
        allow_partial: bool = False,
    ) -> Tuple[str, bool]:
        """Delete relationships matching the filter.  Returns (revision,
        complete).  With a limit, at most ``limit`` are removed and
        ``complete`` reports whether the filter is now empty — the engine
        behind both DeleteAtomic (no limit; one transaction,
        client/client.go:319-336) and batched Delete
        (client/client.go:340-358)."""
        with self._lock:
            self._require_schema()
            now_us = self._now_us()
            self._check_preconditions(pf.preconditions, now_us)
            keys = [k for k, r in self._live.items() if pf.filter.matches(r)]
            victims = keys if limit <= 0 else keys[:limit]
            applied = []
            for k in victims:
                applied.append(Update(UpdateType.DELETE, self._live.pop(k)))
            complete = limit <= 0 or len(keys) <= limit
            self._head_rev += 1
            self._log.append(_LogEntry(self._head_rev, applied))
            self._new_data.notify_all()
            return RevisionToken(self._head_rev), complete

    def import_relationships(self, rs: Iterable[Relationship]) -> str:
        """Bulk-create a batch; raises AlreadyExistsError (with nothing
        applied) if any key exists or repeats within the batch — the
        BulkImport contract the client's TOUCH fallback depends on
        (client/client.go:449-459).  Returns the minted revision token."""
        with self._lock:
            compiled = self._require_schema()
            now_us = self._now_us()
            batch = list(rs)
            seen: set = set()
            for r in batch:
                compiled.validate_relationship(r)
                key = r.key()
                existing = self._live.get(key)
                if key in seen or (
                    existing is not None and self._is_live(existing, now_us)
                ):
                    raise AlreadyExistsError(f"relationship already exists: {r}")
                seen.add(key)
            applied = []
            for r in batch:
                self._live[r.key()] = r
                self._intern(r)
                applied.append(Update(UpdateType.CREATE, r))
            self._head_rev += 1
            self._log.append(_LogEntry(self._head_rev, applied))
            self._new_data.notify_all()
            return RevisionToken(self._head_rev)

    # -- snapshots / consistency ------------------------------------------
    @property
    def head_revision(self) -> int:
        with self._lock:
            return self._head_rev

    def _materialize_locked(self, rev: int) -> Snapshot:
        snap = self._delta_materialize_locked(rev)
        if snap is None:
            snap = build_snapshot(
                rev, self._require_schema(), self.interner, list(self._live.values())
            )
        self._snapshots[rev] = snap
        if len(self._snapshots) > self._keep_generations:
            for old in sorted(self._snapshots)[: len(self._snapshots) - self._keep_generations]:
                del self._snapshots[old]
        return snap

    def _delta_materialize_locked(self, rev: int) -> Optional[Snapshot]:
        """Incremental path: advance the newest materialized snapshot to
        ``rev`` by replaying the update log through store/delta.py's sorted
        merge — the Watch-driven re-index of BASELINE config 5.  Returns
        None when a full rebuild is required (no usable base, schema
        changed since the base, or the delta rivals the graph in size)."""
        if not self._snapshots:
            return None
        base_rev = max(self._snapshots)
        base = self._snapshots[base_rev]
        if base_rev >= rev or base.compiled is not self._compiled:
            return None
        collapsed: Dict[_Key, Tuple[bool, Relationship]] = {}
        start = bisect.bisect_right(self._log, base_rev, key=lambda e: e.revision)
        for entry in self._log[start:]:
            if entry.revision > rev:
                break
            for u in entry.updates:
                key = u.relationship.key()
                is_add = u.update_type in (UpdateType.CREATE, UpdateType.TOUCH)
                collapsed[key] = (is_add, u.relationship)
        if len(collapsed) > max(1024, base.num_edges // 4):
            return None
        adds = [r for is_add, r in collapsed.values() if is_add]
        deletes = [r for is_add, r in collapsed.values() if not is_add]
        from .delta import apply_delta

        return apply_delta(base, rev, adds, deletes, interner=self.interner)

    def snapshot_for(self, strategy: Strategy) -> Snapshot:
        """Select (materializing if needed) the snapshot generation a
        request evaluates at (consistency/consistency.go:29-77)."""
        with self._lock:
            self._require_schema()
            req = strategy.requirement
            latest = max(self._snapshots) if self._snapshots else None
            if req == Requirement.FULL:
                if latest == self._head_rev:
                    return self._snapshots[latest]
                return self._materialize_locked(self._head_rev)
            if req == Requirement.MIN_LATENCY:
                if latest is not None:
                    return self._snapshots[latest]
                return self._materialize_locked(self._head_rev)
            if req == Requirement.AT_LEAST:
                want = parse_revision(strategy.revision or "")
                if want > self._head_rev:
                    raise RevisionUnavailableError(
                        f"revision {strategy.revision} is in the future"
                    )
                if latest is not None and latest >= want:
                    return self._snapshots[latest]
                return self._materialize_locked(self._head_rev)
            if req == Requirement.SNAPSHOT:
                want = parse_revision(strategy.revision or "")
                if want in self._snapshots:
                    return self._snapshots[want]
                if want == self._head_rev:
                    return self._materialize_locked(self._head_rev)
                raise RevisionUnavailableError(
                    f"revision {strategy.revision} is not materialized"
                    " (written snapshots are kept for a bounded number of"
                    " generations)"
                )
            raise ValueError(f"unknown consistency requirement {req}")

    # -- reads -------------------------------------------------------------
    def read(self, strategy: Strategy, f: Filter) -> Iterator[Relationship]:
        snap = self.snapshot_for(strategy)
        return snap.iter_relationships(f, now_us=self._now_us())

    def export_at(self, revision: str) -> Iterator[Relationship]:
        snap = self.snapshot_for(Strategy(Requirement.SNAPSHOT, revision))
        return snap.iter_relationships(None, now_us=self._now_us())

    # -- watch -------------------------------------------------------------
    def updates_since(
        self, since_rev: int, *, stop: Optional[threading.Event] = None,
        poll_interval: float = 0.1,
        cancelled: Optional[Callable[[], bool]] = None,
    ) -> Iterator[Tuple[int, Update]]:
        """Yield (revision, update) in log order, blocking for new writes.
        Resumable: pass the revision of the last seen entry
        (client/client.go:370-382).  Ends when ``stop`` is set or
        ``cancelled()`` returns True (polled between waits, so a blocked
        subscriber unblocks within ``poll_interval`` of cancellation)."""
        import bisect

        next_rev = since_rev
        while True:
            batch: List[_LogEntry] = []
            with self._lock:
                while True:
                    # _log is append-only and revision-ordered: bisect for
                    # the first entry newer than the cursor.
                    i = bisect.bisect_right(
                        self._log, next_rev, key=lambda e: e.revision
                    )
                    batch = self._log[i:]
                    if batch:
                        break
                    if stop is not None and stop.is_set():
                        return
                    if cancelled is not None and cancelled():
                        return
                    self._new_data.wait(timeout=poll_interval)
            for entry in batch:
                for u in entry.updates:
                    if stop is not None and stop.is_set():
                        return
                    yield entry.revision, u
                next_rev = entry.revision

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._live)

    def live_relationships(self) -> List[Relationship]:
        with self._lock:
            return list(self._live.values())

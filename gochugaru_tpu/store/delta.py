"""Incremental snapshot materialization (Watch-driven re-index).

A full rebuild (`build_snapshot`) walks every live relationship through
Python objects, re-interns, and re-sorts — O(E log E) with a Python-loop
constant.  That is fine at write-schema time, but BASELINE config 5
(Leopard-scale Watch-driven re-index) needs each new revision to cost
O(E + D log D) for a delta of D updates against an E-edge graph, with no
per-old-edge Python work.

`apply_delta` takes the previous revision's Snapshot plus the collapsed
delta (last-writer-wins per tuple key) and produces the next Snapshot by:

1. lowering only the delta's relationships to int32 columns (interning at
   most O(D) new strings),
2. locating the delta keys in the previous primary order with a two-level
   packed-int64 binary search ((rel,res) run, then (subj,srel1) inside the
   run — the primary sort is lex (rel, res, subj, srel1) so both levels
   are sorted),
3. tombstoning replaced/deleted rows and merging the surviving rows with
   the sorted additions in one O(E + D) pass, and
4. re-deriving the secondary views (userset / membership / arrow) through
   the same `finish_snapshot` used by the full build, so delta and full
   materialization produce identical snapshots by construction.

The derived views are O(E) vectorized work with small constants; the
expensive parts of a full rebuild (per-edge Python, global lexsort,
re-interning) are all avoided.  Reference semantics being reproduced:
the Watch feed is the ordered update log (client/client.go:364-413) and a
revision is a consistent snapshot of it (consistency/consistency.go).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..rel.relationship import Relationship, expiration_micros
from ..schema.compiler import CompiledSchema
from .interner import Interner
from .snapshot import Snapshot, _exp_to_rel32, finish_snapshot


@dataclass
class DeltaInfo:
    """Machine-readable description of the delta that produced a snapshot,
    attached to it by ``apply_delta`` (as ``snap.delta_info``) so the
    device engine can advance its resident tables incrementally
    (engine/flat.py build_delta_arrays) instead of re-shipping O(E) state.

    ``a_*``: the upserted rows (lowered, epoch-relative expiry).
    ``g_*``: primary-identity columns of every row REMOVED from the
    previous snapshot — deletions plus rows replaced by an upsert.
    """

    prev_revision: int
    a_rel: np.ndarray
    a_res: np.ndarray
    a_subj: np.ndarray
    a_srel1: np.ndarray
    a_cav: np.ndarray
    a_ctx: np.ndarray
    a_exp: np.ndarray  # epoch-relative int32 (device form)
    g_rel: np.ndarray
    g_res: np.ndarray
    g_subj: np.ndarray
    g_srel1: np.ndarray
    #: True when context indices were renumbered by compaction — stored
    #: ctx ids inside device-resident base tables are then stale and the
    #: device must do a full prepare
    contexts_renumbered: bool = False

#: contexts-list compaction floor: below this length, dead context dicts
#: are retained so indices stay append-only stable (the device delta-
#: prepare depends on that; tests lower it to force renumbering)
CTX_COMPACT_MIN = 1024

# (rel, res) packed: rel < 2**15 slots, res < 2**31 nodes → 46 bits.
_RES_BITS = 31
# (subj, srel1) packed: subj < 2**31, srel1 < 2**16 → 47 bits.
_SREL_BITS = 16


def _pack_rr(rel: np.ndarray, res: np.ndarray) -> np.ndarray:
    return (rel.astype(np.int64) << _RES_BITS) | res.astype(np.int64)


def _pack_ss(subj: np.ndarray, srel1: np.ndarray) -> np.ndarray:
    return (subj.astype(np.int64) << _SREL_BITS) | srel1.astype(np.int64)


def _grouped(inverse: np.ndarray) -> "list[np.ndarray]":
    """Index arrays of each group in ``inverse`` (np.unique's inverse),
    in group order — argsort+split so grouping is O(D log D) total, not
    O(runs × D)."""
    order = np.argsort(inverse, kind="stable")
    counts = np.bincount(inverse)
    return np.split(order, np.cumsum(counts)[:-1])


def find_in_view(
    old_k1: np.ndarray, old_k2: np.ndarray, q1: np.ndarray, q2: np.ndarray
) -> np.ndarray:
    """Row index of each (q1, q2) in a view lexsorted by (k1, k2); -1 when
    absent.  Two-level binary search vectorized over the k1 runs."""
    D = q1.shape[0]
    out = np.full(D, -1, dtype=np.int64)
    if D == 0 or old_k1.shape[0] == 0:
        return out
    lo = np.searchsorted(old_k1, q1, side="left")
    hi = np.searchsorted(old_k1, q1, side="right")
    run = hi > lo
    if np.any(run):
        runs, inverse = np.unique(lo[run], return_inverse=True)
        idx_run = np.nonzero(run)[0]
        for run_lo, group in zip(runs, _grouped(inverse)):
            members = idx_run[group]
            run_hi = hi[members[0]]
            seg = old_k2[run_lo:run_hi]
            pos = run_lo + np.searchsorted(seg, q2[members], side="left")
            ok = (pos < run_hi) & (old_k2[np.clip(pos, 0, old_k2.shape[0] - 1)] == q2[members])
            out[members[ok]] = pos[ok]
    return out


def merge_positions(
    old_k1: np.ndarray, old_k2: np.ndarray, new_k1: np.ndarray, new_k2: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Interleave positions merging two (k1, k2)-lexsorted row sets:
    returns (pos_old, pos_new) into the merged array of len(old)+len(new).
    O(E + D log E) — the argsort-free merge the Watch-driven re-index
    depends on (BASELINE config 5)."""
    E0, A = old_k1.shape[0], new_k1.shape[0]
    ins = np.searchsorted(old_k1, new_k1, side="left")
    hi = np.searchsorted(old_k1, new_k1, side="right")
    run = hi > ins
    if np.any(run):
        runs, inverse = np.unique(ins[run], return_inverse=True)
        idx_run = np.nonzero(run)[0]
        for run_lo, group in zip(runs, _grouped(inverse)):
            members = idx_run[group]
            run_hi = hi[members[0]]
            seg = old_k2[run_lo:run_hi]
            ins[members] = run_lo + np.searchsorted(
                seg, new_k2[members], side="left"
            )
    add_before = np.zeros(E0 + 1, dtype=np.int64)
    np.add.at(add_before, ins, 1)
    add_before = np.cumsum(add_before)[: E0 + 1]
    pos_old = np.arange(E0, dtype=np.int64) + add_before[:E0]
    pos_new = ins + np.arange(A, dtype=np.int64)
    return pos_old, pos_new


def _locate(
    prev: Snapshot, rel: np.ndarray, res: np.ndarray,
    subj: np.ndarray, srel1: np.ndarray,
) -> np.ndarray:
    """Row index in prev's primary arrays of each (rel,res,subj,srel1)
    identity, or -1 when absent.  Two-level search, vectorized over the
    (rel,res) runs the queries land in."""
    D = rel.shape[0]
    out = np.full(D, -1, dtype=np.int64)
    if D == 0 or prev.e_rel.shape[0] == 0:
        return out
    # packed identity keys cached per snapshot: a delta chain locates
    # against the same base every revision, and re-packing 2·E int64
    # columns per delta was the only remaining O(E) term of the LSM path
    packed = prev.__dict__.get("_packed_id_keys")
    if packed is None:
        packed = (
            _pack_rr(prev.e_rel, prev.e_res),
            _pack_ss(prev.e_subj, prev.e_srel1),
        )
        prev.__dict__["_packed_id_keys"] = packed
    prev_rr, prev_ss = packed
    q_rr = _pack_rr(rel, res)
    q_ss = _pack_ss(subj, srel1)
    lo = np.searchsorted(prev_rr, q_rr, side="left")
    hi = np.searchsorted(prev_rr, q_rr, side="right")
    # group queries by run so each run's slice is searched once
    nonempty = hi > lo
    runs, inverse = np.unique(lo[nonempty], return_inverse=True)
    idx_nonempty = np.nonzero(nonempty)[0]
    for run_lo, group in zip(runs, _grouped(inverse)):
        members = idx_nonempty[group]
        run_hi = hi[members[0]]
        seg = prev_ss[run_lo:run_hi]
        pos = np.searchsorted(seg, q_ss[members], side="left")
        ok = (pos < seg.shape[0]) & (seg[np.minimum(pos, seg.shape[0] - 1)] == q_ss[members])
        out[members[ok]] = run_lo + pos[ok]
    return out


def _lower_delta(
    compiled: CompiledSchema,
    interner: Interner,
    rels: Sequence[Relationship],
    contexts: List[Mapping[str, Any]],
    ctx_index: Optional[dict] = None,
) -> Tuple[np.ndarray, ...]:
    """Relationship objects → unsorted int columns (interning new strings),
    appending any caveat contexts to ``contexts`` in place.  Contexts are
    deduplicated by value so re-touching a caveated tuple revision after
    revision reuses one stored dict instead of growing the list."""
    D = len(rels)
    res = np.empty(D, dtype=np.int64)
    rel_s = np.empty(D, dtype=np.int64)
    subj = np.empty(D, dtype=np.int64)
    srel1 = np.empty(D, dtype=np.int64)
    cav = np.zeros(D, dtype=np.int32)
    ctx = np.full(D, -1, dtype=np.int32)
    exp_us = np.zeros(D, dtype=np.int64)
    slot_of = compiled.slot_of_name
    caveat_ids = compiled.caveat_ids
    if ctx_index is None:
        ctx_index = {}
        for i, c in enumerate(contexts):
            ctx_index.setdefault(
                repr(sorted(c.items(), key=lambda kv: kv[0])), i
            )
    for i, r in enumerate(rels):
        res[i] = interner.node(r.resource_type, r.resource_id)
        rel_s[i] = slot_of[r.resource_relation]
        subj[i] = interner.node(r.subject_type, r.subject_id)
        srel1[i] = slot_of[r.subject_relation] + 1 if r.subject_relation else 0
        if r.caveat_name:
            cav[i] = caveat_ids[r.caveat_name]
            if r.caveat_context:
                key = repr(sorted(r.caveat_context.items(), key=lambda kv: kv[0]))
                at = ctx_index.get(key)
                if at is None:
                    at = len(contexts)
                    ctx_index[key] = at
                    contexts.append(r.caveat_context)
                ctx[i] = at
        exp_us[i] = expiration_micros(r.expiration) if r.has_expiration() else 0
    return res, rel_s, subj, srel1, cav, ctx, exp_us


#: host-side LSM compaction floor: once the accumulated overlay (adds +
#: tombstones) crosses max(this, E/8), apply_delta materializes the chain
#: into a fresh base instead of growing it.  Mirrors the device's
#: EngineConfig.flat_delta_min_compact so host and device compact on the
#: same revision (the device bails to a full prepare at the same bound,
#: which touches every view and would materialize anyway).  Tunable per
#: store via EngineConfig.lsm_compact_min (threaded through apply_delta's
#: ``compact_min``); this module constant is only the default.
LSM_COMPACT_MIN = 65_536


class _lazycol:
    """Non-data descriptor for one deferred Snapshot column: first access
    materializes the whole snapshot (filling the instance __dict__, after
    which instance attributes win and this descriptor is never consulted
    again)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __get__(self, obj, owner=None):
        if obj is None:
            return self
        obj._materialize()
        return obj.__dict__[self.name]


#: every Snapshot column derived from the primary arrays — exactly the
#: fields LsmSnapshot defers until something actually reads them
_LAZY_FIELDS = (
    "e_rel", "e_res", "e_subj", "e_srel1", "e_caveat", "e_ctx", "e_exp",
    "e_exp_us",
    "us_rel", "us_res", "us_subj", "us_srel", "us_caveat", "us_ctx",
    "us_exp", "us_perm", "pus_n", "pus_r",
    "ms_subj", "ms_res", "ms_rel", "ms_caveat", "ms_ctx", "ms_exp",
    "mp_subj", "mp_srel", "mp_res", "mp_rel", "mp_caveat", "mp_ctx",
    "mp_exp",
    "ar_rel", "ar_res", "ar_child", "ar_caveat", "ar_ctx", "ar_exp",
)


class LsmSnapshot(Snapshot):
    """Deferred-merge snapshot: a materialized base plus one collapsed,
    (rel,res,subj,srel1)-sorted overlay of adds and a tombstone set of
    base rows.  ``apply_delta`` returns these so a Watch-driven revision
    costs O(D log E) host work instead of rewriting E rows — the host
    half of BASELINE config 5's re-index budget.

    The device's incremental prepare reads only ``delta_info`` and the
    eager scalars (num_nodes, node_type, wildcard table, us_used_keys);
    every derived column is a non-data descriptor that materializes the
    full merge on first touch (host oracle fallback, exports, full
    device prepares), after which the instance behaves exactly like the
    snapshot the eager path would have produced — same
    ``finish_snapshot``, so identical by construction."""

    def __init__(self, base: Snapshot, revision: int, *, interner,
                 contexts, ov, gone_base: np.ndarray, num_nodes: int,
                 node_type: np.ndarray, wc: np.ndarray):
        # deliberately NOT calling the dataclass __init__: column fields
        # stay unset so the class-level _lazycol descriptors fire
        self.revision = revision
        self.compiled = base.compiled
        self.interner = interner
        self.num_nodes = num_nodes
        self.num_slots = base.num_slots
        self.epoch_us = base.epoch_us
        self.node_type = node_type
        self.wildcard_node_of_type = wc
        self.contexts = contexts
        # conservative carry-forward: eligible deltas never grow the set
        # (new userset subjects bail the device to a full prepare, which
        # materializes and recomputes); a stale superset only causes
        # extra full prepares, never wrong answers
        self.us_used_keys = getattr(base, "us_used_keys", None)
        self._lsm_base = base
        self._lsm_ov = ov  # dict of sorted overlay columns
        self._lsm_gone = gone_base  # sorted unique base-row tombstones
        self._lsm_lock = threading.Lock()  # one merge even under races

    @property
    def num_edges(self) -> int:
        if self.__dict__.get("_lsm_done"):
            return int(self.__dict__["e_rel"].shape[0])
        return int(
            self._lsm_base.e_rel.shape[0]
            - self._lsm_gone.shape[0]
            + self._lsm_ov["rel"].shape[0]
        )

    @property
    def overlay_rows(self) -> int:
        """Accumulated chain size (overlay adds + base tombstones): the
        quantity the compaction bound compares against max(compact_min,
        E/8), and what every probe pays an extra binary search over.
        0 once materialized."""
        if self.__dict__.get("_lsm_done"):
            return 0
        return int(self._lsm_ov["rel"].shape[0] + self._lsm_gone.shape[0])

    @property
    def chain_base_revision(self) -> int:
        """Revision of the materialized base this chain grows from (the
        chain length in revisions is ``revision - chain_base_revision``);
        own revision once materialized."""
        if self.__dict__.get("_lsm_done"):
            return int(self.revision)
        return int(self._lsm_base.revision)

    def _materialize(self, compact_ctx: bool = False) -> bool:
        if self.__dict__.get("_lsm_done"):
            return False
        with self._lsm_lock:
            return self._materialize_locked(compact_ctx)

    def _materialize_locked(self, compact_ctx: bool) -> bool:
        if self.__dict__.get("_lsm_done"):
            return False
        base, ov = self._lsm_base, self._lsm_ov
        keep = np.ones(base.e_rel.shape[0], dtype=bool)
        keep[self._lsm_gone] = False
        old_rr = _pack_rr(base.e_rel, base.e_res)[keep]
        old_ss = _pack_ss(base.e_subj, base.e_srel1)[keep]
        new_rr = _pack_rr(ov["rel"], ov["res"])
        new_ss = _pack_ss(ov["subj"], ov["srel1"])
        E0, A = old_rr.shape[0], new_rr.shape[0]
        pos_old, pos_new = merge_positions(old_rr, old_ss, new_rr, new_ss)

        def interleave(old: np.ndarray, new: np.ndarray) -> np.ndarray:
            out = np.empty(E0 + A, dtype=old.dtype)
            out[pos_old] = old[keep]
            out[pos_new] = new
            return out

        e_ctx = interleave(base.e_ctx, ov["ctx"])
        contexts = self.contexts
        renumbered = False
        if compact_ctx:
            # renumbering is only sound at BUILD time (before the device
            # consumed this revision's delta_info): the caller flags the
            # delta contexts_renumbered so baked-in ctx ids are not
            # trusted.  A lazy (post-handoff) materialization must never
            # compact — the device may already hold the old ids
            used = e_ctx >= 0
            if not used.any():
                renumbered = bool(contexts)
                contexts = []
            else:
                live_ctx, inv = np.unique(e_ctx[used], return_inverse=True)
                if len(contexts) > live_ctx.shape[0]:
                    contexts = [contexts[i] for i in live_ctx]
                    e_ctx[used] = inv.astype(np.int32)
                    renumbered = True
            self.contexts = contexts
        nxt = finish_snapshot(
            self.revision, self.compiled, self.interner,
            e_rel=interleave(base.e_rel, ov["rel"]),
            e_res=interleave(base.e_res, ov["res"]),
            e_subj=interleave(base.e_subj, ov["subj"]),
            e_srel1=interleave(base.e_srel1, ov["srel1"]),
            e_caveat=interleave(base.e_caveat, ov["cav"]),
            e_ctx=e_ctx,
            e_exp=interleave(base.e_exp, ov["exp"]),
            e_exp_us=interleave(base.e_exp_us, ov["exp_us"]),
            contexts=contexts, epoch_us=self.epoch_us,
        )
        for f in _LAZY_FIELDS:
            self.__dict__[f] = getattr(nxt, f)
        # finish_snapshot recomputes the used-userset set from the merged
        # rows — replace the conservative carry-forward with the truth
        self.__dict__["us_used_keys"] = nxt.us_used_keys
        # carry the lookup index across the chain BEFORE the state that
        # feeds the advance is dropped: identity-based advance from the
        # base's index with the accumulated tombstones + overlay — the
        # O(E + D log E) path that keeps warm lookup_resources warm
        # across a Watch chain (engine/lookup.py advance_lookup_index).
        # _lsm_done publishes only AFTER this block, so a concurrent
        # first lookup either waits on the lock (and finds the carried
        # index) or arrives later — it can never slip between the merge
        # and the carry and pay a redundant rebuild
        if (
            getattr(base, "_lookup_index", None) is None
            and base.__dict__.get("_lookup_chain_stash") is not None
        ):
            # the base itself carries an unredeemed stash (it was the
            # tip of an earlier chain, materialized while its index was
            # still unused): redeem it now so the carry below has a base
            # index to advance from — otherwise the stash is orphaned
            # and the chain's index lineage is silently dropped
            from ..engine.lookup import redeem_chain_stash

            redeem_chain_stash(base)
        if (
            getattr(self, "_lookup_index", None) is None
            and getattr(base, "_lookup_index", None) is not None
        ):
            g = ~keep  # the accumulated base-row tombstone mask
            if (
                getattr(base, "_lookup_used", False)
                or getattr(self, "_lookup_used", False)
            ):
                # lookups are live on this store: advance eagerly so the
                # next one stays warm
                from ..engine.lookup import advance_lookup_index

                advance_lookup_index(
                    base._lookup_index, self,
                    num_slots=base.num_slots,
                    tupleset_slots=base.compiled.tupleset_slots,
                    ra_rel_src=base,
                    g_rel=base.e_rel[g], g_res=base.e_res[g],
                    g_subj=base.e_subj[g], g_srel1=base.e_srel1[g],
                    a_rel=ov["rel"], a_res=ov["res"],
                    a_subj=ov["subj"], a_srel1=ov["srel1"],
                )
            else:
                # index exists but nobody reads it (the prepare-time
                # prewarm): paying the O(E) advance on every Watch
                # revision costs ~4x the whole re-index step (measured,
                # bench5 r05: 17.9 -> 78ms overlay+probe).  Stash the
                # O(D) advance inputs instead — the FIRST real lookup
                # advances from the stash (engine/lookup.py
                # redeem_chain_stash) and flips the store onto the
                # eager path above
                from ..engine.lookup import _ra_rel_of

                _ra_rel_of(base, base._lookup_index)  # self-contain idx
                self.__dict__["_lookup_chain_stash"] = (
                    base._lookup_index,
                    base.e_rel[g], base.e_res[g],
                    base.e_subj[g], base.e_srel1[g],
                    ov["rel"], ov["res"], ov["subj"], ov["srel1"],
                )
        self.__dict__["_lsm_done"] = True
        # drop the chain state: a materialized snapshot otherwise pins
        # the whole previous base's columns (~2× E-row memory) forever
        self._lsm_base = self._lsm_ov = self._lsm_gone = None
        return renumbered


for _f in _LAZY_FIELDS:
    setattr(LsmSnapshot, _f, _lazycol(_f))


def apply_delta(
    prev: Snapshot,
    revision: int,
    adds: Sequence[Relationship],
    deletes: Sequence[Relationship],
    *,
    interner: Optional[Interner] = None,
    defer: Optional[bool] = None,
    compact_min: Optional[int] = None,
) -> Snapshot:
    """Next-revision Snapshot from the previous one plus a collapsed delta.

    ``adds`` are upserts (CREATE/TOUCH both replace any existing row with
    the same tuple key, matching the store's keyed ``_live`` dict);
    ``deletes`` are tuple keys to remove (extra keys not present are
    ignored, matching DELETE semantics).  A key must not appear in both —
    the store collapses the delta last-writer-wins before calling this.

    ``defer`` controls the host LSM: True returns an LsmSnapshot whose
    column merge is deferred to first access (O(D log E) now); False
    merges eagerly; None (default) defers unless the previous snapshot
    carries a live lookup index (advance_lookup_index needs merged-row
    positions) or the accumulated overlay would cross the compaction
    bound (then the merge is due anyway).

    ``compact_min`` overrides the module-level LSM_COMPACT_MIN floor —
    the store threads EngineConfig.lsm_compact_min through here so the
    tuner can trade probe depth against materialization frequency."""
    interner = interner if interner is not None else prev.interner
    compiled = prev.compiled
    contexts = list(prev.contexts)

    # the value→index dedup map is append-only between renumberings, so
    # chained deltas carry it forward instead of re-hashing every stored
    # context dict per revision
    ctx_index = getattr(prev, "_ctx_index", None)
    if ctx_index is None:
        ctx_index = {}
        for i, c in enumerate(contexts):
            ctx_index.setdefault(repr(sorted(c.items(), key=lambda kv: kv[0])), i)
    a_res, a_rel, a_subj, a_srel1, a_cav, a_ctx, a_exp_us = _lower_delta(
        compiled, interner, adds, contexts, ctx_index=ctx_index
    )
    d_contexts: List[Mapping[str, Any]] = []
    d_res, d_rel, d_subj, d_srel1, _, _, _ = _lower_delta(
        compiled, interner, deletes, d_contexts
    )
    a_exp32 = _exp_to_rel32(a_exp_us, prev.epoch_us)
    a_order = np.lexsort((a_srel1, a_subj, a_res, a_rel))

    # resolve the chain: an unmaterialized LsmSnapshot extends its own
    # base/overlay; anything else (plain or already-materialized) starts
    # a fresh chain with itself as base
    chained = isinstance(prev, LsmSnapshot) and not prev.__dict__.get(
        "_lsm_done"
    )
    base = prev._lsm_base if chained else prev
    ov0 = prev._lsm_ov if chained else {
        k: np.zeros(0, np.int64 if k in ("rel", "res", "subj", "srel1", "exp_us") else np.int32)
        for k in ("rel", "res", "subj", "srel1", "cav", "ctx", "exp", "exp_us")
    }
    gone0 = prev._lsm_gone if chained else np.zeros(0, np.int64)

    # locate this delta's identities in the base and in the overlay
    all_rel = np.concatenate([a_rel, d_rel])
    all_res = np.concatenate([a_res, d_res])
    all_subj = np.concatenate([a_subj, d_subj])
    all_srel1 = np.concatenate([a_srel1, d_srel1])
    base_hit = _locate(base, all_rel, all_res, all_subj, all_srel1)
    ov_hit = find_in_view(
        _pack_rr(ov0["rel"], ov0["res"]), _pack_ss(ov0["subj"], ov0["srel1"]),
        _pack_rr(all_rel, all_res), _pack_ss(all_subj, all_srel1),
    )

    # per-revision removal set (delta_info.g_*): identities live at prev —
    # a base row not already tombstoned, or an overlay row
    base_live = base_hit >= 0
    if gone0.size:
        pos = np.searchsorted(gone0, base_hit)
        already = (pos < gone0.shape[0]) & (
            gone0[np.clip(pos, 0, gone0.shape[0] - 1)] == base_hit
        )
        base_live &= ~already
    was_live = base_live | (ov_hit >= 0)
    g_rel = all_rel[was_live].astype(np.int32)
    g_res = all_res[was_live].astype(np.int32)
    g_subj = all_subj[was_live].astype(np.int32)
    g_srel1 = all_srel1[was_live].astype(np.int32)

    # new chain state: tombstones grow by the base hits; replaced/deleted
    # overlay rows drop; sorted adds merge in
    gone = np.union1d(gone0, base_hit[base_hit >= 0])
    ov_keep = np.ones(ov0["rel"].shape[0], dtype=bool)
    ov_keep[ov_hit[ov_hit >= 0]] = False
    new_cols = {
        "rel": a_rel[a_order], "res": a_res[a_order],
        "subj": a_subj[a_order], "srel1": a_srel1[a_order],
        "cav": a_cav[a_order], "ctx": a_ctx[a_order],
        "exp": a_exp32[a_order], "exp_us": a_exp_us[a_order],
    }
    pos_old, pos_new = merge_positions(
        _pack_rr(ov0["rel"], ov0["res"])[ov_keep],
        _pack_ss(ov0["subj"], ov0["srel1"])[ov_keep],
        _pack_rr(new_cols["rel"], new_cols["res"]),
        _pack_ss(new_cols["subj"], new_cols["srel1"]),
    )
    O0, A = int(ov_keep.sum()), new_cols["rel"].shape[0]
    ov = {}
    for k in ov0:
        out = np.empty(O0 + A, dtype=ov0[k].dtype)
        out[pos_old] = ov0[k][ov_keep]
        out[pos_new] = new_cols[k].astype(ov0[k].dtype)
        ov[k] = out

    cm = LSM_COMPACT_MIN if compact_min is None else int(compact_min)
    over_bound = ov["rel"].shape[0] + gone.shape[0] > max(
        cm, base.e_rel.shape[0] // 8
    )
    # contexts-list compaction check on an O(delta)-maintained UPPER bound
    # of live context uses (base count at chain start + overlay ctx rows;
    # tombstones only shrink the truth, so this over-estimates and
    # compacts no more often than the exact check would)
    base_nctx = (
        prev.__dict__.get("_lsm_base_nctx") if chained else None
    )
    if base_nctx is None:
        base_nctx = int(np.count_nonzero(base.e_ctx >= 0))
    nctx_ub = base_nctx + int(np.count_nonzero(ov["ctx"] >= 0))
    ctx_over = len(contexts) > CTX_COMPACT_MIN and (
        nctx_ub == 0 or len(contexts) > 2 * nctx_ub
    )
    if defer is None:
        # "_lookup_used" (set when a lookup actually consumes the index,
        # engine/lookup.py) — NOT mere index presence: the prepare-time
        # prewarm plants an index on every big snapshot, and keying on it
        # would push all Watch revisions onto the eager O(E) path
        defer = (
            not getattr(prev, "_lookup_used", False)
            and not over_bound
            and not ctx_over
        )

    num_nodes = max(len(interner), 1)
    node_type = np.concatenate([
        base.node_type, interner.node_type_tail(base.node_type.shape[0])
    ]) if num_nodes > base.node_type.shape[0] else base.node_type
    wc = np.full(max(interner.num_types, 1), -1, dtype=np.int32)
    from ..rel.relationship import WILDCARD_ID

    for tname in compiled.type_ids:
        n = interner.lookup(tname, WILDCARD_ID)
        if n >= 0:
            wc[interner.type_lookup(tname)] = n

    nxt = LsmSnapshot(
        base, revision, interner=interner, contexts=contexts, ov=ov,
        gone_base=gone, num_nodes=num_nodes, node_type=node_type, wc=wc,
    )
    nxt._lsm_base_nctx = base_nctx
    renumbered = False
    if not defer:
        renumbered = nxt._materialize(compact_ctx=ctx_over)
    if not renumbered:
        nxt._ctx_index = ctx_index  # still valid: indices were append-only
    nxt.delta_info = DeltaInfo(
        prev_revision=prev.revision,
        a_rel=a_rel.astype(np.int32), a_res=a_res.astype(np.int32),
        a_subj=a_subj.astype(np.int32), a_srel1=a_srel1.astype(np.int32),
        a_cav=a_cav, a_ctx=a_ctx, a_exp=a_exp32,
        g_rel=g_rel, g_res=g_res, g_subj=g_subj, g_srel1=g_srel1,
        contexts_renumbered=renumbered,
    )
    if (
        not defer
        and getattr(nxt, "_lookup_index", None) is None
        and getattr(prev, "_lookup_index", None) is not None
    ):
        # carry the lookup index forward: advance prev's by this
        # revision's removal identities + additions (O(E + D log E)
        # merges) instead of letting the next lookup pay a full
        # O(E log E) rebuild.  Removal is identity-based, so the chained
        # path works too: g_* is exactly the set of identities live at
        # prev that this revision removes or replaces (base rows not
        # already tombstoned, plus overlay rows).  A chained prev WITHOUT
        # an index leaves the work to lookup_index()'s chain-advance
        from ..engine.lookup import advance_lookup_index

        advance_lookup_index(
            prev._lookup_index, nxt,
            num_slots=prev.num_slots,
            tupleset_slots=prev.compiled.tupleset_slots,
            ra_rel_src=prev,
            g_rel=g_rel, g_res=g_res, g_subj=g_subj, g_srel1=g_srel1,
            a_rel=a_rel, a_res=a_res, a_subj=a_subj, a_srel1=a_srel1,
        )
    return nxt

"""Incremental snapshot materialization (Watch-driven re-index).

A full rebuild (`build_snapshot`) walks every live relationship through
Python objects, re-interns, and re-sorts — O(E log E) with a Python-loop
constant.  That is fine at write-schema time, but BASELINE config 5
(Leopard-scale Watch-driven re-index) needs each new revision to cost
O(E + D log D) for a delta of D updates against an E-edge graph, with no
per-old-edge Python work.

`apply_delta` takes the previous revision's Snapshot plus the collapsed
delta (last-writer-wins per tuple key) and produces the next Snapshot by:

1. lowering only the delta's relationships to int32 columns (interning at
   most O(D) new strings),
2. locating the delta keys in the previous primary order with a two-level
   packed-int64 binary search ((rel,res) run, then (subj,srel1) inside the
   run — the primary sort is lex (rel, res, subj, srel1) so both levels
   are sorted),
3. tombstoning replaced/deleted rows and merging the surviving rows with
   the sorted additions in one O(E + D) pass, and
4. re-deriving the secondary views (userset / membership / arrow) through
   the same `finish_snapshot` used by the full build, so delta and full
   materialization produce identical snapshots by construction.

The derived views are O(E) vectorized work with small constants; the
expensive parts of a full rebuild (per-edge Python, global lexsort,
re-interning) are all avoided.  Reference semantics being reproduced:
the Watch feed is the ordered update log (client/client.go:364-413) and a
revision is a consistent snapshot of it (consistency/consistency.go).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..rel.relationship import Relationship, expiration_micros
from ..schema.compiler import CompiledSchema
from .interner import Interner
from .snapshot import Snapshot, _exp_to_rel32, finish_snapshot


@dataclass
class DeltaInfo:
    """Machine-readable description of the delta that produced a snapshot,
    attached to it by ``apply_delta`` (as ``snap.delta_info``) so the
    device engine can advance its resident tables incrementally
    (engine/flat.py build_delta_arrays) instead of re-shipping O(E) state.

    ``a_*``: the upserted rows (lowered, epoch-relative expiry).
    ``g_*``: primary-identity columns of every row REMOVED from the
    previous snapshot — deletions plus rows replaced by an upsert.
    """

    prev_revision: int
    a_rel: np.ndarray
    a_res: np.ndarray
    a_subj: np.ndarray
    a_srel1: np.ndarray
    a_cav: np.ndarray
    a_ctx: np.ndarray
    a_exp: np.ndarray  # epoch-relative int32 (device form)
    g_rel: np.ndarray
    g_res: np.ndarray
    g_subj: np.ndarray
    g_srel1: np.ndarray
    #: True when context indices were renumbered by compaction — stored
    #: ctx ids inside device-resident base tables are then stale and the
    #: device must do a full prepare
    contexts_renumbered: bool = False

#: contexts-list compaction floor: below this length, dead context dicts
#: are retained so indices stay append-only stable (the device delta-
#: prepare depends on that; tests lower it to force renumbering)
CTX_COMPACT_MIN = 1024

# (rel, res) packed: rel < 2**15 slots, res < 2**31 nodes → 46 bits.
_RES_BITS = 31
# (subj, srel1) packed: subj < 2**31, srel1 < 2**16 → 47 bits.
_SREL_BITS = 16


def _pack_rr(rel: np.ndarray, res: np.ndarray) -> np.ndarray:
    return (rel.astype(np.int64) << _RES_BITS) | res.astype(np.int64)


def _pack_ss(subj: np.ndarray, srel1: np.ndarray) -> np.ndarray:
    return (subj.astype(np.int64) << _SREL_BITS) | srel1.astype(np.int64)


def _grouped(inverse: np.ndarray) -> "list[np.ndarray]":
    """Index arrays of each group in ``inverse`` (np.unique's inverse),
    in group order — argsort+split so grouping is O(D log D) total, not
    O(runs × D)."""
    order = np.argsort(inverse, kind="stable")
    counts = np.bincount(inverse)
    return np.split(order, np.cumsum(counts)[:-1])


def find_in_view(
    old_k1: np.ndarray, old_k2: np.ndarray, q1: np.ndarray, q2: np.ndarray
) -> np.ndarray:
    """Row index of each (q1, q2) in a view lexsorted by (k1, k2); -1 when
    absent.  Two-level binary search vectorized over the k1 runs."""
    D = q1.shape[0]
    out = np.full(D, -1, dtype=np.int64)
    if D == 0 or old_k1.shape[0] == 0:
        return out
    lo = np.searchsorted(old_k1, q1, side="left")
    hi = np.searchsorted(old_k1, q1, side="right")
    run = hi > lo
    if np.any(run):
        runs, inverse = np.unique(lo[run], return_inverse=True)
        idx_run = np.nonzero(run)[0]
        for run_lo, group in zip(runs, _grouped(inverse)):
            members = idx_run[group]
            run_hi = hi[members[0]]
            seg = old_k2[run_lo:run_hi]
            pos = run_lo + np.searchsorted(seg, q2[members], side="left")
            ok = (pos < run_hi) & (old_k2[np.clip(pos, 0, old_k2.shape[0] - 1)] == q2[members])
            out[members[ok]] = pos[ok]
    return out


def merge_positions(
    old_k1: np.ndarray, old_k2: np.ndarray, new_k1: np.ndarray, new_k2: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Interleave positions merging two (k1, k2)-lexsorted row sets:
    returns (pos_old, pos_new) into the merged array of len(old)+len(new).
    O(E + D log E) — the argsort-free merge the Watch-driven re-index
    depends on (BASELINE config 5)."""
    E0, A = old_k1.shape[0], new_k1.shape[0]
    ins = np.searchsorted(old_k1, new_k1, side="left")
    hi = np.searchsorted(old_k1, new_k1, side="right")
    run = hi > ins
    if np.any(run):
        runs, inverse = np.unique(ins[run], return_inverse=True)
        idx_run = np.nonzero(run)[0]
        for run_lo, group in zip(runs, _grouped(inverse)):
            members = idx_run[group]
            run_hi = hi[members[0]]
            seg = old_k2[run_lo:run_hi]
            ins[members] = run_lo + np.searchsorted(
                seg, new_k2[members], side="left"
            )
    add_before = np.zeros(E0 + 1, dtype=np.int64)
    np.add.at(add_before, ins, 1)
    add_before = np.cumsum(add_before)[: E0 + 1]
    pos_old = np.arange(E0, dtype=np.int64) + add_before[:E0]
    pos_new = ins + np.arange(A, dtype=np.int64)
    return pos_old, pos_new


def _locate(
    prev: Snapshot, rel: np.ndarray, res: np.ndarray,
    subj: np.ndarray, srel1: np.ndarray,
) -> np.ndarray:
    """Row index in prev's primary arrays of each (rel,res,subj,srel1)
    identity, or -1 when absent.  Two-level search, vectorized over the
    (rel,res) runs the queries land in."""
    D = rel.shape[0]
    out = np.full(D, -1, dtype=np.int64)
    if D == 0 or prev.e_rel.shape[0] == 0:
        return out
    prev_rr = _pack_rr(prev.e_rel, prev.e_res)
    prev_ss = _pack_ss(prev.e_subj, prev.e_srel1)
    q_rr = _pack_rr(rel, res)
    q_ss = _pack_ss(subj, srel1)
    lo = np.searchsorted(prev_rr, q_rr, side="left")
    hi = np.searchsorted(prev_rr, q_rr, side="right")
    # group queries by run so each run's slice is searched once
    nonempty = hi > lo
    runs, inverse = np.unique(lo[nonempty], return_inverse=True)
    idx_nonempty = np.nonzero(nonempty)[0]
    for run_lo, group in zip(runs, _grouped(inverse)):
        members = idx_nonempty[group]
        run_hi = hi[members[0]]
        seg = prev_ss[run_lo:run_hi]
        pos = np.searchsorted(seg, q_ss[members], side="left")
        ok = (pos < seg.shape[0]) & (seg[np.minimum(pos, seg.shape[0] - 1)] == q_ss[members])
        out[members[ok]] = run_lo + pos[ok]
    return out


def _lower_delta(
    compiled: CompiledSchema,
    interner: Interner,
    rels: Sequence[Relationship],
    contexts: List[Mapping[str, Any]],
    ctx_index: Optional[dict] = None,
) -> Tuple[np.ndarray, ...]:
    """Relationship objects → unsorted int columns (interning new strings),
    appending any caveat contexts to ``contexts`` in place.  Contexts are
    deduplicated by value so re-touching a caveated tuple revision after
    revision reuses one stored dict instead of growing the list."""
    D = len(rels)
    res = np.empty(D, dtype=np.int64)
    rel_s = np.empty(D, dtype=np.int64)
    subj = np.empty(D, dtype=np.int64)
    srel1 = np.empty(D, dtype=np.int64)
    cav = np.zeros(D, dtype=np.int32)
    ctx = np.full(D, -1, dtype=np.int32)
    exp_us = np.zeros(D, dtype=np.int64)
    slot_of = compiled.slot_of_name
    caveat_ids = compiled.caveat_ids
    if ctx_index is None:
        ctx_index = {}
        for i, c in enumerate(contexts):
            ctx_index.setdefault(
                repr(sorted(c.items(), key=lambda kv: kv[0])), i
            )
    for i, r in enumerate(rels):
        res[i] = interner.node(r.resource_type, r.resource_id)
        rel_s[i] = slot_of[r.resource_relation]
        subj[i] = interner.node(r.subject_type, r.subject_id)
        srel1[i] = slot_of[r.subject_relation] + 1 if r.subject_relation else 0
        if r.caveat_name:
            cav[i] = caveat_ids[r.caveat_name]
            if r.caveat_context:
                key = repr(sorted(r.caveat_context.items(), key=lambda kv: kv[0]))
                at = ctx_index.get(key)
                if at is None:
                    at = len(contexts)
                    ctx_index[key] = at
                    contexts.append(r.caveat_context)
                ctx[i] = at
        exp_us[i] = expiration_micros(r.expiration) if r.has_expiration() else 0
    return res, rel_s, subj, srel1, cav, ctx, exp_us


def apply_delta(
    prev: Snapshot,
    revision: int,
    adds: Sequence[Relationship],
    deletes: Sequence[Relationship],
    *,
    interner: Optional[Interner] = None,
) -> Snapshot:
    """Next-revision Snapshot from the previous one plus a collapsed delta.

    ``adds`` are upserts (CREATE/TOUCH both replace any existing row with
    the same tuple key, matching the store's keyed ``_live`` dict);
    ``deletes`` are tuple keys to remove (extra keys not present are
    ignored, matching DELETE semantics).  A key must not appear in both —
    the store collapses the delta last-writer-wins before calling this.
    """
    interner = interner if interner is not None else prev.interner
    compiled = prev.compiled
    contexts = list(prev.contexts)

    # the value→index dedup map is append-only between renumberings, so
    # chained deltas carry it forward instead of re-hashing every stored
    # context dict per revision
    ctx_index = getattr(prev, "_ctx_index", None)
    if ctx_index is None:
        ctx_index = {}
        for i, c in enumerate(contexts):
            ctx_index.setdefault(repr(sorted(c.items(), key=lambda kv: kv[0])), i)
    a_res, a_rel, a_subj, a_srel1, a_cav, a_ctx, a_exp_us = _lower_delta(
        compiled, interner, adds, contexts, ctx_index=ctx_index
    )
    d_contexts: List[Mapping[str, Any]] = []
    d_res, d_rel, d_subj, d_srel1, _, _, _ = _lower_delta(
        compiled, interner, deletes, d_contexts
    )

    # tombstone every row whose identity is re-added or deleted
    gone = np.concatenate([
        _locate(prev, a_rel, a_res, a_subj, a_srel1),
        _locate(prev, d_rel, d_res, d_subj, d_srel1),
    ]) if (len(adds) + len(deletes)) else np.empty(0, np.int64)
    keep = np.ones(prev.e_rel.shape[0], dtype=bool)
    keep[gone[gone >= 0]] = False

    # sort the additions by the primary order
    a_order = np.lexsort((a_srel1, a_subj, a_res, a_rel))
    a_exp32 = _exp_to_rel32(a_exp_us, prev.epoch_us)

    # merge positions: surviving old rows and sorted additions interleaved
    # by (rel,res | subj,srel1); computed on the packed keys so the merge
    # itself is one argsort-free scatter.
    old_rr = _pack_rr(prev.e_rel, prev.e_res)[keep]
    old_ss = _pack_ss(prev.e_subj, prev.e_srel1)[keep]
    new_rr = _pack_rr(a_rel, a_res)[a_order]
    new_ss = _pack_ss(a_subj, a_srel1)[a_order]
    E0, A = old_rr.shape[0], new_rr.shape[0]

    # interleave positions: two-level merge by (rel,res | subj,srel1)
    pos_old, pos_new = merge_positions(old_rr, old_ss, new_rr, new_ss)

    def interleave(old: np.ndarray, new: np.ndarray) -> np.ndarray:
        out = np.empty(E0 + A, dtype=old.dtype)
        out[pos_old] = old[keep]
        out[pos_new] = new
        return out

    e_rel = interleave(prev.e_rel, a_rel[a_order].astype(np.int32))
    e_res = interleave(prev.e_res, a_res[a_order].astype(np.int32))
    e_subj = interleave(prev.e_subj, a_subj[a_order].astype(np.int32))
    e_srel1 = interleave(prev.e_srel1, a_srel1[a_order].astype(np.int32))
    e_cav = interleave(prev.e_caveat, a_cav[a_order])
    e_ctx = interleave(prev.e_ctx, a_ctx[a_order])
    e_exp = interleave(prev.e_exp, a_exp32[a_order])
    e_exp_us = interleave(prev.e_exp_us, a_exp_us[a_order])

    # compact contexts only when the dead fraction is substantial:
    # renumbering invalidates the ctx ids baked into device-resident base
    # tables, forcing the engine's delta-prepare into a full rebuild, so
    # small deltas keep indices append-only stable
    renumbered = False
    used = e_ctx >= 0
    n_used = int(np.count_nonzero(used))
    if n_used == 0:
        renumbered = bool(contexts)
        contexts = []
    elif len(contexts) > CTX_COMPACT_MIN and len(contexts) > 2 * n_used:
        live_ctx, inv = np.unique(e_ctx[used], return_inverse=True)
        contexts = [contexts[i] for i in live_ctx]
        e_ctx = e_ctx.copy()
        e_ctx[used] = inv.astype(np.int32)
        renumbered = True

    nxt = finish_snapshot(
        revision, compiled, interner,
        e_rel=e_rel, e_res=e_res, e_subj=e_subj, e_srel1=e_srel1,
        e_caveat=e_cav, e_ctx=e_ctx, e_exp=e_exp, e_exp_us=e_exp_us,
        contexts=contexts, epoch_us=prev.epoch_us,
    )
    if not renumbered:
        nxt._ctx_index = ctx_index  # still valid: indices were append-only
    # attach the machine-readable delta for the device engine's
    # incremental prepare (identity columns of removed rows come from the
    # previous snapshot's primary arrays)
    gone_rows = (
        np.unique(gone[gone >= 0]) if gone.size else np.empty(0, np.int64)
    )
    nxt.delta_info = DeltaInfo(
        prev_revision=prev.revision,
        a_rel=a_rel.astype(np.int32), a_res=a_res.astype(np.int32),
        a_subj=a_subj.astype(np.int32), a_srel1=a_srel1.astype(np.int32),
        a_cav=a_cav, a_ctx=a_ctx, a_exp=a_exp32,
        g_rel=prev.e_rel[gone_rows], g_res=prev.e_res[gone_rows],
        g_subj=prev.e_subj[gone_rows], g_srel1=prev.e_srel1[gone_rows],
        contexts_renumbered=renumbered,
    )
    # carry the lookup index forward: when the previous snapshot has one,
    # advance it by the delta (O(E + D log E) merges) instead of letting
    # the next lookup pay a full O(E log E) rebuild (round-2 Weak #4)
    if getattr(prev, "_lookup_index", None) is not None:
        from ..engine.lookup import advance_lookup_index

        advance_lookup_index(
            prev, nxt,
            gone_rows=np.unique(gone[gone >= 0]) if gone.size else gone,
            a_rel=a_rel, a_res=a_res, a_subj=a_subj, a_srel1=a_srel1,
        )
    return nxt

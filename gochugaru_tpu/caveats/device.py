"""On-device CEL caveat evaluation (BASELINE config 4).

The host compiler (``cel.py``) gives each caveat a typed AST.  This module
lowers the *device-eligible* subset to straight-line JAX ops so caveated
edges resolve to definite permissionship inside the jitted check instead of
falling back to the host oracle.  The reference delegates caveat evaluation
to SpiceDB's server-side CEL interpreter (context travels in the
CheckBulkPermissions items, client/client.go:241-259); here the "server" is
the TPU, so the predicate itself must vectorize.

Design:

- **Static typing.**  CEL is dynamically typed, but caveat declarations
  carry parameter types (``caveat c(a int, b string)``), so the whole tree
  types statically: int/uint → i32, bool → tri-state i32, double → f32,
  string → interned i32 id, timestamp/duration → a two-limb i32 pair of
  epoch/signed microseconds (see below).  Anything outside that (lists,
  maps, ``any``, member access, dynamic ``timestamp(x)`` construction)
  marks the caveat host-only.

- **Time as i32 limb pairs.**  The host evaluates the CEL time algebra
  in exact integer microseconds (cel.py Timestamp/Duration); the year
  9999 is ≈2^57.8 µs, far outside i32, and this build keeps jax x64
  disabled.  So a time value rides in TWO i32 lanes:
  ``us = hi·2^30 + lo`` with ``lo ∈ [0, 2^30)`` canonical.  Add/sub
  work limb-wise with one arithmetic-shift carry normalization
  (``lo >> 30`` floors for negatives, so the pair stays canonical);
  ordered compares are lexicographic on (hi, lo), exact because lo is
  non-negative.  Every operation is integer-exact — no f64 round-trip —
  so device results are bitwise the host's.  The same interval analysis
  that bounds int arithmetic bounds the time algebra: every
  intermediate must stay under 2^58 µs (canonical ``|hi| ≤ 2^28``, so a
  limb-wise add can never overflow i32), with a per-caveat bound ladder
  and encode-time eviction to the host flag beyond it.

- **Tri-state Kleene logic.**  Results are 0=FALSE, 1=UNKNOWN, 2=TRUE in
  i32; ``or``=max, ``and``=min, ``not``=2-x — the same encoding the host
  oracle uses (engine/oracle.py).  A missing context parameter is UNKNOWN,
  which the caller maps to CONDITIONAL → host resolution.

- **Exactness over coverage.**  The device only evaluates what it can
  evaluate *bit-exactly* against the host oracle: int arithmetic is bounded
  by interval analysis so i32 can never overflow (rows with larger values
  get a per-(row, caveat) host flag); doubles must round-trip through f32;
  unknown-at-build strings get fresh negative ids so they compare equal
  only to themselves.  Rows that violate a bound fall back to the host —
  coverage shrinks, correctness never does.

- **Merge semantics.**  Stored (edge) context wins over query context
  per-parameter, exactly as the oracle merges (oracle.py:120-122).
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..schema.compiler import CompiledSchema
from .cel import (
    CelCompileError,
    CelProgram,
    Duration,
    Timestamp,
    _TimeValue,
    compile_cel,
    parse_duration,
    parse_timestamp,
)

F, U, T = 0, 1, 2
I32_MAX = 2**31 - 1
#: ints exactly representable in f32
F32_EXACT_INT = 2**24

#: time limb split: us = hi * 2^30 + lo with lo ∈ [0, 2^30) canonical.
#: 30 bits keeps a limb-wise add of two canonical los < 2^31 (no i32
#: wrap) while hi spans ±2^28 at the 2^58-µs intermediate ceiling.
TIME_RADIX_BITS = 30
TIME_LO_MASK = (1 << TIME_RADIX_BITS) - 1
#: max |µs| any intermediate time value may reach on device: canonical
#: |hi| ≤ 2^28, so one un-normalized add stays far inside i32
TIME_MAX_US = 1 << 58
_TIMED_KINDS = ("timestamp", "duration")


class _HostOnly(Exception):
    """Raised during lowering when a construct can't run on device."""


# device value representation:
#   bool  → tri i32 (0/1/2)
#   int   → (i32 value, bool known)
#   double→ (f32 value, bool known)
#   string→ (i32 id, bool known)
#   timestamp/duration → ((i32 hi, i32 lo), bool known) µs limb pair
_VALUE_KINDS = ("int", "double", "string")


@dataclass
class ContextTable:
    """Encoded context rows: [N, P] typed values + per-(row, caveat) host
    flags.  N is always ≥ 1 so clipped gathers on index -1 stay in range."""

    vi: np.ndarray  # int32[N, P] int/bool/string-id values
    vf: np.ndarray  # float32[N, P] double values
    present: np.ndarray  # bool[N, P]
    host: np.ndarray  # bool[N, C+1] needs-host flag per caveat id


@dataclass
class CaveatDevicePlan:
    """Static, schema-derived caveat lowering shared by every snapshot."""

    num_params: int  # P: global param slots across caveats
    num_caveats: int  # C (ids are 1-based; 0 = no caveat)
    #: (caveat_name, param_name) → global slot
    slot_of: Dict[Tuple[str, str], int]
    #: per slot: declared device type ('int' | 'double' | 'bool' | 'string')
    slot_type: List[str]
    #: param name → [(caveat_id, slot)] for query-context fan-out
    slots_of_param: Dict[str, List[Tuple[int, int]]]
    #: per caveat id: True → always host-evaluated
    host_only: np.ndarray  # bool[C+1]
    #: per caveat id: max |int| context value evaluable on device
    int_bound: np.ndarray  # int64[C+1]
    #: per caveat id: max |µs| context time value evaluable on device
    time_bound: np.ndarray  # int64[C+1]
    #: caveat id → traced (vi, vf, present) → tri; operates on [..., P]
    programs: Dict[int, Callable]
    #: string literal pool (extended by snapshot contexts)
    base_strings: Dict[str, int]
    caveat_params: Dict[str, Mapping[str, str]]  # name → declared params
    name_of_id: Dict[int, str]

    @property
    def has_device_programs(self) -> bool:
        return bool(self.programs)


_DEVICE_PARAM_TYPES = {"int": "int", "uint": "int", "double": "double",
                       "bool": "bool", "string": "string",
                       "timestamp": "timestamp", "duration": "duration"}


def _base_type(ptype: str) -> str:
    return ptype.split("<", 1)[0].strip()


# ---------------------------------------------------------------------------
# interval analysis: can i32 arithmetic overflow with |var| ≤ B?
# ---------------------------------------------------------------------------


def _int_extent(node, types: Dict[str, str], bound: int, state: Dict[str, bool]) -> int:
    """Max |value| of an int-typed node with every int context value bounded
    by ``bound`` in magnitude; 0 for non-value nodes.  Sets ``state['ovf']``
    when any int arithmetic node can exceed i32."""
    op = node[0]
    if op == "lit":
        v = node[1]
        return abs(v) if isinstance(v, int) and not isinstance(v, bool) else 0
    if op == "var":
        return bound if types.get(node[1]) == "int" else 0
    if op == "neg":
        return _int_extent(node[1], types, bound, state)
    if op == "arith":
        a = _int_extent(node[2], types, bound, state)
        b = _int_extent(node[3], types, bound, state)
        o = node[1]
        if o in ("+", "-"):
            m = a + b
        elif o == "*":
            m = a * b
        elif o == "/":
            m = a  # |a / b| ≤ |a| for truncated division
        else:  # %: truncated remainder has |r| < |b| and |r| ≤ |a|
            m = min(a, b)
        if m >= I32_MAX:
            state["ovf"] = True
        return m
    if op == "cond":
        _int_extent(node[1], types, bound, state)
        return max(
            _int_extent(node[2], types, bound, state),
            _int_extent(node[3], types, bound, state),
        )
    if op in ("not",):
        _int_extent(node[1], types, bound, state)
        return 0
    if op in ("or", "and", "in"):
        _int_extent(node[1], types, bound, state)
        _int_extent(node[2], types, bound, state)
        return 0
    if op == "cmp":
        _int_extent(node[2], types, bound, state)
        _int_extent(node[3], types, bound, state)
        return 0
    if op == "list":
        for it in node[1]:
            _int_extent(it, types, bound, state)
        return 0
    return 0


def _arith_safe(ast, types: Dict[str, str], bound: int) -> bool:
    """True if no int-typed arithmetic node can exceed i32 with every int
    context value bounded by ``bound`` in magnitude."""
    state = {"ovf": False}
    _int_extent(ast, types, bound, state)
    return not state["ovf"]


def _time_extent(node, types: Dict[str, str], bound: int,
                 state: Dict[str, bool]) -> int:
    """Max |µs| of a time-typed node with every timed context value
    bounded by ``bound`` µs in magnitude; 0 for non-time nodes.  Sets
    ``state['tovf']`` when any time arithmetic node can exceed the 2^58
    intermediate ceiling, and ``state['tarith']`` when the tree does any
    time arithmetic at all (no arithmetic ⇒ compares only ⇒ no bound
    needed beyond the limb representation itself)."""
    op = node[0]
    if op == "lit":
        v = node[1]
        return abs(v.us) if isinstance(v, _TimeValue) else 0
    if op == "var":
        return bound if types.get(node[1]) in _TIMED_KINDS else 0
    if op == "neg":
        return _time_extent(node[1], types, bound, state)
    if op == "arith":
        a = _time_extent(node[2], types, bound, state)
        b = _time_extent(node[3], types, bound, state)
        if a == 0 and b == 0:
            return 0
        state["tarith"] = True
        m = a + b  # only ± reach the device lowering for timed operands
        if m >= TIME_MAX_US:
            state["tovf"] = True
        return m
    if op == "cond":
        _time_extent(node[1], types, bound, state)
        return max(
            _time_extent(node[2], types, bound, state),
            _time_extent(node[3], types, bound, state),
        )
    if op == "not":
        _time_extent(node[1], types, bound, state)
        return 0
    if op in ("or", "and", "in"):
        _time_extent(node[1], types, bound, state)
        _time_extent(node[2], types, bound, state)
        return 0
    if op == "cmp":
        _time_extent(node[2], types, bound, state)
        _time_extent(node[3], types, bound, state)
        return 0
    if op == "list":
        for it in node[1]:
            _time_extent(it, types, bound, state)
        return 0
    return 0


def _time_safe(ast, types: Dict[str, str], bound: int) -> bool:
    state: Dict[str, bool] = {"tovf": False}
    _time_extent(ast, types, bound, state)
    return not state["tovf"]


# ---------------------------------------------------------------------------
# AST → JAX lowering
# ---------------------------------------------------------------------------


def _time_norm(hi, lo, jnp):
    """Re-canonicalize a µs limb pair after a limb-wise ±: the shift is
    arithmetic, so the carry floors and lo lands back in [0, 2^30) for
    negative sums too."""
    carry = lo >> TIME_RADIX_BITS
    return hi + carry, lo & jnp.int32(TIME_LO_MASK)


def _lower_program(
    prog: CelProgram,
    slot_of: Dict[Tuple[str, str], int],
    strings: Dict[str, int],
) -> Callable:
    """Lower one caveat AST to ``fn(vi, vf, present) → tri`` over [..., P]
    arrays.  Raises _HostOnly for unsupported constructs."""
    import jax.numpy as jnp

    types: Dict[str, str] = {}
    for pname, ptype in prog.params.items():
        dt = _DEVICE_PARAM_TYPES.get(_base_type(ptype))
        if dt is None:
            raise _HostOnly(f"param type {ptype}")
        types[pname] = dt

    def intern(s: str) -> int:
        if s not in strings:
            strings[s] = len(strings) + 1
        return strings[s]

    # int-typed subtrees that get promoted to f32 in a double comparison;
    # build_caveat_plan must prove their interval max ≤ F32_EXACT_INT under
    # the chosen int bound, or evict the caveat to the host (compound int
    # expressions can exceed 2^24 while still passing the i32 overflow
    # check — e.g. 'a + 99999999 > lim' rounds in f32)
    promoted_int: List[Any] = []

    # Each lowered node is (kind, emit).  For kind 'bool', emit(vi,vf,pr)
    # returns tri; for value kinds it returns (value, known).
    def lower(node):
        op = node[0]
        if op == "lit":
            v = node[1]
            if isinstance(v, bool):
                return "bool", lambda vi, vf, pr, t=(T if v else F): jnp.int32(t)
            if isinstance(v, _TimeValue):
                # timestamp("...")/duration("...") literals folded at parse
                # time; split into canonical µs limbs here
                if abs(v.us) >= TIME_MAX_US:
                    raise _HostOnly("time literal out of device range")
                hi, lo = v.us >> TIME_RADIX_BITS, v.us & TIME_LO_MASK
                kind = "timestamp" if isinstance(v, Timestamp) else "duration"
                return kind, lambda vi, vf, pr, h=hi, l=lo: (
                    (jnp.int32(h), jnp.int32(l)), jnp.bool_(True))
            if isinstance(v, int):
                if abs(v) >= I32_MAX:
                    raise _HostOnly("int literal out of i32 range")
                return "int", lambda vi, vf, pr, c=v: (
                    jnp.int32(c), jnp.bool_(True))
            if isinstance(v, float):
                if float(np.float32(v)) != v:
                    raise _HostOnly("double literal not f32-exact")
                return "double", lambda vi, vf, pr, c=v: (
                    jnp.float32(c), jnp.bool_(True))
            if isinstance(v, str):
                return "string", lambda vi, vf, pr, c=intern(v): (
                    jnp.int32(c), jnp.bool_(True))
            raise _HostOnly(f"literal {v!r}")
        if op == "var":
            name = node[1]
            kind = types[name]
            s = slot_of[(prog.name, name)]
            if kind == "bool":
                def emit_b(vi, vf, pr, s=s):
                    known = pr[..., s]
                    return jnp.where(
                        known, jnp.where(vi[..., s] != 0, T, F), U
                    ).astype(jnp.int32)
                return "bool", emit_b
            if kind == "double":
                return "double", lambda vi, vf, pr, s=s: (vf[..., s], pr[..., s])
            if kind in _TIMED_KINDS:
                # two consecutive i32 slots: hi at s, lo at s + 1
                return kind, lambda vi, vf, pr, s=s: (
                    (vi[..., s], vi[..., s + 1]), pr[..., s])
            return kind, lambda vi, vf, pr, s=s: (vi[..., s], pr[..., s])
        if op == "not":
            k, e = lower(node[1])
            if k != "bool":
                raise _HostOnly("! on non-bool")
            return "bool", lambda vi, vf, pr: jnp.int32(2) - e(vi, vf, pr)
        if op == "neg":
            k, e = lower(node[1])
            if k == "int":
                return "int", lambda vi, vf, pr: (
                    lambda v: (-v[0], v[1]))(e(vi, vf, pr))
            if k == "double":
                return "double", lambda vi, vf, pr: (
                    lambda v: (-v[0], v[1]))(e(vi, vf, pr))
            if k == "duration":
                def emit_nd(vi, vf, pr):
                    (hi, lo), kn = e(vi, vf, pr)
                    return _time_norm(-hi, -lo, jnp), kn
                return "duration", emit_nd
            # -timestamp is a host TypeError too
            raise _HostOnly("unary - on non-numeric")
        if op in ("or", "and"):
            ka, ea = lower(node[1])
            kb, eb = lower(node[2])
            if ka != "bool" or kb != "bool":
                raise _HostOnly(f"{op} on non-bool")
            red = jnp.maximum if op == "or" else jnp.minimum
            return "bool", lambda vi, vf, pr: red(ea(vi, vf, pr), eb(vi, vf, pr))
        if op == "cond":
            kc, ec = lower(node[1])
            if kc != "bool":
                raise _HostOnly("?: condition not bool")
            kt, et = lower(node[2])
            kf, ef = lower(node[3])
            if kt != kf:
                raise _HostOnly("?: branches differ in type")
            if kt == "bool":
                def emit_cb(vi, vf, pr):
                    c = ec(vi, vf, pr)
                    return jnp.where(
                        c == U, U, jnp.where(c == T, et(vi, vf, pr), ef(vi, vf, pr))
                    ).astype(jnp.int32)
                return "bool", emit_cb

            def emit_cv(vi, vf, pr):
                c = ec(vi, vf, pr)
                tv, tk = et(vi, vf, pr)
                fv, fk = ef(vi, vf, pr)
                if isinstance(tv, tuple):  # timed: select per limb
                    val = (jnp.where(c == T, tv[0], fv[0]),
                           jnp.where(c == T, tv[1], fv[1]))
                else:
                    val = jnp.where(c == T, tv, fv)
                known = (c != U) & jnp.where(c == T, tk, fk)
                return val, known
            return kt, emit_cv
        if op == "cmp":
            o = node[1]
            ka, ea = lower(node[2])
            kb, eb = lower(node[3])
            if ka == "bool" and kb == "bool":
                if o not in ("==", "!="):
                    raise _HostOnly("ordered comparison on bools")

                def emit_bb(vi, vf, pr, neq=(o == "!=")):
                    a = ea(vi, vf, pr)
                    b = eb(vi, vf, pr)
                    eq = (a == b) ^ neq
                    unknown = (a == U) | (b == U)
                    return jnp.where(
                        unknown, U, jnp.where(eq, T, F)
                    ).astype(jnp.int32)
                return "bool", emit_bb
            if ka == "bool" or kb == "bool":
                raise _HostOnly("comparison mixes bool and value")
            if ka in _TIMED_KINDS or kb in _TIMED_KINDS:
                if ka != kb:
                    # cross-kind == is a constant False on the host and
                    # ordered compares are a host TypeError; neither is
                    # worth a device lowering
                    raise _HostOnly("comparison mixes time and non-time")

                def emit_tc(vi, vf, pr, o=o):
                    (ah, al), akn = ea(vi, vf, pr)
                    (bh, bl), bkn = eb(vi, vf, pr)
                    # canonical lo ≥ 0, so (hi, lo) orders lexicographically
                    if o == "==":
                        raw = (ah == bh) & (al == bl)
                    elif o == "!=":
                        raw = (ah != bh) | (al != bl)
                    elif o in ("<", "<="):
                        tie = (al < bl) if o == "<" else (al <= bl)
                        raw = (ah < bh) | ((ah == bh) & tie)
                    else:
                        tie = (al > bl) if o == ">" else (al >= bl)
                        raw = (ah > bh) | ((ah == bh) & tie)
                    return jnp.where(
                        akn & bkn, jnp.where(raw, T, F), U
                    ).astype(jnp.int32)
                return "bool", emit_tc
            if ka == "string" or kb == "string":
                if ka != kb:
                    raise _HostOnly("comparison mixes string and numeric")
                if o not in ("==", "!="):
                    raise _HostOnly("ordered comparison on strings")
            promote = "double" if "double" in (ka, kb) else ka
            if promote == "double":
                if ka == "int":
                    promoted_int.append(node[2])
                if kb == "int":
                    promoted_int.append(node[3])

            def emit_cmp(vi, vf, pr, o=o, promote=promote):
                av, akn = ea(vi, vf, pr)
                bv, bkn = eb(vi, vf, pr)
                if promote == "double":
                    av = av.astype(jnp.float32) if hasattr(av, "astype") else jnp.float32(av)
                    bv = bv.astype(jnp.float32) if hasattr(bv, "astype") else jnp.float32(bv)
                if o == "==":
                    raw = av == bv
                elif o == "!=":
                    raw = av != bv
                elif o == "<":
                    raw = av < bv
                elif o == "<=":
                    raw = av <= bv
                elif o == ">":
                    raw = av > bv
                else:
                    raw = av >= bv
                return jnp.where(
                    akn & bkn, jnp.where(raw, T, F), U
                ).astype(jnp.int32)
            return "bool", emit_cmp
        if op == "arith":
            o = node[1]
            ka, ea = lower(node[2])
            kb, eb = lower(node[3])
            if ka in _TIMED_KINDS or kb in _TIMED_KINDS:
                # the CEL time algebra: ts − ts = dur, ts ± dur = ts,
                # dur ± dur = dur.  Everything else (ts + ts, *, /, %,
                # time mixed with numerics) is a host TypeError.
                if o == "+" and (ka, kb) in (
                    ("timestamp", "duration"), ("duration", "timestamp")
                ):
                    res = "timestamp"
                elif o == "-" and (ka, kb) == ("timestamp", "timestamp"):
                    res = "duration"
                elif o == "-" and (ka, kb) == ("timestamp", "duration"):
                    res = "timestamp"
                elif o in ("+", "-") and (ka, kb) == ("duration", "duration"):
                    res = "duration"
                else:
                    raise _HostOnly("time arithmetic outside the CEL algebra")

                def emit_ta(vi, vf, pr, sub=(o == "-")):
                    (ah, al), akn = ea(vi, vf, pr)
                    (bh, bl), bkn = eb(vi, vf, pr)
                    if sub:
                        bh, bl = -bh, -bl
                    return _time_norm(ah + bh, al + bl, jnp), akn & bkn
                return res, emit_ta
            if ka != "int" or kb != "int":
                # device arithmetic is int-only; float arithmetic would
                # round differently from the host's f64
                raise _HostOnly("non-int arithmetic")

            def emit_ar(vi, vf, pr, o=o):
                av, akn = ea(vi, vf, pr)
                bv, bkn = eb(vi, vf, pr)
                known = akn & bkn
                if o == "+":
                    return av + bv, known
                if o == "-":
                    return av - bv, known
                if o == "*":
                    return av * bv, known
                # CEL integer / and % truncate toward zero; divide-by-zero
                # is a host-side error → UNKNOWN here
                bz = bv == 0
                safe_b = jnp.where(bz, 1, bv)
                q = jnp.sign(av) * jnp.sign(safe_b) * (
                    jnp.abs(av) // jnp.abs(safe_b))
                q = q.astype(jnp.int32)
                known = known & ~bz
                if o == "/":
                    return q, known
                return av - q * bv, known
            return "int", emit_ar
        if op == "in":
            ka, ea = lower(node[1])
            if ka not in _VALUE_KINDS + _TIMED_KINDS:
                raise _HostOnly("'in' on non-value")
            if node[2][0] != "list":
                raise _HostOnly("'in' target not a list literal")
            elems = [lower(it) for it in node[2][1]]
            for it, (ke, _) in zip(node[2][1], elems):
                if ke != ka and not (ka == "double" and ke == "int"):
                    raise _HostOnly("'in' list element type mismatch")
                if ka == "double" and ke == "int":
                    promoted_int.append(it)

            def emit_in(vi, vf, pr):
                av, akn = ea(vi, vf, pr)
                hit = jnp.bool_(False)
                kn = akn
                for _, ee in elems:
                    ev, ekn = ee(vi, vf, pr)
                    if isinstance(av, tuple):  # timed: equal limb pairs
                        hit = hit | ((av[0] == ev[0]) & (av[1] == ev[1]))
                    else:
                        if ka == "double":
                            ev = jnp.asarray(ev).astype(jnp.float32)
                        hit = hit | (av == ev)
                    kn = kn & ekn
                return jnp.where(kn, jnp.where(hit, T, F), U).astype(jnp.int32)
            return "bool", emit_in
        raise _HostOnly(f"construct {op!r}")

    kind, emit = lower(prog.ast)
    if kind != "bool":
        raise _HostOnly("caveat does not evaluate to bool")

    def run(vi, vf, pr):
        shape = vi.shape[:-1]
        return jnp.broadcast_to(emit(vi, vf, pr), shape).astype(jnp.int32)

    return run, types, promoted_int


# ---------------------------------------------------------------------------
# plan construction
# ---------------------------------------------------------------------------

_INT_BOUNDS = (2**30, 2**20, 2**16, 2**12, 2**8, 2**4)
#: time context-value bound ladder (µs): 2^57 keeps `ts ± dur` chains of
#: two inside the 2^58 intermediate ceiling while covering year 9999
#: contexts (≈2^57.8) via the no-arithmetic fast path above the ladder
_TIME_BOUNDS = (2**57, 2**52, 2**46, 2**40)


def build_caveat_plan(compiled: CompiledSchema) -> CaveatDevicePlan:
    """Assign global param slots and lower every device-eligible caveat.
    Caveats that fail lowering stay host-only — same behavior as before
    this module existed, just scoped per-caveat instead of per-schema."""
    caveats = compiled.schema.caveats
    C = len(compiled.caveat_ids)
    slot_of: Dict[Tuple[str, str], int] = {}
    slot_type: List[str] = []
    slots_of_param: Dict[str, List[Tuple[int, int]]] = {}
    caveat_params: Dict[str, Mapping[str, str]] = {}
    name_of_id = {cid: name for name, cid in compiled.caveat_ids.items()}

    for name in sorted(caveats):
        decl = caveats[name]
        cid = compiled.caveat_ids[name]
        caveat_params[name] = dict(decl.params)
        for pname in sorted(decl.params):
            dt = _DEVICE_PARAM_TYPES.get(_base_type(decl.params[pname]), "int")
            slot = len(slot_type)
            slot_of[(name, pname)] = slot
            slot_type.append(dt)
            if dt in _TIMED_KINDS:
                # companion lo limb rides in the next slot; it is never
                # listed in slots_of_param — the encoder fills both limbs
                # when it visits the primary slot
                slot_type.append("time_lo")
            slots_of_param.setdefault(pname, []).append((cid, slot))

    host_only = np.zeros(C + 1, bool)
    int_bound = np.full(C + 1, I32_MAX - 1, np.int64)
    time_bound = np.full(C + 1, TIME_MAX_US - 1, np.int64)
    programs: Dict[int, Callable] = {}
    base_strings: Dict[str, int] = {}

    for name in sorted(caveats):
        decl = caveats[name]
        cid = compiled.caveat_ids[name]
        try:
            prog = compile_cel(name, decl.params, decl.expression)
            fn, types, promoted = _lower_program(prog, slot_of, base_strings)
        except (_HostOnly, CelCompileError):
            host_only[cid] = True
            continue

        # pick the largest int bound under which (a) no int arithmetic can
        # overflow i32 and (b) every int subtree promoted to f32 in a double
        # comparison stays within F32_EXACT_INT, so the promotion is exact
        def bound_ok(b: int) -> bool:
            if not _arith_safe(prog.ast, types, b):
                return False
            st = {"ovf": False}
            return all(
                _int_extent(sub, types, b, st) <= F32_EXACT_INT
                for sub in promoted
            )

        chosen = next((b for b in _INT_BOUNDS if bound_ok(b)), None)
        if chosen is None:
            host_only[cid] = True
            continue
        if not _ast_has_arith(prog.ast) and not promoted:
            chosen = I32_MAX - 1
        int_bound[cid] = chosen

        # same ladder for time values: pick the largest µs bound under
        # which no ± chain can exceed the 2^58 intermediate ceiling.
        # Compares alone can't overflow, so keep the full range then.
        tstate: Dict[str, bool] = {"tovf": False}
        _time_extent(prog.ast, types, _TIME_BOUNDS[0], tstate)
        if tstate.get("tarith"):
            tchosen = next(
                (b for b in _TIME_BOUNDS if _time_safe(prog.ast, types, b)),
                None,
            )
            if tchosen is None:
                host_only[cid] = True
                continue
            time_bound[cid] = tchosen
        programs[cid] = fn

    return CaveatDevicePlan(
        num_params=len(slot_type),
        num_caveats=C,
        slot_of=slot_of,
        slot_type=slot_type,
        slots_of_param=slots_of_param,
        host_only=host_only,
        int_bound=int_bound,
        time_bound=time_bound,
        programs=programs,
        base_strings=base_strings,
        caveat_params=caveat_params,
        name_of_id=name_of_id,
    )


def _ast_has_arith(ast) -> bool:
    if ast[0] == "arith":
        return True
    return any(
        _ast_has_arith(c)
        for c in ast[1:]
        if isinstance(c, tuple)
    ) or (ast[0] == "list" and any(_ast_has_arith(it) for it in ast[1]))


# ---------------------------------------------------------------------------
# context encoding
# ---------------------------------------------------------------------------


def _time_us(base: str, v: Any) -> Optional[int]:
    """Mirror of CelProgram._coerced for one value: µs for anything the
    host would coerce into the declared timestamp/duration type, None
    for anything it would reject (the caller sets the host flag, and the
    host path raises exactly as before this lowering existed)."""
    if isinstance(v, bool):
        return None
    if base == "timestamp":
        if isinstance(v, Timestamp):
            return v.us
        if isinstance(v, _dt.datetime):
            return round(v.timestamp() * 1_000_000)
        if isinstance(v, str):
            try:
                return parse_timestamp(v).us
            except CelCompileError:
                return None
        if isinstance(v, (int, float)):
            return round(v * 1_000_000)
        return None
    if isinstance(v, Duration):
        return v.us
    if isinstance(v, _dt.timedelta):
        return round(v.total_seconds() * 1_000_000)
    if isinstance(v, str):
        try:
            return parse_duration(v).us
        except CelCompileError:
            return None
    if isinstance(v, (int, float)):
        return round(v * 1_000_000)
    return None


def encode_contexts(
    plan: CaveatDevicePlan,
    rows: Sequence[Mapping[str, Any]],
    strings: Dict[str, int],
    *,
    extra_strings: Optional[Dict[str, int]] = None,
) -> ContextTable:
    """Encode context maps into typed [N, P] columns.

    ``strings`` is the shared pool (literals + snapshot strings); when
    ``extra_strings`` is given (query-time), unknown strings get fresh
    *negative* ids there instead of growing the pool — equal unknown
    strings still compare equal, but never collide with stored ids.

    A value a slot can't hold exactly (wrong type, out of the caveat's int
    bound, not f32-exact) sets the (row, caveat) host flag; that caveat's
    probes on the row fall back to the host oracle.
    """
    N = max(len(rows), 1)
    P = max(plan.num_params, 1)
    vi = np.zeros((N, P), np.int32)
    vf = np.zeros((N, P), np.float32)
    present = np.zeros((N, P), bool)
    host = np.zeros((N, plan.num_caveats + 1), bool)

    def string_id(s: str) -> int:
        sid = strings.get(s)
        if sid is not None:
            return sid
        if extra_strings is None:
            sid = len(strings) + 1
            strings[s] = sid
            return sid
        sid = extra_strings.get(s)
        if sid is None:
            sid = -2 - len(extra_strings)
            extra_strings[s] = sid
        return sid

    for i, ctx in enumerate(rows):
        for pname, value in ctx.items():
            for cid, slot in plan.slots_of_param.get(pname, ()):  # noqa: B905
                st = plan.slot_type[slot]
                if st == "int":
                    if isinstance(value, bool) or not isinstance(value, int):
                        host[i, cid] = True
                        continue
                    if abs(value) > plan.int_bound[cid]:
                        host[i, cid] = True
                        continue
                    vi[i, slot] = value
                elif st == "double":
                    if isinstance(value, bool) or not isinstance(value, (int, float)):
                        host[i, cid] = True
                        continue
                    f = float(value)
                    if float(np.float32(f)) != f:
                        host[i, cid] = True
                        continue
                    vf[i, slot] = f
                elif st in _TIMED_KINDS:
                    us = _time_us(st, value)
                    if us is None or abs(us) > plan.time_bound[cid]:
                        host[i, cid] = True
                        continue
                    vi[i, slot] = us >> TIME_RADIX_BITS
                    vi[i, slot + 1] = us & TIME_LO_MASK
                    present[i, slot + 1] = True
                elif st == "bool":
                    if not isinstance(value, bool):
                        host[i, cid] = True
                        continue
                    vi[i, slot] = int(value)
                else:  # string
                    if not isinstance(value, str):
                        host[i, cid] = True
                        continue
                    vi[i, slot] = string_id(value)
                present[i, slot] = True
    return ContextTable(vi=vi, vf=vf, present=present, host=host)


def make_tri_fn(plan: CaveatDevicePlan):
    """Build the traced tri-state gate:

    ``tri(cav, ctx_idx, qctx_idx, tables) → i32`` over any batch shape,
    where ``tables`` holds ectx_* / qctx_* arrays.  Caveat 0 → TRUE;
    host-only caveats and host-flagged rows → UNKNOWN.
    """
    import jax.numpy as jnp

    host_only = np.asarray(plan.host_only)

    def tri(cav, ctx_idx, qctx_idx, tables):
        e = jnp.clip(ctx_idx, 0)
        has_e = ctx_idx >= 0
        q = jnp.clip(qctx_idx, 0)
        has_q = qctx_idx >= 0
        ep = tables["ectx_pr"][e] & has_e[..., None]
        qp = tables["qctx_pr"][q] & has_q[..., None]
        vi = jnp.where(ep, tables["ectx_vi"][e], tables["qctx_vi"][q])
        vf = jnp.where(ep, tables["ectx_vf"][e], tables["qctx_vf"][q])
        pr = ep | qp
        cavc = jnp.clip(cav, 0, plan.num_caveats)
        row_host = (
            (tables["ectx_host"][e, cavc] & has_e)
            | (tables["qctx_host"][q, cavc] & has_q)
        )
        out = jnp.full(jnp.shape(cav), U, jnp.int32)
        for cid, fn in plan.programs.items():
            out = jnp.where(cav == cid, fn(vi, vf, pr), out)
        hostish = jnp.asarray(host_only)[cavc] | row_host
        out = jnp.where(hostish, U, out)
        return jnp.where(cav == 0, T, out).astype(jnp.int32)

    return tri

"""On-device CEL caveat evaluation (BASELINE config 4).

The host compiler (``cel.py``) gives each caveat a typed AST.  This module
lowers the *device-eligible* subset to straight-line JAX ops so caveated
edges resolve to definite permissionship inside the jitted check instead of
falling back to the host oracle.  The reference delegates caveat evaluation
to SpiceDB's server-side CEL interpreter (context travels in the
CheckBulkPermissions items, client/client.go:241-259); here the "server" is
the TPU, so the predicate itself must vectorize.

Design:

- **Static typing.**  CEL is dynamically typed, but caveat declarations
  carry parameter types (``caveat c(a int, b string)``), so the whole tree
  types statically: int/uint → i32, bool → tri-state i32, double → f32,
  string → interned i32 id.  Anything outside that (timestamps, lists,
  maps, ``any``, member access) marks the caveat host-only.

- **Tri-state Kleene logic.**  Results are 0=FALSE, 1=UNKNOWN, 2=TRUE in
  i32; ``or``=max, ``and``=min, ``not``=2-x — the same encoding the host
  oracle uses (engine/oracle.py).  A missing context parameter is UNKNOWN,
  which the caller maps to CONDITIONAL → host resolution.

- **Exactness over coverage.**  The device only evaluates what it can
  evaluate *bit-exactly* against the host oracle: int arithmetic is bounded
  by interval analysis so i32 can never overflow (rows with larger values
  get a per-(row, caveat) host flag); doubles must round-trip through f32;
  unknown-at-build strings get fresh negative ids so they compare equal
  only to themselves.  Rows that violate a bound fall back to the host —
  coverage shrinks, correctness never does.

- **Merge semantics.**  Stored (edge) context wins over query context
  per-parameter, exactly as the oracle merges (oracle.py:120-122).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..schema.compiler import CompiledSchema
from .cel import CelCompileError, CelProgram, compile_cel

F, U, T = 0, 1, 2
I32_MAX = 2**31 - 1
#: ints exactly representable in f32
F32_EXACT_INT = 2**24


class _HostOnly(Exception):
    """Raised during lowering when a construct can't run on device."""


# device value representation:
#   bool  → tri i32 (0/1/2)
#   int   → (i32 value, bool known)
#   double→ (f32 value, bool known)
#   string→ (i32 id, bool known)
_VALUE_KINDS = ("int", "double", "string")


@dataclass
class ContextTable:
    """Encoded context rows: [N, P] typed values + per-(row, caveat) host
    flags.  N is always ≥ 1 so clipped gathers on index -1 stay in range."""

    vi: np.ndarray  # int32[N, P] int/bool/string-id values
    vf: np.ndarray  # float32[N, P] double values
    present: np.ndarray  # bool[N, P]
    host: np.ndarray  # bool[N, C+1] needs-host flag per caveat id


@dataclass
class CaveatDevicePlan:
    """Static, schema-derived caveat lowering shared by every snapshot."""

    num_params: int  # P: global param slots across caveats
    num_caveats: int  # C (ids are 1-based; 0 = no caveat)
    #: (caveat_name, param_name) → global slot
    slot_of: Dict[Tuple[str, str], int]
    #: per slot: declared device type ('int' | 'double' | 'bool' | 'string')
    slot_type: List[str]
    #: param name → [(caveat_id, slot)] for query-context fan-out
    slots_of_param: Dict[str, List[Tuple[int, int]]]
    #: per caveat id: True → always host-evaluated
    host_only: np.ndarray  # bool[C+1]
    #: per caveat id: max |int| context value evaluable on device
    int_bound: np.ndarray  # int64[C+1]
    #: caveat id → traced (vi, vf, present) → tri; operates on [..., P]
    programs: Dict[int, Callable]
    #: string literal pool (extended by snapshot contexts)
    base_strings: Dict[str, int]
    caveat_params: Dict[str, Mapping[str, str]]  # name → declared params
    name_of_id: Dict[int, str]

    @property
    def has_device_programs(self) -> bool:
        return bool(self.programs)


_DEVICE_PARAM_TYPES = {"int": "int", "uint": "int", "double": "double",
                       "bool": "bool", "string": "string"}


def _base_type(ptype: str) -> str:
    return ptype.split("<", 1)[0].strip()


# ---------------------------------------------------------------------------
# interval analysis: can i32 arithmetic overflow with |var| ≤ B?
# ---------------------------------------------------------------------------


def _int_extent(node, types: Dict[str, str], bound: int, state: Dict[str, bool]) -> int:
    """Max |value| of an int-typed node with every int context value bounded
    by ``bound`` in magnitude; 0 for non-value nodes.  Sets ``state['ovf']``
    when any int arithmetic node can exceed i32."""
    op = node[0]
    if op == "lit":
        v = node[1]
        return abs(v) if isinstance(v, int) and not isinstance(v, bool) else 0
    if op == "var":
        return bound if types.get(node[1]) == "int" else 0
    if op == "neg":
        return _int_extent(node[1], types, bound, state)
    if op == "arith":
        a = _int_extent(node[2], types, bound, state)
        b = _int_extent(node[3], types, bound, state)
        o = node[1]
        if o in ("+", "-"):
            m = a + b
        elif o == "*":
            m = a * b
        elif o == "/":
            m = a  # |a / b| ≤ |a| for truncated division
        else:  # %: truncated remainder has |r| < |b| and |r| ≤ |a|
            m = min(a, b)
        if m >= I32_MAX:
            state["ovf"] = True
        return m
    if op == "cond":
        _int_extent(node[1], types, bound, state)
        return max(
            _int_extent(node[2], types, bound, state),
            _int_extent(node[3], types, bound, state),
        )
    if op in ("not",):
        _int_extent(node[1], types, bound, state)
        return 0
    if op in ("or", "and", "in"):
        _int_extent(node[1], types, bound, state)
        _int_extent(node[2], types, bound, state)
        return 0
    if op == "cmp":
        _int_extent(node[2], types, bound, state)
        _int_extent(node[3], types, bound, state)
        return 0
    if op == "list":
        for it in node[1]:
            _int_extent(it, types, bound, state)
        return 0
    return 0


def _arith_safe(ast, types: Dict[str, str], bound: int) -> bool:
    """True if no int-typed arithmetic node can exceed i32 with every int
    context value bounded by ``bound`` in magnitude."""
    state = {"ovf": False}
    _int_extent(ast, types, bound, state)
    return not state["ovf"]


# ---------------------------------------------------------------------------
# AST → JAX lowering
# ---------------------------------------------------------------------------


def _lower_program(
    prog: CelProgram,
    slot_of: Dict[Tuple[str, str], int],
    strings: Dict[str, int],
) -> Callable:
    """Lower one caveat AST to ``fn(vi, vf, present) → tri`` over [..., P]
    arrays.  Raises _HostOnly for unsupported constructs."""
    import jax.numpy as jnp

    types: Dict[str, str] = {}
    for pname, ptype in prog.params.items():
        dt = _DEVICE_PARAM_TYPES.get(_base_type(ptype))
        if dt is None:
            raise _HostOnly(f"param type {ptype}")
        types[pname] = dt

    def intern(s: str) -> int:
        if s not in strings:
            strings[s] = len(strings) + 1
        return strings[s]

    # int-typed subtrees that get promoted to f32 in a double comparison;
    # build_caveat_plan must prove their interval max ≤ F32_EXACT_INT under
    # the chosen int bound, or evict the caveat to the host (compound int
    # expressions can exceed 2^24 while still passing the i32 overflow
    # check — e.g. 'a + 99999999 > lim' rounds in f32)
    promoted_int: List[Any] = []

    # Each lowered node is (kind, emit).  For kind 'bool', emit(vi,vf,pr)
    # returns tri; for value kinds it returns (value, known).
    def lower(node):
        op = node[0]
        if op == "lit":
            v = node[1]
            if isinstance(v, bool):
                return "bool", lambda vi, vf, pr, t=(T if v else F): jnp.int32(t)
            if isinstance(v, int):
                if abs(v) >= I32_MAX:
                    raise _HostOnly("int literal out of i32 range")
                return "int", lambda vi, vf, pr, c=v: (
                    jnp.int32(c), jnp.bool_(True))
            if isinstance(v, float):
                if float(np.float32(v)) != v:
                    raise _HostOnly("double literal not f32-exact")
                return "double", lambda vi, vf, pr, c=v: (
                    jnp.float32(c), jnp.bool_(True))
            if isinstance(v, str):
                return "string", lambda vi, vf, pr, c=intern(v): (
                    jnp.int32(c), jnp.bool_(True))
            raise _HostOnly(f"literal {v!r}")
        if op == "var":
            name = node[1]
            kind = types[name]
            s = slot_of[(prog.name, name)]
            if kind == "bool":
                def emit_b(vi, vf, pr, s=s):
                    known = pr[..., s]
                    return jnp.where(
                        known, jnp.where(vi[..., s] != 0, T, F), U
                    ).astype(jnp.int32)
                return "bool", emit_b
            if kind == "double":
                return "double", lambda vi, vf, pr, s=s: (vf[..., s], pr[..., s])
            return kind, lambda vi, vf, pr, s=s: (vi[..., s], pr[..., s])
        if op == "not":
            k, e = lower(node[1])
            if k != "bool":
                raise _HostOnly("! on non-bool")
            return "bool", lambda vi, vf, pr: jnp.int32(2) - e(vi, vf, pr)
        if op == "neg":
            k, e = lower(node[1])
            if k == "int":
                return "int", lambda vi, vf, pr: (
                    lambda v: (-v[0], v[1]))(e(vi, vf, pr))
            if k == "double":
                return "double", lambda vi, vf, pr: (
                    lambda v: (-v[0], v[1]))(e(vi, vf, pr))
            raise _HostOnly("unary - on non-numeric")
        if op in ("or", "and"):
            ka, ea = lower(node[1])
            kb, eb = lower(node[2])
            if ka != "bool" or kb != "bool":
                raise _HostOnly(f"{op} on non-bool")
            red = jnp.maximum if op == "or" else jnp.minimum
            return "bool", lambda vi, vf, pr: red(ea(vi, vf, pr), eb(vi, vf, pr))
        if op == "cond":
            kc, ec = lower(node[1])
            if kc != "bool":
                raise _HostOnly("?: condition not bool")
            kt, et = lower(node[2])
            kf, ef = lower(node[3])
            if kt != kf:
                raise _HostOnly("?: branches differ in type")
            if kt == "bool":
                def emit_cb(vi, vf, pr):
                    c = ec(vi, vf, pr)
                    return jnp.where(
                        c == U, U, jnp.where(c == T, et(vi, vf, pr), ef(vi, vf, pr))
                    ).astype(jnp.int32)
                return "bool", emit_cb

            def emit_cv(vi, vf, pr):
                c = ec(vi, vf, pr)
                tv, tk = et(vi, vf, pr)
                fv, fk = ef(vi, vf, pr)
                val = jnp.where(c == T, tv, fv)
                known = (c != U) & jnp.where(c == T, tk, fk)
                return val, known
            return kt, emit_cv
        if op == "cmp":
            o = node[1]
            ka, ea = lower(node[2])
            kb, eb = lower(node[3])
            if ka == "bool" and kb == "bool":
                if o not in ("==", "!="):
                    raise _HostOnly("ordered comparison on bools")

                def emit_bb(vi, vf, pr, neq=(o == "!=")):
                    a = ea(vi, vf, pr)
                    b = eb(vi, vf, pr)
                    eq = (a == b) ^ neq
                    unknown = (a == U) | (b == U)
                    return jnp.where(
                        unknown, U, jnp.where(eq, T, F)
                    ).astype(jnp.int32)
                return "bool", emit_bb
            if ka == "bool" or kb == "bool":
                raise _HostOnly("comparison mixes bool and value")
            if ka == "string" or kb == "string":
                if ka != kb:
                    raise _HostOnly("comparison mixes string and numeric")
                if o not in ("==", "!="):
                    raise _HostOnly("ordered comparison on strings")
            promote = "double" if "double" in (ka, kb) else ka
            if promote == "double":
                if ka == "int":
                    promoted_int.append(node[2])
                if kb == "int":
                    promoted_int.append(node[3])

            def emit_cmp(vi, vf, pr, o=o, promote=promote):
                av, akn = ea(vi, vf, pr)
                bv, bkn = eb(vi, vf, pr)
                if promote == "double":
                    av = av.astype(jnp.float32) if hasattr(av, "astype") else jnp.float32(av)
                    bv = bv.astype(jnp.float32) if hasattr(bv, "astype") else jnp.float32(bv)
                if o == "==":
                    raw = av == bv
                elif o == "!=":
                    raw = av != bv
                elif o == "<":
                    raw = av < bv
                elif o == "<=":
                    raw = av <= bv
                elif o == ">":
                    raw = av > bv
                else:
                    raw = av >= bv
                return jnp.where(
                    akn & bkn, jnp.where(raw, T, F), U
                ).astype(jnp.int32)
            return "bool", emit_cmp
        if op == "arith":
            o = node[1]
            ka, ea = lower(node[2])
            kb, eb = lower(node[3])
            if ka != "int" or kb != "int":
                # device arithmetic is int-only; float arithmetic would
                # round differently from the host's f64
                raise _HostOnly("non-int arithmetic")

            def emit_ar(vi, vf, pr, o=o):
                av, akn = ea(vi, vf, pr)
                bv, bkn = eb(vi, vf, pr)
                known = akn & bkn
                if o == "+":
                    return av + bv, known
                if o == "-":
                    return av - bv, known
                if o == "*":
                    return av * bv, known
                # CEL integer / and % truncate toward zero; divide-by-zero
                # is a host-side error → UNKNOWN here
                bz = bv == 0
                safe_b = jnp.where(bz, 1, bv)
                q = jnp.sign(av) * jnp.sign(safe_b) * (
                    jnp.abs(av) // jnp.abs(safe_b))
                q = q.astype(jnp.int32)
                known = known & ~bz
                if o == "/":
                    return q, known
                return av - q * bv, known
            return "int", emit_ar
        if op == "in":
            ka, ea = lower(node[1])
            if ka not in _VALUE_KINDS:
                raise _HostOnly("'in' on non-value")
            if node[2][0] != "list":
                raise _HostOnly("'in' target not a list literal")
            elems = [lower(it) for it in node[2][1]]
            for it, (ke, _) in zip(node[2][1], elems):
                if ke != ka and not (ka == "double" and ke == "int"):
                    raise _HostOnly("'in' list element type mismatch")
                if ka == "double" and ke == "int":
                    promoted_int.append(it)

            def emit_in(vi, vf, pr):
                av, akn = ea(vi, vf, pr)
                hit = jnp.bool_(False)
                kn = akn
                for _, ee in elems:
                    ev, ekn = ee(vi, vf, pr)
                    if ka == "double":
                        ev = jnp.asarray(ev).astype(jnp.float32)
                    hit = hit | (av == ev)
                    kn = kn & ekn
                return jnp.where(kn, jnp.where(hit, T, F), U).astype(jnp.int32)
            return "bool", emit_in
        raise _HostOnly(f"construct {op!r}")

    kind, emit = lower(prog.ast)
    if kind != "bool":
        raise _HostOnly("caveat does not evaluate to bool")

    def run(vi, vf, pr):
        shape = vi.shape[:-1]
        return jnp.broadcast_to(emit(vi, vf, pr), shape).astype(jnp.int32)

    return run, types, promoted_int


# ---------------------------------------------------------------------------
# plan construction
# ---------------------------------------------------------------------------

_INT_BOUNDS = (2**30, 2**20, 2**16, 2**12, 2**8, 2**4)


def build_caveat_plan(compiled: CompiledSchema) -> CaveatDevicePlan:
    """Assign global param slots and lower every device-eligible caveat.
    Caveats that fail lowering stay host-only — same behavior as before
    this module existed, just scoped per-caveat instead of per-schema."""
    caveats = compiled.schema.caveats
    C = len(compiled.caveat_ids)
    slot_of: Dict[Tuple[str, str], int] = {}
    slot_type: List[str] = []
    slots_of_param: Dict[str, List[Tuple[int, int]]] = {}
    caveat_params: Dict[str, Mapping[str, str]] = {}
    name_of_id = {cid: name for name, cid in compiled.caveat_ids.items()}

    for name in sorted(caveats):
        decl = caveats[name]
        cid = compiled.caveat_ids[name]
        caveat_params[name] = dict(decl.params)
        for pname in sorted(decl.params):
            dt = _DEVICE_PARAM_TYPES.get(_base_type(decl.params[pname]), "int")
            slot = len(slot_type)
            slot_of[(name, pname)] = slot
            slot_type.append(dt)
            slots_of_param.setdefault(pname, []).append((cid, slot))

    host_only = np.zeros(C + 1, bool)
    int_bound = np.full(C + 1, I32_MAX - 1, np.int64)
    programs: Dict[int, Callable] = {}
    base_strings: Dict[str, int] = {}

    for name in sorted(caveats):
        decl = caveats[name]
        cid = compiled.caveat_ids[name]
        try:
            prog = compile_cel(name, decl.params, decl.expression)
            fn, types, promoted = _lower_program(prog, slot_of, base_strings)
        except (_HostOnly, CelCompileError):
            host_only[cid] = True
            continue

        # pick the largest int bound under which (a) no int arithmetic can
        # overflow i32 and (b) every int subtree promoted to f32 in a double
        # comparison stays within F32_EXACT_INT, so the promotion is exact
        def bound_ok(b: int) -> bool:
            if not _arith_safe(prog.ast, types, b):
                return False
            st = {"ovf": False}
            return all(
                _int_extent(sub, types, b, st) <= F32_EXACT_INT
                for sub in promoted
            )

        chosen = next((b for b in _INT_BOUNDS if bound_ok(b)), None)
        if chosen is None:
            host_only[cid] = True
            continue
        if not _ast_has_arith(prog.ast) and not promoted:
            chosen = I32_MAX - 1
        int_bound[cid] = chosen
        programs[cid] = fn

    return CaveatDevicePlan(
        num_params=len(slot_type),
        num_caveats=C,
        slot_of=slot_of,
        slot_type=slot_type,
        slots_of_param=slots_of_param,
        host_only=host_only,
        int_bound=int_bound,
        programs=programs,
        base_strings=base_strings,
        caveat_params=caveat_params,
        name_of_id=name_of_id,
    )


def _ast_has_arith(ast) -> bool:
    if ast[0] == "arith":
        return True
    return any(
        _ast_has_arith(c)
        for c in ast[1:]
        if isinstance(c, tuple)
    ) or (ast[0] == "list" and any(_ast_has_arith(it) for it in ast[1]))


# ---------------------------------------------------------------------------
# context encoding
# ---------------------------------------------------------------------------


def encode_contexts(
    plan: CaveatDevicePlan,
    rows: Sequence[Mapping[str, Any]],
    strings: Dict[str, int],
    *,
    extra_strings: Optional[Dict[str, int]] = None,
) -> ContextTable:
    """Encode context maps into typed [N, P] columns.

    ``strings`` is the shared pool (literals + snapshot strings); when
    ``extra_strings`` is given (query-time), unknown strings get fresh
    *negative* ids there instead of growing the pool — equal unknown
    strings still compare equal, but never collide with stored ids.

    A value a slot can't hold exactly (wrong type, out of the caveat's int
    bound, not f32-exact) sets the (row, caveat) host flag; that caveat's
    probes on the row fall back to the host oracle.
    """
    N = max(len(rows), 1)
    P = max(plan.num_params, 1)
    vi = np.zeros((N, P), np.int32)
    vf = np.zeros((N, P), np.float32)
    present = np.zeros((N, P), bool)
    host = np.zeros((N, plan.num_caveats + 1), bool)

    def string_id(s: str) -> int:
        sid = strings.get(s)
        if sid is not None:
            return sid
        if extra_strings is None:
            sid = len(strings) + 1
            strings[s] = sid
            return sid
        sid = extra_strings.get(s)
        if sid is None:
            sid = -2 - len(extra_strings)
            extra_strings[s] = sid
        return sid

    for i, ctx in enumerate(rows):
        for pname, value in ctx.items():
            for cid, slot in plan.slots_of_param.get(pname, ()):  # noqa: B905
                st = plan.slot_type[slot]
                if st == "int":
                    if isinstance(value, bool) or not isinstance(value, int):
                        host[i, cid] = True
                        continue
                    if abs(value) > plan.int_bound[cid]:
                        host[i, cid] = True
                        continue
                    vi[i, slot] = value
                elif st == "double":
                    if isinstance(value, bool) or not isinstance(value, (int, float)):
                        host[i, cid] = True
                        continue
                    f = float(value)
                    if float(np.float32(f)) != f:
                        host[i, cid] = True
                        continue
                    vf[i, slot] = f
                elif st == "bool":
                    if not isinstance(value, bool):
                        host[i, cid] = True
                        continue
                    vi[i, slot] = int(value)
                else:  # string
                    if not isinstance(value, str):
                        host[i, cid] = True
                        continue
                    vi[i, slot] = string_id(value)
                present[i, slot] = True
    return ContextTable(vi=vi, vf=vf, present=present, host=host)


def make_tri_fn(plan: CaveatDevicePlan):
    """Build the traced tri-state gate:

    ``tri(cav, ctx_idx, qctx_idx, tables) → i32`` over any batch shape,
    where ``tables`` holds ectx_* / qctx_* arrays.  Caveat 0 → TRUE;
    host-only caveats and host-flagged rows → UNKNOWN.
    """
    import jax.numpy as jnp

    host_only = np.asarray(plan.host_only)

    def tri(cav, ctx_idx, qctx_idx, tables):
        e = jnp.clip(ctx_idx, 0)
        has_e = ctx_idx >= 0
        q = jnp.clip(qctx_idx, 0)
        has_q = qctx_idx >= 0
        ep = tables["ectx_pr"][e] & has_e[..., None]
        qp = tables["qctx_pr"][q] & has_q[..., None]
        vi = jnp.where(ep, tables["ectx_vi"][e], tables["qctx_vi"][q])
        vf = jnp.where(ep, tables["ectx_vf"][e], tables["qctx_vf"][q])
        pr = ep | qp
        cavc = jnp.clip(cav, 0, plan.num_caveats)
        row_host = (
            (tables["ectx_host"][e, cavc] & has_e)
            | (tables["qctx_host"][q, cavc] & has_q)
        )
        out = jnp.full(jnp.shape(cav), U, jnp.int32)
        for cid, fn in plan.programs.items():
            out = jnp.where(cav == cid, fn(vi, vf, pr), out)
        hostish = jnp.asarray(host_only)[cavc] | row_host
        out = jnp.where(hostish, U, out)
        return jnp.where(cav == 0, T, out).astype(jnp.int32)

    return tri
